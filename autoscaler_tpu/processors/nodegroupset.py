"""Balancing similar node groups: find look-alike groups and spread a
scale-up across them.

Reference: cluster-autoscaler/processors/nodegroupset/ —
BalancingNodeGroupSetProcessor (FindSimilarNodeGroups balancing_processor.go
:37, BalanceScaleUpBetweenGroups :79) and the similarity comparator
compare_nodegroups.go:84,103 (allocatable within 5%, memory capacity within
1.5%, free resources within 5%, matching labels up to an ignore-list of
zone/hostname-style keys).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from autoscaler_tpu.cloudprovider.interface import NodeGroup
from autoscaler_tpu.config.options import NodeGroupDifferenceRatios
from autoscaler_tpu.kube.objects import Node

# labels ignored when comparing groups (compare_nodegroups.go ignore list)
DEFAULT_IGNORED_LABELS = {
    "kubernetes.io/hostname",
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/region",
    "beta.kubernetes.io/instance-type",
    "node.kubernetes.io/instance-type",
}


def _within(a: float, b: float, max_ratio: float) -> bool:
    if a == b:
        return True
    larger = max(abs(a), abs(b))
    if larger == 0:
        return True
    return abs(a - b) / larger <= max_ratio


@dataclass
class BalancingNodeGroupSetProcessor:
    ratios: NodeGroupDifferenceRatios = field(default_factory=NodeGroupDifferenceRatios)
    ignored_labels: set = field(default_factory=lambda: set(DEFAULT_IGNORED_LABELS))
    # non-empty -> the reference's --balancing-label mode: similarity is
    # decided by these label values ALONE (CreateLabelNodeInfoComparator,
    # compare_nodegroups.go:54) — resource/remaining-label comparisons are
    # skipped entirely, per the flag's documented contract
    label_keys: List[str] = field(default_factory=list)

    def is_similar(self, a: Node, b: Node) -> bool:
        """compare_nodegroups.go:84 IsCloudProviderNodeInfoSimilar."""
        if self.label_keys:
            return all(
                a.labels.get(k) == b.labels.get(k) for k in self.label_keys
            )
        if not _within(
            a.allocatable.cpu_m, b.allocatable.cpu_m,
            self.ratios.max_allocatable_difference_ratio,
        ):
            return False
        if not _within(
            a.allocatable.memory, b.allocatable.memory,
            self.ratios.max_capacity_memory_difference_ratio,
        ):
            return False
        if a.allocatable.gpu != b.allocatable.gpu:
            return False
        la = {k: v for k, v in a.labels.items() if k not in self.ignored_labels}
        lb = {k: v for k, v in b.labels.items() if k not in self.ignored_labels}
        return la == lb

    def find_similar_node_groups(
        self,
        group: NodeGroup,
        templates: Dict[str, Node],
        all_groups: Sequence[NodeGroup],
    ) -> List[NodeGroup]:
        """balancing_processor.go:37."""
        base = templates.get(group.id())
        if base is None:
            return []
        out = []
        for other in all_groups:
            if other.id() == group.id():
                continue
            tmpl = templates.get(other.id())
            if tmpl is not None and self.is_similar(base, tmpl):
                out.append(other)
        return out

    def balance_scale_up(
        self, groups: Sequence[NodeGroup], new_nodes: int
    ) -> List[Tuple[NodeGroup, int]]:
        """balancing_processor.go:79 BalanceScaleUpBetweenGroups: even out
        target sizes — repeatedly grow the currently-smallest group, skipping
        full ones."""
        sizes = {g.id(): g.target_size() for g in groups}
        caps = {g.id(): g.max_size() for g in groups}
        by_id = {g.id(): g for g in groups}
        added: Dict[str, int] = {gid: 0 for gid in sizes}
        for _ in range(new_nodes):
            candidates = [
                gid for gid in sizes if sizes[gid] + added[gid] < caps[gid]
            ]
            if not candidates:
                break
            smallest = min(candidates, key=lambda gid: sizes[gid] + added[gid])
            added[smallest] += 1
        return [(by_id[gid], n) for gid, n in added.items() if n > 0]
