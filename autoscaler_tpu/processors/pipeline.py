"""The processors container — the reference's 18 extension points, with
defaults.

Reference: cluster-autoscaler/processors/processors.go:36
(AutoscalingProcessors struct) and DefaultProcessors. Interfaces without a
TPU-specific twist are small Protocols with default implementations;
heavyweight ones live in sibling modules (nodegroupset.py, nodeinfos.py,
core/podlistprocessor.py). Provider-specific overrides replace fields on the
container, exactly like main.go:406-440 does.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol, Sequence, Tuple

from autoscaler_tpu.cloudprovider.interface import CloudProvider, NodeGroup
from autoscaler_tpu.core.podlistprocessor import FilterOutSchedulablePodListProcessor
from autoscaler_tpu.kube.objects import Node, Pod
from autoscaler_tpu.processors.nodegroupset import BalancingNodeGroupSetProcessor
from autoscaler_tpu.processors.nodeinfos import MixedTemplateNodeInfoProvider


class ScaleUpStatusProcessor(Protocol):
    def process(self, result) -> None: ...


class ScaleDownStatusProcessor(Protocol):
    def process(self, result) -> None: ...


@dataclass
class EventingScaleUpStatusProcessor:
    """Default: surface scale-up outcomes as events/log lines (reference
    processors/status/eventing_scale_up_processor.go)."""

    sink: Callable[[str, str], None] = lambda reason, msg: None

    def process(self, result) -> None:
        if result is None:
            return
        if result.scaled_up:
            self.sink(
                "TriggeredScaleUp",
                f"scale-up: group {result.chosen_group} +{result.new_nodes} "
                f"for {len(result.pods_triggered)} pods",
            )
        for pod in result.pods_remain_unschedulable:
            self.sink("NotTriggerScaleUp", f"pod {pod.key()} can't be helped")


@dataclass
class NoOpScaleDownStatusProcessor:
    def process(self, result) -> None:
        return


class CustomResourcesProcessor:
    """GPU/TPU readiness: a node advertising an accelerator label but 0
    allocatable devices is still initializing — treat as unready so
    utilization/scale-down logic doesn't misread it (reference
    processors/customresources/gpu_processor.go)."""

    def __init__(self, gpu_label: str = "cloud.google.com/gke-accelerator"):
        self.gpu_label = gpu_label

    def filter_out_nodes_with_unready_resources(
        self, nodes: Sequence[Node]
    ) -> Tuple[List[Node], List[Node]]:
        ready, not_ready = [], []
        for node in nodes:
            if (
                self.gpu_label in node.labels
                and node.allocatable.gpu == 0
                and node.allocatable.tpu == 0
            ):
                not_ready.append(node)
            else:
                ready.append(node)
        return ready, not_ready


class ScaleDownCandidatesSortingProcessor:
    """Order scale-down candidates: previously-unneeded first so decisions
    stabilize across loops (reference processors/scaledowncandidates/
    previous_candidates.go + sorting)."""

    def __init__(self) -> None:
        self._previous: set = set()

    def sort(self, candidates: Sequence[Node]) -> List[Node]:
        prev = [n for n in candidates if n.name in self._previous]
        rest = [n for n in candidates if n.name not in self._previous]
        return prev + rest

    def update(self, unneeded_names: Sequence[str]) -> None:
        self._previous = set(unneeded_names)


class NodeGroupListProcessor(Protocol):
    """reference processors/nodegroups/NodeGroupListProcessor — may add
    (e.g. NAP candidate) groups to the scale-up consideration set."""

    def process(self, provider, pending_pods, groups) -> List[NodeGroup]: ...


class PassthroughNodeGroupListProcessor:
    def process(self, provider, pending_pods, groups) -> List[NodeGroup]:
        return []


class ScaleDownNodeProcessor:
    """reference processors/nodes/ScaleDownNodeProcessor — pre-filter the
    scale-down candidate list before the planner sees it. Default: pass
    everything through."""

    def get_scale_down_candidates(
        self, nodes: Sequence[Node], all_nodes: Sequence[Node]
    ) -> List[Node]:
        return list(nodes)


class ScaleDownSetProcessor:
    """reference processors/nodes/ScaleDownSetProcessor — final selection of
    the deletion set from the removable candidates. Default mirrors the
    reference's max-parallelism crop (post_filtering_processor.go)."""

    def get_nodes_to_remove(self, candidates: List, max_count: int) -> List:
        if max_count <= 0:
            return list(candidates)
        return list(candidates)[:max_count]


class AutoscalingStatusProcessor:
    """reference processors/status/AutoscalingStatusProcessor — observe the
    cluster state after every iteration. Default: no-op."""

    def process(self, result, now_ts: float) -> None:
        return


class ActionableClusterProcessor:
    """reference processors/actionablecluster — whether the autoscaler should
    act on the cluster at all this iteration. Default: always actionable."""

    def should_autoscale(self, nodes: Sequence[Node], now_ts: float) -> bool:
        return True


class EmptyClusterProcessor(ActionableClusterProcessor):
    """The reference's EmptyClusterProcessor
    (actionablecluster/actionable_cluster_processor.go:40): with
    scale-up-from-zero disabled, a cluster with no nodes — or none ready —
    is not actionable, so the autoscaler must not scale it from nothing."""

    def __init__(self, scale_up_from_zero: bool = True):
        self.scale_up_from_zero = scale_up_from_zero

    def should_autoscale(self, nodes: Sequence[Node], now_ts: float) -> bool:
        if self.scale_up_from_zero:
            return True
        if not nodes:
            return False
        return any(n.ready for n in nodes)


class NodeInfoProcessor:
    """reference processors/nodeinfos/NodeInfoProcessor — post-process the
    template NodeInfos before estimation. Default: identity."""

    def process(self, node_infos: Dict[str, Node]) -> Dict[str, Node]:
        return node_infos


class NodeGroupConfigProcessor:
    """reference processors/nodegroupconfig — resolve per-group autoscaling
    options. Default delegates to AutoscalingOptions.group_options (the
    NodeGroup.GetOptions fallback chain, cloud_provider.go:230)."""

    def options_for(self, options, group_id: str):
        return options.group_options(group_id)


class BinpackingLimiter:
    """reference processors/binpacking/binpacking_limiter.go (InitBinpacking/
    StopBinpacking). The reference stops the serial per-group estimate loop
    early; here every group is estimated in ONE batched device dispatch, so
    the seam pre-bounds the group set (and per-group headrooms) before that
    dispatch. Default: no limiting."""

    def limit_groups(
        self,
        viable: Dict[str, NodeGroup],
        templates: Dict[str, Node],
        headrooms: Dict[str, int],
        pending_pods: Sequence[Pod],
    ) -> Tuple[Dict[str, NodeGroup], Dict[str, Node], Dict[str, int]]:
        return viable, templates, headrooms


class ScaleDownCandidatesObserver(Protocol):
    """reference processors/scaledowncandidates/ObserversList entry."""

    def update(self, unneeded_names: Sequence[str]) -> None: ...


class NodeGroupManager:
    """Node-group autoprovisioning lifecycle (reference processors/nodegroups/
    — NAP creates groups for pods no existing group fits and deletes empty
    autoprovisioned groups). The default implementation is a no-op unless the
    provider supports group creation."""

    def __init__(self, max_autoprovisioned: int = 15):
        self.max_autoprovisioned = max_autoprovisioned

    def remove_unneeded_node_groups(
        self, provider: CloudProvider, metrics=None
    ) -> List[str]:
        removed = []
        for group in provider.node_groups():
            if group.autoprovisioned() and group.target_size() == 0:
                try:
                    group.delete()
                    removed.append(group.id())
                    if metrics is not None:
                        metrics.deleted_node_groups_total.inc()
                except Exception:
                    pass
        return removed


@dataclass
class AutoscalingProcessors:
    """processors.go:36 — one container wired through the control loop.
    16 of the reference's 18 seams; absent: DebuggingSnapshotter lives in
    debugging.py outside the container (same function), and the reference's
    pod-injection PodListProcessor chain is folded into
    FilterOutSchedulablePodListProcessor's currently-drained-nodes input."""

    pod_list_processor: FilterOutSchedulablePodListProcessor = field(
        default_factory=FilterOutSchedulablePodListProcessor
    )
    node_group_list: PassthroughNodeGroupListProcessor = field(
        default_factory=PassthroughNodeGroupListProcessor
    )
    node_group_set: BalancingNodeGroupSetProcessor = field(
        default_factory=BalancingNodeGroupSetProcessor
    )
    template_node_info_provider: MixedTemplateNodeInfoProvider = field(
        default_factory=MixedTemplateNodeInfoProvider
    )
    node_info: NodeInfoProcessor = field(default_factory=NodeInfoProcessor)
    node_group_config: NodeGroupConfigProcessor = field(
        default_factory=NodeGroupConfigProcessor
    )
    binpacking_limiter: BinpackingLimiter = field(default_factory=BinpackingLimiter)
    scale_up_status: EventingScaleUpStatusProcessor = field(
        default_factory=EventingScaleUpStatusProcessor
    )
    scale_down_node: ScaleDownNodeProcessor = field(
        default_factory=ScaleDownNodeProcessor
    )
    scale_down_set: ScaleDownSetProcessor = field(
        default_factory=ScaleDownSetProcessor
    )
    scale_down_status: NoOpScaleDownStatusProcessor = field(
        default_factory=NoOpScaleDownStatusProcessor
    )
    autoscaling_status: AutoscalingStatusProcessor = field(
        default_factory=AutoscalingStatusProcessor
    )
    actionable_cluster: ActionableClusterProcessor = field(
        default_factory=ActionableClusterProcessor
    )
    custom_resources: CustomResourcesProcessor = field(
        default_factory=CustomResourcesProcessor
    )
    scale_down_candidates_sorting: ScaleDownCandidatesSortingProcessor = field(
        default_factory=ScaleDownCandidatesSortingProcessor
    )
    # ObserversList analog: every observer hears the new unneeded set
    scale_down_candidates_observers: List[ScaleDownCandidatesObserver] = field(
        default_factory=list
    )
    node_group_manager: NodeGroupManager = field(default_factory=NodeGroupManager)

    def __post_init__(self) -> None:
        if self.scale_down_candidates_sorting not in self.scale_down_candidates_observers:
            self.scale_down_candidates_observers.append(
                self.scale_down_candidates_sorting
            )

    def notify_scale_down_candidates(self, unneeded_names: Sequence[str]) -> None:
        for obs in self.scale_down_candidates_observers:
            obs.update(unneeded_names)


def default_processors(options=None) -> AutoscalingProcessors:
    """Default wiring; with options, knob-driven processors pick up their
    config (balancing ratios + extra ignored labels, like the reference's
    NewDefaultProcessors(opts))."""
    procs = AutoscalingProcessors()
    if options is not None:
        from autoscaler_tpu.processors.nodegroupset import DEFAULT_IGNORED_LABELS

        procs.node_group_set = BalancingNodeGroupSetProcessor(
            ratios=options.node_group_difference_ratios,
            ignored_labels=set(DEFAULT_IGNORED_LABELS)
            | set(options.balancing_extra_ignored_labels),
            label_keys=list(options.balancing_label_keys),
        )
        procs.template_node_info_provider = MixedTemplateNodeInfoProvider(
            ttl_s=options.node_info_cache_expire_time_s,
            ignored_taints=options.ignored_taints,
        )
        procs.actionable_cluster = EmptyClusterProcessor(
            scale_up_from_zero=options.scale_up_from_zero
        )
        procs.node_group_manager = NodeGroupManager(
            max_autoprovisioned=options.max_autoprovisioned_node_group_count
        )
        # NOTE: AutoprovisioningNodeGroupListProcessor needs a provider-
        # specific group factory, so embedders construct it themselves —
        # pass options.max_autoprovisioned_node_group_count as its
        # max_autoprovisioned_groups to keep the two caps consistent.
    return procs
