"""The processors container — the reference's 18 extension points, with
defaults.

Reference: cluster-autoscaler/processors/processors.go:36
(AutoscalingProcessors struct) and DefaultProcessors. Interfaces without a
TPU-specific twist are small Protocols with default implementations;
heavyweight ones live in sibling modules (nodegroupset.py, nodeinfos.py,
core/podlistprocessor.py). Provider-specific overrides replace fields on the
container, exactly like main.go:406-440 does.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol, Sequence, Tuple

from autoscaler_tpu.cloudprovider.interface import CloudProvider, NodeGroup
from autoscaler_tpu.core.podlistprocessor import FilterOutSchedulablePodListProcessor
from autoscaler_tpu.kube.objects import Node, Pod
from autoscaler_tpu.processors.nodegroupset import BalancingNodeGroupSetProcessor
from autoscaler_tpu.processors.nodeinfos import MixedTemplateNodeInfoProvider


class ScaleUpStatusProcessor(Protocol):
    def process(self, result) -> None: ...


class ScaleDownStatusProcessor(Protocol):
    def process(self, result) -> None: ...


@dataclass
class EventingScaleUpStatusProcessor:
    """Default: surface scale-up outcomes as events/log lines (reference
    processors/status/eventing_scale_up_processor.go)."""

    sink: Callable[[str, str], None] = lambda reason, msg: None

    def process(self, result) -> None:
        if result is None:
            return
        if result.scaled_up:
            self.sink(
                "TriggeredScaleUp",
                f"scale-up: group {result.chosen_group} +{result.new_nodes} "
                f"for {len(result.pods_triggered)} pods",
            )
        for pod in result.pods_remain_unschedulable:
            self.sink("NotTriggerScaleUp", f"pod {pod.key()} can't be helped")


@dataclass
class NoOpScaleDownStatusProcessor:
    def process(self, result) -> None:
        return


class CustomResourcesProcessor:
    """GPU/TPU readiness: a node advertising an accelerator label but 0
    allocatable devices is still initializing — treat as unready so
    utilization/scale-down logic doesn't misread it (reference
    processors/customresources/gpu_processor.go)."""

    def __init__(self, gpu_label: str = "cloud.google.com/gke-accelerator"):
        self.gpu_label = gpu_label

    def filter_out_nodes_with_unready_resources(
        self, nodes: Sequence[Node]
    ) -> Tuple[List[Node], List[Node]]:
        ready, not_ready = [], []
        for node in nodes:
            if (
                self.gpu_label in node.labels
                and node.allocatable.gpu == 0
                and node.allocatable.tpu == 0
            ):
                not_ready.append(node)
            else:
                ready.append(node)
        return ready, not_ready


class ScaleDownCandidatesSortingProcessor:
    """Order scale-down candidates: previously-unneeded first so decisions
    stabilize across loops (reference processors/scaledowncandidates/
    previous_candidates.go + sorting)."""

    def __init__(self) -> None:
        self._previous: set = set()

    def sort(self, candidates: Sequence[Node]) -> List[Node]:
        prev = [n for n in candidates if n.name in self._previous]
        rest = [n for n in candidates if n.name not in self._previous]
        return prev + rest

    def update(self, unneeded_names: Sequence[str]) -> None:
        self._previous = set(unneeded_names)


class NodeGroupManager:
    """Node-group autoprovisioning lifecycle (reference processors/nodegroups/
    — NAP creates groups for pods no existing group fits and deletes empty
    autoprovisioned groups). The default implementation is a no-op unless the
    provider supports group creation."""

    def __init__(self, max_autoprovisioned: int = 15):
        self.max_autoprovisioned = max_autoprovisioned

    def remove_unneeded_node_groups(self, provider: CloudProvider) -> List[str]:
        removed = []
        for group in provider.node_groups():
            if group.autoprovisioned() and group.target_size() == 0:
                try:
                    group.delete()
                    removed.append(group.id())
                except Exception:
                    pass
        return removed


@dataclass
class AutoscalingProcessors:
    """processors.go:36 — one container wired through the control loop."""

    pod_list_processor: FilterOutSchedulablePodListProcessor = field(
        default_factory=FilterOutSchedulablePodListProcessor
    )
    node_group_set: BalancingNodeGroupSetProcessor = field(
        default_factory=BalancingNodeGroupSetProcessor
    )
    template_node_info_provider: MixedTemplateNodeInfoProvider = field(
        default_factory=MixedTemplateNodeInfoProvider
    )
    scale_up_status: EventingScaleUpStatusProcessor = field(
        default_factory=EventingScaleUpStatusProcessor
    )
    scale_down_status: NoOpScaleDownStatusProcessor = field(
        default_factory=NoOpScaleDownStatusProcessor
    )
    custom_resources: CustomResourcesProcessor = field(
        default_factory=CustomResourcesProcessor
    )
    scale_down_candidates_sorting: ScaleDownCandidatesSortingProcessor = field(
        default_factory=ScaleDownCandidatesSortingProcessor
    )
    node_group_manager: NodeGroupManager = field(default_factory=NodeGroupManager)


def default_processors() -> AutoscalingProcessors:
    return AutoscalingProcessors()
