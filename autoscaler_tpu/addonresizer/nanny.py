"""Addon-resizer ("nanny"): scale one workload's resources with cluster size.

Reference: addon-resizer/nanny/ — the linear estimator (base + per-node
delta) estimator.go:52,86 with a ±offset deadband so tiny cluster-size
changes don't churn the deployment, and the control loop
nanny_lib.go:103,125 (PollAPIServer → checkResource → updateResources).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from autoscaler_tpu.kube.objects import Resources


@dataclass
class LinearEstimator:
    base_cpu_m: float
    cpu_per_node_m: float
    base_memory: float
    memory_per_node: float
    deadband_fraction: float = 0.10  # nanny's acceptance range

    def estimate(self, num_nodes: int) -> Resources:
        """estimator.go:52 — linear in node count."""
        return Resources(
            cpu_m=self.base_cpu_m + self.cpu_per_node_m * num_nodes,
            memory=self.base_memory + self.memory_per_node * num_nodes,
        )

    def needs_update(self, current: Resources, num_nodes: int) -> Optional[Resources]:
        """nanny_lib.go:125 — return new resources when current requests are
        outside the ±deadband around the estimate, else None."""
        want = self.estimate(num_nodes)

        def outside(cur: float, target: float) -> bool:
            if target <= 0:
                return cur != 0
            return abs(cur - target) / target > self.deadband_fraction

        if outside(current.cpu_m, want.cpu_m) or outside(current.memory, want.memory):
            return want
        return None


class Nanny:
    """The control loop: watch node count, resize the dependent workload."""

    def __init__(self, estimator: LinearEstimator, update_fn):
        self.estimator = estimator
        self.update_fn = update_fn
        self.last_applied: Optional[Resources] = None

    def poll(self, current: Resources, num_nodes: int) -> bool:
        """→ True when an update was applied (nanny_lib.go:103)."""
        new = self.estimator.needs_update(current, num_nodes)
        if new is None:
            return False
        self.update_fn(new)
        self.last_applied = new
        return True
