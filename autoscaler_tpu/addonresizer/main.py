"""Addon-resizer process: poll node count, resize one deployment's container.

Reference: addon-resizer/nanny/main.go (flags: --cpu/--extra-cpu/--memory/
--extra-memory per node, --deployment/--container/--namespace, --poll-period,
--threshold) and nanny_lib.go:103 (PollAPIServer) / :125 (updateResources).
The reference writes requests=limits on the target container; so does this.
"""
from __future__ import annotations

import argparse
import logging
import sys
from typing import Optional

from autoscaler_tpu.addonresizer.nanny import LinearEstimator, Nanny
from autoscaler_tpu.kube.client import ApiError, KubeRestClient
from autoscaler_tpu.kube.convert import (
    format_cpu_millis,
    format_memory_quantity,
    parse_cpu_millis,
    parse_quantity,
    resources_from_map,
)
from autoscaler_tpu.kube.objects import Resources
from autoscaler_tpu.utils.poll import poll_loop

log = logging.getLogger("nanny")


class NannyRunner:
    """One poll: count nodes, read the target container, resize on drift."""

    def __init__(
        self,
        client: KubeRestClient,
        namespace: str,
        deployment: str,
        container: str,
        estimator: LinearEstimator,
    ):
        self.client = client
        self.namespace = namespace
        self.deployment = deployment
        self.container = container
        self.nanny = Nanny(estimator, self._apply)
        # the deployment object fetched by the current poll; _apply mutates
        # and PUTs it back whole (read-modify-write — a JSON merge-patch
        # would REPLACE the containers array per RFC 7386, stripping
        # image/env from the container and failing apiserver validation)
        self._dep: Optional[dict] = None
        self._target: Optional[dict] = None

    def _dep_path(self) -> str:
        return (
            f"/apis/apps/v1/namespaces/{self.namespace}"
            f"/deployments/{self.deployment}"
        )

    def _apply(self, new: Resources) -> None:
        qty = {
            "cpu": format_cpu_millis(new.cpu_m),
            "memory": format_memory_quantity(new.memory),
        }
        # nanny writes requests == limits
        self._target["resources"] = {"requests": dict(qty), "limits": dict(qty)}
        # PUT carries the GET's resourceVersion: a concurrent writer makes
        # this 409 and the next poll retries from fresh state
        self.client.put(self._dep_path(), self._dep)

    def run_once(self) -> bool:
        """→ True when the deployment was resized (nanny_lib.go:103)."""
        nodes = self.client.get("/api/v1/nodes").get("items") or []
        self._dep = self.client.get(self._dep_path())
        containers = (
            ((self._dep.get("spec") or {}).get("template") or {}).get("spec")
            or {}
        ).get("containers") or []
        self._target = next(
            (c for c in containers if c.get("name") == self.container), None
        )
        if self._target is None:
            raise ApiError(
                0, f"container {self.container!r} not in {self.deployment}"
            )
        resources = self._target.get("resources") or {}
        current = resources_from_map(resources.get("requests"))
        if self.nanny.poll(current, len(nodes)):
            return True
        # requests are in-band, but the reference's checkResource compares
        # limits too (nanny_lib.go:125 enforces requests == limits): a
        # drifted or missing limit is reconciled even when requests hold
        limits = resources_from_map(resources.get("limits"))
        if (limits.cpu_m, limits.memory) != (current.cpu_m, current.memory):
            self._apply(self.nanny.estimator.estimate(len(nodes)))
            return True
        return False


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("tpu-autoscaler-nanny")
    p.add_argument("--kube-api", required=True)
    p.add_argument("--namespace", default="kube-system")
    p.add_argument("--deployment", required=True)
    p.add_argument("--container", default="")
    p.add_argument("--cpu", default="300m", help="base cpu")
    p.add_argument("--extra-cpu", default="2m", help="cpu per node")
    p.add_argument("--memory", default="200Mi", help="base memory")
    p.add_argument("--extra-memory", default="1Mi", help="memory per node")
    p.add_argument("--threshold", type=float, default=10.0,
                   help="deadband percent before resizing")
    p.add_argument("--poll-period", type=float, default=10.0)
    p.add_argument("--max-iterations", type=int, default=0)
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.kube_api == "in-cluster":
        client = KubeRestClient.in_cluster(user_agent="tpu-autoscaler-nanny")
    else:
        client = KubeRestClient(args.kube_api, user_agent="tpu-autoscaler-nanny")
    runner = NannyRunner(
        client,
        args.namespace,
        args.deployment,
        args.container or args.deployment,
        LinearEstimator(
            base_cpu_m=parse_cpu_millis(args.cpu),
            cpu_per_node_m=parse_cpu_millis(args.extra_cpu),
            base_memory=parse_quantity(args.memory),
            memory_per_node=parse_quantity(args.extra_memory),
            deadband_fraction=args.threshold / 100.0,
        ),
    )
    print(f"tpu-autoscaler-nanny: {args.namespace}/{args.deployment} "
          f"container {runner.container}, every {args.poll_period}s")

    def tick():
        if runner.run_once():
            log.info("resized %s", args.deployment)

    return poll_loop(tick, args.poll_period, args.max_iterations, logger=log)


if __name__ == "__main__":
    sys.exit(main())
