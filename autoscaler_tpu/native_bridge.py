"""ctypes bridge to the native C++ components (native/ffd_serial.cpp).

Builds the shared library on first use with g++ (cached beside the source,
rebuilt when the source is newer). Falls back cleanly when no compiler is
available — callers check `available()`.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "ffd_serial.cpp")
_LIB = os.path.join(_ROOT, "native", "libffd_serial.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _ensure_built() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if (
                not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    [
                        "g++", "-O3", "-march=native", "-shared", "-fPIC",
                        # IEEE per-op rounding: the FFD score spec must be
                        # bit-identical to numpy/XLA (no FMA contraction)
                        "-ffp-contract=off",
                        "-std=c++17", _SRC, "-o", _LIB,
                    ],
                    check=True,
                    capture_output=True,
                    text=True,
                )
            lib = ctypes.CDLL(_LIB)
            lib.ffd_binpack_serial.restype = ctypes.c_int32
            lib.ffd_binpack_serial.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.ffd_binpack_serial_affinity.restype = ctypes.c_int32
            lib.ffd_binpack_serial_affinity.argtypes = [
                ctypes.POINTER(ctypes.c_float), u8p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                u8p, u8p, u8p, u8p, u8p, u8p,
            ]
            lib.first_fit_serial.restype = None
            lib.first_fit_serial.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            _lib = lib
        except Exception as e:  # compiler missing / build failure
            _build_error = str(e)
    return _lib


def available() -> bool:
    return _ensure_built() is not None


def build_error() -> Optional[str]:
    _ensure_built()
    return _build_error


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def ffd_binpack_native(
    pod_req: np.ndarray,        # [P, R] f32
    pod_mask: np.ndarray,       # [P] bool
    template_alloc: np.ndarray,  # [R] f32
    max_nodes: int,
    cpu_axis: int = 0,
    mem_axis: int = 1,
) -> Tuple[int, np.ndarray]:
    """→ (node_count, scheduled[P] bool). Same contract as
    estimator.reference_impl.ffd_binpack_reference."""
    lib = _ensure_built()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    req = np.ascontiguousarray(pod_req, np.float32)
    mask = np.ascontiguousarray(pod_mask, np.uint8)
    alloc = np.ascontiguousarray(template_alloc, np.float32)
    P, R = req.shape
    out = np.zeros(P, np.uint8)
    count = lib.ffd_binpack_serial(
        _fptr(req),
        mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        _fptr(alloc),
        P, R, max_nodes, cpu_axis, mem_axis,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if count < 0:
        raise RuntimeError("ffd_binpack_serial failed")
    return int(count), out.astype(bool)


def ffd_binpack_affinity_native(
    pod_req: np.ndarray,        # [P, R] f32
    pod_mask: np.ndarray,       # [P] bool
    template_alloc: np.ndarray,  # [R] f32
    max_nodes: int,
    match: np.ndarray,          # [T, P] bool
    aff_of: np.ndarray,         # [T, P] bool
    anti_of: np.ndarray,        # [T, P] bool
    node_level: np.ndarray,     # [T] bool
    has_label: np.ndarray,      # [T] bool (this group's template)
    cpu_axis: int = 0,
    mem_axis: int = 1,
) -> Tuple[int, np.ndarray]:
    """→ (node_count, scheduled[P] bool). Same contract as
    estimator.reference_impl.ffd_binpack_reference_affinity (parity-locked
    in tests/test_processors_rpc_native.py); the compiled baseline the
    affinity bench compares the TPU kernel against."""
    lib = _ensure_built()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    req = np.ascontiguousarray(pod_req, np.float32)
    mask = np.ascontiguousarray(pod_mask, np.uint8)
    alloc = np.ascontiguousarray(template_alloc, np.float32)
    P, R = req.shape
    T = match.shape[0]
    m = np.ascontiguousarray(match, np.uint8)
    a = np.ascontiguousarray(aff_of, np.uint8)
    x = np.ascontiguousarray(anti_of, np.uint8)
    nl = np.ascontiguousarray(node_level, np.uint8)
    hl = np.ascontiguousarray(has_label, np.uint8)
    out = np.zeros(P, np.uint8)

    def u8(arr):
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

    count = lib.ffd_binpack_serial_affinity(
        _fptr(req), u8(mask), _fptr(alloc),
        P, R, max_nodes, cpu_axis, mem_axis, T,
        u8(m), u8(a), u8(x), u8(nl), u8(hl), u8(out),
    )
    if count < 0:
        raise RuntimeError("ffd_binpack_serial_affinity failed")
    return int(count), out.astype(bool)


def first_fit_native(
    pod_req: np.ndarray,  # [P, R] f32
    free: np.ndarray,     # [N, R] f32
    mask: np.ndarray,     # [P, N] bool
) -> np.ndarray:
    """→ first-fit node index per pod, -1 when none."""
    lib = _ensure_built()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    req = np.ascontiguousarray(pod_req, np.float32)
    fr = np.ascontiguousarray(free, np.float32)
    m = np.ascontiguousarray(mask, np.uint8)
    P, R = req.shape
    N = fr.shape[0]
    out = np.zeros(P, np.int32)
    lib.first_fit_serial(
        _fptr(req), _fptr(fr),
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        P, N, R,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out
