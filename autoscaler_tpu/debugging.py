"""Debugging snapshot: capture the autoscaler's working state on demand.

Reference: cluster-autoscaler/debuggingsnapshot/ — DebuggingSnapshotter
state machine :56,72, the /snapshotz HTTP trigger :113, captured payload
(NodeInfos, template nodes, "unscheduled pods that could schedule")
debugging_snapshot.go:36-135. Here the capture additionally dumps the packed
tensor shapes/stats, since the tensors ARE the decision state.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class DebuggingSnapshotter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._requested = False
        self._payload: Optional[Dict[str, Any]] = None

    def request(self) -> None:
        """Arm capture for the next loop iteration (the /snapshotz trigger)."""
        with self._lock:
            self._requested = True

    def is_data_collection_allowed(self) -> bool:
        with self._lock:
            return self._requested

    def capture(
        self, autoscaler, snapshot, pending_pods, result, filtered_pods=(),
        now: Optional[float] = None,
    ) -> None:
        """Called at the end of a loop iteration when armed. filtered_pods:
        the pods filter-out-schedulable absorbed this loop — the reference's
        'unscheduled pods that could be scheduled' population. ``now`` is
        the capture timestamp (run_once passes its tick's now_ts, keeping
        replayed snapshots deterministic); wall time is only the fallback
        for bare invocations."""
        with self._lock:
            if not self._requested:
                return
            self._requested = False
            tensors, meta = snapshot.tensors()
            free = np.asarray(tensors.free())
            nodes = []
            for node in snapshot.nodes():
                j = meta.node_index[node.name]
                nodes.append(
                    {
                        "name": node.name,
                        "ready": node.ready,
                        "pods": len(snapshot.pods_on_node(node.name)),
                        "free_cpu_m": float(free[j, 0]),
                        "free_mem_mib": float(free[j, 1]),
                        "taints": [t.key for t in node.taints],
                    }
                )
            # "unscheduled pods that could be scheduled" — the reference's
            # debugging_snapshot.go:36-135 headline field IS the set filter-
            # out-schedulable absorbed this loop (filter_out_schedulable.go
            # feeds it). Additionally report still-pending pods that fit raw
            # free capacity individually but lost the greedy packing race —
            # the "why is this pod pending" answer an operator wants next.
            could_schedule = [p.key() for p in filtered_pods]
            lost_packing_race = []
            if pending_pods:
                from autoscaler_tpu.ops.fit import fits_any_node

                any_fit = np.asarray(fits_any_node(tensors))
                for p in pending_pods:
                    i = meta.pod_index.get(p.key())
                    if i is not None and any_fit[i]:
                        lost_packing_race.append(p.key())
            if now is None:
                now = time.time()  # graftlint: disable=GL001 — operator-artifact fallback; replay-reachable callers inject now
            self._payload = {
                "captured_at": now,
                "node_count": len(nodes),
                "pod_count": len(snapshot.pods()),
                "pending_pods": [p.key() for p in pending_pods],
                "unscheduled_pods_can_be_scheduled": could_schedule,
                "pending_pods_fitting_free_capacity": lost_packing_race,
                "tensor_shapes": {
                    "pods": list(tensors.pod_req.shape),
                    "nodes": list(tensors.node_alloc.shape),
                    # stable schema across mask modes: always an object
                    "mask": (
                        {"form": "dense", "shape": list(tensors.sched_mask.shape)}
                        if tensors.sched_mask is not None
                        else {
                            "form": "factored",
                            "class_mask": list(tensors.class_mask.shape),
                            "exc_rows": list(tensors.exc_rows.shape),
                            "cell_overrides": int(tensors.cell_pod.shape[0]),
                        }
                    ),
                },
                "nodes": nodes,
                "templates": [
                    {"group": g.id(), "template": g.template_node_info().name}
                    for g in autoscaler.provider.node_groups()
                ],
                "last_result": {
                    "scaled_up": bool(result.scale_up and result.scale_up.scaled_up),
                    "pending": result.pending_pods,
                    "unneeded": result.unneeded_nodes,
                },
            }

    def get(self) -> Optional[str]:
        with self._lock:
            return (
                json.dumps(self._payload, indent=2, sort_keys=True)
                if self._payload else None
            )

    @staticmethod
    def dump_tensors(snapshot, path: str) -> List[str]:
        """Write the packed decision tensors to a compressed .npz — the exact
        arrays the kernels consumed, for offline replay of a decision. The
        reference's /snapshotz captures NodeInfos; here the tensors ARE the
        state. Returns the saved array names."""
        tensors, _meta = snapshot.tensors()
        arrays: Dict[str, np.ndarray] = {}
        for name in (
            "node_alloc",
            "node_used",
            "node_valid",
            "node_group",
            "pod_req",
            "pod_valid",
            "pod_node",
            "sched_mask",
            "pod_class",
            "node_class",
            "class_mask",
            "exc_rows",
            "pod_exc",
            "cell_pod",
            "cell_node",
            "cell_val",
        ):
            value = getattr(tensors, name)
            if value is not None:
                arrays[name] = np.asarray(value)
        np.savez_compressed(path, **arrays)
        return sorted(arrays)
