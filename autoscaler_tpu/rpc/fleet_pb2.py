"""Fleet-serving protobuf messages (protos/autoscaler_fleet.proto).

Built PROGRAMMATICALLY: the FileDescriptorProto is assembled field by field
at import time and registered in the default descriptor pool — no protoc
dependency (the container has none) and nothing for the hack/verify.sh
proto-freshness check to drift against. protos/autoscaler_fleet.proto is
the reviewable source of truth; tests/test_fleet.py asserts this module's
runtime descriptor matches its declared message/field layout, which is the
programmatic analog of the protoc freshness diff.

Depends on autoscaler.proto (PackedPods), so autoscaler_pb2 must be — and
is — imported first to seed the pool.
"""
from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from autoscaler_tpu.rpc import autoscaler_pb2 as _base_pb  # noqa: F401 — pool seed

_F = descriptor_pb2.FieldDescriptorProto

# (name, number, type, extra) — extra: label override or message type name
_REQUEST_FIELDS = (
    ("pods", 1, _F.TYPE_MESSAGE, ".autoscaler_tpu.PackedPods"),
    ("pod_masks", 2, _F.TYPE_BYTES, None),
    ("template_allocs", 3, _F.TYPE_BYTES, None),
    ("group_ids", 4, _F.TYPE_STRING, "repeated"),
    ("node_caps", 5, _F.TYPE_BYTES, None),
    ("max_nodes", 6, _F.TYPE_INT32, None),
    ("tenant_id", 7, _F.TYPE_STRING, None),
    ("prices", 8, _F.TYPE_BYTES, None),
    ("trace_context", 9, _F.TYPE_STRING, None),
)
_RESPONSE_FIELDS = (
    ("node_counts", 1, _F.TYPE_BYTES, None),
    ("scheduled", 2, _F.TYPE_BYTES, None),
    ("bucket", 3, _F.TYPE_STRING, None),
    ("batch_size", 4, _F.TYPE_INT32, None),
    ("padding_waste", 5, _F.TYPE_DOUBLE, None),
    ("route", 6, _F.TYPE_STRING, None),
    ("best_group", 7, _F.TYPE_INT32, None),
    ("best_cost", 8, _F.TYPE_DOUBLE, None),
)

MESSAGE_LAYOUT = {
    "BatchEstimateRequest": _REQUEST_FIELDS,
    "BatchEstimateResponse": _RESPONSE_FIELDS,
}


def _build_file_proto() -> descriptor_pb2.FileDescriptorProto:
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "autoscaler_fleet.proto"
    fdp.package = "autoscaler_tpu"
    fdp.syntax = "proto3"
    fdp.dependency.append("autoscaler.proto")
    for msg_name, fields in MESSAGE_LAYOUT.items():
        msg = fdp.message_type.add()
        msg.name = msg_name
        for name, number, ftype, extra in fields:
            f = msg.field.add()
            f.name = name
            f.number = number
            f.type = ftype
            f.label = (
                _F.LABEL_REPEATED if extra == "repeated" else _F.LABEL_OPTIONAL
            )
            if ftype == _F.TYPE_MESSAGE:
                f.type_name = extra
    return fdp


def _register():
    pool = descriptor_pool.Default()
    try:
        # a prior registration (this module imported under a second name,
        # e.g. by test collection) wins — but only after the layout check
        # below proves it IS this file, not a conflicting namesake
        fd = pool.FindFileByName("autoscaler_fleet.proto")
    except KeyError:
        fd = pool.Add(_build_file_proto())
    for msg_name, fields in MESSAGE_LAYOUT.items():
        desc = fd.message_types_by_name[msg_name]
        got = {(f.name, f.number) for f in desc.fields}
        want = {(name, number) for name, number, _, _ in fields}
        if got != want:
            raise ImportError(
                f"descriptor pool already holds autoscaler_fleet.proto with "
                f"a DIFFERENT {msg_name} layout ({sorted(got ^ want)}); wire "
                "fields would decode under wrong numbers"
            )
    return (
        message_factory.GetMessageClass(
            fd.message_types_by_name["BatchEstimateRequest"]
        ),
        message_factory.GetMessageClass(
            fd.message_types_by_name["BatchEstimateResponse"]
        ),
    )


BatchEstimateRequest, BatchEstimateResponse = _register()

__all__ = ["BatchEstimateRequest", "BatchEstimateResponse", "MESSAGE_LAYOUT"]
