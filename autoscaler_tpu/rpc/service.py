"""gRPC sidecar service + host-side client.

The process split mirrors the BASELINE north-star: a host control plane
(Go/Python, owns cluster watch + actuation) flattens cluster state to dense
tensors and calls a device-owning sidecar over gRPC; the sidecar runs the
batched kernels. The protocol (protos/autoscaler.proto) is modeled on the
reference's in-tree gRPC plugin seams (expander/grpcplugin/protos/
expander.proto:10, cloudprovider/externalgrpc/protos/externalgrpc.proto:29).

Service handlers are registered via grpc's generic-handler API (no
grpc_tools codegen needed; messages come from protoc --python_out).
"""
from __future__ import annotations

import contextlib
import random
import threading
import time
from concurrent import futures
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

import grpc

from autoscaler_tpu import trace
from autoscaler_tpu.metrics import metrics as metrics_mod
from autoscaler_tpu.rpc import autoscaler_pb2 as pb
from autoscaler_tpu.rpc import fleet_pb2 as fleet_pb

SERVICE_NAME = "autoscaler_tpu.TpuSimulation"

# gRPC metadata key carrying the caller's trace context
# ("<trace_id>:<span_id>", trace.current_context): the sidecar adopts it as
# the parent of its serving span so the two processes' span trees join
# under ONE trace id. The fleet proto additionally carries it as a first-
# class field (BatchEstimateRequest.trace_context) for programmatic
# clients that bypass gRPC.
TRACE_METADATA_KEY = "x-autoscaler-trace-context"

# trailing-metadata key carrying the server's pacing hint on
# RESOURCE_EXHAUSTED (seconds, decimal string) — the gRPC analog of the
# HTTP Retry-After header utils/http.RetryPolicy already honors
RETRY_AFTER_METADATA_KEY = "retry-after-s"

# the drain detail prefix on UNAVAILABLE: the client failover path keys on
# it (a draining sidecar means "go elsewhere NOW", not "backoff and retry
# here"), and hack/verify.sh's live-drain gate asserts it surfaces
DRAIN_DETAIL = "draining: sidecar shutting down"


class DrainState:
    """The sidecar's readiness bit. ``begin_drain()`` flips it exactly
    once; RPC handlers consult :meth:`ready` to stop admitting (UNAVAILABLE
    + drain detail) and the health endpoint serves it as
    readinessProbe/preStop state (deploy/chart wires /healthz + /drain)."""

    def __init__(self) -> None:
        self._draining = threading.Event()

    def ready(self) -> bool:
        return not self._draining.is_set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> None:
        self._draining.set()


def start_health_server(drain: DrainState, port: int = 0, host: str = "127.0.0.1"):
    """Serve the sidecar's readiness surface on a daemon thread:

    - ``GET /healthz`` — 200 ``ok`` while ready, 503 ``draining`` after
      drain begins (the chart's readinessProbe);
    - ``GET/POST /drain`` — flips the drain bit and returns 200 (the
      chart's preStop hook, so admission closes BEFORE SIGTERM lands).

    → (httpd, bound_port). Callers shut it down with httpd.shutdown()."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _respond(self, code: int, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/healthz":
                if drain.ready():
                    self._respond(200, b"ok\n")
                else:
                    self._respond(503, b"draining\n")
            elif self.path == "/drain":
                drain.begin_drain()
                self._respond(200, b"draining\n")
            else:
                self._respond(404, b"not found\n")

        do_POST = do_GET  # noqa: N815 — preStop httpGet vs kubectl POST

        def log_message(self, *args):  # silence per-probe stderr noise
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(
        target=httpd.serve_forever, name="sidecar-healthz", daemon=True
    )
    thread.start()
    return httpd, httpd.server_address[1]


def _metadata_context(context) -> str:
    """Extract the caller's trace context from gRPC invocation metadata
    (best-effort: propagation must never fail a request)."""
    try:
        md = context.invocation_metadata()
    except Exception:  # noqa: BLE001 — fake/partial test contexts
        return ""
    for key, value in md or ():
        if key == TRACE_METADATA_KEY:
            return str(value)
    return ""


def _f32(blob: bytes, *shape: int) -> np.ndarray:
    return np.frombuffer(blob, np.dtype("<f4")).reshape(shape).copy()


def _i32(blob: bytes, *shape: int) -> np.ndarray:
    return np.frombuffer(blob, np.dtype("<i4")).reshape(shape).copy()


def _u8(blob: bytes, *shape: int) -> np.ndarray:
    return np.frombuffer(blob, np.uint8).reshape(shape).astype(bool)


def _checked_blob(
    blob: bytes, dtype, shape: tuple, name: str, context
) -> np.ndarray:
    """Decode one operand blob with its axes VALIDATED: a blob whose byte
    count disagrees with the declared axes aborts the RPC as
    INVALID_ARGUMENT with the one consistent message shape — previously
    each servicer method re-decoded raw and a mismatched axis surfaced as
    an opaque numpy reshape error deep in the handler."""
    want = int(np.prod(shape)) * np.dtype(dtype).itemsize
    if len(blob) != want:
        context.abort(
            grpc.StatusCode.INVALID_ARGUMENT,
            f"operand axis mismatch: {name} carries {len(blob)} bytes but "
            f"the declared axes {tuple(int(d) for d in shape)} require {want}",
        )
    return (
        np.frombuffer(blob, np.dtype(dtype)).reshape(shape).copy()
    )


def _decode_estimate_operands(request, context):
    """THE checked decode path shared by Estimate and BatchEstimate (the
    two RPCs carrying the estimator operand set): resource-axis schema
    check, then every blob validated against the declared (P, G, R) axes.
    → (pod_req [P,R] f32, masks [G,P] bool, allocs [G,R] f32, caps [G]
    i32)."""
    _check_resource_axis(request.pods, context)
    P = request.pods.num_pods
    R = request.pods.num_resources
    G = len(request.group_ids)
    if P < 0 or R <= 0 or G <= 0:
        context.abort(
            grpc.StatusCode.INVALID_ARGUMENT,
            f"operand axis mismatch: P={P}, R={R}, G={G} do not describe "
            "an estimable request (need R > 0 and at least one group)",
        )
    pod_req = _checked_blob(
        request.pods.requests, "<f4", (P, R), "pods.requests", context
    )
    masks = _checked_blob(
        request.pod_masks, np.uint8, (G, P), "pod_masks", context
    ).astype(bool)
    allocs = _checked_blob(
        request.template_allocs, "<f4", (G, R), "template_allocs", context
    )
    caps = _checked_blob(
        request.node_caps, "<i4", (G,), "node_caps", context
    )
    return pod_req, masks, allocs, caps


def _check_resource_axis(pods: "pb.PackedPods", context) -> None:
    """Extended-resource schema contract (r4 verdict missing #1): when the
    caller names extended columns, the resource axis must be exactly
    base-6 + those names — a silent mismatch would let a device-plugin
    column be read as (or shadow) a base axis and flip verdicts without
    any error. Aborts the RPC as INVALID_ARGUMENT on violation."""
    from autoscaler_tpu.kube import objects as k8s

    ext = list(pods.extended_resources)
    if ext and pods.num_resources != k8s.NUM_RESOURCES + len(ext):
        context.abort(
            grpc.StatusCode.INVALID_ARGUMENT,
            f"num_resources={pods.num_resources} but schema is "
            f"{k8s.NUM_RESOURCES} base + {len(ext)} extended {ext}",
        )


class TpuSimulationServicer:
    """Device-side implementation: each RPC is one batched kernel dispatch.

    ``residency`` (a perf.ResidencyLedger, optional) accounts each method's
    unpacked what-if batch tensors in the ``scenario_batches`` pool — the
    sidecar's contribution to device_resident_bytes.

    ``fleet`` (a fleet.FleetCoalescer, optional) backs the BatchEstimate
    coalescing surface; absent, the first BatchEstimate builds a default
    coalescer (default buckets, pre-warm off) so the RPC works out of the
    box — deploy sites pass FleetCoalescer.from_options for the
    --fleet-* knobs.

    ``tracer`` (a trace.Tracer, optional): the sidecar-side flight
    recorder. Each Estimate/BatchEstimate opens one ``rpcServe`` serving
    trace that ADOPTS the caller's propagated trace context (gRPC metadata
    / the fleet proto's trace_context field) — client and sidecar spans
    for one request share one trace id, so /tracez on either process joins
    the tree. Absent, a bounded default is created (always-on, like the
    host-side tracer)."""

    def __init__(self, residency=None, fleet=None, tracer=None, drain=None):
        self.residency = residency
        self.fleet = fleet
        if tracer is None:
            tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=64))
        self.tracer = tracer
        # drain (a DrainState, optional): once begin_drain() fires, every
        # RPC is refused UNAVAILABLE + DRAIN_DETAIL before touching the
        # coalescer — new work goes elsewhere while in-flight buckets flush
        self.drain = drain
        self._fleet_lock = threading.Lock()

    def _check_admitting(self, context) -> None:
        if self.drain is not None and self.drain.draining:
            context.abort(grpc.StatusCode.UNAVAILABLE, DRAIN_DETAIL)

    @staticmethod
    def _abort_admission(context, e) -> None:
        """Typed fleet shed → gRPC status (the mapping fleet/errors.py
        documents): drain → UNAVAILABLE + drain detail (fail over), queue
        expiry → DEADLINE_EXCEEDED (do NOT resend), overload →
        RESOURCE_EXHAUSTED with the retry-after hint in trailing metadata
        AND the detail text."""
        from autoscaler_tpu.fleet import FleetDeadlineError, FleetDrainError

        if isinstance(e, FleetDrainError):
            context.abort(grpc.StatusCode.UNAVAILABLE, f"{DRAIN_DETAIL}: {e}")
        if isinstance(e, FleetDeadlineError):
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        retry_after = float(getattr(e, "retry_after_s", 0.0))
        context.set_trailing_metadata(
            ((RETRY_AFTER_METADATA_KEY, f"{retry_after:.6f}"),)
        )
        context.abort(
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            f"fleet overload ({getattr(e, 'outcome', 'shed')}): {e}",
        )

    def _ensure_fleet(self):
        with self._fleet_lock:
            if self.fleet is None:
                from autoscaler_tpu.fleet import FleetCoalescer

                self.fleet = FleetCoalescer()
            # ensure_running, NOT start: a request racing the drain must
            # never re-arm a stopping coalescer (its submit raises the
            # typed FleetDrainError instead, mapped to UNAVAILABLE+detail)
            self.fleet.ensure_running()
            return self.fleet

    @contextlib.contextmanager
    def _account(self, method: str, *arrays):
        """Account the unpacked batch tensors as resident for the duration
        of the dispatch, released when the RPC returns — the what-if batch
        is garbage once the response is serialized, and leaving it seated
        would report dead tensors as live until the next call."""
        if self.residency is None:
            yield
            return
        from autoscaler_tpu.perf import POOL_SCENARIO_BATCHES, array_bytes

        self.residency.set(
            POOL_SCENARIO_BATCHES, method, array_bytes(list(arrays))
        )
        try:
            yield
        finally:
            self.residency.drop(POOL_SCENARIO_BATCHES, method)

    def Estimate(self, request: pb.EstimateRequest, context) -> pb.EstimateResponse:
        import jax.numpy as jnp

        from autoscaler_tpu.ops.binpack import ffd_binpack_groups

        self._check_admitting(context)
        pod_req, masks, allocs, caps = _decode_estimate_operands(request, context)
        with self.tracer.tick(
            metrics_mod.RPC_SERVE,
            parent_context=_metadata_context(context),
            method="Estimate",
        ), self._account("Estimate", pod_req, masks, allocs, caps):
            # graftlint: disable=GL003 — sidecar server side: the ladder lives in the CLIENT process (TpuSimulationClient's caller); a fault here surfaces as an RPC error the client's ladder absorbs
            res = ffd_binpack_groups(
                jnp.asarray(pod_req),
                jnp.asarray(masks),
                jnp.asarray(allocs),
                max_nodes=int(request.max_nodes),
                node_caps=jnp.asarray(caps),
            )
            return pb.EstimateResponse(
                node_counts=np.asarray(res.node_count, np.dtype("<i4")).tobytes(),
                scheduled=np.asarray(res.scheduled, np.uint8).tobytes(),
            )

    def BatchEstimate(
        self, request: "fleet_pb.BatchEstimateRequest", context
    ) -> "fleet_pb.BatchEstimateResponse":
        """The fleet serving surface: park the tenant's request in the
        coalescer's admission queue and block until its batch dispatches —
        N concurrent tenants pay ONE sharded mesh dispatch per shape
        bucket per window instead of N. Operands ride the SAME checked
        decode path as Estimate, so an axis mismatch fails identically on
        both routes."""
        self._check_admitting(context)
        pod_req, masks, allocs, caps = _decode_estimate_operands(request, context)
        G = len(request.group_ids)
        prices = None
        if request.prices:
            prices = _checked_blob(
                request.prices, "<f4", (G,), "prices", context
            )
        from autoscaler_tpu.fleet import (
            FleetAdmissionError,
            FleetDeadlineError,
            FleetDrainError,
            FleetOverloadError,
            FleetRequest,
        )

        fleet = self._ensure_fleet()
        # the proto field wins (programmatic clients), gRPC metadata is the
        # fallback (the stub stamps both); the ticket carries it into the
        # shared fleetDispatch span's links
        ctx = request.trace_context or _metadata_context(context)
        # the caller's remaining deadline budget rides into the ticket so
        # the coalescer can shed it typed if it expires in the queue
        remaining = context.time_remaining()
        with self.tracer.tick(
            metrics_mod.RPC_SERVE,
            parent_context=ctx,
            method="BatchEstimate",
            tenant=request.tenant_id or "anonymous",
        ), self._account("BatchEstimate", pod_req, masks, allocs, caps):
            try:
                ticket = fleet.submit(
                    FleetRequest(
                        tenant_id=request.tenant_id or "anonymous",
                        pod_req=pod_req,
                        pod_masks=masks,
                        template_allocs=allocs,
                        node_caps=caps,
                        max_nodes=int(request.max_nodes),
                        prices=prices,
                        trace_context=ctx,
                        deadline_s=remaining,
                    )
                )
            except FleetAdmissionError as e:
                self._abort_admission(context, e)
            # the coalescing window plus dispatch must finish inside the
            # caller's deadline — never block PAST it (gRPC has already
            # cancelled the RPC by then, and an over-wait pins an executor
            # worker). With no deadline set, bound the wait anyway: window
            # plus a dispatch allowance, so a wedged dispatcher fails the
            # RPC instead of hanging the handler.
            timeout = (
                remaining if remaining is not None
                else fleet.window_s + 30.0
            )
            try:
                answer = ticket.result(timeout=timeout)
            except TimeoutError:
                context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    "fleet batch did not dispatch within the deadline",
                )
            except (FleetOverloadError, FleetDrainError, FleetDeadlineError) as e:
                # a ticket shed AFTER admission (queue expiry, drain flush)
                # surfaces with the same typed status as an admission shed
                self._abort_admission(context, e)
            except Exception as e:  # noqa: BLE001 — every fleet rung failed;
                # surface the typed ladder error to the caller
                context.abort(grpc.StatusCode.INTERNAL, f"fleet dispatch failed: {e}")
            return fleet_pb.BatchEstimateResponse(
                node_counts=np.ascontiguousarray(
                    answer.node_counts, "<i4"
                ).tobytes(),
                scheduled=np.ascontiguousarray(
                    answer.scheduled, np.uint8
                ).tobytes(),
                bucket=answer.bucket,
                batch_size=answer.batch_size,
                padding_waste=answer.padding_waste,
                route=answer.route,
                best_group=answer.best_group,
                best_cost=answer.best_cost,
            )

    def TrySchedule(self, request: pb.TryScheduleRequest, context) -> pb.TryScheduleResponse:
        """Greedy kernel over packed tensors. When the request carries a
        SpreadContext, the kernel runs greedy_schedule's within-wave
        topology-spread re-counting — the same path the host-side
        HintingSimulator drives — so a remote sidecar caller no longer gets
        the pre-round-3 batch-width overpack (closed the round-3 RPC-surface
        note of PREDICATES.md divergence 2)."""
        import jax.numpy as jnp

        from autoscaler_tpu.ops.schedule import greedy_schedule
        from autoscaler_tpu.snapshot.tensors import SnapshotTensors

        self._check_admitting(context)
        _check_resource_axis(request.pods, context)
        P = request.pods.num_pods
        R = request.pods.num_resources
        N = request.num_nodes
        pod_req = _f32(request.pods.requests, P, R)
        free = _f32(request.node_free, N, R)
        mask = _u8(request.sched_mask, P, N)
        slots = _i32(request.pod_slots, -1)
        hints = _i32(request.hints, -1)
        with self._account("TrySchedule", pod_req, free, mask, slots, hints):
            spread = None
            if request.HasField("spread"):
                sp = request.spread
                S, D = sp.num_terms, sp.num_domains
                spread = tuple(
                    jnp.asarray(a)
                    for a in (
                        _u8(sp.sp_of, P, S),
                        _u8(sp.sp_match, P, S),
                        _i32(sp.node_dom, S, N),
                        _u8(sp.sp_elig, S, N),
                        _u8(sp.dom_valid, S, D),
                        _i32(sp.static_counts, S, D),
                        _i32(sp.skew, S),
                        _i32(sp.min_dom, S),
                        _i32(sp.domnum, S),
                    )
                )
            snap = SnapshotTensors(
                node_alloc=jnp.asarray(free),
                node_used=jnp.zeros((N, R), jnp.float32),
                node_valid=jnp.ones((N,), bool),
                node_group=jnp.full((N,), -1, jnp.int32),
                pod_req=jnp.asarray(pod_req),
                pod_valid=jnp.ones((P,), bool),
                pod_node=jnp.full((P,), -1, jnp.int32),
                sched_mask=jnp.asarray(mask),
            )
            res = greedy_schedule(
                snap, jnp.asarray(slots), jnp.asarray(hints), spread=spread
            )
            return pb.TryScheduleResponse(
                placed=np.asarray(res.placed, np.uint8).tobytes(),
                dest=np.asarray(res.dest, np.dtype("<i4")).tobytes(),
            )

    def FindNodesToRemove(
        self, request: pb.FindNodesToRemoveRequest, context
    ) -> pb.FindNodesToRemoveResponse:
        import jax.numpy as jnp

        from autoscaler_tpu.ops.scaledown import removal_feasibility
        from autoscaler_tpu.snapshot.tensors import SnapshotTensors

        self._check_admitting(context)
        _check_resource_axis(request.pods, context)
        P = request.pods.num_pods
        R = request.pods.num_resources
        N = request.num_nodes
        S = request.slots_per_node
        pod_req = _f32(request.pods.requests, P, R)
        alloc = _f32(request.node_alloc, N, R)
        used = _f32(request.node_used, N, R)
        mask = _u8(request.sched_mask, P, N)
        cands = _i32(request.candidate_nodes, -1)
        slots = _i32(request.pod_slots, len(cands), S)
        blocked = _u8(request.blocked, len(cands))
        with self._account(
            "FindNodesToRemove", pod_req, alloc, used, mask, cands, slots,
            blocked,
        ):
            snap = SnapshotTensors(
                node_alloc=jnp.asarray(alloc),
                node_used=jnp.asarray(used),
                node_valid=jnp.ones((N,), bool),
                node_group=jnp.full((N,), -1, jnp.int32),
                pod_req=jnp.asarray(pod_req),
                pod_valid=jnp.ones((P,), bool),
                pod_node=jnp.full((P,), -1, jnp.int32),
                sched_mask=jnp.asarray(mask),
            )
            res = removal_feasibility(
                snap, jnp.asarray(cands), jnp.asarray(slots), jnp.asarray(blocked)
            )
            return pb.FindNodesToRemoveResponse(
                feasible=np.asarray(res.feasible, np.uint8).tobytes(),
                destinations=np.asarray(res.destinations, np.dtype("<i4")).tobytes(),
            )

    def BestOptions(self, request: pb.BestOptionsRequest, context) -> pb.BestOptionsResponse:
        """Least-waste-style reduction over the option list (the expander
        gRPC seam; host embeddings can point the reference's own
        --grpc-expander-url at this)."""
        self._check_admitting(context)
        if not request.options:
            return pb.BestOptionsResponse()
        scored = sorted(
            request.options,
            key=lambda o: (o.score_hint if o.score_hint else -len(o.pod_keys)),
        )
        return pb.BestOptionsResponse(best=[scored[0]])


_METHODS = {
    "Estimate": (pb.EstimateRequest, pb.EstimateResponse),
    "BatchEstimate": (
        fleet_pb.BatchEstimateRequest, fleet_pb.BatchEstimateResponse
    ),
    "TrySchedule": (pb.TryScheduleRequest, pb.TryScheduleResponse),
    "FindNodesToRemove": (pb.FindNodesToRemoveRequest, pb.FindNodesToRemoveResponse),
    "BestOptions": (pb.BestOptionsRequest, pb.BestOptionsResponse),
}


def _generic_handler(servicer: TpuSimulationServicer) -> grpc.GenericRpcHandler:
    handlers = {}
    for name, (req_cls, _resp_cls) in _METHODS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


def serve(
    address: str = "127.0.0.1:0",
    max_workers: int = 4,
    residency=None,
    fleet=None,
    options=None,
    tracer=None,
    slo=None,
    drain=None,
):
    """→ (server, bound_port). The sidecar process entrypoint. ``fleet``
    (a fleet.FleetCoalescer) backs BatchEstimate; when absent and
    ``options`` (an AutoscalingOptions) is given, one is built from the
    --fleet-* surface via FleetCoalescer.from_options — buckets, window,
    batch width, pre-warm, and the overload-armor knobs (queue depth,
    tenant quotas) all take effect (``python -m autoscaler_tpu.rpc`` is
    the flag-parsing launcher). ``drain`` (a DrainState) makes the server
    drainable: once its bit flips, every RPC refuses UNAVAILABLE +
    DRAIN_DETAIL while drain_server() flushes in-flight work. The
    coalescing window only pays off when max_workers admits concurrent
    tenants."""
    if fleet is None and options is not None:
        from autoscaler_tpu.fleet import FleetCoalescer

        # ``slo`` (an slo.SloEngine built on fleet_slos()) rides into the
        # coalescer so every served ticket feeds the fleet_e2e objective —
        # the sidecar-side half of fleet mission control
        fleet = FleetCoalescer.from_options(options, slo=slo)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (
            _generic_handler(
                TpuSimulationServicer(
                    residency=residency, fleet=fleet, tracer=tracer,
                    drain=drain,
                )
            ),
        )
    )
    port = server.add_insecure_port(address)
    server.start()
    return server, port


def drain_server(server, fleet=None, drain=None, grace_s: float = 5.0) -> None:
    """The graceful drain sequence (SIGTERM / preStop path, in order):

    1. flip the drain bit — readiness goes 503, new RPCs refuse
       UNAVAILABLE + DRAIN_DETAIL (clients fail over immediately);
    2. stop the coalescer — its own drain bit sheds racing submits typed
       while the final flush answers every in-flight bucket;
    3. ``server.stop(grace_s)`` — in-flight handlers finish inside the
       grace, then the port closes.

    Idempotent: a second call finds everything already stopped."""
    if drain is not None:
        drain.begin_drain()
    if fleet is not None:
        fleet.stop()
    server.stop(grace=grace_s).wait(timeout=grace_s + 1.0)


class TpuSimulationClient:
    """Host-side stub with health-weighted endpoint balancing, typed-status
    retry scoping, and optional hedging.

    ``target`` names one endpoint or several (comma-separated string or a
    sequence — the --rpc-address surface). With several, every endpoint's
    health is scored continuously (fleet/balance.EndpointBalancer: EWMA
    latency, windowed error rate, consecutive-UNAVAILABLE streak,
    drain-observed bit) and BOTH first attempts and failover/hedge targets
    come from a power-of-two-choices pick over those scores with
    breaker-style outlier ejection — a flapping replica stops eating
    first-attempt traffic after a few failures instead of keeping its
    static rotation slot. On UNAVAILABLE the client fails over to a picked
    healthy endpoint with jittered bounded backoff (RetryPolicy semantics;
    a drain-detail UNAVAILABLE skips the backoff — the server just said
    "go elsewhere NOW"). The resend scope is a closed matrix:

    - UNAVAILABLE        → reconnect/fail over and resend, bounded
      (every RPC here is a pure function of its request);
    - RESOURCE_EXHAUSTED → honor the server's retry-after trailing
      metadata, at most once, never past the caller's deadline — a blind
      resend is exactly the extra load a shedding server cannot absorb.
      The honored sleep carries bounded jitter from the injected rng seam:
      co-shed tenants must NOT all retry at the same instant (a
      synchronized herd straight back into admission);
    - DEADLINE_EXCEEDED  → NEVER resent: retrying a timed-out estimate
      doubles load exactly when the server is drowning;
    - anything else      → raised as-is.

    ``default_timeout_s`` is the deadline applied when a call site passes
    none (plumbed from ``AutoscalingOptions.rpc_default_deadline_s``); the
    whole retry/failover/hedge budget lives INSIDE it — the client never
    spends past the caller's deadline.

    ``hedge=True`` additionally hedges the idempotent Estimate /
    BatchEstimate: when the primary hasn't answered after a p99-derived
    delay (learned from this client's own recent latencies), a second
    attempt fires at a balancer-picked HEALTHY endpoint; first answer
    wins, the loser is cancelled. An endpoint that is ejected, draining,
    or mid-UNAVAILABLE-streak is never hedged at — a hedge fired at a
    draining sidecar burns deadline budget for a guaranteed UNAVAILABLE —
    and when no healthy alternative exists the hedge is skipped entirely.
    Off by default — hedging doubles worst-case load.

    ``clock``/``sleep``/``rng`` are injectable for tests; production
    callers take the wall defaults (the client is NOT on the replay path —
    loadgen drives the coalescer in-process)."""

    # the hedgeable subset: pure estimate reads (TrySchedule and friends
    # are pure too, but hedging is only worth its load cost on the two
    # fleet-facing hot calls)
    HEDGED_METHODS = ("Estimate", "BatchEstimate")
    # floor used until enough latency samples exist to derive a p99
    HEDGE_MIN_DELAY_S = 0.05
    # bounded jitter fraction on the honored retry-after sleep: the pause
    # lands in [hint, hint * (1 + this)] so co-shed tenants desynchronize
    # instead of herding back into admission at the same instant
    RETRY_AFTER_JITTER = 0.25

    def __init__(
        self,
        target: Union[str, Sequence[str]],
        default_timeout_s: Optional[float] = None,
        hedge: bool = False,
        failover_base_sleep_s: float = 0.05,
        failover_max_sleep_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Callable[[], float] = random.random,
    ):
        raw = [target] if isinstance(target, str) else list(target)
        # every element may itself be comma-separated (--rpc-address
        # accepts both "repeat the flag" and "comma-join" forms, and the
        # repeated form must not smuggle an unsplit "a:1,b:2" into
        # grpc.insecure_channel as one bogus endpoint)
        targets = [
            piece.strip()
            for entry in raw
            for piece in str(entry).split(",")
            if piece.strip()
        ]
        if not targets:
            raise ValueError("TpuSimulationClient needs at least one endpoint")
        # dedupe preserving order: the PR-14 static rotation tolerated a
        # repeated --rpc-address (it just revisited the endpoint), and a
        # duplicate must keep being a config wrinkle, not a startup crash
        # (EndpointBalancer rejects duplicates — one health record per
        # endpoint)
        seen: set = set()
        targets = [t for t in targets if not (t in seen or seen.add(t))]
        self._targets = targets
        self._active = 0
        self.default_timeout_s = default_timeout_s
        self.hedge = hedge
        self._clock = clock
        self._sleep = sleep
        self._rng = rng
        from autoscaler_tpu.fleet.balance import EndpointBalancer

        # per-endpoint health scorer + P2C picker (ARCHITECTURE.md "Fleet
        # HA"): first attempts, failover targets, and hedge legs all come
        # from its picks; every call outcome feeds it back
        self._balancer = EndpointBalancer(targets, clock=clock, rng=rng)
        from autoscaler_tpu.utils.http import RetryPolicy

        # the failover pacing: same jittered-bounded-exponential semantics
        # as the kube/GCE REST boundary, one attempt per endpoint plus one
        self._backoff = RetryPolicy(
            attempts=len(targets) + 1,
            base_sleep_s=failover_base_sleep_s,
            max_sleep_s=failover_max_sleep_s,
            sleep=sleep,
            rng=rng,
        )
        # recent per-method success latencies (bounded) — the hedge-delay
        # derivation input
        from collections import deque

        self._latency = {m: deque(maxlen=64) for m in self.HEDGED_METHODS}
        # guards the mutable connection state (_active, _channel,
        # _retired): hedging reads it from worker context while a
        # failover rewrites it
        self._conn_lock = threading.Lock()
        # channels replaced by an explicit _reconnect are RETIRED, not
        # closed: another thread may have an RPC in flight on one, and
        # closing it would turn that call into CANCELLED "Channel closed!"
        # instead of its real status. The graveyard is bounded; close()
        # empties it.
        self._retired: List[Any] = []
        # ONE long-lived channel per target, shared by first attempts,
        # failovers, and hedge legs. Failover SWITCHES channels instead of
        # rebuilding them: gRPC channels self-heal when their endpoint
        # returns, and rebuilding per failing thread made a thundering
        # failover overflow the retire graveyard and close channels with
        # live callers (their in-flight calls died CANCELLED instead of
        # failing over — caught by the two-sidecar SIGKILL drill).
        self._channels: dict = {}
        self._channel = self._channel_for(self._targets[0])

    @property
    def _target(self) -> str:
        with self._conn_lock:
            return self._targets[self._active]

    def endpoint_health(self) -> dict:
        """Per-endpoint scorer snapshot (score, EWMA, error rate, streak,
        drain bit, breaker state) — the observability surface the
        two-sidecar drill asserts rebalancing on."""
        return self._balancer.snapshot()

    def _channel_for(self, target: str):
        """The per-target channel cache (first attempts, failovers, and
        hedge legs all draw from it): one long-lived channel per endpoint,
        created lazily, never torn down by routine failover — no
        connection setup on a latency-critical leg, no close racing a
        live caller."""
        with self._conn_lock:
            channel = self._channels.get(target)
            if channel is None:
                channel = grpc.insecure_channel(target)
                self._channels[target] = channel
            return channel

    def close(self) -> None:
        with self._conn_lock:
            channels = [self._channel] + self._retired
            channels += list(self._channels.values())
            self._channels = {}
            self._retired = []
        for channel in channels:
            try:
                channel.close()
            except Exception:  # noqa: BLE001 — a dead channel may refuse
                pass

    def _reconnect(self) -> None:
        """Rebuild the ACTIVE target's channel (the single-endpoint
        reconnect-in-place path; multi-endpoint failover switches cached
        channels instead and never calls this)."""
        with self._conn_lock:
            target = self._targets[self._active]
        fresh = grpc.insecure_channel(target)
        doomed = []
        with self._conn_lock:
            self._retired.append(self._channel)
            self._channel = fresh
            self._channels[target] = fresh
            # bound the graveyard: anything this deep has no live callers
            while len(self._retired) > 4:
                doomed.append(self._retired.pop(0))
        for channel in doomed:
            try:
                channel.close()
            except Exception:  # noqa: BLE001 — a dead channel may refuse
                pass

    def _switch_to(self, target: str) -> None:
        """Make ``target`` the active endpoint on its cached (self-
        healing) channel — the failover/rebalance move. No channel is
        rebuilt or closed, so the threads still blocked on the previous
        endpoint keep their in-flight calls and surface REAL statuses."""
        channel = self._channel_for(target)
        with self._conn_lock:
            self._active = self._targets.index(target)
            self._channel = channel

    def _failover(self, failed: Optional[str] = None) -> None:
        """Move off ``failed`` (default: the current endpoint) to a
        balancer-picked alternative on its cached channel. A
        single-endpoint client (no alternative exists) reconnects in
        place — the historical behavior. When the pick lands on the
        ALREADY-active endpoint (a racing thread failed over first) this
        is a no-op: rebuilding the healthy channel per failing thread
        would churn the retire graveyard into closing channels that still
        have live callers (their calls would die CANCELLED instead of
        surfacing real statuses)."""
        with self._conn_lock:
            current = self._targets[self._active]
        # deferred release: the picked endpoint becomes (or already is)
        # the active one, and the NEXT rpc on it reports the outcome via
        # record_response/record_failure — statically unprovable
        # graftlint: disable=GL016 — probe slot resolves through the next rpc's outcome on the now-active endpoint
        nxt = self._balancer.pick(exclude=(failed or current,))
        if nxt is None:
            self._reconnect()
            return
        if nxt != current:
            self._switch_to(nxt)

    def _ensure_primary(self) -> str:
        """Health-weighted FIRST-attempt selection (the static-rotation
        replacement): ask the balancer for today's best endpoint and
        switch channels only when it differs from the active one.
        Single-endpoint clients skip the pick entirely — there is nothing
        to balance, and the seated channel (tests seat scripted ones)
        must stay untouched. Returns the active target."""
        if len(self._targets) == 1:
            return self._targets[0]
        # deferred release: the pick selects the endpoint the imminent
        # first attempt rides, and that attempt's record_response/
        # record_failure is the slot's outcome — statically unprovable
        # graftlint: disable=GL016 — probe slot resolves through the imminent first attempt's outcome
        target = self._balancer.pick()
        with self._conn_lock:
            current = self._targets[self._active]
        if target is not None and target != current:
            self._switch_to(target)
            return target
        return current

    def _note_latency(self, method: str, seconds: float) -> None:
        samples = self._latency.get(method)
        if samples is not None:
            samples.append(seconds)

    def _hedge_delay(self, method: str) -> float:
        """The p99 of this client's own recent successes for ``method`` —
        hedging earlier than that fires on healthy tail latency; later
        wastes the win. Falls back to a floor until enough samples exist."""
        samples = self._latency.get(method)
        if not samples or len(samples) < 5:
            return self.HEDGE_MIN_DELAY_S
        ordered = sorted(samples)
        idx = max(0, int(0.99 * len(ordered)) - 1)
        return max(ordered[idx], self.HEDGE_MIN_DELAY_S)

    @staticmethod
    def _packed_pods(
        pod_req: np.ndarray, extended_resources: Sequence[str]
    ) -> "pb.PackedPods":
        from autoscaler_tpu.kube import objects as k8s

        P, R = pod_req.shape
        ext = list(extended_resources)
        if ext and R != k8s.NUM_RESOURCES + len(ext):
            raise ValueError(
                f"pod_req has {R} columns but schema is "
                f"{k8s.NUM_RESOURCES} base + {len(ext)} extended {ext}"
            )
        return pb.PackedPods(
            requests=np.ascontiguousarray(pod_req, "<f4").tobytes(),
            num_pods=P,
            num_resources=R,
            extended_resources=ext,
        )

    @staticmethod
    def _retry_after_from(error) -> Optional[float]:
        """The server's pacing hint from RESOURCE_EXHAUSTED trailing
        metadata (RETRY_AFTER_METADATA_KEY, seconds)."""
        try:
            trailing = error.trailing_metadata() or ()
        except Exception:  # noqa: BLE001 — duck-typed test errors
            return None
        for key, value in trailing:
            if key == RETRY_AFTER_METADATA_KEY:
                try:
                    return max(float(value), 0.0)
                except (TypeError, ValueError):
                    return None
        return None

    @staticmethod
    def _is_drain(error) -> bool:
        try:
            return str(error.details() or "").startswith(DRAIN_DETAIL)
        except Exception:  # noqa: BLE001 — duck-typed test errors
            return False

    def _call(self, method: str, request, timeout: Optional[float] = None):
        req_cls, resp_cls = _METHODS[method]
        if timeout is None:
            timeout = self.default_timeout_s
        # the whole retry/failover/hedge budget lives inside the caller's
        # deadline: every resend's timeout is the REMAINING budget, and a
        # backoff that would outlive it raises instead of sleeping
        deadline_ts = self._clock() + timeout if timeout is not None else None

        def remaining() -> Optional[float]:
            if deadline_ts is None:
                return None
            return deadline_ts - self._clock()

        # one span per sidecar RPC — failovers and retry-after waits are
        # events INSIDE it, so a tick slowed by a sidecar restart shows one
        # long rpcCall span with failover markers, not mystery gaps
        with trace.span(
            metrics_mod.RPC_CALL, method=method,
            deadline_s=timeout if timeout is not None else 0.0,
        ):
            # cross-process propagation: THE rpcCall span is the remote
            # parent — stamped into gRPC metadata on every method, and
            # into the fleet proto's trace_context field when the message
            # carries one (BatchEstimate), so the sidecar's serving span
            # adopts this exact span and the trees join under one id
            ctx = trace.current_context()
            metadata = ((TRACE_METADATA_KEY, ctx),) if ctx else None
            if (
                ctx
                and hasattr(request, "trace_context")
                and not request.trace_context
            ):
                request.trace_context = ctx

            def send(send_target: str, budget: Optional[float]):
                # the channel must be THIS attempt's target, not the
                # shared active channel: a concurrent thread's failover
                # can rewrite self._channel between the pick and the
                # send, and then the balancer would charge this call's
                # outcome to an endpoint it never talked to (ejecting a
                # healthy survivor on a dead replica's UNAVAILABLE).
                # Single-endpoint clients keep the seated channel — there
                # is no attribution to get wrong, and tests seat scripted
                # channels there.
                if len(self._targets) == 1:
                    with self._conn_lock:
                        channel = self._channel
                else:
                    channel = self._channel_for(send_target)
                rpc = channel.unary_unary(
                    f"/{SERVICE_NAME}/{method}",
                    request_serializer=lambda msg: msg.SerializeToString(),
                    response_deserializer=resp_cls.FromString,
                )
                if metadata is None:
                    # no active trace: keep the bare call shape (duck-typed
                    # channels in tests need not accept the kwarg)
                    return rpc(request, timeout=budget)
                return rpc(request, timeout=budget, metadata=metadata)

            max_attempts = max(2, len(self._targets) + 1)
            quota_retried = False
            hedging = (
                self.hedge
                and method in self.HEDGED_METHODS
                and len(self._targets) > 1
            )
            attempt = 0
            while True:
                attempt += 1
                # first attempt gets the caller's full deadline; every
                # resend runs on what's LEFT of it. The first attempt's
                # TARGET is a balancer pick (health-weighted P2C, not a
                # static rotation slot); resends run on whatever endpoint
                # the failover picked.
                if attempt == 1:
                    target = self._ensure_primary()
                else:
                    with self._conn_lock:
                        target = self._targets[self._active]
                budget = timeout if attempt == 1 else remaining()
                try:
                    if hedging:
                        return self._hedged_send(
                            method, request, budget, metadata, resp_cls,
                            target,
                        )
                    t0 = self._clock()
                    resp = send(target, budget)
                    self._note_latency(method, self._clock() - t0)
                    self._balancer.record_success(
                        target, self._clock() - t0
                    )
                    return resp
                except grpc.RpcError as e:
                    code = e.code() if hasattr(e, "code") else None
                    # hedged sends did their own per-leg health accounting
                    # (and the re-raised error may be the HEDGE leg's, not
                    # the primary's) — recording it here again would
                    # double-charge the primary or charge it with a status
                    # another endpoint returned
                    if code is grpc.StatusCode.UNAVAILABLE:
                        # health feedback even when out of attempts: the
                        # NEXT call's pick must know this endpoint failed
                        if not hedging:
                            self._balancer.record_failure(
                                target, unavailable=True,
                                drain=self._is_drain(e),
                            )
                    elif code is grpc.StatusCode.DEADLINE_EXCEEDED:
                        # a slowness signal (error rate + EWMA pressure),
                        # NOT an outage signal — no UNAVAILABLE streak
                        if not hedging:
                            self._balancer.record_failure(
                                target, unavailable=False
                            )
                    else:
                        # every OTHER status (RESOURCE_EXHAUSTED shed,
                        # INVALID_ARGUMENT, INTERNAL, ...) was still an
                        # ANSWER: the endpoint is alive. This must reach
                        # the balancer — a half-open probe whose outcome
                        # is never recorded holds the single-flight slot
                        # forever and wedges the endpoint out of rotation
                        if not hedging:
                            self._balancer.record_response(target)
                    if (
                        code is grpc.StatusCode.UNAVAILABLE
                        and attempt < max_attempts
                    ):
                        # failover: a drain detail skips the backoff (the
                        # server said "go elsewhere NOW"); plain
                        # unavailability pays the jittered bounded pause
                        pause = (
                            0.0 if self._is_drain(e)
                            else self._backoff.backoff_s(attempt, None)
                        )
                        rem = remaining()
                        if rem is not None and pause >= rem:
                            raise
                        trace.add_event(
                            "rpc.failover", method=method, attempt=attempt,
                            drain=self._is_drain(e),
                        )
                        if pause > 0.0:
                            self._sleep(pause)
                        self._failover(failed=target)
                        continue
                    if (
                        code is grpc.StatusCode.RESOURCE_EXHAUSTED
                        and not quota_retried
                    ):
                        retry_after = self._retry_after_from(e)
                        rem = remaining()
                        if retry_after is not None:
                            # bounded jitter on the honored hint: every
                            # co-shed tenant got the SAME retry-after, and
                            # sleeping it exactly marches the whole herd
                            # back into admission at one instant. The rng
                            # rides the injected seam so replays with a
                            # seeded rng stay byte-stable. Whether the
                            # retry happens at all is decided by the
                            # UNJITTERED hint; the jitter then expands
                            # only into HALF the headroom past it, so the
                            # resend always keeps some budget — sleeping
                            # to exactly the deadline would doom the
                            # retry to DEADLINE_EXCEEDED, losing a call
                            # the unjittered sleep would have saved.
                            pause = retry_after * (
                                1.0 + self.RETRY_AFTER_JITTER * self._rng()
                            )
                            if rem is None or retry_after < rem:
                                if rem is not None:
                                    pause = min(
                                        pause,
                                        retry_after
                                        + 0.5 * (rem - retry_after),
                                    )
                                quota_retried = True
                                trace.add_event(
                                    "rpc.retry_after", method=method,
                                    retry_after_s=retry_after,
                                )
                                if pause > 0.0:
                                    self._sleep(pause)
                                continue
                    # DEADLINE_EXCEEDED and everything else: NEVER resent
                    raise

    def _hedged_send(
        self, method, request, budget, metadata, resp_cls,
        primary_target: Optional[str] = None,
    ):
        """Hedge one idempotent call: primary now, a second leg at a
        balancer-picked HEALTHY endpoint after the p99-derived delay;
        first answer wins, the loser is cancelled. Both legs share the
        caller's remaining budget.

        The hedge target is chosen at FIRE time, not call time (health can
        change during the delay), via ``EndpointBalancer.pick_hedge``: an
        ejected, draining, or UNAVAILABLE-streaking endpoint is never
        hedged at — a hedge into a known-bad replica spends deadline
        budget on a guaranteed failure — and when no healthy alternative
        exists the hedge is skipped (the primary leg keeps the whole
        budget)."""

        def future_on(channel, leg_budget):
            rpc = channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )
            if metadata is None:
                return rpc.future(request, timeout=leg_budget)
            return rpc.future(request, timeout=leg_budget, metadata=metadata)

        t0 = self._clock()
        deadline_ts = t0 + budget if budget is not None else None
        if primary_target is None:
            # direct invocation (tests seat a scripted self._channel):
            # primary is whatever is active right now
            with self._conn_lock:
                channel = self._channel
                primary_target = self._targets[self._active]
        else:
            # _call named the target — the leg must ride THAT target's
            # cached channel, not the shared active one a concurrent
            # failover may have rewritten (outcome attribution feeds the
            # balancer; see _call.send)
            channel = self._channel_for(primary_target)
        primary = future_on(channel, budget)
        fired = threading.Event()
        primary.add_done_callback(lambda _f: fired.set())
        delay = self._hedge_delay(method)
        if budget is not None:
            delay = min(delay, max(budget, 0.0))
        # each leg carries its own start instant: the balancer's latency
        # sample must be the LEG's service time, not time-since-t0 — a
        # winning hedge measured from t0 would charge the healthy rescuer
        # with the hedge delay plus the slow primary's elapsed time,
        # drifting the picker TOWARD the degraded endpoint
        legs = [(primary, primary_target, t0)]
        if not fired.wait(timeout=delay):
            rem = (
                deadline_ts - self._clock() if deadline_ts is not None
                else None
            )
            # budget check BEFORE the pick: pick_hedge may hand out a
            # half-open probe slot, and a pick taken with the budget
            # already exhausted would never reach an outcome — the slot
            # (and its endpoint's probe budget) would leak until restart
            hedge_target = (
                self._balancer.pick_hedge(primary_target)
                if rem is None or rem > 0
                else None
            )
            if hedge_target is not None:
                trace.add_event(
                    "rpc.hedge", method=method, target=hedge_target,
                    delay_s=round(delay, 6),
                )
                # long-lived cached channel: no connection setup on the
                # latency-critical hedge leg
                hedge = future_on(
                    self._channel_for(hedge_target), rem
                )
                hedge.add_done_callback(lambda _f: fired.set())
                legs.append((hedge, hedge_target, self._clock()))
        try:
            pending = list(legs)
            last_error: Optional[BaseException] = None
            while pending:
                fired.clear()
                for entry in list(pending):
                    leg, leg_target, leg_start = entry
                    if not leg.done():
                        continue
                    pending.remove(entry)
                    try:
                        result = leg.result()
                    except Exception as e:  # noqa: BLE001 — grpc future errs
                        code = e.code() if hasattr(e, "code") else None
                        if code is grpc.StatusCode.UNAVAILABLE:
                            self._balancer.record_failure(
                                leg_target, unavailable=True,
                                drain=self._is_drain(e),
                            )
                        elif code is grpc.StatusCode.DEADLINE_EXCEEDED:
                            # same slowness-not-outage semantics as the
                            # unhedged path, attributed to the leg that
                            # actually timed out
                            self._balancer.record_failure(
                                leg_target, unavailable=False
                            )
                        else:
                            # any other status is still an ANSWER (see
                            # _call): resolve a held probe, clear streak
                            self._balancer.record_response(leg_target)
                        last_error = e
                        continue
                    for loser, loser_target, _start in pending:
                        loser.cancel()
                        # a cancelled leg never reaches an outcome: if its
                        # pick was a half-open probe, return the slot —
                        # nothing else ever will
                        self._balancer.release(loser_target)
                    # caller-perceived latency (feeds the hedge-delay p99)
                    # runs from t0; the ENDPOINT's sample runs from its
                    # own leg start
                    self._note_latency(method, self._clock() - t0)
                    self._balancer.record_success(
                        leg_target, self._clock() - leg_start
                    )
                    return result
                if pending and not fired.wait(
                    timeout=(
                        deadline_ts - self._clock() + 0.1
                        if deadline_ts is not None else None
                    )
                ):
                    for leg, leg_target, _start in pending:
                        leg.cancel()
                        self._balancer.release(leg_target)
                    break
            if last_error is not None:
                raise last_error
            raise TimeoutError(
                f"hedged {method} exhausted its deadline budget"
            )
        finally:
            for leg, leg_target, _start in legs:
                if not leg.done():
                    leg.cancel()
                    self._balancer.release(leg_target)

    def estimate(
        self,
        pod_req: np.ndarray,
        pod_masks: np.ndarray,
        template_allocs: np.ndarray,
        group_ids: Sequence[str],
        node_caps: np.ndarray,
        max_nodes: int,
        extended_resources: Sequence[str] = (),
    ):
        """`extended_resources` names the pod_req/template_allocs columns
        beyond the base 6, in packer.extended_schema order (pass
        `packer_meta.extended_resources` straight through) — the wire
        carries the schema so the sidecar keeps device-plugin fit
        dimensions instead of silently dropping them."""
        P, R = pod_req.shape
        resp = self._call(
            "Estimate",
            pb.EstimateRequest(
                pods=self._packed_pods(pod_req, extended_resources),
                pod_masks=np.ascontiguousarray(pod_masks, np.uint8).tobytes(),
                template_allocs=np.ascontiguousarray(template_allocs, "<f4").tobytes(),
                group_ids=list(group_ids),
                node_caps=np.ascontiguousarray(node_caps, "<i4").tobytes(),
                max_nodes=max_nodes,
            ),
        )
        G = len(group_ids)
        counts = np.frombuffer(resp.node_counts, "<i4")
        scheduled = (
            np.frombuffer(resp.scheduled, np.uint8).reshape(G, -1).astype(bool)
        )
        return counts, scheduled

    def batch_estimate(
        self,
        pod_req: np.ndarray,
        pod_masks: np.ndarray,
        template_allocs: np.ndarray,
        group_ids: Sequence[str],
        node_caps: np.ndarray,
        max_nodes: int,
        tenant_id: str = "",
        prices: Optional[np.ndarray] = None,  # [G] — present = what-if rank
        extended_resources: Sequence[str] = (),
        timeout: Optional[float] = None,
    ):
        """The fleet path of estimate(): same operands and same return
        shape (counts [G], scheduled [G, P]) plus a provenance dict
        (bucket, batch_size, padding_waste, route, best_group, best_cost).
        The sidecar coalesces concurrent tenants into one sharded mesh
        dispatch per shape bucket; the answer is byte-identical to the
        solo route. The deadline must cover the coalescing window
        (--fleet-coalesce-window-ms) on top of the dispatch."""
        P, R = pod_req.shape
        G = len(group_ids)
        resp = self._call(
            "BatchEstimate",
            fleet_pb.BatchEstimateRequest(
                pods=self._packed_pods(pod_req, extended_resources),
                pod_masks=np.ascontiguousarray(pod_masks, np.uint8).tobytes(),
                template_allocs=np.ascontiguousarray(
                    template_allocs, "<f4"
                ).tobytes(),
                group_ids=list(group_ids),
                node_caps=np.ascontiguousarray(node_caps, "<i4").tobytes(),
                max_nodes=max_nodes,
                tenant_id=tenant_id,
                prices=(
                    b"" if prices is None
                    else np.ascontiguousarray(prices, "<f4").tobytes()
                ),
            ),
            timeout=timeout,
        )
        counts = np.frombuffer(resp.node_counts, "<i4")
        scheduled = (
            np.frombuffer(resp.scheduled, np.uint8).reshape(G, -1).astype(bool)
        )
        meta = {
            "bucket": resp.bucket,
            "batch_size": int(resp.batch_size),
            "padding_waste": float(resp.padding_waste),
            "route": resp.route,
            "best_group": int(resp.best_group),
            "best_cost": float(resp.best_cost),
        }
        return counts, scheduled, meta

    def try_schedule(
        self,
        pod_req: np.ndarray,     # [P, R]
        node_free: np.ndarray,   # [N, R]
        sched_mask: np.ndarray,  # [P, N]
        pod_slots: np.ndarray,   # [K]
        hints: np.ndarray,       # [K]
        spread: Optional[tuple] = None,  # affinity.build_spread_schedule_context
        extended_resources: Sequence[str] = (),
    ):
        """→ (placed [K] bool, dest [K] i32). `spread` is the host-side
        9-array context; packing it onto the wire gives the remote kernel
        host-path within-wave spread semantics. `extended_resources` names
        the resource columns beyond the base 6 (see estimate)."""
        P, R = pod_req.shape
        N = node_free.shape[0]
        spread_msg = None
        if spread is not None:
            (sp_of, sp_match, node_dom, sp_elig, dom_valid,
             static_counts, skew, min_dom, domnum) = (
                np.asarray(a) for a in spread
            )
            spread_msg = pb.SpreadContext(
                sp_of=np.ascontiguousarray(sp_of, np.uint8).tobytes(),
                sp_match=np.ascontiguousarray(sp_match, np.uint8).tobytes(),
                node_dom=np.ascontiguousarray(node_dom, "<i4").tobytes(),
                sp_elig=np.ascontiguousarray(sp_elig, np.uint8).tobytes(),
                dom_valid=np.ascontiguousarray(dom_valid, np.uint8).tobytes(),
                static_counts=np.ascontiguousarray(
                    static_counts, "<i4"
                ).tobytes(),
                skew=np.ascontiguousarray(skew, "<i4").tobytes(),
                min_dom=np.ascontiguousarray(min_dom, "<i4").tobytes(),
                domnum=np.ascontiguousarray(domnum, "<i4").tobytes(),
                num_terms=int(sp_of.shape[1]),
                num_domains=int(dom_valid.shape[1]),
            )
        resp = self._call(
            "TrySchedule",
            pb.TryScheduleRequest(
                pods=self._packed_pods(pod_req, extended_resources),
                node_free=np.ascontiguousarray(node_free, "<f4").tobytes(),
                sched_mask=np.ascontiguousarray(sched_mask, np.uint8).tobytes(),
                pod_slots=np.ascontiguousarray(pod_slots, "<i4").tobytes(),
                hints=np.ascontiguousarray(hints, "<i4").tobytes(),
                num_nodes=N,
                spread=spread_msg,
            ),
        )
        placed = np.frombuffer(resp.placed, np.uint8).astype(bool)
        dest = np.frombuffer(resp.dest, "<i4")
        return placed, dest

    def best_options(
        self, options: Sequence[pb.Option], timeout: Optional[float] = None
    ) -> List[pb.Option]:
        resp = self._call(
            "BestOptions",
            pb.BestOptionsRequest(options=list(options)),
            timeout=timeout,
        )
        return list(resp.best)
