"""Wire-compatible adapters for the reference's public gRPC plugin protocols.

The reference ships two out-of-process plugin protocols third parties build
against:

  * the external cloud provider —
    cluster-autoscaler/cloudprovider/externalgrpc/protos/externalgrpc.proto:29
    (service ``clusterautoscaler.cloudprovider.v1.externalgrpc.CloudProvider``)
  * the expander plugin —
    cluster-autoscaler/expander/grpcplugin/protos/expander.proto:10
    (service ``grpcplugin.Expander``)

This module makes those binaries plug into THIS framework unmodified, and
exposes this framework's components to reference autoscalers, in both
directions:

  * :class:`RefProtocolCloudProvider` — our ``CloudProvider`` interface
    backed by a remote server speaking the REFERENCE provider protocol (an
    operator's existing externalgrpc provider binary just works).
  * :class:`RefExpanderClient` — calls an operator's existing gRPC expander
    plugin with reference-format ``BestOptionsRequest``s.
  * :func:`serve_ref_provider` / :func:`serve_ref_expander` — serve the
    reference wire formats backed by our provider/expander implementations,
    so a stock reference autoscaler can consume this framework's components
    (``--cloud-provider=externalgrpc`` / ``--grpc-expander-url``).

Why a hand-rolled codec: the reference messages embed ``k8s.io.api.core.v1``
objects, whose generated clients are enormous and which this framework
deliberately does not vendor (SURVEY.md scopes generated clients out). The
autoscaler touches a narrow, stable subset — object name/labels/annotations,
allocatable/capacity quantity maps, taints, container resource requests — so
the codec speaks exactly that subset at the protobuf wire level and ignores
unknown fields, which is precisely proto3's compatibility contract. Field
numbers are re-derived from the public schemas (not copied code):

  externalgrpc.proto messages as cited per function below;
  vendor/k8s.io/api/core/v1/generated.proto — Node{metadata=1,spec=2,
  status=3} (:2209), NodeSpec{providerID=3,unschedulable=4,taints=5}
  (:2420-2440), NodeStatus{capacity=1,allocatable=2} (:2453), Taint{key=1,
  value=2,effect=3} (:5441), Pod{metadata=1,spec=2} (:3058),
  PodSpec{containers=2,nodeSelector=7} (:3544,3593), Container{name=1,
  resources=8} (:723), ResourceRequirements{limits=1,requests=2} (:4500);
  apimachinery resource Quantity{string=1} (:96), meta/v1
  ObjectMeta{name=1,namespace=3,labels=11,annotations=12} (:650-761),
  Duration{duration=1, nanoseconds} (:315).

Byte-level compatibility is locked by tests/test_refcompat.py, which
protoc-compiles the reference .proto files at test time and round-trips
messages between the generated oracle and this codec.
"""
from __future__ import annotations

import struct
from concurrent import futures
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import grpc

from autoscaler_tpu.cloudprovider.interface import (
    CloudProvider,
    Instance,
    InstanceErrorClass,
    InstanceErrorInfo,
    InstanceState,
    NodeGroup,
    PricingModel,
    ResourceLimiter,
)
from autoscaler_tpu.config.options import NodeGroupAutoscalingOptions
from autoscaler_tpu.kube.convert import GPU_RESOURCE, TPU_RESOURCE, parse_quantity
from autoscaler_tpu.kube.objects import Node, Pod, Resources, Taint

PROVIDER_SERVICE = "clusterautoscaler.cloudprovider.v1.externalgrpc.CloudProvider"
EXPANDER_SERVICE = "grpcplugin.Expander"

# ---------------------------------------------------------------------------
# protobuf wire primitives (proto3): varint (wt 0), 64-bit (wt 1),
# length-delimited (wt 2), 32-bit (wt 5)


def _varint(n: int) -> bytes:
    if n < 0:  # proto3 int32/int64: negatives sign-extend to 64 bits
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(no: int, wt: int) -> bytes:
    return _varint((no << 3) | wt)


def _len_f(no: int, payload: bytes) -> bytes:
    return _tag(no, 2) + _varint(len(payload)) + payload


def _str_f(no: int, s: str) -> bytes:
    return _len_f(no, s.encode()) if s else b""


def _int_f(no: int, n: int) -> bytes:
    return (_tag(no, 0) + _varint(int(n))) if n else b""


def _bool_f(no: int, v: bool) -> bytes:
    return (_tag(no, 0) + _varint(1)) if v else b""


def _double_f(no: int, x: float) -> bytes:
    return (_tag(no, 1) + struct.pack("<d", float(x))) if x else b""


def _map_ss_f(no: int, d: Dict[str, str]) -> bytes:
    out = b""
    for k, v in d.items():
        out += _len_f(no, _str_f(1, k) + _str_f(2, v))
    return out


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _decode(buf: bytes) -> Dict[int, list]:
    """Parse one message into {field_no: [raw values]} (varints as int,
    len-delimited as bytes, fixed64/32 as raw bytes). Unknown fields are
    retained here and simply never read — proto3 forward compatibility."""
    fields: Dict[int, list] = {}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        no, wt = key >> 3, key & 7
        if wt == 0:
            val, i = _read_varint(buf, i)
        elif wt == 1:
            val, i = buf[i : i + 8], i + 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            val, i = buf[i : i + ln], i + ln
        elif wt == 5:
            val, i = buf[i : i + 4], i + 4
        else:  # wire types 3/4 (groups) do not appear in proto3 schemas
            raise ValueError(f"unsupported wire type {wt}")
        fields.setdefault(no, []).append(val)
    return fields


def _get_str(f: Dict[int, list], no: int, default: str = "") -> str:
    return f[no][-1].decode() if no in f else default


def _get_int(f: Dict[int, list], no: int, default: int = 0) -> int:
    if no not in f:
        return default
    n = f[no][-1]
    return n - (1 << 64) if n >= (1 << 63) else n  # undo 64-bit sign-extend


def _get_bytes(f: Dict[int, list], no: int) -> bytes:
    return f[no][-1] if no in f else b""


def _get_double(f: Dict[int, list], no: int, default: float = 0.0) -> float:
    return struct.unpack("<d", f[no][-1])[0] if no in f else default


def _get_map_ss(f: Dict[int, list], no: int) -> Dict[str, str]:
    out = {}
    for entry in f.get(no, ()):
        e = _decode(entry)
        out[_get_str(e, 1)] = _get_str(e, 2)
    return out


# ---------------------------------------------------------------------------
# k8s core/v1 object subset <-> our object model


def _quantity_msg(s: str) -> bytes:
    return _str_f(1, s)


def _resources_to_qmap(res: Resources) -> Dict[str, str]:
    """Our dense vector -> k8s quantity strings (canonical integer forms:
    cpu in millicores 'Nm', byte and count quantities as plain integers)."""
    out: Dict[str, str] = {}
    if res.cpu_m:
        out["cpu"] = f"{int(res.cpu_m)}m"
    if res.memory:
        out["memory"] = str(int(res.memory))
    if res.ephemeral:
        out["ephemeral-storage"] = str(int(res.ephemeral))
    if res.gpu:
        out[GPU_RESOURCE] = str(int(res.gpu))
    if res.tpu:
        out[TPU_RESOURCE] = str(int(res.tpu))
    if res.pods:
        out["pods"] = str(int(res.pods))
    return out


def _qmap_to_resources(f: Dict[int, list], no: int) -> Resources:
    vals = {"cpu": 0.0, "memory": 0.0, "ephemeral-storage": 0.0,
            GPU_RESOURCE: 0.0, TPU_RESOURCE: 0.0, "pods": 0.0}
    for entry in f.get(no, ()):
        e = _decode(entry)
        name = _get_str(e, 1)
        q = _get_str(_decode(_get_bytes(e, 2)), 1)
        if name in vals:
            vals[name] = parse_quantity(q)
    return Resources(
        cpu_m=vals["cpu"] * 1000.0,
        memory=vals["memory"],
        ephemeral=vals["ephemeral-storage"],
        gpu=vals[GPU_RESOURCE],
        tpu=vals[TPU_RESOURCE],
        pods=vals["pods"],
    )


def _qmap_f(no: int, qmap: Dict[str, str]) -> bytes:
    out = b""
    for name, q in qmap.items():
        out += _len_f(no, _str_f(1, name) + _len_f(2, _quantity_msg(q)))
    return out


def _objectmeta(name: str, labels: Dict[str, str],
                annotations: Dict[str, str], namespace: str = "") -> bytes:
    return (
        _str_f(1, name)
        + _str_f(3, namespace)
        + _map_ss_f(11, labels)
        + _map_ss_f(12, annotations)
    )


def encode_v1_node(node: Node) -> bytes:
    """Our Node -> k8s.io.api.core.v1.Node wire bytes (subset)."""
    qmap = _resources_to_qmap(node.allocatable)
    taints = b"".join(
        _len_f(5, _str_f(1, t.key) + _str_f(2, t.value) + _str_f(3, t.effect))
        for t in node.taints
    )
    spec = _str_f(3, node.provider_id) + _bool_f(4, node.unschedulable) + taints
    status = _qmap_f(1, qmap) + _qmap_f(2, qmap)  # capacity + allocatable
    return (
        _len_f(1, _objectmeta(node.name, node.labels, node.annotations))
        + _len_f(2, spec)
        + _len_f(3, status)
    )


def decode_v1_node(buf: bytes) -> Node:
    """k8s.io.api.core.v1.Node wire bytes -> our Node (subset; allocatable
    preferred, falling back to capacity as the kubelet does)."""
    f = _decode(buf)
    meta = _decode(_get_bytes(f, 1))
    spec = _decode(_get_bytes(f, 2))
    status = _decode(_get_bytes(f, 3))
    alloc = _qmap_to_resources(status, 2)
    if alloc == Resources():
        alloc = _qmap_to_resources(status, 1)
    taints = []
    for t in spec.get(5, ()):
        tf = _decode(t)
        taints.append(
            Taint(key=_get_str(tf, 1), value=_get_str(tf, 2),
                  effect=_get_str(tf, 3))
        )
    return Node(
        name=_get_str(meta, 1),
        allocatable=alloc,
        labels=_get_map_ss(meta, 11),
        annotations=_get_map_ss(meta, 12),
        taints=taints,
        unschedulable=bool(_get_int(spec, 4)),
        provider_id=_get_str(spec, 3),
    )


def encode_v1_pod(pod: Pod) -> bytes:
    """Our Pod -> k8s.io.api.core.v1.Pod wire bytes (one container carrying
    the pod's aggregate requests — the shape the reference's expander and
    pricing consumers read back via PodRequests)."""
    requests = _qmap_f(2, _resources_to_qmap(pod.requests))
    container = _str_f(1, "main") + _len_f(8, requests)
    spec = _len_f(2, container) + _map_ss_f(7, dict(pod.node_selector or {}))
    return (
        _len_f(1, _objectmeta(pod.name, dict(pod.labels), {}, pod.namespace))
        + _len_f(2, spec)
    )


def decode_v1_pod(buf: bytes) -> Pod:
    f = _decode(buf)
    meta = _decode(_get_bytes(f, 1))
    spec = _decode(_get_bytes(f, 2))
    total = Resources()
    for c in spec.get(2, ()):
        cf = _decode(c)
        rr = _decode(_get_bytes(cf, 8))
        total = total + _qmap_to_resources(rr, 2)
    return Pod(
        name=_get_str(meta, 1),
        namespace=_get_str(meta, 3, "default") or "default",
        labels=_get_map_ss(meta, 11),
        requests=total,
        node_selector=_get_map_ss(spec, 7),
    )


def _duration_f(no: int, seconds: float) -> bytes:
    # meta.v1.Duration wraps Go time.Duration: int64 nanoseconds, field 1
    return _len_f(no, _int_f(1, int(seconds * 1e9)))


def _duration_get(f: Dict[int, list], no: int) -> float:
    return _get_int(_decode(_get_bytes(f, no)), 1) / 1e9


# ---------------------------------------------------------------------------
# externalgrpc.proto message helpers (field numbers per the reference file)


def _ext_node_msg(node: Node) -> bytes:
    # ExternalGrpcNode{providerID=1, name=2, labels=3, annotations=4}
    return (
        _str_f(1, node.provider_id)
        + _str_f(2, node.name)
        + _map_ss_f(3, node.labels)
        + _map_ss_f(4, node.annotations)
    )


def _decode_ext_node(buf: bytes) -> Node:
    f = _decode(buf)
    return Node(
        name=_get_str(f, 2),
        provider_id=_get_str(f, 1),
        labels=_get_map_ss(f, 3),
        annotations=_get_map_ss(f, 4),
    )


def _options_msg(o: NodeGroupAutoscalingOptions) -> bytes:
    # NodeGroupAutoscalingOptions{1 double, 2 double, 3 Duration, 4 Duration}
    return (
        _double_f(1, o.scale_down_utilization_threshold)
        + _double_f(2, o.scale_down_gpu_utilization_threshold)
        + _duration_f(3, o.scale_down_unneeded_time_s)
        + _duration_f(4, o.scale_down_unready_time_s)
    )


def _decode_options(buf: bytes) -> NodeGroupAutoscalingOptions:
    f = _decode(buf)
    return NodeGroupAutoscalingOptions(
        scale_down_utilization_threshold=_get_double(f, 1),
        scale_down_gpu_utilization_threshold=_get_double(f, 2),
        scale_down_unneeded_time_s=_duration_get(f, 3),
        scale_down_unready_time_s=_duration_get(f, 4),
        # not part of the reference protocol; callers keep their default
        max_node_provision_time_s=0.0,
    )


_STATE_TO_WIRE = {
    InstanceState.RUNNING: 1,
    InstanceState.CREATING: 2,
    InstanceState.DELETING: 3,
}
_WIRE_TO_STATE = {v: k for k, v in _STATE_TO_WIRE.items()}
# reference cloud_provider.go:278-283: OutOfResourcesErrorClass=1 (covers
# stockout AND quota-exceeded), OtherErrorClass=99 — our finer-grained
# QUOTA_EXCEEDED folds onto the out-of-resources wire value both ways
_ERRCLASS_TO_WIRE = {
    InstanceErrorClass.OUT_OF_RESOURCES: 1,
    InstanceErrorClass.QUOTA_EXCEEDED: 1,
    InstanceErrorClass.OTHER: 99,
}
_WIRE_TO_ERRCLASS = {
    1: InstanceErrorClass.OUT_OF_RESOURCES,
    99: InstanceErrorClass.OTHER,
}


# ---------------------------------------------------------------------------
# Client adapter: our CloudProvider interface over the reference protocol


def _raw_rpc(channel: grpc.Channel, service: str, method: str):
    return channel.unary_unary(
        f"/{service}/{method}",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )


class _RefRemoteNodeGroup(NodeGroup):
    """NodeGroup view over the reference NodeGroup* RPCs."""

    def __init__(self, provider: "RefProtocolCloudProvider", gid: str,
                 min_size: int, max_size: int, debug: str):
        self._p = provider
        self._id = gid
        self._min = min_size
        self._max = max_size
        self._debug = debug

    def id(self) -> str:
        return self._id

    def min_size(self) -> int:
        return self._min

    def max_size(self) -> int:
        return self._max

    def debug(self) -> str:
        return self._debug

    def target_size(self) -> int:
        # NodeGroupTargetSizeRequest{id=1} -> Response{targetSize=1}
        resp = self._p._call("NodeGroupTargetSize", _str_f(1, self._id))
        return _get_int(_decode(resp), 1)

    def increase_size(self, delta: int) -> None:
        # NodeGroupIncreaseSizeRequest{delta=1, id=2}
        self._p._call(
            "NodeGroupIncreaseSize", _int_f(1, delta) + _str_f(2, self._id)
        )

    def delete_nodes(self, nodes: Sequence[Node]) -> None:
        # NodeGroupDeleteNodesRequest{nodes=1 repeated ExternalGrpcNode, id=2}
        req = b"".join(_len_f(1, _ext_node_msg(n)) for n in nodes)
        self._p._call("NodeGroupDeleteNodes", req + _str_f(2, self._id))

    def decrease_target_size(self, delta: int) -> None:
        # NodeGroupDecreaseTargetSizeRequest{delta=1, id=2}; the reference
        # contract passes delta negative
        self._p._call(
            "NodeGroupDecreaseTargetSize",
            _int_f(1, delta if delta < 0 else -delta) + _str_f(2, self._id),
        )

    def nodes(self) -> List[Instance]:
        # NodeGroupNodesRequest{id=1} -> {instances=1 repeated Instance}
        resp = _decode(self._p._call("NodeGroupNodes", _str_f(1, self._id)))
        out: List[Instance] = []
        for ib in resp.get(1, ()):
            f = _decode(ib)
            st = _decode(_get_bytes(f, 2))
            state = _WIRE_TO_STATE.get(_get_int(st, 1), InstanceState.RUNNING)
            err = None
            ei = _decode(_get_bytes(st, 2))
            if _get_str(ei, 1):
                err = InstanceErrorInfo(
                    error_class=_WIRE_TO_ERRCLASS.get(
                        _get_int(ei, 3), InstanceErrorClass.OTHER
                    ),
                    error_code=_get_str(ei, 1),
                    error_message=_get_str(ei, 2),
                )
            out.append(Instance(id=_get_str(f, 1), state=state, error_info=err))
        return out

    def template_node_info(self) -> Node:
        # NodeGroupTemplateNodeInfoResponse{nodeInfo=1 v1.Node}
        resp = _decode(
            self._p._call("NodeGroupTemplateNodeInfo", _str_f(1, self._id))
        )
        return decode_v1_node(_get_bytes(resp, 1))

    def exist(self) -> bool:
        return True

    def autoprovisioned(self) -> bool:
        return False

    def get_options(self, defaults: NodeGroupAutoscalingOptions):
        # NodeGroupAutoscalingOptionsRequest{id=1, defaults=2}; a grpc error
        # means "use defaults" (reference contract), absent message too
        try:
            resp = _decode(
                self._p._call(
                    "NodeGroupGetOptions",
                    _str_f(1, self._id) + _len_f(2, _options_msg(defaults)),
                )
            )
        except grpc.RpcError:
            return None
        if 1 not in resp:
            return None
        opts = _decode_options(_get_bytes(resp, 1))
        # the reference protocol carries no provision-time override
        opts.max_node_provision_time_s = defaults.max_node_provision_time_s
        return opts


class _RefPricing(PricingModel):
    def __init__(self, provider: "RefProtocolCloudProvider"):
        self._p = provider

    def node_price(self, node: Node, start_s: float, end_s: float) -> float:
        # PricingNodePriceRequest{node=1 ExternalGrpcNode, start=2, end=3 Time}
        t1 = _len_f(2, _int_f(1, int(start_s)))
        t2 = _len_f(3, _int_f(1, int(end_s)))
        resp = self._p._call(
            "PricingNodePrice", _len_f(1, _ext_node_msg(node)) + t1 + t2
        )
        return _get_double(_decode(resp), 1)

    def pod_price(self, pod: Pod, start_s: float, end_s: float) -> float:
        # PricingPodPriceRequest{pod=1 v1.Pod, start=2, end=3}
        t1 = _len_f(2, _int_f(1, int(start_s)))
        t2 = _len_f(3, _int_f(1, int(end_s)))
        resp = self._p._call(
            "PricingPodPrice", _len_f(1, encode_v1_pod(pod)) + t1 + t2
        )
        return _get_double(_decode(resp), 1)


class RefProtocolCloudProvider(CloudProvider):
    """Our CloudProvider interface over an operator's EXISTING reference
    externalgrpc provider binary — no changes on their side. Resource limits
    are host-side (the reference protocol has no limiter RPC)."""

    def __init__(self, target: str,
                 resource_limiter: Optional[ResourceLimiter] = None):
        self._channel = grpc.insecure_channel(target)
        self._limiter = resource_limiter or ResourceLimiter({}, {})
        self._groups: List[_RefRemoteNodeGroup] = []

    def _call(self, method: str, request: bytes) -> bytes:
        return _raw_rpc(self._channel, PROVIDER_SERVICE, method)(request)

    def name(self) -> str:
        return "externalgrpc-ref"

    def node_groups(self) -> List[NodeGroup]:
        if not self._groups:
            self.refresh()
        return list(self._groups)

    def node_group_for_node(self, node: Node) -> Optional[NodeGroup]:
        # NodeGroupForNodeRequest{node=1} -> {nodeGroup=1}; id "" = no group
        resp = _decode(
            self._call("NodeGroupForNode", _len_f(1, _ext_node_msg(node)))
        )
        g = _decode(_get_bytes(resp, 1))
        gid = _get_str(g, 1)
        if not gid:
            return None
        for known in self._groups:
            if known.id() == gid:
                return known
        return _RefRemoteNodeGroup(
            self, gid, _get_int(g, 2), _get_int(g, 3), _get_str(g, 4)
        )

    def pricing(self) -> Optional[PricingModel]:
        return _RefPricing(self)

    def gpu_label(self) -> str:
        return _get_str(_decode(self._call("GPULabel", b"")), 1)

    def get_available_gpu_types(self) -> List[str]:
        # GetAvailableGPUTypesResponse{gpuTypes=1 map<string, Any>}
        resp = _decode(self._call("GetAvailableGPUTypes", b""))
        return [
            _get_str(_decode(e), 1) for e in resp.get(1, ())
        ]

    def get_resource_limiter(self) -> ResourceLimiter:
        return self._limiter

    def refresh(self) -> None:
        self._call("Refresh", b"")
        resp = _decode(self._call("NodeGroups", b""))
        groups = []
        for gb in resp.get(1, ()):
            f = _decode(gb)
            groups.append(
                _RefRemoteNodeGroup(
                    self, _get_str(f, 1), _get_int(f, 2), _get_int(f, 3),
                    _get_str(f, 4),
                )
            )
        self._groups = groups

    def cleanup(self) -> None:
        try:
            self._call("Cleanup", b"")
        finally:
            self._channel.close()


# ---------------------------------------------------------------------------
# Expander plugin client (reference grpcplugin.Expander consumer)


@dataclass
class RefExpanderOption:
    """expander.proto Option{nodeGroupId=1, nodeCount=2, debug=3, pod=4}."""

    group_id: str
    node_count: int
    debug: str = ""
    pods: List[Pod] = field(default_factory=list)


class RefExpanderClient:
    """Call an operator's existing reference gRPC expander plugin. Every
    call carries a deadline so a hung plugin fails open in the caller
    instead of blocking the scale-up loop."""

    def __init__(self, target: str, timeout_s: float = 5.0):
        self._channel = grpc.insecure_channel(target)
        self._timeout_s = timeout_s

    def close(self) -> None:
        self._channel.close()

    def best_options(
        self,
        options: Sequence[RefExpanderOption],
        node_map: Dict[str, Node],
    ) -> List[RefExpanderOption]:
        # BestOptionsRequest{options=1 repeated, nodeMap=2 map<str, v1.Node>}
        req = b"".join(_len_f(1, self._opt_msg(o)) for o in options)
        for gid, node in node_map.items():
            req += _len_f(2, _str_f(1, gid) + _len_f(2, encode_v1_node(node)))
        resp = _decode(
            _raw_rpc(self._channel, EXPANDER_SERVICE, "BestOptions")(
                req, timeout=self._timeout_s
            )
        )
        out = []
        for ob in resp.get(1, ()):
            f = _decode(ob)
            out.append(
                RefExpanderOption(
                    group_id=_get_str(f, 1),
                    node_count=_get_int(f, 2),
                    debug=_get_str(f, 3),
                    pods=[decode_v1_pod(p) for p in f.get(4, ())],
                )
            )
        return out

    @staticmethod
    def _opt_msg(o: RefExpanderOption) -> bytes:
        return (
            _str_f(1, o.group_id)
            + _int_f(2, o.node_count)
            + _str_f(3, o.debug)
            + b"".join(_len_f(4, encode_v1_pod(p)) for p in o.pods)
        )


# ---------------------------------------------------------------------------
# Server bridges: serve the reference wire formats over OUR implementations


def serve_ref_provider(provider: CloudProvider, address: str = "127.0.0.1:0",
                       max_workers: int = 4):
    """Serve the reference externalgrpc CloudProvider protocol backed by any
    of our CloudProvider implementations — a stock reference autoscaler's
    --cloud-provider=externalgrpc can point here. → (server, port)."""

    def _group_by_id(gid: str) -> NodeGroup:
        for g in provider.node_groups():
            if g.id() == gid:
                return g
        raise KeyError(gid)

    def NodeGroups(req: bytes) -> bytes:
        out = b""
        for g in provider.node_groups():
            out += _len_f(
                1,
                _str_f(1, g.id()) + _int_f(2, g.min_size())
                + _int_f(3, g.max_size()),
            )
        return out

    def NodeGroupForNode(req: bytes) -> bytes:
        node = _decode_ext_node(_get_bytes(_decode(req), 1))
        g = provider.node_group_for_node(node)
        if g is None:
            return _len_f(1, b"")
        return _len_f(
            1,
            _str_f(1, g.id()) + _int_f(2, g.min_size()) + _int_f(3, g.max_size()),
        )

    def PricingNodePrice(req: bytes) -> bytes:
        f = _decode(req)
        model = provider.pricing()
        node = _decode_ext_node(_get_bytes(f, 1))
        start = _get_int(_decode(_get_bytes(f, 2)), 1)
        end = _get_int(_decode(_get_bytes(f, 3)), 1)
        return _double_f(1, model.node_price(node, start, end)) if model else b""

    def PricingPodPrice(req: bytes) -> bytes:
        f = _decode(req)
        model = provider.pricing()
        pod = decode_v1_pod(_get_bytes(f, 1))
        start = _get_int(_decode(_get_bytes(f, 2)), 1)
        end = _get_int(_decode(_get_bytes(f, 3)), 1)
        return _double_f(1, model.pod_price(pod, start, end)) if model else b""

    def GPULabel(req: bytes) -> bytes:
        return _str_f(1, provider.gpu_label())

    def GetAvailableGPUTypes(req: bytes) -> bytes:
        out = b""
        for t in provider.get_available_gpu_types():
            # map<string, google.protobuf.Any>: empty Any value
            out += _len_f(1, _str_f(1, t) + _len_f(2, b""))
        return out

    def Cleanup(req: bytes) -> bytes:
        provider.cleanup()
        return b""

    def Refresh(req: bytes) -> bytes:
        provider.refresh()
        return b""

    def NodeGroupTargetSize(req: bytes) -> bytes:
        g = _group_by_id(_get_str(_decode(req), 1))
        return _int_f(1, g.target_size())

    def NodeGroupIncreaseSize(req: bytes) -> bytes:
        f = _decode(req)
        _group_by_id(_get_str(f, 2)).increase_size(_get_int(f, 1))
        return b""

    def NodeGroupDeleteNodes(req: bytes) -> bytes:
        f = _decode(req)
        nodes = [_decode_ext_node(nb) for nb in f.get(1, ())]
        _group_by_id(_get_str(f, 2)).delete_nodes(nodes)
        return b""

    def NodeGroupDecreaseTargetSize(req: bytes) -> bytes:
        f = _decode(req)
        _group_by_id(_get_str(f, 2)).decrease_target_size(_get_int(f, 1))
        return b""

    def NodeGroupNodes(req: bytes) -> bytes:
        g = _group_by_id(_get_str(_decode(req), 1))
        out = b""
        for inst in g.nodes():
            status = _int_f(1, _STATE_TO_WIRE[inst.state])
            if inst.error_info is not None:
                status += _len_f(
                    2,
                    _str_f(1, inst.error_info.error_code or "Error")
                    + _str_f(2, inst.error_info.error_message)
                    + _int_f(3, _ERRCLASS_TO_WIRE[inst.error_info.error_class]),
                )
            out += _len_f(1, _str_f(1, inst.id) + _len_f(2, status))
        return out

    def NodeGroupTemplateNodeInfo(req: bytes) -> bytes:
        g = _group_by_id(_get_str(_decode(req), 1))
        return _len_f(1, encode_v1_node(g.template_node_info()))

    def NodeGroupGetOptions(req: bytes) -> bytes:
        f = _decode(req)
        defaults = _decode_options(_get_bytes(f, 2))
        opts = _group_by_id(_get_str(f, 1)).get_options(defaults)
        if opts is None:
            return b""
        return _len_f(1, _options_msg(opts))

    # Explicit wire surface (every reference CloudProvider RPC, greppable):
    methods = {
        "NodeGroups": NodeGroups,
        "NodeGroupForNode": NodeGroupForNode,
        "PricingNodePrice": PricingNodePrice,
        "PricingPodPrice": PricingPodPrice,
        "GPULabel": GPULabel,
        "GetAvailableGPUTypes": GetAvailableGPUTypes,
        "Cleanup": Cleanup,
        "Refresh": Refresh,
        "NodeGroupTargetSize": NodeGroupTargetSize,
        "NodeGroupIncreaseSize": NodeGroupIncreaseSize,
        "NodeGroupDeleteNodes": NodeGroupDeleteNodes,
        "NodeGroupDecreaseTargetSize": NodeGroupDecreaseTargetSize,
        "NodeGroupNodes": NodeGroupNodes,
        "NodeGroupTemplateNodeInfo": NodeGroupTemplateNodeInfo,
        "NodeGroupGetOptions": NodeGroupGetOptions,
    }

    def _wrap(fn):
        def handler(req, ctx):
            try:
                return fn(req)
            except KeyError as e:  # unknown node group id
                ctx.abort(grpc.StatusCode.NOT_FOUND, f"node group {e} unknown")

        return handler

    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            _wrap(fn),
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )
        for name, fn in methods.items()
    }
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(PROVIDER_SERVICE, handlers),)
    )
    port = server.add_insecure_port(address)
    server.start()
    return server, port


def serve_ref_expander(
    choose: Callable[[List[RefExpanderOption], Dict[str, Node]],
                     List[RefExpanderOption]],
    address: str = "127.0.0.1:0",
):
    """Serve grpcplugin.Expander backed by one of our expander strategies —
    a stock reference autoscaler's --grpc-expander-url can point here.
    → (server, port)."""

    def BestOptions(req: bytes, ctx) -> bytes:
        f = _decode(req)
        options = []
        for ob in f.get(1, ()):
            of = _decode(ob)
            options.append(
                RefExpanderOption(
                    group_id=_get_str(of, 1),
                    node_count=_get_int(of, 2),
                    debug=_get_str(of, 3),
                    pods=[decode_v1_pod(p) for p in of.get(4, ())],
                )
            )
        node_map = {}
        for e in f.get(2, ()):
            ef = _decode(e)
            node_map[_get_str(ef, 1)] = decode_v1_node(_get_bytes(ef, 2))
        best = choose(options, node_map)
        return b"".join(
            _len_f(1, RefExpanderClient._opt_msg(o)) for o in best
        )

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                EXPANDER_SERVICE,
                {
                    "BestOptions": grpc.unary_unary_rpc_method_handler(
                        BestOptions,
                        request_deserializer=lambda b: b,
                        response_serializer=lambda b: b,
                    )
                },
            ),
        )
    )
    port = server.add_insecure_port(address)
    server.start()
    return server, port
