"""Sidecar launcher: ``python -m autoscaler_tpu.rpc``.

The deploy manifests used to inline ``python -c "...serve(...)..."`` with
no flag surface, which left every --fleet-* knob parsed by the host
process but unreachable by the sidecar that actually serves BatchEstimate.
This entrypoint closes that gap: it parses the sidecar-relevant flags,
folds them into AutoscalingOptions, and hands them to ``serve()`` — so
``--fleet-shape-buckets``/``--fleet-coalesce-window-ms``/
``--fleet-batch-scenarios`` configure the coalescer and ``--fleet-prewarm``
compiles every bucket before the port is announced.
"""
from __future__ import annotations

import argparse
import threading

from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.fleet.buckets import DEFAULT_BUCKETS
from autoscaler_tpu.main import _bool_flag
from autoscaler_tpu.rpc.service import serve


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m autoscaler_tpu.rpc", description=__doc__,
    )
    p.add_argument("--address", default="127.0.0.1:9090",
                   help="host:port to bind (port 0 picks a free one)")
    p.add_argument("--max-workers", type=int, default=8,
                   help="gRPC handler threads; the coalescing window only "
                        "pays off when concurrent tenants can be admitted")
    # the --fleet-* surface, same spellings/defaults as the host process
    # (main.build_arg_parser) so one flag vocabulary configures both sides
    p.add_argument("--fleet-coalesce-window-ms", type=float, default=5.0)
    p.add_argument("--fleet-shape-buckets", default=DEFAULT_BUCKETS)
    p.add_argument("--fleet-prewarm", type=_bool_flag, default=True)
    p.add_argument("--fleet-batch-scenarios", type=int, default=8)
    p.add_argument("--fleet-max-tenant-labels", type=int, default=64)
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    options = AutoscalingOptions(
        fleet_coalesce_window_ms=args.fleet_coalesce_window_ms,
        fleet_shape_buckets=args.fleet_shape_buckets,
        fleet_prewarm=args.fleet_prewarm,
        fleet_batch_scenarios=args.fleet_batch_scenarios,
        fleet_max_tenant_labels=args.fleet_max_tenant_labels,
    )
    server, port = serve(
        args.address, max_workers=args.max_workers, options=options
    )
    print(f"tpu-autoscaler sidecar serving on port {port} "
          f"(buckets={options.fleet_shape_buckets}, "
          f"prewarm={options.fleet_prewarm})", flush=True)
    try:
        threading.Event().wait()  # serve until the pod is torn down
    except KeyboardInterrupt:
        server.stop(grace=2.0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
