"""Sidecar launcher: ``python -m autoscaler_tpu.rpc``.

The deploy manifests used to inline ``python -c "...serve(...)..."`` with
no flag surface, which left every --fleet-* knob parsed by the host
process but unreachable by the sidecar that actually serves BatchEstimate.
This entrypoint closes that gap: it parses the sidecar-relevant flags,
folds them into AutoscalingOptions, and hands them to ``serve()`` — so
``--fleet-shape-buckets``/``--fleet-coalesce-window-ms``/
``--fleet-batch-scenarios`` configure the coalescer, ``--fleet-prewarm``
compiles every bucket before the port is announced, and the overload
armor (``--fleet-max-queue-depth``/``--fleet-tenant-qps``/
``--fleet-tenant-burst``) guards admission.

Graceful drain (the ARCHITECTURE.md "Fleet overload & drain" lifecycle):

    SIGTERM (or preStop GET /drain)
      → readiness bit down (/healthz 503; the chart's readinessProbe
        pulls the endpoint out of rotation)
      → stop admitting (every RPC refuses UNAVAILABLE + drain detail;
        clients fail over to another endpoint immediately)
      → flush in-flight coalescer buckets (every admitted ticket
        resolves or fails typed — zero hangs)
      → server.stop(--fleet-drain-grace-s) and exit 0.
"""
from __future__ import annotations

import argparse
import signal
import threading

from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.fleet import FleetCoalescer
from autoscaler_tpu.fleet.buckets import DEFAULT_BUCKETS
from autoscaler_tpu.main import _bool_flag
from autoscaler_tpu.rpc.service import (
    DrainState,
    drain_server,
    serve,
    start_health_server,
)


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m autoscaler_tpu.rpc", description=__doc__,
    )
    p.add_argument("--address", default="127.0.0.1:9090",
                   help="host:port to bind (port 0 picks a free one)")
    p.add_argument("--max-workers", type=int, default=8,
                   help="gRPC handler threads; the coalescing window only "
                        "pays off when concurrent tenants can be admitted")
    # the --fleet-* surface, same spellings/defaults as the host process
    # (main.build_arg_parser) so one flag vocabulary configures both sides
    p.add_argument("--fleet-coalesce-window-ms", type=float, default=5.0)
    p.add_argument("--fleet-shape-buckets", default=DEFAULT_BUCKETS)
    p.add_argument("--fleet-prewarm", type=_bool_flag, default=True)
    p.add_argument("--fleet-batch-scenarios", type=int, default=8)
    p.add_argument("--fleet-max-tenant-labels", type=int, default=64)
    # overload armor + drain (fleet/admission.py, service.drain_server)
    p.add_argument("--fleet-max-queue-depth", type=int, default=0,
                   help="shed submits typed (RESOURCE_EXHAUSTED + "
                        "retry-after) past this queue depth; 0 = unbounded")
    p.add_argument("--fleet-tenant-qps", type=float, default=0.0,
                   help="per-tenant token-bucket quota, requests/second; "
                        "0 = no quotas")
    p.add_argument("--fleet-tenant-burst", type=float, default=0.0,
                   help="token-bucket burst capacity; 0 = max(qps, 1)")
    p.add_argument("--fleet-tenant-tiers", default="",
                   help="tenant quota tiers, JSON tier name -> {qps, "
                        "burst, queue_share, default_deadline_s, "
                        "shed_priority, tenants} incl. a 'default' "
                        "catch-all; supersedes --fleet-tenant-qps")
    p.add_argument("--fleet-drain-grace-s", type=float, default=5.0,
                   help="how long server.stop() waits for in-flight RPCs "
                        "after the drain sequence flushed the coalescer")
    p.add_argument("--health-port", type=int, default=8081,
                   help="HTTP readiness surface: GET /healthz (200 ready, "
                        "503 draining — the chart's readinessProbe) and "
                        "GET /drain (preStop: begin draining). "
                        "0 disables, -1 binds an ephemeral port")
    p.add_argument("--health-host", default="0.0.0.0",
                   help="bind address for the readiness surface; the "
                        "default answers the kubelet's pod-IP httpGet "
                        "probes (127.0.0.1 would make readinessProbe and "
                        "preStop fail in-cluster)")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    options = AutoscalingOptions(
        fleet_coalesce_window_ms=args.fleet_coalesce_window_ms,
        fleet_shape_buckets=args.fleet_shape_buckets,
        fleet_prewarm=args.fleet_prewarm,
        fleet_batch_scenarios=args.fleet_batch_scenarios,
        fleet_max_tenant_labels=args.fleet_max_tenant_labels,
        fleet_max_queue_depth=args.fleet_max_queue_depth,
        fleet_tenant_qps=args.fleet_tenant_qps,
        fleet_tenant_burst=args.fleet_tenant_burst,
        fleet_tenant_tiers=args.fleet_tenant_tiers,
        fleet_drain_grace_s=args.fleet_drain_grace_s,
    )
    drain = DrainState()
    fleet = FleetCoalescer.from_options(options)
    server, port = serve(
        args.address, max_workers=args.max_workers, fleet=fleet, drain=drain
    )
    health_port = 0
    httpd = None
    if args.health_port != 0:
        httpd, health_port = start_health_server(
            drain, port=max(args.health_port, 0), host=args.health_host
        )
    print(f"tpu-autoscaler sidecar serving on port {port} "
          f"(buckets={options.fleet_shape_buckets}, "
          f"prewarm={options.fleet_prewarm}, "
          f"max_queue_depth={options.fleet_max_queue_depth}, "
          f"tenant_qps={options.fleet_tenant_qps}, "
          f"health_port={health_port})", flush=True)

    # SIGTERM (kubelet pod termination) and SIGINT both enter the drain
    # sequence; the handler only sets an event — the actual drain runs on
    # the main thread so signal-context restrictions never bite
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    print("sidecar drain: readiness down, admission closed, flushing "
          "in-flight buckets", flush=True)
    drain_server(
        server, fleet=fleet, drain=drain, grace_s=options.fleet_drain_grace_s
    )
    if httpd is not None:
        # the health server answers 503 throughout the drain (so the
        # readinessProbe sees the bit) and shuts down only once the gRPC
        # port is closed
        httpd.shutdown()
    print("sidecar drained cleanly", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
