"""JournalReader: time-travel reconstruction with typed failure modes.

``reconstruct(tick)`` replays the latest keyframe at or before the tick
plus every delta after it into a bit-exact ``SnapshotTensors`` twin of
what the live packer served that tick. Corruption never reconstructs
wrong — it raises one of the typed errors below, which is the whole
contract: a forensic tool that silently returns a plausible-but-drifted
state is worse than none.

- TruncatedJournalError: the file ends (or breaks) mid-line — a crashed
  writer's torn append;
- MissingKeyframeError: no keyframe at or before the requested tick (a
  ring that evicted its keyframe, or a file whose head was cut);
- OutOfOrderTickError: the tick axis is not strictly increasing — every
  reconstruction after the inversion would be built on the wrong base;
- SchemaDriftError: a record from another schema, an unknown kind, or a
  delta whose ops no longer fit the keyframe's shapes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from autoscaler_tpu.journal.codec import (
    apply_names_delta,
    apply_ops,
    decode_array,
)
from autoscaler_tpu.journal.ledger import SCHEMA, load_jsonl


class JournalError(ValueError):
    """Base of every journal read/reconstruction failure."""


class TruncatedJournalError(JournalError):
    """The journal file breaks mid-line (torn append / cut tail)."""


class MissingKeyframeError(JournalError):
    """No keyframe at or before the requested tick."""


class OutOfOrderTickError(JournalError):
    """Tick axis not strictly increasing."""


class SchemaDriftError(JournalError):
    """Record schema/kind/op shape no longer matches this reader."""


# the SnapshotTensors field names — journal fields outside this set (the
# captured pod_evictable channel) ride along in ReconstructedState.fields
# but stay out of the tensors() constructor
def _tensor_field_names() -> frozenset:
    import dataclasses

    from autoscaler_tpu.snapshot.tensors import SnapshotTensors

    return frozenset(f.name for f in dataclasses.fields(SnapshotTensors))


@dataclass
class ReconstructedState:
    """One tick's reconstructed decision-input state."""

    tick: int
    fields: Dict[str, np.ndarray]
    names: Dict[str, List[Optional[str]]]
    ext: List[str] = field(default_factory=list)
    options_fp: str = ""
    options: Dict[str, Any] = field(default_factory=dict)
    explain_sha256: str = ""
    ctx: Dict[str, Any] = field(default_factory=dict)

    def tensors(self):
        from autoscaler_tpu.snapshot.tensors import SnapshotTensors

        keep = _tensor_field_names()
        return SnapshotTensors(
            **{k: v for k, v in self.fields.items() if k in keep}
        )

    def evictable(self) -> np.ndarray:
        return self.fields["pod_evictable"]


class JournalReader:
    """Reads a journal (ring records or a JSONL file) and reconstructs."""

    def __init__(self, records: List[Dict[str, Any]]) -> None:
        last_tick: Optional[int] = None
        for i, rec in enumerate(records):
            if not isinstance(rec, dict):
                raise SchemaDriftError(f"record {i}: not an object")
            if rec.get("schema") != SCHEMA:
                raise SchemaDriftError(
                    f"record {i}: schema {rec.get('schema')!r} != {SCHEMA!r}"
                )
            if rec.get("kind") not in ("keyframe", "delta"):
                raise SchemaDriftError(
                    f"record {i}: kind {rec.get('kind')!r} not keyframe|delta"
                )
            tick = rec.get("tick")
            if not isinstance(tick, int):
                raise SchemaDriftError(f"record {i}: tick must be an int")
            if last_tick is not None and tick <= last_tick:
                raise OutOfOrderTickError(
                    f"record {i}: tick {tick} not increasing "
                    f"(prev {last_tick})"
                )
            last_tick = tick
        self._records = records

    @classmethod
    def from_path(cls, path: str) -> "JournalReader":
        try:
            records = load_jsonl(path)
        except ValueError as e:
            raise TruncatedJournalError(str(e)) from None
        return cls(records)

    def ticks(self) -> List[int]:
        return [rec["tick"] for rec in self._records]

    def records(self) -> List[Dict[str, Any]]:
        return list(self._records)

    def reconstruct(self, tick: int) -> ReconstructedState:
        """Bit-exact state at ``tick``: latest keyframe ≤ tick, deltas
        applied forward in order."""
        upto = [r for r in self._records if r["tick"] <= tick]
        if not upto or upto[-1]["tick"] != tick:
            raise MissingKeyframeError(f"tick {tick} not journaled")
        base = None
        for i in range(len(upto) - 1, -1, -1):
            if upto[i]["kind"] == "keyframe":
                base = i
                break
        if base is None:
            raise MissingKeyframeError(
                f"no keyframe at or before tick {tick} (ring evicted it or "
                "the journal head was cut)"
            )
        key = upto[base]
        state = key.get("state", {})
        try:
            fields = {
                name: decode_array(doc)
                for name, doc in state.get("fields", {}).items()
            }
            names = {
                k: list(v) for k, v in state.get("names", {}).items()
            }
            ext = list(state.get("ext", ()))
        except (KeyError, TypeError, ValueError) as e:
            raise SchemaDriftError(
                f"tick {key['tick']}: undecodable keyframe: {e}"
            ) from None
        if not fields:
            raise SchemaDriftError(
                f"tick {key['tick']}: keyframe carries no tensor fields"
            )
        options = dict(key.get("options", {}))
        for rec in upto[base + 1:]:
            st = rec.get("state", {})
            try:
                apply_ops(fields, st.get("ops", []))
                for table, delta in st.get("names", {}).items():
                    names[table] = apply_names_delta(
                        names.get(table, []), delta
                    )
            except (KeyError, TypeError, ValueError) as e:
                raise SchemaDriftError(
                    f"tick {rec['tick']}: delta does not fit its keyframe: "
                    f"{e}"
                ) from None
        last = upto[-1]
        return ReconstructedState(
            tick=tick,
            fields=fields,
            names=names,
            ext=ext,
            options_fp=last.get("options_fp", ""),
            options=options,
            explain_sha256=last.get("explain_sha256", ""),
            ctx=dict(last.get("ctx", {})),
        )


def tensors_from_fields(fields: Dict[str, np.ndarray]):
    """SnapshotTensors from a raw journal/shadow field dict (drops the
    non-tensor channels, e.g. pod_evictable)."""
    from autoscaler_tpu.snapshot.tensors import SnapshotTensors

    keep = _tensor_field_names()
    return SnapshotTensors(
        **{k: v for k, v in fields.items() if k in keep}
    )
