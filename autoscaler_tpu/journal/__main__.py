"""Flight-journal CLI: reconstruct | diff | replay over a journal file.

    python -m autoscaler_tpu.journal reconstruct JOURNAL --tick N
    python -m autoscaler_tpu.journal diff JOURNAL A B
    python -m autoscaler_tpu.journal replay JOURNAL --explain-ledger LEDGER

``reconstruct`` prints the tick's state summary (per-field shape/dtype/
sha256, name-table sizes); ``diff`` prints the semantic state diff between
two ticks; ``replay`` re-executes every journaled tick's decision path and
byte-compares against the recorded explain ledger — exit 1 on any
divergence (hack/verify.sh drives this as the journal gate).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import sys
from typing import List

from autoscaler_tpu.journal.diff import semantic_diff
from autoscaler_tpu.journal.ledger import stable_json, validate_records
from autoscaler_tpu.journal.reader import JournalError, JournalReader
from autoscaler_tpu.journal.replay import replay_journal


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m autoscaler_tpu.journal",
        description="black-box flight journal forensics",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("reconstruct",
                         help="rebuild one tick's state from the journal")
    rec.add_argument("journal")
    rec.add_argument("--tick", type=int, default=None,
                     help="tick to reconstruct (default: newest)")

    dif = sub.add_parser("diff",
                         help="semantic state diff between two ticks")
    dif.add_argument("journal")
    dif.add_argument("tick_a", type=int)
    dif.add_argument("tick_b", type=int)

    rep = sub.add_parser(
        "replay",
        help="re-execute each journaled tick's decision path and byte-"
             "compare against the recorded explain ledger (exit 1 on "
             "divergence)",
    )
    rep.add_argument("journal")
    rep.add_argument("--explain-ledger", required=True,
                     help="the run's decision ledger JSONL (loadgen "
                          "--explain-ledger)")
    rep.add_argument("--tick", type=int, default=None,
                     help="replay one tick only (default: all)")
    return p


def _reader(path: str) -> JournalReader:
    reader = JournalReader.from_path(path)
    errors = validate_records(reader.records())
    if errors:
        for e in errors:
            print(f"journal invalid: {e}", file=sys.stderr)
        raise SystemExit(1)
    return reader


def _reconstruct(args) -> int:
    reader = _reader(args.journal)
    ticks = reader.ticks()
    if not ticks:
        print("empty journal", file=sys.stderr)
        return 1
    tick = args.tick if args.tick is not None else ticks[-1]
    state = reader.reconstruct(tick)
    doc = {
        "tick": state.tick,
        "options_fp": state.options_fp,
        "explain_sha256": state.explain_sha256,
        "names": {k: sum(1 for n in v if n is not None)
                  for k, v in sorted(state.names.items())},
        "fields": {
            name: {
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
                "sha256": hashlib.sha256(arr.tobytes()).hexdigest(),
            }
            for name, arr in sorted(state.fields.items())
        },
        "ctx": state.ctx,
    }
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 0


def _diff(args) -> int:
    reader = _reader(args.journal)
    diff = semantic_diff(
        reader.reconstruct(args.tick_a), reader.reconstruct(args.tick_b)
    )
    print(json.dumps(diff, indent=2, sort_keys=True))
    return 0


def _replay(args) -> int:
    reader = _reader(args.journal)
    records = []
    lines: List[str] = []
    with open(args.explain_ledger) as f:
        for lineno, raw in enumerate(f, 1):
            if not raw.strip():
                continue
            try:
                records.append(json.loads(raw))
            except json.JSONDecodeError as e:
                print(f"{args.explain_ledger}:{lineno}: not JSON: {e}",
                      file=sys.stderr)
                return 1
            # hash the RAW line bytes: the journal pinned the line as
            # written, not a re-serialization of it
            lines.append(raw if raw.endswith("\n") else raw + "\n")
    results = replay_journal(reader, records, lines, tick=args.tick)
    diverged = 0
    replayed = 0
    for result in results:
        if result["replayed"]:
            replayed += 1
        for finding in result["divergence"]:
            diverged += 1
            print(f"tick {result['tick']}: DIVERGED: {finding}",
                  file=sys.stderr)
    print(stable_json({
        "ticks": len(results),
        "replayed": replayed,
        "diverged": diverged,
    }))
    return 1 if diverged else 0


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    try:
        if args.cmd == "reconstruct":
            return _reconstruct(args)
        if args.cmd == "diff":
            return _diff(args)
        return _replay(args)
    except JournalError as e:
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
