"""JournalRecorder: the black-box flight recorder behind /journalz.

One recorder per autoscaler, same lifecycle as the perf observatory and
the decision explainer: ``begin_tick`` opens the tick, the packer's
journal sink (``observe_update``) captures the tick's FIRST tensor
materialization — which is the decision-input state: ClusterSnapshot
caches tensors per version and ``revert()`` restores the fork-time
version, so everything the tick decided (estimator, expander, preemption
plan) read exactly this materialization — and ``record_tick`` closes the
tick into one journal line: a full keyframe (init, packer reseed, shape
change, options change, or every K ticks) or a byte-level row-scatter
delta against the previous line (codec.py).

The diff is computed against the recorder's own host shadow, not the
packer's dirty sets, so fork/revert churn inside the tick is invisible
and reconstruction is bit-exact by construction. The ring is always on
(bounded memory); ``journal_enabled`` gates only the endpoint, and
``journal_path`` appends the same strict ``record_line`` bytes to disk
for post-mortem reconstruct/diff/replay.

Lock discipline (graftlint GL004): record/observe run on the loop thread,
the JSON surfaces on server threads — every touch of the ring, shadow,
and staging state holds ``_lock``.
"""
from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from autoscaler_tpu.journal.ledger import (
    SCHEMA,
    record_line,
    summarize,
)
from autoscaler_tpu.journal.codec import (
    delta_ops,
    encode_array,
    names_delta,
    sha256_hex,
)


def options_fingerprint(options_doc: Dict[str, Any]) -> str:
    """sha256 of the strict sorted-key options JSON — the per-record
    effective-configuration stamp (a fingerprint mismatch between journal
    and replay environment is itself a divergence finding)."""
    import json

    return sha256_hex(
        json.dumps(options_doc, sort_keys=True, separators=(",", ":"),
                   default=str)
    )


class JournalRecorder:
    """Delta-encoded per-tick state history with typed reconstruction."""

    def __init__(
        self,
        ring_capacity: int = 64,
        keyframe_interval: int = 16,
        path: str = "",
        options_doc: Optional[Dict[str, Any]] = None,
        metrics=None,
    ) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(ring_capacity), 1))
        self._keyframe_interval = max(int(keyframe_interval), 1)
        self._path = path
        self._metrics = metrics
        self._options_doc = dict(options_doc or {})
        self._options_fp = options_fingerprint(self._options_doc)
        # open-tick staging (loop thread): the first packer materialization
        self._tick: Optional[int] = None
        self._captured = False
        self._notes: Dict[str, Any] = {}
        self._cap_fields: Optional[Dict[str, np.ndarray]] = None
        self._cap_names: Optional[Dict[str, List[Optional[str]]]] = None
        self._cap_ext: List[str] = []
        self._cap_full_packs: Optional[int] = None
        self._cap_reseed_reason = ""
        # shadow of the last RECORDED state — the delta base and the
        # probe's live reference
        self._shadow_fields: Optional[Dict[str, np.ndarray]] = None
        self._shadow_names: Optional[Dict[str, List[Optional[str]]]] = None
        self._shadow_ext: List[str] = []
        self._last_full_packs: Optional[int] = None
        self._since_keyframe = 0

    # ------------------------------------------------------ tick lifecycle
    def begin_tick(self, tick: int) -> None:
        with self._lock:
            self._tick = int(tick)
            self._captured = False
            self._notes = {}
            self._cap_fields = None
            self._cap_names = None

    def note(self, key: str, value: Any) -> None:
        """Attach replay context to the open tick (e.g. the preemption
        pass's eligible pending keys — state the decision path consumed
        that the tensors alone do not carry)."""
        with self._lock:
            if self._tick is not None:
                self._notes[key] = value

    def observe_update(self, tensors, meta, packer=None) -> None:
        """Packer journal sink (IncrementalPacker.journal_sink): host-copy
        the tick's first materialization. Later materializations in the
        same tick are fork-churn the decisions never saw — ignored."""
        with self._lock:
            if self._tick is None or self._captured:
                return
            fields: Dict[str, np.ndarray] = {}
            for f in dataclasses.fields(tensors):
                value = getattr(tensors, f.name)
                if value is not None:
                    fields[f.name] = np.array(value)
            # the victim-eligibility channel is a function of Pod objects
            # the journal does not carry — capture it as one more field so
            # `journal replay` can re-run the preemption kernel
            from autoscaler_tpu.preempt.policy import evictable_mask

            fields["pod_evictable"] = np.array(
                evictable_mask(meta.pods, tensors.num_pods)
            )
            pods: List[Optional[str]] = [None] * len(meta.pods)
            for key, row in meta.pod_index.items():
                pods[row] = key
            nodes: List[Optional[str]] = [None] * len(meta.nodes)
            for name, row in meta.node_index.items():
                nodes[row] = name
            self._cap_fields = fields
            self._cap_names = {
                "pods": pods,
                "nodes": nodes,
                "groups": list(meta.group_names),
            }
            self._cap_ext = list(meta.extended_resources)
            if packer is not None:
                self._cap_full_packs = getattr(packer, "full_packs", None)
                self._cap_reseed_reason = getattr(
                    packer, "last_repack_reason", ""
                )
            self._captured = True

    def record_tick(self, explain_rec: Optional[Dict[str, Any]] = None):
        """Close the open tick into one journal record (None before the
        first materialization — the journal starts at first state)."""
        with self._lock:
            tick = self._tick
            self._tick = None
            if tick is None:
                return None
            if self._captured:
                fields = self._cap_fields or {}
                names = self._cap_names or {}
                ext = self._cap_ext
            elif self._shadow_fields is not None:
                # nothing materialized this tick: the decision input was
                # the standing state — journal an empty delta so the tick
                # still reconstructs (and the tick axis stays gap-free
                # from the journal's first record on)
                fields = self._shadow_fields
                names = self._shadow_names or {}
                ext = self._shadow_ext
            else:
                return None
            explain_sha = ""
            if explain_rec is not None:
                from autoscaler_tpu.explain import record_line as explain_line

                explain_sha = sha256_hex(explain_line(explain_rec))
            reason = self._keyframe_reason(fields, ext)
            rec: Dict[str, Any] = {
                "schema": SCHEMA,
                "tick": tick,
                "options_fp": self._options_fp,
                "ids": {"trace": tick, "explain": tick, "perf": tick},
                "explain_sha256": explain_sha,
                "ctx": dict(self._notes),
            }
            if reason is not None:
                rec["kind"] = "keyframe"
                rec["reason"] = reason
                rec["options"] = dict(self._options_doc)
                rec["state"] = {
                    "fields": {
                        k: encode_array(v) for k, v in sorted(fields.items())
                    },
                    "names": {k: list(v) for k, v in sorted(names.items())},
                    "ext": list(ext),
                }
                self._since_keyframe = 0
            else:
                assert self._shadow_fields is not None
                rec["kind"] = "delta"
                rec["state"] = {
                    "ops": delta_ops(self._shadow_fields, fields),
                    "names": {
                        k: names_delta(
                            (self._shadow_names or {}).get(k, []), list(v)
                        )
                        for k, v in sorted(names.items())
                    },
                }
                self._since_keyframe += 1
            self._shadow_fields = dict(fields)
            self._shadow_names = {k: list(v) for k, v in names.items()}
            self._shadow_ext = list(ext)
            if self._cap_full_packs is not None:
                self._last_full_packs = self._cap_full_packs
            self._ring.append(rec)
            path = self._path
        if self._metrics is not None:
            self._metrics.journal_records_total.inc()
            if reason is not None:
                self._metrics.journal_keyframes_total.inc()
        if path:
            with open(path, "a") as f:
                f.write(record_line(rec))
        return rec

    def _keyframe_reason(self, fields, ext) -> Optional[str]:
        """Why this tick is a keyframe, None = delta. Precedence: first
        state, then structure (shape/field-set/schema), then packer reseed
        (promotion/full repack), then the every-K interval."""
        prev = self._shadow_fields
        if prev is None:
            return "init"
        if set(prev) != set(fields) or any(
            prev[k].shape != fields[k].shape or prev[k].dtype != fields[k].dtype
            for k in fields
        ):
            return "shape_change"
        if list(ext) != list(self._shadow_ext):
            return "shape_change"
        if (
            self._cap_full_packs is not None
            and self._last_full_packs is not None
            and self._cap_full_packs != self._last_full_packs
        ):
            return "reseed:" + (self._cap_reseed_reason or "init")
        if self._since_keyframe + 1 >= self._keyframe_interval:
            return "interval"
        return None

    # ---------------------------------------------------------- divergence
    def probe(self) -> Dict[str, Any]:
        """Reconstruct the newest journaled tick from the ring and bit-
        compare it against the live shadow (the host copy of what the
        arena-backed packer actually served), then cross-check the fit
        kernel's verdicts on the reconstructed twin. Any mismatch is
        drift: a codec, shadow, or arena bug surfacing as a metric + trace
        event instead of a silently wrong forensic answer."""
        with self._lock:
            records = [dict(r) for r in self._ring]
            shadow = (
                None
                if self._shadow_fields is None
                else dict(self._shadow_fields)
            )
        if not records or shadow is None:
            return {"checked": False}
        from autoscaler_tpu.journal.reader import JournalError, JournalReader

        tick = records[-1]["tick"]
        out: Dict[str, Any] = {"checked": True, "tick": tick}
        try:
            state = JournalReader(records).reconstruct(tick)
        except JournalError as e:
            out.update(drift=True, error=str(e))
            return out
        drifted = [
            k
            for k in sorted(set(shadow) | set(state.fields))
            if k not in shadow
            or k not in state.fields
            or shadow[k].dtype != state.fields[k].dtype
            or shadow[k].shape != state.fields[k].shape
            or shadow[k].tobytes() != state.fields[k].tobytes()
        ]
        fit_drift = False
        if not drifted:
            from autoscaler_tpu.ops.fit import fits_any_node
            from autoscaler_tpu.journal.reader import tensors_from_fields

            recon = np.asarray(fits_any_node(state.tensors()))
            live = np.asarray(fits_any_node(tensors_from_fields(shadow)))
            fit_drift = not np.array_equal(recon, live)
        out["drift"] = bool(drifted or fit_drift)
        out["fields"] = drifted
        out["fit_drift"] = fit_drift
        return out

    # -------------------------------------------------------- JSON surface
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def list_json(self) -> str:
        records = self.records()
        from autoscaler_tpu.journal.ledger import stable_json

        return (
            stable_json({
                "schema": SCHEMA,
                "summary": summarize(records),
                "ticks": [
                    {
                        "tick": r["tick"],
                        "kind": r["kind"],
                        "reason": r.get("reason"),
                        "ops": len(r.get("state", {}).get("ops", ())),
                        "explain_sha256": r.get("explain_sha256", ""),
                    }
                    for r in records
                ],
            })
            + "\n"
        )

    def detail_json(self, tick: int) -> Optional[str]:
        from autoscaler_tpu.journal.ledger import stable_json

        with self._lock:
            for r in self._ring:
                if r.get("tick") == tick:
                    return stable_json(r) + "\n"
        return None

    def diff_json(self, tick_a: int, tick_b: int) -> str:
        """Semantic state diff between two ring ticks (the ?diff=a,b
        drill-down); reconstruction failures report as typed errors, never
        as a wrong diff."""
        from autoscaler_tpu.journal.diff import semantic_diff
        from autoscaler_tpu.journal.ledger import stable_json
        from autoscaler_tpu.journal.reader import JournalError, JournalReader

        records = self.records()
        try:
            reader = JournalReader(records)
            doc = semantic_diff(
                reader.reconstruct(tick_a), reader.reconstruct(tick_b)
            )
        except JournalError as e:
            doc = {"error": f"{type(e).__name__}: {e}"}
        return stable_json(doc) + "\n"
