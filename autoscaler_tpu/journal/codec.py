"""Journal state codec: bit-exact array serialization and row-scatter
deltas.

The wire shape reuses PR 11's ``DeltaProgram`` vocabulary (snapshot/
arena.py): one op per dirty field, axis-0 row indices plus payload rows.
The journal's diff is computed HERE, byte-level, against the recorder's
shadow copy — not trusted from the packer's dirty-row sets — so a row the
packer happened to rewrite with identical bytes journals as unchanged and
a row it missed can never journal wrong: reconstruction is bit-exact by
construction.

Bit-exact means byte-exact: rows are compared on their raw bytes, never
with ``!=`` on the values, so ``-0.0`` vs ``0.0`` and NaN payload bits
survive a journal round-trip (f32 capacity columns make this load-bearing,
not theoretical).
"""
from __future__ import annotations

import base64
import hashlib
from typing import Any, Dict, List, Optional

import numpy as np


def sha256_hex(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def encode_array(arr: np.ndarray) -> Dict[str, Any]:
    """{dtype, shape, b64}: dtype.str keeps the byte order explicit, the
    payload is the C-order buffer — decode is a reshape, no parsing."""
    a = np.ascontiguousarray(arr)
    return {
        "dtype": a.dtype.str,
        # np.ascontiguousarray promotes 0-d to 1-d; journal the source
        # shape so scalars decode back 0-d (the buffer is identical)
        "shape": list(np.shape(arr)),
        "b64": base64.b64encode(a.tobytes(order="C")).decode("ascii"),
    }


def decode_array(doc: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(doc["b64"])
    arr = np.frombuffer(raw, dtype=np.dtype(doc["dtype"]))
    return arr.reshape(tuple(doc["shape"])).copy()


def _row_view(arr: np.ndarray) -> np.ndarray:
    """[rows, row_bytes] uint8 view of an array's C-order buffer."""
    a = np.ascontiguousarray(arr)
    rows = a.shape[0]
    width = a.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
    return np.frombuffer(a.tobytes(order="C"), dtype=np.uint8).reshape(
        rows, width
    )


def changed_rows(prev: np.ndarray, cur: np.ndarray) -> np.ndarray:
    """Axis-0 indices whose raw bytes differ (shapes/dtypes must match —
    a shape or dtype change is a keyframe, not a delta)."""
    if prev.shape != cur.shape or prev.dtype != cur.dtype:
        raise ValueError(
            f"delta across shape/dtype change: {prev.dtype}{prev.shape} vs "
            f"{cur.dtype}{cur.shape}"
        )
    if cur.ndim == 0 or cur.size == 0:
        return np.zeros((0,), dtype=np.int64)
    diff = _row_view(prev) != _row_view(cur)
    return np.nonzero(diff.any(axis=1))[0]


def delta_ops(
    prev: Dict[str, np.ndarray], cur: Dict[str, np.ndarray]
) -> List[Dict[str, Any]]:
    """Row-scatter ops turning ``prev`` into ``cur`` (DeltaProgram shape:
    field name, axis 0, index list, payload rows). Field names iterate
    sorted so two identical states emit byte-identical op lists. Scalars
    (0-d) ship as full replacements with axis -1."""
    ops: List[Dict[str, Any]] = []
    for name in sorted(cur):
        p, c = prev[name], cur[name]
        if c.ndim == 0:
            if np.ascontiguousarray(p).tobytes() != np.ascontiguousarray(
                c
            ).tobytes():
                ops.append({"field": name, "axis": -1,
                            "payload": encode_array(c)})
            continue
        idx = changed_rows(p, c)
        if idx.size:
            ops.append({
                "field": name,
                "axis": 0,
                "idx": [int(i) for i in idx],
                "payload": encode_array(c[idx]),
            })
    return ops


def apply_ops(
    fields: Dict[str, np.ndarray], ops: List[Dict[str, Any]]
) -> None:
    """Scatter ``ops`` into ``fields`` in place (reader-side replay of one
    delta record). Raises KeyError/ValueError on drifted ops — the reader
    wraps those into its typed SchemaDriftError rather than reconstructing
    wrong."""
    for op in ops:
        name = op["field"]
        if name not in fields:
            raise KeyError(name)
        payload = decode_array(op["payload"])
        if op.get("axis", 0) == -1:
            if payload.shape != fields[name].shape:
                raise ValueError(
                    f"{name}: replacement shape {payload.shape} != "
                    f"{fields[name].shape}"
                )
            fields[name] = payload
            continue
        idx = np.asarray(op["idx"], dtype=np.int64)
        target = fields[name]
        if idx.size and (idx.min() < 0 or idx.max() >= target.shape[0]):
            raise ValueError(f"{name}: scatter index out of bounds")
        if payload.shape[1:] != target.shape[1:]:
            raise ValueError(
                f"{name}: payload rows {payload.shape} do not fit "
                f"{target.shape}"
            )
        target[idx] = payload


def names_delta(
    prev: List[Optional[str]], cur: List[Optional[str]]
) -> Dict[str, Any]:
    """Patch list for one name table: new length plus [index, name] pairs
    where the entry changed (rows swap-fill on removal, so tables shrink
    and grow without ever renumbering surviving rows)."""
    patches = [
        [i, name]
        for i, name in enumerate(cur)
        if i >= len(prev) or prev[i] != name
    ]
    return {"len": len(cur), "set": patches}


def apply_names_delta(
    prev: List[Optional[str]], delta: Dict[str, Any]
) -> List[Optional[str]]:
    out: List[Optional[str]] = list(prev[: int(delta["len"])])
    out.extend([None] * (int(delta["len"]) - len(out)))
    for i, name in delta["set"]:
        if not 0 <= int(i) < len(out):
            raise ValueError(f"name patch index {i} outside table")
        out[int(i)] = name
    return out
