"""Semantic state diff between two reconstructed ticks.

Renders what an on-call human asks first — which pods appeared, vanished
or moved, which nodes flipped, how capacity drifted — instead of a raw
tensor delta. Everything is keyed by object names (sorted wherever a list
reaches output, graftlint GL010) so the diff reads the same regardless of
row placement: two states that pack the same cluster into different rows
diff empty.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from autoscaler_tpu.journal.reader import ReconstructedState


def _pod_node_names(state: ReconstructedState) -> Dict[str, str]:
    """pod key → node name ("" = pending) for every named pod row."""
    pod_node = np.asarray(state.fields["pod_node"])
    nodes = state.names.get("nodes", [])
    out: Dict[str, str] = {}
    for row, key in enumerate(state.names.get("pods", [])):
        if key is None:
            continue
        idx = int(pod_node[row]) if row < pod_node.shape[0] else -1
        name = nodes[idx] if 0 <= idx < len(nodes) else None
        out[key] = name or ""
    return out


def _node_rows(state: ReconstructedState) -> Dict[str, int]:
    return {
        name: row
        for row, name in enumerate(state.names.get("nodes", []))
        if name is not None
    }


def semantic_diff(
    a: ReconstructedState, b: ReconstructedState
) -> Dict[str, Any]:
    """What changed between tick ``a`` and tick ``b``, in object terms."""
    pods_a = _pod_node_names(a)
    pods_b = _pod_node_names(b)
    moved = [
        {"pod": key, "from": pods_a[key], "to": pods_b[key]}
        for key in sorted(set(pods_a) & set(pods_b))
        if pods_a[key] != pods_b[key]
    ]
    nodes_a = _node_rows(a)
    nodes_b = _node_rows(b)
    flips: List[Dict[str, Any]] = []
    drift_nodes = 0
    alloc_delta: Optional[np.ndarray] = None
    used_delta: Optional[np.ndarray] = None
    na, nb = a.fields["node_alloc"], b.fields["node_alloc"]
    ua, ub = a.fields["node_used"], b.fields["node_used"]
    ga, gb = a.fields["node_group"], b.fields["node_group"]
    for name in sorted(set(nodes_a) & set(nodes_b)):
        ra, rb = nodes_a[name], nodes_b[name]
        if int(ga[ra]) != int(gb[rb]):
            flips.append({
                "node": name,
                "field": "node_group",
                "from": int(ga[ra]),
                "to": int(gb[rb]),
            })
        d_alloc = np.asarray(nb[rb], dtype=np.float64) - np.asarray(
            na[ra], dtype=np.float64
        )
        d_used = np.asarray(ub[rb], dtype=np.float64) - np.asarray(
            ua[ra], dtype=np.float64
        )
        if d_alloc.any() or d_used.any():
            drift_nodes += 1
            alloc_delta = d_alloc if alloc_delta is None else alloc_delta + d_alloc
            used_delta = d_used if used_delta is None else used_delta + d_used
    zeros = np.zeros(np.asarray(na).shape[-1], dtype=np.float64)
    return {
        "ticks": [a.tick, b.tick],
        "pods_added": sorted(set(pods_b) - set(pods_a)),
        "pods_removed": sorted(set(pods_a) - set(pods_b)),
        "pods_moved": moved,
        "nodes_added": sorted(set(nodes_b) - set(nodes_a)),
        "nodes_removed": sorted(set(nodes_a) - set(nodes_b)),
        "node_flips": flips,
        "capacity_drift": {
            "nodes_changed": drift_nodes,
            "alloc_delta": [
                float(x)
                for x in (alloc_delta if alloc_delta is not None else zeros)
            ],
            "used_delta": [
                float(x)
                for x in (used_delta if used_delta is not None else zeros)
            ],
        },
        "options_changed": a.options_fp != b.options_fp,
    }
