"""Live-vs-replay divergence probing: re-execute a journaled tick's
decision path and byte-compare against the recorded explain-ledger line.

For every journaled tick, ``replay_tick`` reconstructs the decision-input
state, re-runs the preemption pass exactly as the control loop did —
``BinpackingNodeEstimator.estimate_preemption`` over the reconstructed
tensors, the journaled victim-eligibility channel, and the journaled
eligible pending set (preempt/engine.py's row semantics, names from the
journal's tables) — and byte-compares the rebuilt preemption section
against the one in the recorded decision ledger. The kernel route is
spliced from the record (provenance of which rung served the live
dispatch is environment, not state); everything else must match to the
byte. The tick's full explain line is additionally pinned by sha256 to
the hash stamped in the journal, so the ledger on disk is provably the
ledger that was recorded.

Divergence here means one of three things broke: the journal codec, the
determinism contract of the decision path, or the ledger file — exactly
the three failure modes the flight journal exists to catch.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from autoscaler_tpu.journal.codec import sha256_hex
from autoscaler_tpu.journal.reader import JournalReader, ReconstructedState


def _strict(doc: Any) -> str:
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def _replan(state: ReconstructedState, eligible: List[str]) -> Dict[str, Any]:
    """Re-run the preemption pass on reconstructed state (the engine's
    plan() semantics, keyed by the journal's name tables)."""
    from autoscaler_tpu.estimator.binpacking import BinpackingNodeEstimator
    from autoscaler_tpu.explain.reasons import EVICTION_PREEMPTED_BY

    tensors = state.tensors()
    pod_names = state.names.get("pods", [])
    node_names = state.names.get("nodes", [])
    pod_node = np.asarray(tensors.pod_node)
    valid = np.asarray(tensors.pod_valid).copy()
    elig = set(eligible)
    for row, key in enumerate(pod_names):
        if valid[row] and pod_node[row] < 0 and key not in elig:
            valid[row] = False
    scheduled, placed, victim_of, route = (
        BinpackingNodeEstimator().estimate_preemption(
            tensors, state.evictable(), pod_valid=valid
        )
    )
    scheduled = np.asarray(scheduled)
    victim_of = np.asarray(victim_of)
    admitted: List[str] = []
    victims: Dict[str, str] = {}
    victim_node: Dict[str, str] = {}
    for row, key in enumerate(pod_names):
        if key is None:
            continue
        if scheduled[row]:
            admitted.append(key)
        evictor = int(victim_of[row])
        if evictor >= 0:
            victims[key] = pod_names[evictor] or ""
            node_row = int(pod_node[row])
            victim_node[key] = (
                node_names[node_row]
                if 0 <= node_row < len(node_names)
                else ""
            ) or ""
    return {
        "route": route,
        "admitted": sorted(admitted),
        "evictions": [
            {
                "pod": victim,
                "reason": EVICTION_PREEMPTED_BY,
                "by": victims[victim],
                "node": victim_node[victim],
            }
            for victim in sorted(victims)
        ],
    }


def replay_tick(
    state: ReconstructedState,
    explain_rec: Optional[Dict[str, Any]],
    explain_line: Optional[str] = None,
) -> Dict[str, Any]:
    """One tick's verdict: {'tick', 'divergence': [findings], 'replayed'}.
    Empty divergence list = the recorded decisions re-derive exactly."""
    divergence: List[str] = []
    replayed = False
    if explain_line is not None and state.explain_sha256:
        got = sha256_hex(explain_line)
        if got != state.explain_sha256:
            divergence.append(
                "explain-ledger line hash "
                f"{got[:12]} != journaled {state.explain_sha256[:12]} — the "
                "ledger on disk is not the ledger that was recorded"
            )
    recorded = None if explain_rec is None else explain_rec.get("preemption")
    eligible = state.ctx.get("preempt_eligible")
    if recorded is None and eligible is None:
        return {"tick": state.tick, "divergence": divergence,
                "replayed": False}
    if recorded is None or eligible is None:
        divergence.append(
            "preemption context mismatch: journal eligible="
            f"{eligible is not None} vs ledger section={recorded is not None}"
        )
        return {"tick": state.tick, "divergence": divergence,
                "replayed": False}
    derived = _replan(state, list(eligible))
    # the live route is dispatch provenance (arena vs cold rung), not
    # state — splice it, then require byte equality on the decisions
    derived["route"] = recorded.get("route")
    # actuated evictions: victims minus scale-up-covered evictors minus
    # API failures — coverage and failures are journaled context, the
    # victim set itself is RE-DERIVED (preempt_covered is present exactly
    # when the live tick actuated, i.e. when it had victims)
    covered = state.ctx.get("preempt_covered")
    if covered is not None:
        cov = set(covered)
        failed = set(state.ctx.get("preempt_evict_failed") or ())
        victims = {row["pod"]: row["by"] for row in derived["evictions"]}
        derived["evicted"] = [
            victim
            for victim in sorted(victims)
            if victims[victim] not in cov and victim not in failed
        ]
    replayed = True
    a, b = _strict(derived), _strict(recorded)
    if a != b:
        divergence.append(f"preemption section diverged: replay={a} != "
                          f"recorded={b}")
    return {"tick": state.tick, "divergence": divergence,
            "replayed": replayed}


def replay_journal(
    reader: JournalReader,
    explain_records: List[Dict[str, Any]],
    explain_lines: Optional[List[str]] = None,
    tick: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Replay every journaled tick (or one) against the decision ledger."""
    by_tick: Dict[int, Dict[str, Any]] = {
        rec["tick"]: rec for rec in explain_records if "tick" in rec
    }
    lines_by_tick: Dict[int, str] = {}
    if explain_lines is not None:
        for rec, line in zip(explain_records, explain_lines):
            if "tick" in rec:
                lines_by_tick[rec["tick"]] = line
    results: List[Dict[str, Any]] = []
    for t in reader.ticks():
        if tick is not None and t != tick:
            continue
        state = reader.reconstruct(t)
        rec = by_tick.get(t)
        result = replay_tick(state, rec, lines_by_tick.get(t))
        if rec is None and state.explain_sha256:
            result["divergence"].append(
                "journaled tick missing from the decision ledger"
            )
        results.append(result)
    return results
