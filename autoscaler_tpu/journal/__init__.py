"""Black-box flight journal: delta-encoded per-tick state history,
time-travel reconstruction, and live-vs-replay divergence probes.

The ledgers that came before (explain/perf/slo) record *decisions*; this
package records the *state* that produced them — the packed snapshot
tensors, journaled per tick as keyframes plus PR 11's row-scatter deltas
through the same strict ``record_line`` choke, stamped with the options
fingerprint and the sha256 of the tick's decision line. On top of it:
``JournalReader.reconstruct`` (bit-exact SnapshotTensors twin with typed
corruption errors), the reconstruct/diff/replay CLI (``__main__``), the
gated /journalz endpoint, and the in-loop divergence probe
(``--journal-probe-interval``).

Same determinism contract as the other rings: every journaled value is a
pure function of the tick's packed state, so two loadgen replays of one
scenario write byte-identical journals (hack/verify.sh gates on exactly
that, then replays every tick against the decision ledger).

Dependency-free at import time (stdlib + numpy): the fit/preemption
kernels are reached lazily by the probe and replay paths, never at
import.
"""
from autoscaler_tpu.journal.ledger import (
    KEYFRAME_REASONS,
    SCHEMA,
    dump_jsonl,
    load_jsonl,
    record_line,
    stable_json,
    summarize,
    validate_records,
)
from autoscaler_tpu.journal.reader import (
    JournalError,
    JournalReader,
    MissingKeyframeError,
    OutOfOrderTickError,
    ReconstructedState,
    SchemaDriftError,
    TruncatedJournalError,
)
from autoscaler_tpu.journal.recorder import JournalRecorder

__all__ = [
    "JournalError",
    "JournalReader",
    "JournalRecorder",
    "KEYFRAME_REASONS",
    "MissingKeyframeError",
    "OutOfOrderTickError",
    "ReconstructedState",
    "SCHEMA",
    "SchemaDriftError",
    "TruncatedJournalError",
    "dump_jsonl",
    "load_jsonl",
    "record_line",
    "stable_json",
    "summarize",
    "validate_records",
]
