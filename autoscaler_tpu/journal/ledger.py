"""Flight-journal serialization and schema validation.

One journal line per control-loop tick: the tick's packed cluster state —
a full keyframe (every tensor field, name tables, the effective options
document) or a row-scatter delta against the previous line (PR 11's
``DeltaProgram`` shape: per-field axis-0 index lists plus payload rows) —
alongside the options fingerprint, the tick's trace/explain/perf ids, the
preemption replay context, and the sha256 of the tick's explain-ledger
line. Every value is a pure function of the tick's packed state, so two
loadgen replays of one scenario write byte-identical JSONL journals
(hack/verify.sh diffs them).

``validate_records`` is the machine-checked gate behind
``bench.py --journal-ledger``: beyond shape checks it enforces the
reconstruction invariants the subsystem exists for —

- the first record is a keyframe (a journal that opens on a delta can
  never be reconstructed) and every keyframe names its promotion reason;
- ticks increase strictly (an out-of-order tick silently corrupts every
  reconstruction after it);
- every record carries the options fingerprint and the explain-line hash
  (state history without decision provenance answers no incident).
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List

# /1: keyframe/delta state history over the packer's row-scatter delta
# format, options fingerprint per record, preemption replay context, and
# the explain-line hash that pins each state line to its decision line
SCHEMA = "autoscaler_tpu.journal.tick/1"

# the machine-readable field contract (graftlint GL017): change the
# field set → update this AND bump the version tag above
SCHEMA_FIELDS = {
    SCHEMA: {
        "required": (
            "tick",
            "kind",
            "options_fp",
            "explain_sha256",
            "ids",
            "ctx",
            "state",
        ),
        "optional": ("reason", "options"),
    },
}

# closed keyframe-promotion vocabulary: why a full keyframe was written
# instead of a delta (reseed:* mirrors the packer's full-repack reasons)
KEYFRAME_REASONS = frozenset({
    "init",
    "interval",
    "shape_change",
    "options_change",
    "reseed:init",
    "reseed:schema_change",
    "reseed:capacity_growth",
})


def stable_json(doc: Any) -> str:
    """Byte-stable one-line JSON (sorted keys, tight separators; exotic
    values degrade to str rather than failing the serving handler)."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"), default=str)


def record_line(rec: Dict[str, Any]) -> str:
    """One journal line (newline-terminated) for one tick's state record.

    STRICT serialization, unlike the /journalz serving path: a non-JSON
    value leaking into the journal (a numpy scalar from the codec, say)
    must fail at the writer, not be silently coerced to a quoted string
    that passes the byte-diff gate with the wrong type."""
    return (
        json.dumps(rec, sort_keys=True, separators=(",", ":")) + "\n"
    )


def dump_jsonl(records: Iterable[Dict[str, Any]], path: str) -> int:
    n = 0
    with open(path, "w") as f:
        for rec in records:
            f.write(record_line(rec))
            n += 1
    return n


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from None
    return records


def _check_state(i: int, rec: Dict[str, Any], errors: List[str]) -> None:
    """Keyframes carry full fields + name tables; deltas carry ops."""
    where = f"record {i}"
    state = rec.get("state")
    if not isinstance(state, dict):
        errors.append(f"{where}: state must be an object")
        return
    kind = rec.get("kind")
    if kind == "keyframe":
        fields = state.get("fields")
        if not isinstance(fields, dict) or not fields:
            errors.append(f"{where}: keyframe carries no tensor fields")
        else:
            for name, arr in fields.items():
                if not isinstance(arr, dict) or not all(
                    k in arr for k in ("dtype", "shape", "b64")
                ):
                    errors.append(
                        f"{where}: field {name!r} missing dtype/shape/b64"
                    )
        names = state.get("names")
        if not isinstance(names, dict) or not all(
            isinstance(names.get(k), list) for k in ("pods", "nodes", "groups")
        ):
            errors.append(f"{where}: keyframe missing full name tables")
        if not isinstance(rec.get("options"), dict):
            errors.append(f"{where}: keyframe missing the options document")
    elif kind == "delta":
        ops = state.get("ops")
        if not isinstance(ops, list):
            errors.append(f"{where}: delta.ops must be a list")
            return
        for j, op in enumerate(ops):
            at = f"{where} op {j}"
            if not isinstance(op, dict) or not isinstance(
                op.get("field"), str
            ):
                errors.append(f"{at}: op does not name its field")
                continue
            if not isinstance(op.get("payload"), dict):
                errors.append(f"{at}: op carries no payload")


def validate_records(records: Iterable[Any]) -> List[str]:
    """Validate a journal; returns error strings (empty = valid). Checks
    the record schema, strict tick monotonicity, the keyframe-first and
    keyframe-reason invariants, and per-record provenance (options
    fingerprint + explain-line hash)."""
    errors: List[str] = []
    last_tick = None
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            errors.append(f"record {i}: not an object")
            continue
        if rec.get("schema") != SCHEMA:
            errors.append(
                f"record {i}: schema {rec.get('schema')!r} != {SCHEMA!r}"
            )
        tick = rec.get("tick")
        if not isinstance(tick, int):
            errors.append(f"record {i}: tick must be an int")
        elif last_tick is not None and tick <= last_tick:
            errors.append(
                f"record {i}: tick {tick} not increasing (prev {last_tick})"
            )
        if isinstance(tick, int):
            last_tick = tick
        kind = rec.get("kind")
        if kind not in ("keyframe", "delta"):
            errors.append(f"record {i}: kind {kind!r} not keyframe|delta")
        if i == 0 and kind != "keyframe":
            errors.append(
                "record 0: journal must open on a keyframe (a leading "
                "delta can never be reconstructed)"
            )
        if kind == "keyframe" and rec.get("reason") not in KEYFRAME_REASONS:
            errors.append(
                f"record {i}: keyframe reason {rec.get('reason')!r} outside "
                "the closed promotion vocabulary"
            )
        fp = rec.get("options_fp")
        if not isinstance(fp, str) or not fp:
            errors.append(f"record {i}: missing options fingerprint")
        if not isinstance(rec.get("ctx"), dict):
            errors.append(f"record {i}: ctx must be an object")
        if kind == "keyframe" and not isinstance(rec.get("options"), dict):
            errors.append(
                f"record {i}: keyframe must carry its options document "
                "(the reconstruction anchor)"
            )
        if not isinstance(rec.get("explain_sha256"), str):
            errors.append(f"record {i}: missing explain-line hash")
        ids = rec.get("ids")
        if not isinstance(ids, dict) or not all(
            isinstance(ids.get(k), int) for k in ("trace", "explain", "perf")
        ):
            errors.append(f"record {i}: ids must carry trace/explain/perf")
        _check_state(i, rec, errors)
    return errors


def summarize(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a journal into the figures bench.py and the loadgen
    scorer report: tick/keyframe/delta counts, keyframe promotion reasons,
    delta-op volume, and the encoded state bytes shipped."""
    ticks = 0
    keyframes = 0
    deltas = 0
    delta_ops = 0
    reasons: Dict[str, int] = {}
    state_bytes = 0
    for rec in records:
        ticks += 1
        state = rec.get("state", {})
        if rec.get("kind") == "keyframe":
            keyframes += 1
            reason = str(rec.get("reason"))
            reasons[reason] = reasons.get(reason, 0) + 1
            for arr in state.get("fields", {}).values():
                state_bytes += len(arr.get("b64", ""))
        else:
            deltas += 1
            ops = state.get("ops", ())
            delta_ops += len(ops)
            for op in ops:
                state_bytes += len(op.get("payload", {}).get("b64", ""))
    return {
        "ticks": ticks,
        "keyframes": keyframes,
        "deltas": deltas,
        "delta_ops": delta_ops,
        "keyframe_reasons": {k: reasons[k] for k in sorted(reasons)},
        "state_b64_bytes": state_bytes,
    }
