"""CLI process entrypoint: flags → options, HTTP observability server, and
the reconcile loop.

Reference: cluster-autoscaler/main.go — flag surface :92-227,
createAutoscalingOptions :229-337, metrics/health-check/snapshotz HTTP
server :508-523, the scan-interval loop :471-489. Leader election (:525-573)
runs under --leader-elect: a coordination.k8s.io Lease elects one active
replica (utils/leaderelection.LeaderElector + KubeLease); the process is
stateless so failover needs no handover — a follower simply waits for the
lease and rebuilds its world from the next LIST.

Usage:
    python -m autoscaler_tpu.main --provider=test --scan-interval=10 \
        --expander=least-waste --max-nodes-total=100 --address=:8085
"""
from __future__ import annotations

import argparse
import json
import logging
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.fleet.buckets import (
    DEFAULT_ARENA_BUCKETS as _ARENA_DEFAULT_BUCKETS,
    DEFAULT_BUCKETS as _FLEET_DEFAULT_BUCKETS,
)


def _bool_flag(s: str) -> bool:
    """Accept the usual spellings; reject typos instead of silently
    defaulting (an operator's '--x=0' must not read as True)."""
    v = s.strip().lower()
    if v in ("true", "1", "yes", "on"):
        return True
    if v in ("false", "0", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {s!r}")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-autoscaler", description=__doc__)
    # the reference's most-used flags (main.go:92-227), same semantics
    p.add_argument("--scan-interval", type=float, default=10.0)
    p.add_argument("--v", type=int, default=0, help="log verbosity (klog -v)")
    p.add_argument("--max-nodes-total", type=int, default=0)
    p.add_argument("--cores-total", default="0:320000")
    p.add_argument("--memory-total", default="0:6400000")
    p.add_argument("--estimator", default="binpacking")
    p.add_argument("--expander", default="random",
                   help="comma-separated chain, e.g. priority,least-waste")
    p.add_argument("--expander-priority-config-file", default="",
                   help="hot-reloaded YAML/JSON {priority: [group regexes]} "
                        "file for the priority expander")
    p.add_argument("--expander-priority-config-map", default="",
                   help="live ConfigMap (in --namespace) with a 'priorities' "
                        "key for the priority expander; the reference's "
                        "cluster-autoscaler-priority-expander. Needs "
                        "--kube-api. Takes precedence over the file.")
    p.add_argument("--max-nodes-per-scaleup", type=int, default=1000)
    p.add_argument("--balance-similar-node-groups", action="store_true")
    p.add_argument("--scale-down-enabled", type=_bool_flag, default=True)
    p.add_argument("--scale-down-delay-after-add", type=float, default=600.0)
    p.add_argument("--scale-down-delay-after-delete", type=float, default=0.0)
    p.add_argument("--scale-down-delay-after-failure", type=float, default=180.0)
    p.add_argument("--scale-down-unneeded-time", type=float, default=600.0)
    p.add_argument("--scale-down-unready-time", type=float, default=1200.0)
    p.add_argument("--scale-down-utilization-threshold", type=float, default=0.5)
    p.add_argument("--scale-down-non-empty-candidates-count", type=int, default=30)
    p.add_argument("--scale-down-candidates-pool-ratio", type=float, default=0.1)
    p.add_argument("--scale-down-candidates-pool-min-count", type=int, default=50)
    p.add_argument("--max-empty-bulk-delete", type=int, default=10)
    p.add_argument("--max-graceful-termination-sec", type=float, default=600.0)
    p.add_argument("--max-total-unready-percentage", type=float, default=45.0)
    p.add_argument("--ok-total-unready-count", type=int, default=3)
    p.add_argument("--max-node-provision-time", type=float, default=900.0)
    p.add_argument("--enforce-node-group-min-size", action="store_true")
    p.add_argument("--new-pod-scale-up-delay", type=float, default=0.0)
    p.add_argument("--expendable-pods-priority-cutoff", type=int, default=-10)
    p.add_argument("--provider", "--cloud-provider", default="test",
                   help="cloud provider (reference --cloud-provider): test, "
                        "gce, clusterapi (MachineDeployment/MachineSet "
                        "scaling over the management cluster's CRD API), "
                        "externalgrpc (native tensor protocol), or "
                        "externalgrpc-ref (the reference's externalgrpc.proto "
                        "wire format — existing provider binaries plug in "
                        "unmodified)")
    p.add_argument("--cloud-config", default="",
                   help="provider config file (reference --cloud-config); "
                        "for externalgrpc*: YAML with an `address:` key")
    p.add_argument("--address", default=":8085", help="observability HTTP bind")
    p.add_argument("--profiling", action="store_true",
                   help="expose /debug/pprof/* (main.go:518-520)")
    p.add_argument("--health-check-max-inactivity", "--max-inactivity",
                   type=float, default=600.0)
    p.add_argument("--health-check-max-failing-time", "--max-failing-time",
                   type=float, default=900.0)
    p.add_argument("--max-consecutive-run-once-failures", type=int, default=0,
                   help="crash-only loop: hard-exit (abnormally, for the "
                        "supervisor to restart) after N consecutive "
                        "run_once failures; 0 = never, rely on the "
                        "health-check failing deadline")
    p.add_argument("--run-once-soft-deadline", type=float, default=0.0,
                   help="watchdog soft deadline per loop tick in seconds: "
                        "exceeded -> all-thread stack dump to stderr; "
                        "0 = auto (max of 4x scan interval and 60s)")
    p.add_argument("--rpc-address", action="append", default=[],
                   help="sidecar gRPC endpoint(s) for embedders that build "
                        "a TpuSimulationClient (repeat, or comma-separate, "
                        "for failover: the client fails over on "
                        "UNAVAILABLE/drain with jittered bounded backoff)")
    p.add_argument("--rpc-hedge", type=_bool_flag, default=False,
                   help="hedge idempotent Estimate/BatchEstimate against "
                        "the next --rpc-address endpoint after a "
                        "p99-derived delay (first answer wins, loser "
                        "cancelled; never past the caller's deadline)")
    p.add_argument("--rpc-default-deadline", type=float, default=30.0,
                   help="default deadline for sidecar RPCs without an "
                        "explicit timeout, so a wedged sidecar fails the "
                        "call instead of hanging the loop")
    p.add_argument("--kernel-breaker-failure-threshold", type=int, default=3,
                   help="consecutive failures tripping an estimator kernel "
                        "rung's circuit breaker open")
    p.add_argument("--kernel-breaker-cooldown", type=float, default=120.0,
                   help="seconds a tripped kernel rung stays open before a "
                        "half-open probe re-tests it")
    p.add_argument("--kube-client-get-retries", type=int, default=2,
                   help="transient-failure retries for idempotent control-"
                        "plane GETs (429/5xx honoring Retry-After, "
                        "transport errors); 0 disables")
    p.add_argument("--max-iterations", type=int, default=0,
                   help="stop after N loops (0 = forever); for testing")
    p.add_argument("--initial-node-group-backoff-duration", type=float, default=300.0)
    p.add_argument("--max-node-group-backoff-duration", type=float, default=1800.0)
    p.add_argument("--node-group-backoff-reset-timeout", type=float, default=10800.0)
    p.add_argument("--scale-down-unready-enabled",
                   type=_bool_flag, default=True)
    p.add_argument("--node-delete-delay-after-taint", type=float, default=0.0,
                   help="pause between taint and delete; 0 (default) because "
                        "the actuation wave is synchronous here (see options.py)")
    p.add_argument("--cordon-node-before-terminating", action="store_true")
    p.add_argument("--ignore-daemonsets-utilization", action="store_true")
    p.add_argument("--ignore-taint", action="append", default=[],
                   help="startup taint key ignored in templates (repeatable)")
    p.add_argument("--balancing-label", action="append", default=[],
                   help="compare node groups for similarity using ONLY "
                        "these label values (reference --balancing-label; "
                        "repeatable; overrides the resource comparator)")
    p.add_argument("--balancing-ignore-label", action="append", default=[],
                   help="extra label excluded from group similarity (repeatable)")
    p.add_argument("--node-group-auto-discovery", action="append", default=[],
                   help="provider auto-discovery spec (repeatable)")
    p.add_argument("--nodes", action="append", default=[],
                   help="node group spec min:max:<MIG url> (repeatable; "
                        "gce provider, reference --nodes)")
    p.add_argument("--gce-project", default="",
                   help="GCP project for the gce provider's auto-discovery")
    p.add_argument("--gce-api-url", default="",
                   help="compute API base URL override (tests/proxies); "
                        "empty = https://compute.googleapis.com/compute/v1")
    p.add_argument("--gce-token-file", default="",
                   help="file holding a bearer token for the compute API, "
                        "re-read per request so an external refresher "
                        "(e.g. a sidecar fetching metadata-server tokens) "
                        "just works; REQUIRED with --provider=gce")
    p.add_argument("--kube-api", "--kubernetes", default="",
                   help="control plane binding: 'in-cluster', or an API "
                        "server URL (empty with --provider=test uses the "
                        "in-memory fake)")
    p.add_argument("--kubeconfig", default="",
                   help="kubeconfig file for out-of-cluster runs (token- or "
                        "cert-based credentials; exec plugins are not run). "
                        "Mutually exclusive with a --kube-api URL.")
    p.add_argument("--max-drain-parallelism", type=int, default=1,
                   help="concurrent node drains (actuator worker pool)")
    p.add_argument("--max-scale-down-parallelism", type=int, default=10)
    p.add_argument("--scale-down-simulation-timeout", type=float, default=30.0)
    p.add_argument("--max-pod-eviction-time", type=float, default=120.0)
    p.add_argument("--max-bulk-soft-taint-count", type=int, default=10)
    p.add_argument("--max-bulk-soft-taint-time", type=float, default=3.0)
    p.add_argument("--unremovable-node-recheck-timeout", type=float, default=300.0)
    p.add_argument("--node-deletion-batcher-interval", type=float, default=0.0,
                   help="0 = flush per add (reference default)")
    p.add_argument("--skip-nodes-with-system-pods", type=_bool_flag, default=True)
    p.add_argument("--skip-nodes-with-local-storage", type=_bool_flag, default=True)
    p.add_argument("--skip-nodes-with-custom-controller-pods",
                   type=_bool_flag, default=True)
    p.add_argument("--min-replica-count", type=int, default=0)
    p.add_argument("--ignore-mirror-pods-utilization", action="store_true")
    p.add_argument("--scale-up-from-zero", type=_bool_flag, default=True)
    p.add_argument("--node-autoprovisioning-enabled", action="store_true")
    p.add_argument("--max-autoprovisioned-node-group-count", type=int, default=15)
    p.add_argument("--emit-per-nodegroup-metrics", action="store_true")
    p.add_argument("--user-agent", default="tpu-autoscaler")
    p.add_argument("--kube-client-qps", type=float, default=5.0,
                   help="client-side request rate limit (0 disables)")
    p.add_argument("--kube-client-burst", type=int, default=10)
    p.add_argument("--leader-elect", type=_bool_flag, default=False,
                   help="run under Lease-based leader election (needs a "
                        "control-plane binding); the reference defaults "
                        "this ON in-cluster")
    p.add_argument("--leader-elect-lease-name", default="tpu-autoscaler")
    p.add_argument("--parallel-drain", type=_bool_flag, default=True,
                   help="accepted for compatibility: the planner here IS "
                        "the reference's parallel-drain path (no legacy mode)")
    p.add_argument("--daemonset-eviction-for-empty-nodes",
                   type=_bool_flag, default=False)
    p.add_argument("--daemonset-eviction-for-occupied-nodes",
                   type=_bool_flag, default=True)
    p.add_argument("--max-nodegroup-binpacking-duration", type=float,
                   default=10.0, help="per-group estimate budget (main.go:216)")
    p.add_argument("--node-info-cache-expire-time", type=float, default=60.0,
                   help="template NodeInfo cache TTL seconds")
    p.add_argument("--compile-cache-dir", "--jax-compilation-cache-dir",
                   dest="compile_cache_dir",
                   default="/tmp/autoscaler_tpu_xla_cache",
                   help="persistent XLA compile cache (amortizes first-loop "
                        "kernel compiles across restarts; with the arena "
                        "prewarm, makes the first real tick compile-free); "
                        "empty disables")
    p.add_argument("--arena-enabled", type=_bool_flag, default=False,
                   help="resident device arena: keep packed snapshot "
                        "tensors on-device across ticks and ship only "
                        "delta scatters for dirtied rows "
                        "(snapshot/arena.py, ROADMAP item 2)")
    p.add_argument("--arena-buckets", default=_ARENA_DEFAULT_BUCKETS,
                   help="comma-separated PxNxR power-of-two prewarm "
                        "buckets for the arena apply-kernel ladder (same "
                        "grammar as --fleet-shape-buckets; R is a cap)")
    p.add_argument("--preemption-enabled", type=_bool_flag, default=False,
                   help="priority-aware eviction packing each tick "
                        "(autoscaler_tpu/preempt via ops/preempt.py): plan "
                        "and actuate evictions of strictly-lower-priority "
                        "residents for pending pods no node fits outright; "
                        "off reproduces today's decisions byte-for-byte")
    p.add_argument("--preemption-churn-weight", type=float, default=0.0,
                   help="expander score penalty per eviction a scale-up "
                        "option leaves standing (0 = churn-blind ranking)")
    p.add_argument("--debugging-snapshot-enabled", type=_bool_flag, default=True,
                   help="serve /snapshotz captures")
    p.add_argument("--tracing-enabled", type=_bool_flag, default=True,
                   help="serve /tracez (flight-recorder span trees; the "
                        "tracer itself always runs, bounded)")
    p.add_argument("--trace-ring-size", type=int, default=64,
                   help="how many recent tick traces the in-memory flight "
                        "recorder keeps")
    p.add_argument("--trace-slow-tick-threshold", type=float, default=2.0,
                   help="ticks slower than this (wall seconds) get their "
                        "full span tree logged and the trace pinned in the "
                        "flight recorder; 0 disables")
    p.add_argument("--jax-profiler-dir", default="",
                   help="capture a jax profiler session per tick into "
                        "<dir>/tick_<id> — device timeline keyed by the "
                        "same tick id as the host trace (debug tool)")
    p.add_argument("--perf-enabled", type=_bool_flag, default=True,
                   help="serve /perfz (per-tick perf records: compile "
                        "telemetry, cost model, residency; the observatory "
                        "itself always runs, bounded)")
    p.add_argument("--perf-cost-model", type=_bool_flag, default=False,
                   help="capture the XLA cost model per new (kernel route, "
                        "shape signature) — one extra AOT compile per new "
                        "signature, process-cached")
    p.add_argument("--perf-ring-size", type=int, default=64,
                   help="how many recent per-tick perf records the "
                        "in-memory ring keeps")
    p.add_argument("--explain-enabled", type=_bool_flag, default=True,
                   help="serve /explainz (per-tick decision records: "
                        "constraint attribution, expander scoring table, "
                        "skip/backoff state; the explainer itself always "
                        "runs, bounded)")
    p.add_argument("--explain-ring-size", type=int, default=64,
                   help="how many recent per-tick decision records the "
                        "in-memory ring keeps")
    p.add_argument("--journal-enabled", type=_bool_flag, default=True,
                   help="serve /journalz (per-tick keyframe/delta state "
                        "records — the black-box flight journal; the "
                        "recorder itself always runs, bounded)")
    p.add_argument("--journal-ring-size", type=int, default=64,
                   help="how many recent per-tick state records the "
                        "in-memory journal ring keeps")
    p.add_argument("--journal-keyframe-interval", type=int, default=16,
                   help="write a full journal keyframe every K ticks even "
                        "without a packer reseed or shape change")
    p.add_argument("--journal-probe-interval", type=int, default=0,
                   help="every N ticks, reconstruct the newest journaled "
                        "tick and bit-compare it (and its fit verdicts) "
                        "against the live packer state; drift becomes a "
                        "metric + trace event (0 = off)")
    p.add_argument("--journal-path", default="",
                   help="append the flight journal as JSONL to this file "
                        "for post-mortem reconstruct/diff/replay "
                        "(python -m autoscaler_tpu.journal; empty = "
                        "in-memory ring only)")
    p.add_argument("--fleet-coalesce-window-ms", type=float, default=5.0,
                   help="fleet serving: how long the coalescer waits after "
                        "the first queued estimate request before "
                        "dispatching the batch (autoscaler_tpu/fleet)")
    p.add_argument("--fleet-shape-buckets", default=_FLEET_DEFAULT_BUCKETS,
                   help="fleet serving: comma-separated PxGxR power-of-two "
                        "shape buckets requests pad into — the closed "
                        "compile-cache key set of the service")
    p.add_argument("--fleet-prewarm", type=_bool_flag, default=True,
                   help="fleet serving: compile every configured bucket at "
                        "startup so the first real request never compiles")
    p.add_argument("--fleet-batch-scenarios", type=int, default=8,
                   help="fleet serving: scenario slots per coalesced batch "
                        "(the batched kernel's leading axis)")
    p.add_argument("--fleet-max-tenant-labels", type=int, default=64,
                   help="fleet serving: distinct tenant labels admitted on "
                        "the per-tenant SLI metric series before later "
                        "tenants aggregate into __overflow__ (cardinality "
                        "guard for /metrics; 0 = unbounded)")
    p.add_argument("--fleet-max-queue-depth", type=int, default=0,
                   help="fleet overload armor: shed submits typed "
                        "(RESOURCE_EXHAUSTED + retry-after) past this "
                        "coalescing-queue depth; 0 = unbounded")
    p.add_argument("--fleet-tenant-qps", type=float, default=0.0,
                   help="fleet overload armor: per-tenant token-bucket "
                        "quota in requests/second (0 = no quotas); "
                        "over-quota submits shed typed with retry-after")
    p.add_argument("--fleet-tenant-burst", type=float, default=0.0,
                   help="fleet overload armor: token-bucket burst "
                        "capacity (0 = max(qps, 1))")
    p.add_argument("--fleet-tenant-tiers", default="",
                   help="tenant quota tiers, JSON tier name -> {qps, "
                        "burst, queue_share, default_deadline_s, "
                        "shed_priority, tenants} incl. a 'default' "
                        "catch-all; supersedes --fleet-tenant-qps with "
                        "per-tier budgets and tier-priority shed order")
    p.add_argument("--fleet-drain-grace-s", type=float, default=5.0,
                   help="sidecar drain: grace server.stop() allows "
                        "in-flight RPCs after admission closed and the "
                        "coalescer flushed (SIGTERM/preStop path)")
    p.add_argument("--slo-enabled", type=_bool_flag, default=True,
                   help="serve /sloz (per-SLO multi-window burn rates and "
                        "window history; the SLO engine itself always "
                        "runs, bounded)")
    p.add_argument("--gym-rollout-workers", type=int, default=4,
                   help="policy gym: concurrent candidate rollouts per "
                        "tuning stage (autoscaler_tpu/gym)")
    p.add_argument("--gym-objective-weights", default="",
                   help='policy gym: objective weights as '
                        '"slo=1,cost=8,churn=0.25" (empty = scorer '
                        "defaults); humans and the tuner read the same "
                        "scalar")
    p.add_argument("--gym-fleet-coalesce", type=_bool_flag, default=True,
                   help="policy gym: route rollout estimator dispatches "
                        "through the shared fleet coalescer (scores are "
                        "identical either way)")
    p.add_argument("--record-duplicated-events", type=_bool_flag, default=False,
                   help="post every event instead of suppressing repeats "
                        "within the correlator window")
    p.add_argument("--gce-concurrent-refreshes", type=int, default=1,
                   help="concurrent MIG listings per refresh (main.go:194)")
    p.add_argument("--force-ds", type=_bool_flag, default=False,
                   help="charge suitable pending DaemonSets onto new-node "
                        "capacity (reference --force-ds)")
    p.add_argument("--grpc-expander-url", default="",
                   help="external gRPC expander target (expander grpc in chain)")
    p.add_argument("--cluster-name", default="")
    p.add_argument("--namespace", default="kube-system")
    p.add_argument("--status-config-map-name", default="cluster-autoscaler-status")
    p.add_argument("--write-status-configmap",
                   type=_bool_flag, default=True)
    return p


def options_from_args(args: argparse.Namespace) -> AutoscalingOptions:
    """createAutoscalingOptions analog (main.go:229)."""
    cores_min, cores_max = (float(x) for x in args.cores_total.split(":"))
    mem_min, mem_max = (float(x) for x in args.memory_total.split(":"))
    opts = AutoscalingOptions(
        scan_interval_s=args.scan_interval,
        max_nodes_total=args.max_nodes_total,
        min_cores_total=cores_min * 1000,
        max_cores_total=cores_max * 1000,
        min_memory_total=mem_min * 1024,
        max_memory_total_mib=mem_max * 1024,
        estimator=args.estimator,
        expander=args.expander,
        priority_config_file=args.expander_priority_config_file,
        priority_config_map=args.expander_priority_config_map,
        max_nodes_per_scaleup=args.max_nodes_per_scaleup,
        balance_similar_node_groups=args.balance_similar_node_groups,
        scale_down_enabled=args.scale_down_enabled,
        scale_down_delay_after_add_s=args.scale_down_delay_after_add,
        scale_down_delay_after_delete_s=args.scale_down_delay_after_delete,
        scale_down_delay_after_failure_s=args.scale_down_delay_after_failure,
        scale_down_utilization_threshold=args.scale_down_utilization_threshold,
        scale_down_non_empty_candidates_count=args.scale_down_non_empty_candidates_count,
        scale_down_candidates_pool_ratio=args.scale_down_candidates_pool_ratio,
        scale_down_candidates_pool_min_count=args.scale_down_candidates_pool_min_count,
        max_empty_bulk_delete=args.max_empty_bulk_delete,
        max_graceful_termination_s=args.max_graceful_termination_sec,
        max_total_unready_percentage=args.max_total_unready_percentage,
        ok_total_unready_count=args.ok_total_unready_count,
        max_node_provision_time_s=args.max_node_provision_time,
        enforce_node_group_min_size=args.enforce_node_group_min_size,
        new_pod_scale_up_delay_s=args.new_pod_scale_up_delay,
        expendable_pods_priority_cutoff=args.expendable_pods_priority_cutoff,
        cloud_provider=args.provider,
        max_inactivity_s=args.health_check_max_inactivity,
        max_failing_time_s=args.health_check_max_failing_time,
        max_consecutive_run_once_failures=(
            args.max_consecutive_run_once_failures
        ),
        run_once_soft_deadline_s=args.run_once_soft_deadline,
        rpc_default_deadline_s=args.rpc_default_deadline,
        kernel_breaker_failure_threshold=args.kernel_breaker_failure_threshold,
        kernel_breaker_cooldown_s=args.kernel_breaker_cooldown,
        initial_node_group_backoff_duration_s=args.initial_node_group_backoff_duration,
        max_node_group_backoff_duration_s=args.max_node_group_backoff_duration,
        node_group_backoff_reset_timeout_s=args.node_group_backoff_reset_timeout,
        scale_down_unready_enabled=args.scale_down_unready_enabled,
        node_delete_delay_after_taint_s=args.node_delete_delay_after_taint,
        cordon_node_before_terminating=args.cordon_node_before_terminating,
        ignore_daemonsets_utilization=args.ignore_daemonsets_utilization,
        ignored_taints=list(args.ignore_taint),
        balancing_label_keys=list(args.balancing_label),
        balancing_extra_ignored_labels=list(args.balancing_ignore_label),
        node_group_auto_discovery=list(args.node_group_auto_discovery),
        cluster_name=args.cluster_name,
        config_namespace=args.namespace,
        status_config_map_name=args.status_config_map_name,
        write_status_configmap=args.write_status_configmap,
        max_drain_parallelism=args.max_drain_parallelism,
        max_scale_down_parallelism=args.max_scale_down_parallelism,
        scale_down_simulation_timeout_s=args.scale_down_simulation_timeout,
        max_pod_eviction_time_s=args.max_pod_eviction_time,
        max_bulk_soft_taint_count=args.max_bulk_soft_taint_count,
        max_bulk_soft_taint_time_s=args.max_bulk_soft_taint_time,
        unremovable_node_recheck_timeout_s=args.unremovable_node_recheck_timeout,
        node_deletion_batcher_interval_s=args.node_deletion_batcher_interval,
        skip_nodes_with_system_pods=args.skip_nodes_with_system_pods,
        skip_nodes_with_local_storage=args.skip_nodes_with_local_storage,
        skip_nodes_with_custom_controller_pods=(
            args.skip_nodes_with_custom_controller_pods
        ),
        min_replica_count=args.min_replica_count,
        ignore_mirror_pods_utilization=args.ignore_mirror_pods_utilization,
        scale_up_from_zero=args.scale_up_from_zero,
        node_autoprovisioning_enabled=args.node_autoprovisioning_enabled,
        max_autoprovisioned_node_group_count=(
            args.max_autoprovisioned_node_group_count
        ),
        record_per_node_group_metrics=args.emit_per_nodegroup_metrics,
        user_agent=args.user_agent,
        grpc_expander_url=args.grpc_expander_url,
        daemonset_eviction_for_empty_nodes=(
            args.daemonset_eviction_for_empty_nodes
        ),
        daemonset_eviction_for_occupied_nodes=(
            args.daemonset_eviction_for_occupied_nodes
        ),
        max_nodegroup_binpacking_duration_s=(
            args.max_nodegroup_binpacking_duration
        ),
        node_info_cache_expire_time_s=args.node_info_cache_expire_time,
        debugging_snapshot_enabled=args.debugging_snapshot_enabled,
        tracing_enabled=args.tracing_enabled,
        trace_ring_size=args.trace_ring_size,
        trace_slow_tick_threshold_s=args.trace_slow_tick_threshold,
        jax_profiler_dir=args.jax_profiler_dir,
        perf_enabled=args.perf_enabled,
        perf_cost_model=args.perf_cost_model,
        perf_ring_size=args.perf_ring_size,
        explain_enabled=args.explain_enabled,
        explain_ring_size=args.explain_ring_size,
        journal_enabled=args.journal_enabled,
        journal_ring_size=args.journal_ring_size,
        journal_keyframe_interval=args.journal_keyframe_interval,
        journal_probe_interval=args.journal_probe_interval,
        journal_path=args.journal_path,
        fleet_coalesce_window_ms=args.fleet_coalesce_window_ms,
        fleet_shape_buckets=args.fleet_shape_buckets,
        fleet_prewarm=args.fleet_prewarm,
        fleet_batch_scenarios=args.fleet_batch_scenarios,
        fleet_max_tenant_labels=args.fleet_max_tenant_labels,
        fleet_max_queue_depth=args.fleet_max_queue_depth,
        fleet_tenant_qps=args.fleet_tenant_qps,
        fleet_tenant_burst=args.fleet_tenant_burst,
        fleet_tenant_tiers=args.fleet_tenant_tiers,
        fleet_drain_grace_s=args.fleet_drain_grace_s,
        rpc_addresses=list(args.rpc_address),
        rpc_hedge=args.rpc_hedge,
        slo_enabled=args.slo_enabled,
        arena_enabled=args.arena_enabled,
        arena_buckets=args.arena_buckets,
        preemption_enabled=args.preemption_enabled,
        preemption_churn_weight=args.preemption_churn_weight,
        compile_cache_dir=args.compile_cache_dir,
        gym_rollout_workers=args.gym_rollout_workers,
        gym_objective_weights=args.gym_objective_weights,
        gym_fleet_coalesce=args.gym_fleet_coalesce,
        force_daemonsets=args.force_ds,
    )
    opts.node_group_defaults.scale_down_unneeded_time_s = args.scale_down_unneeded_time
    opts.node_group_defaults.scale_down_unready_time_s = args.scale_down_unready_time
    opts.node_group_defaults.scale_down_utilization_threshold = (
        args.scale_down_utilization_threshold
    )
    return opts


class ObservabilityServer:
    """/metrics, /health-check, /snapshotz, /status (main.go:508-523),
    /tracez, /perfz, /explainz, plus /debug/pprof/* when profiling is
    enabled (main.go:518-520)."""

    def __init__(self, autoscaler, address: str = ":8085", profiling: bool = False):
        host, _, port = address.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self.autoscaler = autoscaler
        self.profiling = profiling
        self._server: Optional[ThreadingHTTPServer] = None
        self._started_tracemalloc = False

    def start(self) -> int:
        autoscaler = self.autoscaler
        profiling = self.profiling

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: str, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/metrics":
                    # content negotiation: exemplars (trace-id suffixes on
                    # histogram buckets) are only legal in the OpenMetrics
                    # dialect — a classic 0.0.4 scraper gets the plain
                    # exposition, an OpenMetrics-aware one (Prometheus with
                    # exemplar storage) opts in via Accept
                    om_type = "application/openmetrics-text"
                    if om_type in (self.headers.get("Accept") or ""):
                        self._send(
                            200,
                            autoscaler.metrics.registry.expose(
                                openmetrics=True
                            ),
                            f"{om_type}; version=1.0.0; charset=utf-8",
                        )
                    else:
                        self._send(200, autoscaler.metrics.registry.expose())
                elif self.path == "/health-check":
                    ok, msg = autoscaler.health_check.healthy()
                    # degraded (kernel rungs tripped, decisions flowing on a
                    # lower rung) is visible but NOT unhealthy: restarting
                    # the process would not heal a faulting device, and the
                    # whole point of the ladder is staying alive through it
                    degraded = getattr(
                        autoscaler, "degraded_rungs", lambda: []
                    )()
                    if ok and degraded:
                        msg = f"{msg} (degraded: {','.join(degraded)})"
                    self._send(200 if ok else 500, msg)
                elif self.path == "/snapshotz":
                    if autoscaler.debugger is None:
                        self._send(404, "debugging snapshotter disabled")
                        return
                    autoscaler.debugger.request()
                    payload = autoscaler.debugger.get()
                    self._send(
                        200,
                        payload or json.dumps({"status": "armed for next loop"}),
                        "application/json",
                    )
                elif self.path.startswith("/tracez"):
                    # flight recorder (autoscaler_tpu/trace): gated like
                    # /snapshotz — the tracer always records, the endpoint
                    # is the opt-out
                    tracer = getattr(autoscaler, "tracer", None)
                    enabled = getattr(
                        autoscaler.options, "tracing_enabled", True
                    )
                    if tracer is None or tracer.recorder is None or not enabled:
                        self._send(404, "tracing disabled (--tracing-enabled)")
                        return
                    from urllib.parse import parse_qs, urlparse

                    url = urlparse(self.path)
                    if url.path.rstrip("/") not in ("", "/tracez"):
                        self._send(404, "not found")
                        return
                    q = parse_qs(url.query)
                    fmt = q.get("format", [""])[0]
                    raw_id = q.get("id", [None])[0]
                    trace_id = None
                    if raw_id is not None:
                        try:
                            trace_id = int(raw_id)
                        except ValueError:
                            self._send(400, f"bad trace id {raw_id!r}")
                            return
                    rec = tracer.recorder
                    if fmt == "chrome":
                        body = rec.chrome(trace_id)
                        if body is None:
                            self._send(404, f"no trace {trace_id}")
                            return
                        self._send(200, body, "application/json")
                    elif fmt:
                        self._send(400, f"unknown format {fmt!r}")
                    elif trace_id is not None:
                        body = rec.detail_json(trace_id)
                        if body is None:
                            self._send(404, f"no trace {trace_id}")
                            return
                        self._send(200, body, "application/json")
                    else:
                        self._send(200, rec.list_json(), "application/json")
                elif self.path.startswith("/perfz"):
                    # perf observatory (autoscaler_tpu/perf): gated like
                    # /tracez — the observatory always records, the
                    # endpoint is the opt-out
                    obs = getattr(autoscaler, "observatory", None)
                    enabled = getattr(
                        autoscaler.options, "perf_enabled", True
                    )
                    if obs is None or not enabled:
                        self._send(
                            404, "perf observatory disabled (--perf-enabled)"
                        )
                        return
                    from urllib.parse import parse_qs, urlparse

                    url = urlparse(self.path)
                    if url.path.rstrip("/") not in ("", "/perfz"):
                        self._send(404, "not found")
                        return
                    q = parse_qs(url.query)
                    raw_tick = q.get("tick", [None])[0]
                    if raw_tick is not None:
                        try:
                            tick = int(raw_tick)
                        except ValueError:
                            self._send(400, f"bad tick {raw_tick!r}")
                            return
                        body = obs.detail_json(tick)
                        if body is None:
                            self._send(404, f"no perf record for tick {tick}")
                            return
                        self._send(200, body, "application/json")
                    else:
                        self._send(200, obs.list_json(), "application/json")
                elif self.path.startswith("/explainz"):
                    # decision explainer (autoscaler_tpu/explain): gated
                    # like /perfz — the explainer always records, the
                    # endpoint is the opt-out
                    explainer = getattr(autoscaler, "explainer", None)
                    enabled = getattr(
                        autoscaler.options, "explain_enabled", True
                    )
                    if explainer is None or not enabled:
                        self._send(
                            404, "decision explainer disabled (--explain-enabled)"
                        )
                        return
                    from urllib.parse import parse_qs, urlparse

                    url = urlparse(self.path)
                    if url.path.rstrip("/") not in ("", "/explainz"):
                        self._send(404, "not found")
                        return
                    q = parse_qs(url.query)
                    raw_tick = q.get("tick", [None])[0]
                    pod = q.get("pod", [None])[0]
                    group = q.get("group", [None])[0]
                    if raw_tick is not None:
                        try:
                            tick = int(raw_tick)
                        except ValueError:
                            self._send(400, f"bad tick {raw_tick!r}")
                            return
                        body = explainer.detail_json(tick)
                        if body is None:
                            self._send(
                                404, f"no decision record for tick {tick}"
                            )
                            return
                        self._send(200, body, "application/json")
                    elif pod is not None:
                        self._send(200, explainer.pod_json(pod), "application/json")
                    elif group is not None:
                        self._send(
                            200, explainer.group_json(group), "application/json"
                        )
                    else:
                        self._send(200, explainer.list_json(), "application/json")
                elif self.path.startswith("/sloz"):
                    # SLO burn-rate engine (autoscaler_tpu/slo): gated like
                    # /perfz — the engine always computes windows, the
                    # endpoint is the opt-out
                    engine = getattr(autoscaler, "slo", None)
                    enabled = getattr(
                        autoscaler.options, "slo_enabled", True
                    )
                    if engine is None or not enabled:
                        self._send(
                            404, "SLO engine disabled (--slo-enabled)"
                        )
                        return
                    from urllib.parse import parse_qs, urlparse

                    url = urlparse(self.path)
                    if url.path.rstrip("/") not in ("", "/sloz"):
                        self._send(404, "not found")
                        return
                    q = parse_qs(url.query)
                    slo_name = q.get("slo", [None])[0]
                    if slo_name is not None:
                        body = engine.detail_json(slo_name)
                        if body is None:
                            self._send(
                                400,
                                f"unknown SLO {slo_name!r} (declared: "
                                f"{', '.join(engine.spec_names())})",
                            )
                            return
                        self._send(200, body, "application/json")
                    else:
                        self._send(200, engine.list_json(), "application/json")
                elif self.path.startswith("/journalz"):
                    # flight journal (autoscaler_tpu/journal): gated like
                    # /explainz — the recorder always journals, the
                    # endpoint is the opt-out. ?tick= drills into one
                    # record, ?diff=a,b renders the semantic state diff
                    # between two reconstructed ticks
                    journal = getattr(autoscaler, "journal", None)
                    enabled = getattr(
                        autoscaler.options, "journal_enabled", True
                    )
                    if journal is None or not enabled:
                        self._send(
                            404, "flight journal disabled (--journal-enabled)"
                        )
                        return
                    from urllib.parse import parse_qs, urlparse

                    url = urlparse(self.path)
                    if url.path.rstrip("/") not in ("", "/journalz"):
                        self._send(404, "not found")
                        return
                    q = parse_qs(url.query)
                    tick_raw = q.get("tick", [None])[0]
                    diff_raw = q.get("diff", [None])[0]
                    if tick_raw is not None:
                        try:
                            tick = int(tick_raw)
                        except ValueError:
                            self._send(400, f"bad tick {tick_raw!r}")
                            return
                        body = journal.detail_json(tick)
                        if body is None:
                            self._send(
                                404, f"no journal record for tick {tick}"
                            )
                            return
                        self._send(200, body, "application/json")
                    elif diff_raw is not None:
                        try:
                            tick_a, tick_b = (
                                int(t) for t in diff_raw.split(",")
                            )
                        except ValueError:
                            self._send(
                                400, f"bad diff {diff_raw!r} (want a,b)"
                            )
                            return
                        self._send(
                            200,
                            journal.diff_json(tick_a, tick_b),
                            "application/json",
                        )
                    else:
                        self._send(200, journal.list_json(), "application/json")
                elif self.path == "/status":
                    from autoscaler_tpu.clusterstate.status import build_status

                    explainer = getattr(autoscaler, "explainer", None)
                    self._send(
                        200,
                        build_status(
                            autoscaler.csr, time.time(),
                            autoscaler.options.cluster_name,
                            degraded_rungs=autoscaler.degraded_rungs(),
                            last_decision=(
                                explainer.last_decision_summary()
                                if explainer is not None else None
                            ),
                        ).render(),
                    )
                elif self.path.startswith("/debug/pprof"):
                    if not profiling:
                        self._send(404, "profiling disabled (--profiling)")
                        return
                    from urllib.parse import parse_qs, urlparse

                    from autoscaler_tpu.utils import pprof

                    url = urlparse(self.path)
                    if url.path.rstrip("/") == "/debug/pprof":
                        self._send(200, pprof.PPROF_INDEX)
                    elif url.path == "/debug/pprof/profile":
                        q = parse_qs(url.query)
                        try:
                            secs = float(q.get("seconds", ["5"])[0])
                        except ValueError:
                            self._send(400, "bad seconds parameter")
                            return
                        if not (0 < secs <= 60):
                            self._send(400, "seconds must be in (0, 60]")
                            return
                        if not pprof.PROFILE_LOCK.acquire(blocking=False):
                            self._send(429, "a profile is already running")
                            return
                        try:
                            body = pprof.SamplingProfiler().run(secs)
                        finally:
                            pprof.PROFILE_LOCK.release()
                        self._send(200, body)
                    elif url.path == "/debug/pprof/heap":
                        self._send(200, pprof.heap_profile())
                    elif url.path == "/debug/pprof/threadz":
                        self._send(200, pprof.thread_dump())
                    else:
                        self._send(404, "unknown pprof endpoint")
                else:
                    self._send(404, "not found")

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        if profiling:
            import tracemalloc

            if not tracemalloc.is_tracing():
                tracemalloc.start()
                self._started_tracemalloc = True
        self.port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return self.port

    def stop(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()
        if self._started_tracemalloc:
            import tracemalloc

            tracemalloc.stop()
            self._started_tracemalloc = False


def run_loop(
    autoscaler,
    scan_interval_s: float,
    max_iterations: int = 0,
    still_leader=None,
    max_consecutive_failures: int = 0,
    watchdog=None,
) -> bool:
    """The steady, CRASH-ONLY loop (main.go:471-489).

    One uncaught exception must not kill the process: each iteration's
    failure is caught, typed via utils/errors.to_autoscaler_error (the
    original traceback rides ``__cause__``), counted, and the loop keeps
    going — the HealthCheck failing deadline (no successful run_once for
    max-failing-time) remains the restart authority, and
    ``max_consecutive_failures`` (--max-consecutive-run-once-failures)
    adds an optional fast hard exit, returning False so main() exits
    abnormally for the supervisor. ``watchdog`` (utils/pprof.LoopWatchdog)
    is armed around each tick: a tick that overruns its soft deadline gets
    an all-thread stack dump before the liveness probe acts.

    still_leader: optional callback consulted between iterations under
    leader election — returning False stops the loop so the process can
    exit and be restarted as a follower (main.go:568 OnStoppedLeading)."""
    from autoscaler_tpu.utils.errors import to_autoscaler_error

    log = logging.getLogger("run_loop")
    iterations = 0
    consecutive_failures = 0
    while True:
        loop_start = time.monotonic()
        if watchdog is not None:
            watchdog.arm()
        try:
            autoscaler.run_once(now_ts=time.time())
            consecutive_failures = 0
        except KeyboardInterrupt:
            raise
        except Exception as e:  # noqa: BLE001 — crash-only: log, count, go on
            err = to_autoscaler_error(e)
            consecutive_failures += 1
            log.error(
                "run_once crashed (%s, consecutive failure %d): %s",
                err.error_type.value, consecutive_failures, err,
                exc_info=err,
            )
            # activity (not success): the inactivity deadline stays quiet,
            # the failing deadline keeps ticking toward a probe restart
            health = getattr(autoscaler, "health_check", None)
            if health is not None:
                health.update_last_activity()
            metrics = getattr(autoscaler, "metrics", None)
            if metrics is not None:
                metrics.errors_total.inc(type=err.error_type.value)
            if (
                max_consecutive_failures
                and consecutive_failures >= max_consecutive_failures
            ):
                print(
                    f"run_once failed {consecutive_failures} times in a row "
                    "(--max-consecutive-run-once-failures); exiting for "
                    "supervisor restart",
                    file=sys.stderr,
                )
                return False
        finally:
            if watchdog is not None:
                watchdog.disarm()
        iterations += 1
        if max_iterations and iterations >= max_iterations:
            return True
        if still_leader is not None and not still_leader():
            print("lost leadership; exiting loop", file=sys.stderr)
            return False
        elapsed = time.monotonic() - loop_start
        time.sleep(max(scan_interval_s - elapsed, 0.0))


def main(argv=None) -> int:
    from autoscaler_tpu.utils.tpu import pin_cpu_if_requested

    pin_cpu_if_requested()  # axon site-hook workaround (see the helper)
    args = build_arg_parser().parse_args(argv)
    opts = options_from_args(args)
    from autoscaler_tpu.utils import klogx

    klogx.set_verbosity(args.v)
    logging.basicConfig(level=logging.INFO)

    if opts.compile_cache_dir:
        # Persistent XLA compile cache: the first reconcile loop pays
        # ~10-40s of kernel compiles (churn_bench first_loop_s vs steady
        # state); across process restarts — the common restart path for a
        # leader-elected singleton — the cache turns that into a disk read,
        # and paired with the arena's bucket-ladder prewarm the first real
        # tick never compiles at all. Applied before any jax import
        # triggers backend init.
        import jax

        jax.config.update("jax_compilation_cache_dir", opts.compile_cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from autoscaler_tpu.debugging import DebuggingSnapshotter

    if args.kube_api and args.kubeconfig:
        # pure argv validation comes before any cloud I/O
        print("--kube-api and --kubeconfig are mutually exclusive",
              file=sys.stderr)
        return 2
    if args.leader_elect and not (args.kube_api or args.kubeconfig):
        print("--leader-elect requires a control-plane binding "
              "(--kube-api or --kubeconfig)", file=sys.stderr)
        return 2

    if opts.cloud_provider == "test":
        from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider

        provider = TestCloudProvider()
    elif opts.cloud_provider == "gce":
        from autoscaler_tpu.cloudprovider.gce import build_gce_provider
        from autoscaler_tpu.cloudprovider.gce_rest import (
            DEFAULT_BASE_URL,
            RestGceApi,
        )

        if args.gce_token_file:
            token_path = args.gce_token_file

            def token_fn() -> str:
                # re-read per request so an external refresher (sidecar
                # writing a fresh token) just works
                with open(token_path) as f:
                    return f.read().strip()
        else:
            print(
                "gce provider needs --gce-token-file (metadata-server "
                "fetch is the deploy site's refresher)",
                file=sys.stderr,
            )
            return 2
        if opts.node_group_auto_discovery and not args.gce_project:
            print(
                "--node-group-auto-discovery needs --gce-project (the "
                "aggregated MIG listing is project-scoped; without it "
                "discovery silently finds nothing)",
                file=sys.stderr,
            )
            return 2
        gce_api = RestGceApi(
            token_fn,
            base_url=args.gce_api_url or DEFAULT_BASE_URL,
            user_agent=opts.user_agent,
            project=args.gce_project or None,
        )
        try:
            provider = build_gce_provider(
                args.nodes, gce_api,
                auto_discovery=opts.node_group_auto_discovery,
                concurrent_refreshes=args.gce_concurrent_refreshes,
            )
        except ValueError as e:  # malformed --nodes/discovery spec
            print(str(e), file=sys.stderr)
            return 2
        if not (args.kube_api or args.kubeconfig):
            # pairing real MIG mutations with the empty in-memory fake would
            # mark every healthy instance unregistered and, after
            # max-node-provision-time, DELETE real VMs — fail closed
            print(
                "--provider=gce requires --kube-api (in-cluster or URL): "
                "without a real control-plane binding every MIG instance "
                "looks unregistered and would be cleaned up",
                file=sys.stderr,
            )
            return 2
    elif opts.cloud_provider in ("externalgrpc", "externalgrpc-ref"):
        # endpoint from the reference-shaped --cloud-config ({address: ...})
        address = ""
        if args.cloud_config:
            import yaml

            try:
                with open(args.cloud_config) as f:
                    cfg = yaml.safe_load(f) or {}
            except (OSError, yaml.YAMLError) as e:
                print(f"--cloud-config unreadable: {e}", file=sys.stderr)
                return 2
            address = str(cfg.get("address", "") or "") if isinstance(
                cfg, dict
            ) else ""
        if not address:
            print(
                f"--provider={opts.cloud_provider} needs --cloud-config with an "
                "`address: host:port` entry (reference externalgrpc "
                "README.md contract)",
                file=sys.stderr,
            )
            return 2
        if opts.cloud_provider == "externalgrpc":
            from autoscaler_tpu.cloudprovider.external_grpc import (
                ExternalGrpcCloudProvider,
            )

            provider = ExternalGrpcCloudProvider(address)
        else:
            from autoscaler_tpu.rpc.refcompat import RefProtocolCloudProvider

            provider = RefProtocolCloudProvider(address)
    elif opts.cloud_provider == "clusterapi":
        # the management cluster IS the cloud: scale MachineDeployments/
        # MachineSets through the same control plane the autoscaler watches
        # (reference cloudprovider/clusterapi; annotation-driven discovery)
        if not (args.kube_api or args.kubeconfig):
            print(
                "--provider=clusterapi requires a management-cluster "
                "binding (--kube-api or --kubeconfig)",
                file=sys.stderr,
            )
            return 2
        from autoscaler_tpu.cloudprovider.clusterapi import (
            build_clusterapi_provider,
        )
        from autoscaler_tpu.kube.client import KubeRestClient

        # same construction rules (incl. in-cluster + qps/burst throttling
        # + clean kubeconfig failure) as the kube-client block below
        if args.kubeconfig:
            try:
                capi_rest = KubeRestClient.from_kubeconfig(
                    args.kubeconfig, user_agent=opts.user_agent,
                    qps=args.kube_client_qps, burst=args.kube_client_burst,
                    get_retries=args.kube_client_get_retries,
                )
            except (OSError, ValueError) as e:
                print(f"--kubeconfig {args.kubeconfig}: {e}", file=sys.stderr)
                return 2
        elif args.kube_api == "in-cluster":
            capi_rest = KubeRestClient.in_cluster(
                user_agent=opts.user_agent,
                qps=args.kube_client_qps, burst=args.kube_client_burst,
                get_retries=args.kube_client_get_retries,
            )
        else:
            capi_rest = KubeRestClient(
                args.kube_api, user_agent=opts.user_agent,
                qps=args.kube_client_qps, burst=args.kube_client_burst,
                get_retries=args.kube_client_get_retries,
            )
        try:
            provider = build_clusterapi_provider(
                capi_rest, auto_discovery=opts.node_group_auto_discovery
            )
        except ValueError as e:
            print(f"--node-group-auto-discovery: {e}", file=sys.stderr)
            return 2
    else:
        print(
            f"unknown cloud provider {opts.cloud_provider!r} (available: test, "
            "gce, externalgrpc, externalgrpc-ref, clusterapi)",
            file=sys.stderr,
        )
        return 2

    if args.expander_priority_config_map and not (
        args.kube_api or args.kubeconfig
    ):
        # fail closed, like --provider=gce: without a control-plane binding
        # the ConfigMap can never be read and the priority expander would
        # silently behave as unconfigured
        print(
            "--expander-priority-config-map requires --kube-api "
            "(the ConfigMap is read from the live control plane); use "
            "--expander-priority-config-file for a mounted config",
            file=sys.stderr,
        )
        return 2

    if args.kube_api or args.kubeconfig:
        from autoscaler_tpu.kube.client import KubeClusterAPI, KubeRestClient

        if args.kubeconfig:
            try:
                client = KubeRestClient.from_kubeconfig(
                    args.kubeconfig, user_agent=opts.user_agent,
                    qps=args.kube_client_qps, burst=args.kube_client_burst,
                    get_retries=args.kube_client_get_retries,
                )
            except (OSError, ValueError) as e:
                print(f"--kubeconfig {args.kubeconfig}: {e}", file=sys.stderr)
                return 2
        elif args.kube_api == "in-cluster":
            client = KubeRestClient.in_cluster(
                user_agent=opts.user_agent,
                qps=args.kube_client_qps, burst=args.kube_client_burst,
                get_retries=args.kube_client_get_retries,
            )
        else:
            client = KubeRestClient(
                args.kube_api, user_agent=opts.user_agent,
                qps=args.kube_client_qps, burst=args.kube_client_burst,
                get_retries=args.kube_client_get_retries,
            )
        api = KubeClusterAPI(
            client, watch=True,
            record_duplicated_events=args.record_duplicated_events,
        )
    else:
        from autoscaler_tpu.kube.api import FakeClusterAPI

        api = FakeClusterAPI()

    if not args.parallel_drain:
        # accepted for reference-command-line compatibility only: the
        # planner here IS the parallel-drain path; there is no legacy
        # serial mode to fall back to
        print("WARNING: --parallel-drain=false is a no-op (the planner is "
              "always the parallel-drain path)", file=sys.stderr)
    autoscaler = StaticAutoscaler(
        provider, api, opts,
        debugger=DebuggingSnapshotter() if opts.debugging_snapshot_enabled else None,
    )
    server = ObservabilityServer(autoscaler, args.address, profiling=args.profiling)
    port = server.start()
    print(f"tpu-autoscaler: observability on :{port}, scan interval {opts.scan_interval_s}s")
    from autoscaler_tpu.utils.pprof import LoopWatchdog

    soft_deadline = opts.run_once_soft_deadline_s or max(
        4 * opts.scan_interval_s, 60.0
    )
    watchdog = LoopWatchdog(soft_deadline)
    try:
        if args.leader_elect:
            from autoscaler_tpu.kube.client import KubeLease
            from autoscaler_tpu.utils.leaderelection import LeaderElector

            elector = LeaderElector(
                KubeLease(client, args.leader_elect_lease_name,
                          opts.config_namespace)
            )
            print(f"waiting for leadership as {elector.identity}")
            outcome = {"clean": True}

            def lead(still_leader):
                outcome["clean"] = run_loop(
                    autoscaler, opts.scan_interval_s, args.max_iterations,
                    still_leader=still_leader,
                    max_consecutive_failures=(
                        opts.max_consecutive_run_once_failures
                    ),
                    watchdog=watchdog,
                )

            elector.run(lead)
            if not outcome["clean"]:
                # abnormal exit so supervisors restart the replica
                # (main.go:568 OnStoppedLeading is a Fatalf)
                return 1
        else:
            clean = run_loop(
                autoscaler, opts.scan_interval_s, args.max_iterations,
                max_consecutive_failures=opts.max_consecutive_run_once_failures,
                watchdog=watchdog,
            )
            if not clean:
                # abnormal exit so supervisors restart the replica
                return 1
    except KeyboardInterrupt:
        pass
    finally:
        watchdog.stop()
        server.stop()
        close = getattr(api, "close", None)
        if close is not None:  # stop KubeClusterAPI watch threads
            close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
