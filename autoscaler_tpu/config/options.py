"""Autoscaling configuration.

Reference: cluster-autoscaler/config/autoscaling_options.go:78 (the ~80-field
AutoscalingOptions struct every layer reads) and the flag defaults of
cluster-autoscaler/main.go:92-227. Field names are pythonized; defaults match
the reference's flag defaults. Per-node-group overrides mirror
NodeGroupAutoscalingOptions (autoscaling_options.go:37-66), resolved through
the NodeGroupConfigProcessor pattern (processors/nodegroupconfig/).
"""
from __future__ import annotations

import dataclasses
import functools
import typing
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from autoscaler_tpu.fleet.buckets import (
    DEFAULT_ARENA_BUCKETS as _DEFAULT_ARENA_BUCKETS,
    DEFAULT_BUCKETS as _DEFAULT_FLEET_BUCKETS,
)


class OptionsError(ValueError):
    """An AutoscalingOptions override that doesn't describe a real knob:
    unknown field name, or a value whose type can't mean what the field
    means. Raised BEFORE construction so the offending key is named —
    loadgen --set and the gym PolicySpec seam both route through this."""


@dataclass
class NodeGroupAutoscalingOptions:
    """Per-node-group overridable knobs
    (reference: config/autoscaling_options.go:37-66)."""

    scale_down_utilization_threshold: float = 0.5
    scale_down_gpu_utilization_threshold: float = 0.5
    scale_down_unneeded_time_s: float = 600.0     # 10m
    scale_down_unready_time_s: float = 1200.0     # 20m
    max_node_provision_time_s: float = 900.0      # 15m


@dataclass
class NodeGroupDifferenceRatios:
    """Similarity tolerances for balancing similar node groups
    (reference: config/autoscaling_options.go:49-66 and
    processors/nodegroupset/compare_nodegroups.go:84,103)."""

    max_allocatable_difference_ratio: float = 0.05
    max_capacity_memory_difference_ratio: float = 0.015
    max_free_difference_ratio: float = 0.05


@dataclass
class AutoscalingOptions:
    # -- global node-group defaults -----------------------------------------
    node_group_defaults: NodeGroupAutoscalingOptions = field(
        default_factory=NodeGroupAutoscalingOptions
    )
    node_group_overrides: Dict[str, NodeGroupAutoscalingOptions] = field(
        default_factory=dict
    )

    # -- loop / process ------------------------------------------------------
    scan_interval_s: float = 10.0
    max_inactivity_s: float = 600.0               # health-check auto-restart
    max_failing_time_s: float = 900.0
    # crash-only loop: run_loop catches per-iteration exceptions and keeps
    # going; after this many CONSECUTIVE run_once failures it hard-exits
    # (abnormally, so a supervisor restarts the process). 0 = never — the
    # HealthCheck max_failing_time deadline remains the restart authority.
    max_consecutive_run_once_failures: int = 0
    # watchdog soft deadline for one run_once tick: exceeded → all-thread
    # stack dump via utils/pprof (evidence before the liveness probe kills
    # a wedged process). 0 = auto: max(4 x scan_interval, 60s).
    run_once_soft_deadline_s: float = 0.0
    # default deadline for sidecar RPCs that don't carry their own timeout
    # (rpc/service.TpuSimulationClient): a wedged sidecar must fail the
    # call, not hang run_once forever
    rpc_default_deadline_s: float = 30.0
    # estimator kernel-ladder circuit breakers (utils/circuit.py wrapped
    # around each rung — Pallas / XLA scan / native FFD / python oracle):
    # consecutive failures to trip a rung OPEN, and the cooldown before a
    # half-open probe re-tests it
    kernel_breaker_failure_threshold: int = 3
    kernel_breaker_cooldown_s: float = 120.0

    # -- tick tracing (autoscaler_tpu/trace) ---------------------------------
    # gates /tracez, like debugging_snapshot_enabled gates /snapshotz; the
    # tracer itself always runs (bounded memory, negligible overhead) so
    # the flight recorder has history the moment the endpoint is enabled
    tracing_enabled: bool = True
    # flight recorder: how many recent tick traces the in-memory ring keeps
    trace_ring_size: int = 64
    # always-on slow-tick dump: a tick whose WALL time exceeds this gets its
    # full span tree logged and the trace pinned in the ring (survives ring
    # eviction). 0 disables.
    trace_slow_tick_threshold_s: float = 2.0
    # when set, each tick captures a jax profiler session into
    # <dir>/tick_<id> — device timeline keyed by the same tick id as the
    # host trace (--jax-profiler-dir; debug tool, off by default)
    jax_profiler_dir: str = ""

    # -- perf observatory (autoscaler_tpu/perf) ------------------------------
    # gates /perfz, like tracing_enabled gates /tracez; the observatory
    # itself always runs (bounded ring, negligible overhead) so the ring
    # has history the moment the endpoint is enabled
    perf_enabled: bool = True
    # capture the XLA cost model (lowered.compile().cost_analysis() /
    # memory_analysis()) per new (kernel route, shape signature): one extra
    # AOT lower+compile per new signature, process-cached. Loadgen turns
    # this on (replayable — cost figures are pure functions of shapes).
    perf_cost_model: bool = False
    # how many recent per-tick perf records the in-memory ring keeps
    perf_ring_size: int = 64

    # -- decision provenance (autoscaler_tpu/explain) -------------------------
    # gates /explainz, like perf_enabled gates /perfz; the explainer itself
    # always assembles records (bounded ring, negligible overhead) so the
    # ring has history the moment the endpoint is enabled
    explain_enabled: bool = True
    # how many recent per-tick decision records the in-memory ring keeps
    explain_ring_size: int = 64

    # -- flight journal (autoscaler_tpu/journal) -----------------------------
    # gates /journalz, like explain_enabled gates /explainz; the recorder
    # itself always runs (bounded ring of keyframe+delta state records,
    # negligible overhead) so time-travel history exists the moment the
    # endpoint is enabled
    journal_enabled: bool = True
    # how many recent per-tick state records the in-memory ring keeps
    journal_ring_size: int = 64
    # write a full keyframe every K ticks even without a packer reseed or
    # shape change: bounds how many deltas a reconstruction replays and how
    # much history a ring eviction can strand behind a lost keyframe
    journal_keyframe_interval: int = 16
    # every N ticks, reconstruct the newest journaled tick and bit-compare
    # it (plus its fit-kernel verdicts) against the live packer state —
    # drift becomes a metric + trace event instead of a silently wrong
    # forensic answer. 0 disables the probe.
    journal_probe_interval: int = 0
    # append the journal (the same strict record_line bytes as the ring) to
    # this JSONL file for post-mortem reconstruct/diff/replay ("" = off)
    journal_path: str = ""

    # -- resident device arena (autoscaler_tpu/snapshot/arena) ---------------
    # keep the packed snapshot tensors device-resident across ticks and ship
    # only delta scatters for dirtied rows (ROADMAP item 2); off = the cold
    # per-field re-upload path
    arena_enabled: bool = False
    # comma-separated PxNxR power-of-two prewarm buckets for the arena's
    # apply-kernel ladder (same grammar as the fleet buckets; R is a cap).
    # The default ladder lives with fleet/buckets.py — ONE source.
    arena_buckets: str = _DEFAULT_ARENA_BUCKETS
    # persistent XLA compilation cache directory ("" = disabled): together
    # with the arena prewarm this makes the first real tick compile-free
    # across process restarts (ROADMAP item 5); main.py applies it before
    # backend init, deploy/ mounts a volume for it
    compile_cache_dir: str = ""

    # -- preemption engine (autoscaler_tpu/preempt) --------------------------
    # run the priority-aware eviction-packing pass each tick (ops/preempt.py
    # via the estimator ladder): pending pods that fit the EXISTING cluster
    # only by displacing strictly-lower-priority residents get planned
    # evictions, ledgered with provenance (preempted_by). Off = today's
    # decisions, byte for byte (hack/verify.sh preemption gate).
    preemption_enabled: bool = False
    # expander churn penalty: each eviction a scale-up option leaves
    # standing (its evictor not covered by the option's pods) costs this
    # much score. 0 = churn-blind ranking (the filter disengages entirely);
    # tuned by the gym's preemption suite under storm load.
    preemption_churn_weight: float = 0.0

    # -- fleet serving (autoscaler_tpu/fleet) --------------------------------
    # how long the coalescer waits after the first queued request before
    # dispatching the batch — the latency/coalescing trade (ms because the
    # useful range is single-digit milliseconds)
    fleet_coalesce_window_ms: float = 5.0
    # comma-separated PxGxR power-of-two shape buckets requests pad into;
    # the closed compile-cache key set of the service. The default ladder
    # lives with the safety argument in fleet/buckets.py — ONE source.
    fleet_shape_buckets: str = _DEFAULT_FLEET_BUCKETS
    # compile every configured bucket at startup so the first real request
    # never compiles (ladder-rung pre-warm, ROADMAP item 5)
    fleet_prewarm: bool = True
    # scenario slots per coalesced batch (the kernel's leading S axis);
    # overflow chunks into further batches in the same window
    fleet_batch_scenarios: int = 8
    # tenant-label cardinality bound on the per-tenant fleet SLI series
    # (fleet_queue_wait/service/e2e_seconds, fleet_requests_total): the
    # first N distinct tenants keep their own label, later arrivals
    # aggregate into "__overflow__" so a misbehaving fleet cannot explode
    # /metrics exposition. 0 = unbounded (trusted closed fleets only).
    fleet_max_tenant_labels: int = 64
    # -- fleet overload armor (fleet/admission.py) ---------------------------
    # admission bound on the coalescing queue: submits past this depth are
    # shed typed (FleetOverloadError → RESOURCE_EXHAUSTED + retry-after)
    # instead of queueing unboundedly. 0 = unbounded (the pre-armor
    # behavior; trusted closed fleets only).
    fleet_max_queue_depth: int = 0
    # per-tenant token-bucket quota: sustained requests/second each tenant
    # may submit (0 = no quotas) and the bucket's burst capacity (0 =
    # max(qps, 1)). Over-quota submits shed typed with the seconds-until-
    # next-token as the retry-after hint.
    fleet_tenant_qps: float = 0.0
    fleet_tenant_burst: float = 0.0
    # tenant quota tiers (fleet/tiers.py), JSON: tier name → {qps, burst,
    # queue_share, default_deadline_s, shed_priority, tenants}; must
    # include a "default" catch-all tier. Supersedes the global
    # fleet_tenant_qps with per-TIER budgets, queue-share slices, tier
    # default deadlines, and tier-priority flush/shed ordering. "" = off.
    fleet_tenant_tiers: str = ""
    # sidecar drain: how long server.stop() waits for in-flight RPCs after
    # the drain sequence stopped admission and flushed the coalescer
    # (SIGTERM → UNAVAILABLE+drain detail → flush → stop(grace))
    fleet_drain_grace_s: float = 5.0
    # client failover (rpc/service.TpuSimulationClient): the sidecar
    # endpoint list (--rpc-address, repeatable). More than one endpoint
    # arms failover — the client advances on UNAVAILABLE/drain with
    # jittered bounded backoff, budgeted inside the caller's deadline.
    rpc_addresses: List[str] = field(default_factory=list)
    # client hedging: hedge idempotent Estimate/BatchEstimate against the
    # next endpoint when the primary hasn't answered after a p99-derived
    # delay (first answer wins, loser cancelled; never past the caller's
    # deadline). Off by default — hedging doubles worst-case load.
    rpc_hedge: bool = False

    # -- SLO engine (autoscaler_tpu/slo) -------------------------------------
    # gates /sloz, like perf_enabled gates /perfz; the engine itself always
    # runs (bounded ring, negligible overhead) so burn-rate history exists
    # the moment the endpoint is enabled. The window-record ring shares
    # explain_ring_size (the SLO windows are computed per tick, the same
    # cadence as the decision records the pending-pod SLI reads).
    slo_enabled: bool = True

    # -- policy gym (autoscaler_tpu/gym) -------------------------------------
    # concurrent candidate rollouts per tuning stage: the population axis
    # of the gym tuner. Rollouts share one fleet coalescer, so estimator
    # calls from parallel rollouts batch into shared mesh dispatches
    # (Podracer-style: the population rides the scenario axis).
    gym_rollout_workers: int = 4
    # objective weights for the scorer's deterministic scalar, as
    # "slo=1,cost=6,churn=0.5" ("" = the scorer's defaults). One number:
    # the gym's reward and the human-facing report read the same section.
    gym_objective_weights: str = ""
    # route gym rollout estimator dispatches through the shared fleet
    # coalescer (off = every rollout pays its own solo dispatches; the
    # score is certified identical either way)
    gym_fleet_coalesce: bool = True

    # -- cluster-wide resource limits (main.go:113-118) ----------------------
    max_nodes_total: int = 0                      # 0 = unlimited
    min_cores_total: float = 0.0
    max_cores_total: float = 320_000.0 * 1000     # millicores
    min_memory_total: float = 0.0
    max_memory_total_mib: float = 6_400_000.0 * 1024
    gpu_total: Dict[str, tuple] = field(default_factory=dict)  # name -> (min,max)

    # -- scale-up ------------------------------------------------------------
    estimator: str = "binpacking"
    expander: str = "random"                      # reference default (main.go:145)
    # priority-expander tiers: static dict, and/or a hot-reloaded config file
    # (the reference's live ConfigMap, expander/priority/priority.go)
    expander_priorities: Dict[int, List[str]] = field(default_factory=dict)
    priority_config_file: str = ""
    # name of the live priority ConfigMap in config_namespace ("" = off);
    # the reference's default is cluster-autoscaler-priority-expander
    priority_config_map: str = ""
    # external gRPC expander target (reference --grpc-expander-url) for the
    # "grpc" entry of the expander chain
    grpc_expander_url: str = ""
    # seed for the expander chain's random fallback (tie-breaks and the
    # "random" strategy). None = entropy, the reference behavior; scenario
    # replay (loadgen) pins it so the same world makes the same choice.
    expander_random_seed: Optional[int] = None
    max_nodes_per_scaleup: int = 1000             # main.go:215
    max_nodegroup_binpacking_duration_s: float = 10.0  # main.go:216
    node_info_cache_expire_time_s: float = 60.0  # template NodeInfo TTL
    # --force-ds: charge suitable pending DaemonSets onto new-node capacity
    force_daemonsets: bool = False
    debugging_snapshot_enabled: bool = True      # serve /snapshotz
    balance_similar_node_groups: bool = False
    balancing_label_keys: List[str] = field(default_factory=list)
    node_group_difference_ratios: NodeGroupDifferenceRatios = field(
        default_factory=NodeGroupDifferenceRatios
    )
    scale_up_from_zero: bool = True
    enforce_node_group_min_size: bool = False
    max_node_provision_time_s: float = 900.0
    new_pod_scale_up_delay_s: float = 0.0         # young-pod filter (main.go:204)
    expendable_pods_priority_cutoff: int = -10

    # -- cluster health (clusterstate gates) ---------------------------------
    max_total_unready_percentage: float = 45.0    # main.go:148
    ok_total_unready_count: int = 3               # main.go:149

    # -- per-nodegroup backoff (utils/backoff/exponential_backoff.go) --------
    initial_node_group_backoff_duration_s: float = 300.0   # 5m
    max_node_group_backoff_duration_s: float = 1800.0      # 30m
    node_group_backoff_reset_timeout_s: float = 10800.0    # 3h

    # -- scale-down ----------------------------------------------------------
    scale_down_enabled: bool = True
    scale_down_delay_after_add_s: float = 600.0   # 10m
    scale_down_delay_after_delete_s: float = 0.0  # defaults to scan interval
    scale_down_delay_after_failure_s: float = 180.0  # 3m
    scale_down_unneeded_time_s: float = 600.0
    scale_down_unready_time_s: float = 1200.0
    scale_down_utilization_threshold: float = 0.5
    scale_down_non_empty_candidates_count: int = 30   # main.go:119
    scale_down_candidates_pool_ratio: float = 0.1     # main.go:124
    scale_down_candidates_pool_min_count: int = 50    # main.go:129
    scale_down_simulation_timeout_s: float = 30.0
    max_scale_down_parallelism: int = 10
    max_drain_parallelism: int = 1
    max_empty_bulk_delete: int = 10
    max_graceful_termination_s: float = 600.0
    # eviction pacing (reference actuation/drain.go constants: EvictionRetryTime,
    # MaxPodEvictionTime, PodEvictionHeadroom)
    eviction_retry_time_s: float = 10.0
    max_pod_eviction_time_s: float = 120.0
    pod_eviction_headroom_s: float = 30.0
    max_bulk_soft_taint_count: int = 10
    max_bulk_soft_taint_time_s: float = 3.0
    unremovable_node_recheck_timeout_s: float = 300.0
    node_deletion_batcher_interval_s: float = 0.0
    skip_nodes_with_system_pods: bool = True
    skip_nodes_with_local_storage: bool = True
    skip_nodes_with_custom_controller_pods: bool = True
    min_replica_count: int = 0
    # unready nodes may be scale-down candidates (ScaleDownUnreadyEnabled,
    # --scale-down-unready-enabled, default true)
    scale_down_unready_enabled: bool = True
    # pacing between tainting a node and deleting it
    # (NodeDeleteDelayAfterTaint). DIVERGENCE: the reference defaults this
    # to 5s *inside its async deletion goroutine* (actuator.go:234); this
    # framework's actuation wave is synchronous by design (the loop joins
    # it), so a nonzero delay extends the control loop directly — default
    # off, opt in if your scheduler lags taint observation. The pause is
    # paid inside the per-node workers, so drain waves overlap it with
    # eviction work. (The reference's NodeDeletionDelayTimeout is not
    # modeled: deletion confirmation here is the synchronous batcher
    # result, not a polled wait.)
    node_delete_delay_after_taint_s: float = 0.0

    # -- misc ---------------------------------------------------------------
    cloud_provider: str = "test"
    cluster_name: str = ""                        # --cluster-name (status header)
    # HTTP User-Agent; consumed by KubeRestClient — deploy sites pass it when
    # constructing their client (no CLI flag: main.py's test provider makes
    # no API calls)
    user_agent: str = "tpu-autoscaler"
    config_namespace: str = "kube-system"         # --namespace
    status_config_map_name: str = "cluster-autoscaler-status"
    write_status_configmap: bool = True
    # startup/ignored taints stripped from templates before comparison and
    # simulation (--ignore-taint; taints.go ignored-taints handling)
    ignored_taints: List[str] = field(default_factory=list)
    # extra labels excluded from node-group similarity comparison, on top of
    # the built-in ignore list (--balancing-ignore-label)
    balancing_extra_ignored_labels: List[str] = field(default_factory=list)
    # node-group auto-discovery specs, parsed by the cloud provider
    # (--node-group-auto-discovery, e.g. "label:k1=v1,k2=v2" or provider
    # MIG/ASG prefix specs)
    node_group_auto_discovery: List[str] = field(default_factory=list)
    # per-nodegroup gauges are opt-in for cardinality, like the reference's
    # --record-node-group-metrics flag (main.go:201)
    record_per_node_group_metrics: bool = False
    node_autoprovisioning_enabled: bool = False
    max_autoprovisioned_node_group_count: int = 15
    cordon_node_before_terminating: bool = False
    ignore_daemonsets_utilization: bool = False
    ignore_mirror_pods_utilization: bool = False
    # DaemonSet pods are gracefully evicted (best-effort, never PDB-simulated
    # — the eviction API enforces PDBs server-side) from nodes being removed.
    # Defaults mirror the reference flags (main.go:198-199): opt-in for empty
    # nodes, on for drained ones.
    daemonset_eviction_for_empty_nodes: bool = False
    daemonset_eviction_for_occupied_nodes: bool = True

    def group_options(self, group_name: str) -> NodeGroupAutoscalingOptions:
        """Resolve per-group options with fallback to defaults (the
        NodeGroupConfigProcessor / NodeGroup.GetOptions path,
        reference cloud_provider.go:230)."""
        return self.node_group_overrides.get(group_name, self.node_group_defaults)


@functools.lru_cache(maxsize=1)
def _field_types() -> Dict[str, Any]:
    """Resolved (PEP 563) annotation per AutoscalingOptions field."""
    hints = typing.get_type_hints(AutoscalingOptions)
    return {f.name: hints[f.name] for f in dataclasses.fields(AutoscalingOptions)}


def _type_ok(expected: Any, value: Any) -> bool:
    """Conservative runtime check of one override value against a field
    annotation. bool is NOT an int/float here (JSON true leaking into a
    numeric knob is exactly the silent corruption this exists to catch);
    ints promote to float fields, matching what JSON round-trips produce."""
    origin = typing.get_origin(expected)
    if origin is typing.Union:  # Optional[X] and friends
        return any(_type_ok(arg, value) for arg in typing.get_args(expected))
    if expected is type(None):
        return value is None
    if origin in (dict, Dict):
        return isinstance(value, dict)
    if origin in (list, List):
        return isinstance(value, list)
    if origin in (tuple,):
        return isinstance(value, (list, tuple))
    if expected is bool:
        return isinstance(value, bool)
    if expected is int:
        return isinstance(value, int) and not isinstance(value, bool)
    if expected is float:
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if expected is str:
        return isinstance(value, str)
    if isinstance(expected, type):
        return isinstance(value, expected)
    return True  # unparameterized/exotic annotation: don't guess


def validate_overrides(overrides: Dict[str, Any]) -> None:
    """Validate a {field name → value} override set against the
    AutoscalingOptions schema BEFORE construction. An unknown key or a
    type-mismatched value raises :class:`OptionsError` naming the offending
    key — dataclasses accept any value silently, so without this gate a
    typo'd ``--set scale_down_unneded_time_s=0`` or a string where a float
    belongs would corrupt a run instead of exiting 2."""
    fields = _field_types()
    for key in sorted(overrides):
        if key not in fields:
            known = ", ".join(sorted(fields)[:6])
            raise OptionsError(
                f"unknown AutoscalingOptions key {key!r} "
                f"(fields are e.g. {known}, ...)"
            )
        expected = fields[key]
        value = overrides[key]
        if not _type_ok(expected, value):
            raise OptionsError(
                f"AutoscalingOptions key {key!r} wants "
                f"{_render_type(expected)}, got "
                f"{type(value).__name__} ({value!r})"
            )


def _render_type(expected: Any) -> str:
    origin = typing.get_origin(expected)
    if origin is typing.Union:
        return " | ".join(_render_type(a) for a in typing.get_args(expected))
    if origin is not None:
        return getattr(origin, "__name__", str(origin))
    return getattr(expected, "__name__", str(expected))
