"""Greedy pod scheduling onto existing capacity — the hinting simulator's
device kernel.

Reference: cluster-autoscaler/simulator/scheduling/hinting_simulator.go:58
(TrySchedulePods: per pod, try the hinted node first, then a full
FitsAnyNodeMatching scan) — the engine behind the filter-out-schedulable
pod-list processor (core/podlistprocessor/filter_out_schedulable.go:46,95).
One scan over the pod list with capacity carried between placements; the
hint becomes a preferred-index fast path inside each step.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from autoscaler_tpu.snapshot.tensors import SnapshotTensors


class ScheduleResult(NamedTuple):
    placed: jax.Array   # [K] bool
    dest: jax.Array     # [K] i32 node index, -1 when not placed


@jax.jit
def greedy_schedule(
    snap: SnapshotTensors,
    pod_slots: jax.Array,  # [K] i32 pod indices to place, in priority order (-1 pad)
    hints: jax.Array,      # [K] i32 hinted node index per pod, -1 = no hint
) -> ScheduleResult:
    """Place pods onto existing nodes greedily, honoring hints. Capacity is
    carried across placements; predicate mask comes from the snapshot."""
    free0 = snap.free()

    def step(free, inp):
        pod_idx, hint = inp
        valid = pod_idx >= 0
        safe = jnp.maximum(pod_idx, 0)
        req = snap.pod_req[safe]
        ok = (
            jnp.all(req[None, :] <= free, axis=-1)
            & snap.sched_row(safe)
            & snap.node_valid
        )
        hint_ok = (hint >= 0) & ok[jnp.maximum(hint, 0)]
        first = jnp.argmax(ok).astype(jnp.int32)
        dest = jnp.where(hint_ok, hint, jnp.where(ok.any(), first, -1))
        place = valid & (dest >= 0)
        target = jnp.maximum(dest, 0)
        free = free.at[target].add(jnp.where(place, -req, jnp.zeros_like(req)))
        return free, (place, jnp.where(place, dest, -1))

    _, (placed, dest) = jax.lax.scan(step, free0, (pod_slots, hints))
    return ScheduleResult(placed=placed, dest=dest)
