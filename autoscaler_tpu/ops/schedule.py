"""Greedy pod scheduling onto existing capacity — the hinting simulator's
device kernel.

Reference: cluster-autoscaler/simulator/scheduling/hinting_simulator.go:58
(TrySchedulePods: per pod, try the hinted node first, then a full
FitsAnyNodeMatching scan) — the engine behind the filter-out-schedulable
pod-list processor (core/podlistprocessor/filter_out_schedulable.go:46,95).
One scan over the pod list with capacity carried between placements; the
hint becomes a preferred-index fast path inside each step.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from autoscaler_tpu.snapshot.tensors import SnapshotTensors


class ScheduleResult(NamedTuple):
    placed: jax.Array   # [K] bool
    dest: jax.Array     # [K] i32 node index, -1 when not placed


BIG_I32 = jnp.int32(2**30)


def spread_gate(sp8, counts, safe_idx):
    """Shared within-wave topology-spread gate over EXISTING nodes →
    (node_ok [N] bool, m [S] bool). sp8 = the 8-array context
    (affinity.build_spread_schedule_context minus static counts, which
    travel in the `counts` carry). One definition for the greedy/hinting
    scheduler AND the scale-down refit kernels so the two surfaces cannot
    drift (the same reason _place_pod_step itself is shared)."""
    (sp_of_T, sp_match_T, node_dom, _sp_elig, dom_valid,
     skew, min_dom, domnum) = sp8
    o = sp_of_T[safe_idx]                               # [S]
    m = sp_match_T[safe_idx]                            # [S]
    minv = jnp.min(jnp.where(dom_valid, counts, BIG_I32), axis=1)
    min_eff = jnp.where(min_dom > domnum, 0, minv)      # [S]
    dom_safe = jnp.maximum(node_dom, 0)                 # [S, N]
    cnt_node = jnp.take_along_axis(counts, dom_safe, axis=1)
    reg_node = (
        jnp.take_along_axis(dom_valid, dom_safe, axis=1) & (node_dom >= 0)
    )
    cnt_node = jnp.where(reg_node, cnt_node, 0)
    ok_sp = (node_dom >= 0) & (
        cnt_node + m.astype(jnp.int32)[:, None] - min_eff[:, None]
        <= skew[:, None]
    )
    return ~(o[:, None] & ~ok_sp).any(axis=0), m


def spread_commit(sp8, counts, m, place, target):
    """Shared count update after a placement: matching pods landing on
    nodes ELIGIBLE for the term raise that domain's count
    (countPodsMatchSelector runs over eligible nodes)."""
    node_dom, sp_elig = sp8[2], sp8[3]
    dom_t = node_dom[:, target]                         # [S]
    upd = (m & place & (dom_t >= 0) & sp_elig[:, target]).astype(jnp.int32)
    return counts.at[
        jnp.arange(counts.shape[0]), jnp.maximum(dom_t, 0)
    ].add(upd)


@jax.jit
def greedy_schedule(
    snap: SnapshotTensors,
    pod_slots: jax.Array,  # [K] i32 pod indices to place, in priority order (-1 pad)
    hints: jax.Array,      # [K] i32 hinted node index per pod, -1 = no hint
    spread: tuple | None = None,  # affinity.build_spread_schedule_context
) -> ScheduleResult:
    """Place pods onto existing nodes greedily, honoring hints. Capacity is
    carried across placements; the static predicate mask comes from the
    snapshot, and hard topology-spread re-counts PER PLACEMENT when the
    spread context is provided — pods placed earlier in this wave raise
    their domain's count for later pods, exactly as the reference's
    hinting simulator observes through the scheduler framework
    (hinting_simulator.go:58 → PodTopologySpread filtering.go:339). This
    closes the last within-wave spread divergence (PREDICATES.md 2)."""
    free0 = snap.free()
    if spread is not None:
        # split the 9-tuple: static counts seed the carry, the rest is the
        # shared 8-array gate context
        (sp_of_T, sp_match_T, node_dom, sp_elig, dom_valid,
         static_counts, skew, min_dom, domnum) = spread
        sp8 = (sp_of_T, sp_match_T, node_dom, sp_elig, dom_valid,
               skew, min_dom, domnum)
        counts0 = static_counts
    else:
        counts0 = jnp.zeros((1, 1), jnp.int32)

    def step(carry, inp):
        free, counts = carry
        pod_idx, hint = inp
        valid = pod_idx >= 0
        safe = jnp.maximum(pod_idx, 0)
        req = snap.pod_req[safe]
        ok = (
            jnp.all(req[None, :] <= free, axis=-1)
            & snap.sched_row(safe)
            & snap.node_valid
        )
        if spread is not None:
            node_ok, m = spread_gate(sp8, counts, safe)
            ok &= node_ok
        hint_ok = (hint >= 0) & ok[jnp.maximum(hint, 0)]
        first = jnp.argmax(ok).astype(jnp.int32)
        dest = jnp.where(hint_ok, hint, jnp.where(ok.any(), first, -1))
        place = valid & (dest >= 0)
        target = jnp.maximum(dest, 0)
        free = free.at[target].add(jnp.where(place, -req, jnp.zeros_like(req)))
        if spread is not None:
            counts = spread_commit(sp8, counts, m, place, target)
        return (free, counts), (place, jnp.where(place, dest, -1))

    _, (placed, dest) = jax.lax.scan(step, (free0, counts0), (pod_slots, hints))
    return ScheduleResult(placed=placed, dest=dest)
