"""The predicate-fit kernel: the batched replacement for the reference's
per-(pod,node) scheduler-framework walk.

Reference: cluster-autoscaler/simulator/predicatechecker/schedulerbased.go:109-163
runs RunPreFilterPlugins + RunFilterPlugins serially per pod per candidate
node (the [HOT HOT HOT] loop of SURVEY.md §3.3), with a round-robin start
index to spread load. Here the entire (pod × node) space is one fused
elementwise reduction on the VPU:

    fits[P, N] = all_r(pod_req[P, r] <= free[N, r]) & sched_mask[P, N]

Non-resource predicates were precomputed into sched_mask by the packer; the
resource comparison stays dynamic because node_used evolves during simulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from autoscaler_tpu.snapshot.tensors import SnapshotTensors


def _factored_too_big(snap: SnapshotTensors) -> bool:
    from autoscaler_tpu.snapshot.packer import DENSE_MASK_CELL_LIMIT

    return (
        snap.sched_mask is None
        and snap.num_pods * snap.num_nodes > DENSE_MASK_CELL_LIMIT
    )


def fit_matrix(snap: SnapshotTensors) -> jax.Array:
    """[P, N] bool — pod i fits node j right now (capacity + predicates).
    Padding rows/cols are False.

    Materializes [P, N]: on factored-mask snapshots beyond the packer's
    dense-cell limit this is refused — the whole point of the factored form
    is to never allocate that array; use ops.pallas_fit.fit_reduce_exact
    (tiled, full mask semantics) for huge worlds."""
    if _factored_too_big(snap):
        raise ValueError(
            f"fit_matrix would materialize {snap.num_pods * snap.num_nodes} "
            "cells from a factored-mask snapshot; use "
            "ops.pallas_fit.fit_reduce_exact on the snapshot instead"
        )
    free = snap.free()  # [N, R], 0 on invalid rows
    fits = jnp.all(snap.pod_req[:, None, :] <= free[None, :, :], axis=-1)
    return (
        fits
        & snap.dense_sched()  # guarded above: small worlds only when factored
        & snap.pod_valid[:, None]
        & snap.node_valid[None, :]
    )


def fits_any_node(snap: SnapshotTensors) -> jax.Array:
    """[P] bool — the FitsAnyNodeMatching analog
    (reference: simulator/predicatechecker/schedulerbased.go:90). Huge
    factored-mask worlds route through the tiled kernel automatically."""
    if _factored_too_big(snap):
        from autoscaler_tpu.ops.pallas_fit import fit_reduce_exact

        return fit_reduce_exact(snap).any_fit
    return fit_matrix(snap).any(axis=1)


def first_fit_node(snap: SnapshotTensors) -> jax.Array:
    """[P] i32 — lowest-index node each pod fits on, -1 if none. This is the
    deterministic analog of CheckPredicates over a candidate list; callers
    that place pods must re-fit after each placement (see ops/binpack.py for
    the sequential-correct scan)."""
    if _factored_too_big(snap):
        from autoscaler_tpu.ops.pallas_fit import fit_reduce_exact

        return fit_reduce_exact(snap).first_fit
    fits = fit_matrix(snap)
    idx = jnp.argmax(fits, axis=1).astype(jnp.int32)
    return jnp.where(fits.any(axis=1), idx, -1)


fit_matrix_jit = jax.jit(fit_matrix)
fits_any_node_jit = jax.jit(fits_any_node)
