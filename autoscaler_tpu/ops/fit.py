"""The predicate-fit kernel: the batched replacement for the reference's
per-(pod,node) scheduler-framework walk.

Reference: cluster-autoscaler/simulator/predicatechecker/schedulerbased.go:109-163
runs RunPreFilterPlugins + RunFilterPlugins serially per pod per candidate
node (the [HOT HOT HOT] loop of SURVEY.md §3.3), with a round-robin start
index to spread load. Here the entire (pod × node) space is one fused
elementwise reduction on the VPU:

    fits[P, N] = all_r(pod_req[P, r] <= free[N, r]) & sched_mask[P, N]

Non-resource predicates were precomputed into sched_mask by the packer; the
resource comparison stays dynamic because node_used evolves during simulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from autoscaler_tpu.snapshot.tensors import SnapshotTensors


def _factored_too_big(snap: SnapshotTensors) -> bool:
    from autoscaler_tpu.snapshot.packer import DENSE_MASK_CELL_LIMIT

    return (
        snap.sched_mask is None
        and snap.num_pods * snap.num_nodes > DENSE_MASK_CELL_LIMIT
    )


def _bf16_ceil(x: jax.Array) -> jax.Array:
    """Smallest bf16 value >= x (x >= 0). Round-to-nearest can land BELOW
    x; bump one ulp (uint16 bit-increment — monotone for positive floats)
    when it did."""
    b = x.astype(jnp.bfloat16)
    bits = jax.lax.bitcast_convert_type(b, jnp.uint16)
    up = jax.lax.bitcast_convert_type(bits + jnp.uint16(1), jnp.bfloat16)
    return jnp.where(b.astype(jnp.float32) < x, up, b)


def _bf16_floor(x: jax.Array) -> jax.Array:
    """Largest bf16 value <= x (x >= 0)."""
    b = x.astype(jnp.bfloat16)
    bits = jax.lax.bitcast_convert_type(b, jnp.uint16)
    down = jax.lax.bitcast_convert_type(
        bits - jnp.uint16(1), jnp.bfloat16
    )
    return jnp.where(b.astype(jnp.float32) > x, down, b)


def bf16_compare_operands(
    pod_req: jax.Array, free: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Conservative bf16 quantization for the fit compare (ROADMAP Scale #3:
    bf16 doubles VPU throughput on v5e). Requests round UP to the bf16 grid
    and free capacity rounds DOWN, so `req_b <= free_b` implies the exact
    f32 `req <= free` — the bf16 verdict can only UNDER-admit (by at most
    one bf16 ulp of free, self-correcting next loop), never over-admit a
    pod onto a node that lacks room. Resource quantities that are already
    bf16-representable (millicores/bytes up to 256 in their leading 8 mantissa
    bits — typical power-of-two node shapes) compare exactly."""
    return _bf16_ceil(pod_req), _bf16_floor(jnp.maximum(free, 0.0))


def fit_matrix(snap: SnapshotTensors, precision: str = "f32") -> jax.Array:
    """[P, N] bool — pod i fits node j right now (capacity + predicates).
    Padding rows/cols are False.

    precision="bf16" runs the resource compare in bfloat16 with one-sided
    conservative rounding (see bf16_compare_operands); "f32" is exact.

    Materializes [P, N]: on factored-mask snapshots beyond the packer's
    dense-cell limit this is refused — the whole point of the factored form
    is to never allocate that array; use ops.pallas_fit.fit_reduce_exact
    (tiled, full mask semantics) for huge worlds."""
    if _factored_too_big(snap):
        raise ValueError(
            f"fit_matrix would materialize {snap.num_pods * snap.num_nodes} "
            "cells from a factored-mask snapshot; use "
            "ops.pallas_fit.fit_reduce_exact on the snapshot instead"
        )
    free = snap.free()  # [N, R], 0 on invalid rows
    if precision == "bf16":
        req_b, free_b = bf16_compare_operands(snap.pod_req, free)
        fits = jnp.all(req_b[:, None, :] <= free_b[None, :, :], axis=-1)
    elif precision == "f32":
        fits = jnp.all(snap.pod_req[:, None, :] <= free[None, :, :], axis=-1)
    else:
        raise ValueError(f"unknown precision {precision!r} (f32|bf16)")
    return (
        fits
        & snap.dense_sched()  # guarded above: small worlds only when factored
        & snap.pod_valid[:, None]
        & snap.node_valid[None, :]
    )


def fits_any_node(snap: SnapshotTensors) -> jax.Array:
    """[P] bool — the FitsAnyNodeMatching analog
    (reference: simulator/predicatechecker/schedulerbased.go:90). Huge
    factored-mask worlds route through the tiled kernel automatically."""
    if _factored_too_big(snap):
        from autoscaler_tpu.ops.pallas_fit import fit_reduce_exact

        return fit_reduce_exact(snap).any_fit
    return fit_matrix(snap).any(axis=1)


def first_fit_node(snap: SnapshotTensors) -> jax.Array:
    """[P] i32 — lowest-index node each pod fits on, -1 if none. This is the
    deterministic analog of CheckPredicates over a candidate list; callers
    that place pods must re-fit after each placement (see ops/binpack.py for
    the sequential-correct scan)."""
    if _factored_too_big(snap):
        from autoscaler_tpu.ops.pallas_fit import fit_reduce_exact

        return fit_reduce_exact(snap).first_fit
    fits = fit_matrix(snap)
    idx = jnp.argmax(fits, axis=1).astype(jnp.int32)
    return jnp.where(fits.any(axis=1), idx, -1)


def fit_reason_matrix(snap: SnapshotTensors) -> jax.Array:
    """[P, N] i32 — WHY pod i does not fit node j right now, as a reason
    code from explain/reasons.py (REASON_NONE where it fits): the
    per-constraint violation mask `fit_matrix` reduces away, kept. Same
    priority chain as the estimator's template attribution
    (ops/binpack.attribute_unschedulable), so "why is this pod pending"
    and "why would a new node not help" speak one vocabulary. Refuses
    factored-mask worlds past the dense-cell limit, like fit_matrix."""
    from autoscaler_tpu.ops.binpack import _reason_codes_one

    if _factored_too_big(snap):
        raise ValueError(
            f"fit_reason_matrix would materialize "
            f"{snap.num_pods * snap.num_nodes} cells from a factored-mask "
            "snapshot; attribute against group templates instead "
            "(ops.binpack.attribute_unschedulable)"
        )
    free = snap.free()                                           # [N, R]
    mask = (
        snap.dense_sched()
        & snap.pod_valid[:, None]
        & snap.node_valid[None, :]
    )                                                            # [P, N]
    involved = jnp.zeros((snap.pod_req.shape[0],), bool)

    def one(free_n, mask_n):
        fits = jnp.all(snap.pod_req <= free_n[None, :], axis=1) & mask_n
        return _reason_codes_one(snap.pod_req, mask_n, free_n, fits, involved)

    return jax.vmap(one, in_axes=(0, 0), out_axes=1)(free, mask.T)


def pending_fit_reasons(snap: SnapshotTensors) -> jax.Array:
    """[P] i32 — each pod's dominant no-fit reason against the CURRENT
    cluster: the MIN code over nodes (reasons.py orders codes by severity,
    nearest-to-schedulable first). REASON_NONE means some node fits now;
    a world with no valid nodes attributes everything to the predicate
    mask (there is no node to measure resources against)."""
    from autoscaler_tpu.explain.reasons import REASON_TOPOLOGY

    codes = fit_reason_matrix(snap)
    return jnp.min(codes, axis=1, initial=REASON_TOPOLOGY).astype(jnp.int32)


fit_matrix_jit = jax.jit(fit_matrix, static_argnames="precision")
fits_any_node_jit = jax.jit(fits_any_node)
