"""Pallas FFD scan with dynamic inter-pod (anti-)affinity — the VMEM fast
path for the reference's single worst scalability case.

The reference documents inter-pod affinity as ~1000× the cost of every other
predicate combined (FAQ.md:151-153) because the InterPodAffinity plugin
re-runs after every simulated placement (binpacking_estimator.go:119-141).
The XLA scan twin (ops/binpack.ffd_binpack_groups_affinity) already turns
that into batched domain arithmetic, but it is HBM-bound the same way the
plain scan was (~50-80µs/step: the [G,T,M] count carries round-trip HBM on
every step, plus per-step gathers of the pod's term rows).

Key observation that makes a VMEM-resident Pallas twin fit: every affinity
gate consumes only the ZERO/NONZERO state of the count planes —
`dom_pm > 0`, `pm_tot == 0`, `ha_tot > 0` (ops/binpack._affinity_node_gates)
— never the magnitudes. So the carry packs T terms as BITS, 32 per i32
plane: `pm_bits/ha_bits [TP, M, GB]` (term t's bit set on node m ⇔ a
matching/anti-holding pod was scan-placed there) and `pm_tot/ha_tot
[TP, GB]` group-domain bitsets, TP = ceil(T/32). At T=64, M=1024, GB=128
that is ~4MB — resident in VMEM for the whole scan next to the free-capacity
carry, with the same nodes-on-sublanes layout as the plain kernel
(ops/pallas_binpack._scan_kernel): every per-step vector is a GB lane
vector, bit-plane ops are [M, GB] i32 elementwise, and the first-fit min is
a sublane reduction.

Gate algebra, transcribed bit-parallel from _affinity_node_gates (viol bits
nonzero ⇒ node vetoed; `dom` blends hostname-level planes with group totals
via the nl bitmask; `seed = m_p & ~pm_tot` is the Kubernetes self-match
seeding rule):

  dom_pm[m] = (pm_bits[m] & nl) | (pm_tot & ~nl)
  viol_aff[m]  = a_p & (~hl | ~(dom_pm[m] | seed))
  viol_anti[m] = x_p & dom_pm[m] & hl
  viol_sym[m]  = m_p & dom_ha[m] & hl
  gate_open[m] = (viol_aff | viol_anti | viol_sym) == 0

  new_viol = a_p & ~( (nl & seed) | (~nl & hl & (pm_tot | seed)) )
           | x_p & ~nl & pm_tot & hl
           | m_p & ~nl & ha_tot & hl
  new_ok   = new_viol == 0

The open-new-node rule folds into the one first-fit min exactly like the
plain kernel (closed nodes hold free == alloc): the per-node gate blends
`where(m < opened, gate_open[m], new_ok)`, so the min lands on the first
admitting open node, else on index `opened` when the pod may seed a fresh
node. Parity is locked against ffd_binpack_groups_affinity (itself
serial-oracle-locked) in tests/test_pallas_affinity.py.

Hard topology spread needs real COUNTS (maxSkew arithmetic), not bits, so
its state rides as S <= 32 i32 COUNT planes (`spc [S, M, GB]` + group
totals) next to the affinity bitsets, with the pod's sp_of/sp_match sets
as two more bitset payload planes — the count-plane transcription of
ops/binpack._spread_gates (see _scan_kernel_aff's docstring). Larger term
sets route to the XLA scan (estimator pre-check).

Reference algorithm: binpacking_estimator.go:65-141 + the InterPodAffinity
filter semantics over scan-placed pods.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.6 names the TPU compiler-params struct TPUCompilerParams; the
# rename to CompilerParams landed alongside jax.shard_map's promotion
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from autoscaler_tpu.ops.telemetry import observed
from autoscaler_tpu.ops.binpack import BinpackResult, ffd_scores
from autoscaler_tpu.ops.pallas_binpack import (
    BIG_I32,
    VMEM_BUDGET,
    _STEP_TILE,
    allocs_to_used,
    clamp_inf_allocs,
)


# Machine-readable kernel contract (graftlint GL007, analysis/contracts.py).
# Shared operand names (pod_req, pod_masks, ...) must agree with the plain
# twin's contract on rank and dtype — the checker enforces it, so an
# f32→i32 repack drift between the twins is a lint failure.
KERNEL_CONTRACTS = {
    "ffd_binpack_groups_affinity_pallas": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "pod_masks": {"dims": ["G", "P"], "dtype": "bool"},
            "template_allocs": {"dims": ["G", "R"], "dtype": "f32"},
            "match": {"dims": ["T", "P"], "dtype": "bool"},
            "aff_of": {"dims": ["T", "P"], "dtype": "bool"},
            "anti_of": {"dims": ["T", "P"], "dtype": "bool"},
            "node_level": {"dims": ["T"], "dtype": "bool"},
            "has_label": {"dims": ["G", "T"], "dtype": "bool"},
            "node_caps": {"dims": ["G"], "dtype": "i32"},
        },
        "static": {
            "chunk": {"multiple_of": "_STEP_TILE", "min": 8, "optional": True},
            "max_nodes": {"min": 1},
        },
        "pad": {
            "P_pad": ["P", "chunk"],
            "G_pad": ["G", "group_block"],
            "M_pad": ["max_nodes", "_STEP_TILE"],
        },
        "grid": ["G_pad // group_block", "P_pad // chunk"],
        "pad_value": "+inf request rows; sentinel term bitsets on pad slots",
        "vmem": "affinity_vmem_estimate",
    },
}


def affinity_vmem_estimate(
    R: int, TP: int, max_nodes: int, chunk: int, group_block: int = 128,
    S: int = 0,
) -> int:
    """Byte model for one grid program of the affinity(+spread) kernel —
    the SINGLE source for both the kernel's chunk auto-sizer and the
    estimator's routing pre-check (so the gate cannot drift from the
    layout): Mosaic double-buffers the request + bit(+spread) streams and
    the placed output; the free carry, the 2·TP term-bit planes, and the
    S spread count planes are revisited (resident)."""
    M_lanes = max_nodes + (-max_nodes) % 128
    sp_stream = 2 if S else 0
    return (
        2 * (R + 3 * TP + sp_stream) * chunk * group_block
        + (R + 2 * TP + S) * group_block * M_lanes
        + 2 * chunk * group_block
    ) * 4 + 3 * 1024 * 1024


def _pack_term_bits(rows: jax.Array, TP: int) -> jax.Array:
    """[T, N] bool → [TP, N] i32 bitsets (term t → bit t%32 of plane t//32)."""
    T, N = rows.shape
    pad = TP * 32 - T
    r = jnp.pad(rows.astype(jnp.int32), ((0, pad), (0, 0)))
    r = r.reshape(TP, 32, N)
    weights = jnp.left_shift(
        jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32)
    )
    planes = jnp.sum(
        r.astype(jnp.uint32) * weights[None, :, None], axis=1, dtype=jnp.uint32
    )
    return jax.lax.bitcast_convert_type(planes, jnp.int32)


def _scan_kernel_aff(
    *refs,
    num_resources: int,
    num_planes: int,
    num_spread: int,
    chunk: int,
    max_nodes: int,
):
    """Affinity (+optional hard-spread) scan step. Refs, in in_specs order:

      req [R, CHUNK, GB] f32, mbits/abits/xbits [TP, CHUNK, GB] i32,
      (spof, spmt [1, CHUNK, GB] i32 — pod spread bitsets, S <= 32,)
      caps [1, GB] i32, allocs [R, GB] f32, nl/hl [TP, GB] i32,
      (spstat [8, S, GB] i32 — per-(term, group) statics in the order
       nl_s, hl_s, skew, mind, st_count, min_others_eff, st_min,
       st_domnum,)
      then outputs: free [R, M, GB] f32, opened [1, GB] i32,
      pm/ha [TP, M, GB] i32, pmt/hat [TP, GB] i32,
      (spc [S, M, GB] i32, spct [S, GB] i32,) placed [CHUNK, GB] i32.

    The spread gates are the count-plane transcription of
    ops/binpack._spread_gates: group-level terms compare
    st_count + scan_total against the precomputed min-over-other-domains
    (force_zero folded into min_others_eff = 0), hostname-level terms
    recompute the masked min over OPEN nodes' scan counts each step, and
    minDomains folds the effective min to 0 while st_domnum + opened
    stays below it. node_ok applies to open nodes only — a fresh node is
    its own 0-count domain and can never violate a hostname term
    (max_skew >= 1), matching the XLA kernel's can_open composition."""
    R, TP, S = num_resources, num_planes, num_spread
    it = iter(refs)
    req_ref = next(it)
    mbits_ref, abits_ref, xbits_ref = next(it), next(it), next(it)
    if S:
        spof_ref, spmt_ref = next(it), next(it)
    caps_ref, allocs_ref, nl_ref, hl_ref = next(it), next(it), next(it), next(it)
    if S:
        spstat_ref = next(it)
    free_ref, opened_ref = next(it), next(it)
    pm_ref, ha_ref, pmt_ref, hat_ref = next(it), next(it), next(it), next(it)
    if S:
        spc_ref, spct_ref = next(it), next(it)
    placed_ref = next(it)

    gb = free_ref.shape[2]
    M = free_ref.shape[1]
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (M, gb), 0)
    caps = caps_ref[0, :]

    @pl.when(pl.program_id(1) == 0)
    def _init():
        for r in range(R):
            free_ref[r, :, :] = jnp.broadcast_to(
                allocs_ref[r, :][None, :], (M, gb)
            )
        opened_ref[:] = jnp.zeros((1, gb), jnp.int32)
        for tp in range(TP):
            pm_ref[tp, :, :] = jnp.zeros((M, gb), jnp.int32)
            ha_ref[tp, :, :] = jnp.zeros((M, gb), jnp.int32)
        pmt_ref[:] = jnp.zeros((TP, gb), jnp.int32)
        hat_ref[:] = jnp.zeros((TP, gb), jnp.int32)
        if S:
            for sp_i in range(S):
                spc_ref[sp_i, :, :] = jnp.zeros((M, gb), jnp.int32)
            spct_ref[:] = jnp.zeros((S, gb), jnp.int32)

    def tile_step(t, _):
        base = t * _STEP_TILE
        req_tiles = [req_ref[r, pl.ds(base, _STEP_TILE), :] for r in range(R)]
        m_tiles = [mbits_ref[tp, pl.ds(base, _STEP_TILE), :] for tp in range(TP)]
        a_tiles = [abits_ref[tp, pl.ds(base, _STEP_TILE), :] for tp in range(TP)]
        x_tiles = [xbits_ref[tp, pl.ds(base, _STEP_TILE), :] for tp in range(TP)]
        if S:
            spof_tile = spof_ref[0, pl.ds(base, _STEP_TILE), :]
            spmt_tile = spmt_ref[0, pl.ds(base, _STEP_TILE), :]
        placed_rows = []

        for st in range(_STEP_TILE):
            opened = opened_ref[0, :]
            req = [req_tiles[r][st, :] for r in range(R)]
            m_p = [m_tiles[tp][st, :] for tp in range(TP)]
            a_p = [a_tiles[tp][st, :] for tp in range(TP)]
            x_p = [x_tiles[tp][st, :] for tp in range(TP)]

            fits = req[0][None, :] <= free_ref[0]
            for r in range(1, R):
                fits &= req[r][None, :] <= free_ref[r]

            # --- bit-parallel affinity gates (module docstring algebra) ---
            bad = None
            new_viol = None
            for tp in range(TP):
                nl = nl_ref[tp, :]
                hl = hl_ref[tp, :]
                pmt = pmt_ref[tp, :]
                hat = hat_ref[tp, :]
                seed = m_p[tp] & ~pmt
                dom_pm = (pm_ref[tp] & nl[None, :]) | (pmt & ~nl)[None, :]
                dom_ha = (ha_ref[tp] & nl[None, :]) | (hat & ~nl)[None, :]
                viol = (
                    (a_p[tp][None, :] & (~hl[None, :] | ~(dom_pm | seed[None, :])))
                    | (x_p[tp][None, :] & dom_pm & hl[None, :])
                    | (m_p[tp][None, :] & dom_ha & hl[None, :])
                )
                bad = viol if bad is None else (bad | viol)
                nv = (
                    (a_p[tp] & ~((nl & seed) | (~nl & hl & (pmt | seed))))
                    | (x_p[tp] & ~nl & pmt & hl)
                    | (m_p[tp] & ~nl & hat & hl)
                )
                new_viol = nv if new_viol is None else (new_viol | nv)

            gate_open = bad == 0
            new_ok = new_viol == 0
            is_open = node_iota < opened[None, :]

            # --- count-plane spread gates (_spread_gates transcription) ---
            if S:
                spof = spof_tile[st, :]                 # [GB] i32 bitsets
                spmt = spmt_tile[st, :]
                group_ok = None                         # [GB] bool
                node_bad = None                         # [M, GB] bool
                upds = []                               # S × [GB] i32 0/1
                for sp_i in range(S):
                    one = jnp.int32(1)
                    sp_o = ((spof >> sp_i) & one) != 0      # [GB] bool
                    self_i = (spmt >> sp_i) & one           # [GB] i32
                    nl_s = spstat_ref[0, sp_i, :] != 0
                    hl_s = spstat_ref[1, sp_i, :] != 0
                    skew = spstat_ref[2, sp_i, :]
                    mind = spstat_ref[3, sp_i, :]
                    st_count = spstat_ref[4, sp_i, :]
                    min_others_eff = spstat_ref[5, sp_i, :]
                    st_min = spstat_ref[6, sp_i, :]
                    st_domnum = spstat_ref[7, sp_i, :]
                    upds.append((self_i != 0) & hl_s)
                    # group-level
                    cnt = st_count + spct_ref[sp_i, :]
                    min_eff_z = jnp.minimum(min_others_eff, cnt)
                    bad_z = (
                        sp_o & ~nl_s & hl_s
                        & (cnt + self_i - min_eff_z > skew)
                    )
                    group_ok = (
                        ~bad_z if group_ok is None else (group_ok & ~bad_z)
                    )
                    # hostname-level: masked min over OPEN nodes' counts
                    dyn_min = jnp.min(
                        jnp.where(is_open, spc_ref[sp_i], BIG_I32), axis=0
                    )                                       # [GB]
                    domnum = st_domnum + opened
                    min_eff_h = jnp.where(
                        mind > domnum, 0, jnp.minimum(st_min, dyn_min)
                    )
                    bad_h = (
                        sp_o[None, :] & nl_s[None, :]
                        & (spc_ref[sp_i] + self_i[None, :]
                           - min_eff_h[None, :] > skew[None, :])
                    )
                    node_bad = bad_h if node_bad is None else (node_bad | bad_h)
                gate = jnp.where(
                    is_open, gate_open & ~node_bad, new_ok[None, :]
                ) & group_ok[None, :]
            else:
                gate = jnp.where(is_open, gate_open, new_ok[None, :])
            fits &= gate

            first = jnp.min(jnp.where(fits, node_iota, BIG_I32), axis=0)
            place = first < caps
            target = jnp.where(place, first, -1)

            hit = node_iota == target[None, :]
            for r in range(R):
                sub = jnp.where(place, req[r], 0.0)[None, :]
                free_ref[r, :, :] = free_ref[r] - jnp.where(hit, sub, 0.0)
            zero = jnp.int32(0)
            for tp in range(TP):
                m_add = jnp.where(place, m_p[tp], zero)
                x_add = jnp.where(place, x_p[tp], zero)
                pm_ref[tp, :, :] = pm_ref[tp] | jnp.where(hit, m_add[None, :], zero)
                ha_ref[tp, :, :] = ha_ref[tp] | jnp.where(hit, x_add[None, :], zero)
                pmt_ref[tp, :] = pmt_ref[tp, :] | m_add
                hat_ref[tp, :] = hat_ref[tp, :] | x_add
            if S:
                for sp_i in range(S):
                    u = jnp.where(place & upds[sp_i], jnp.int32(1), zero)
                    spc_ref[sp_i, :, :] = spc_ref[sp_i] + jnp.where(
                        hit, u[None, :], zero
                    )
                    spct_ref[sp_i, :] = spct_ref[sp_i, :] + u
            opened_ref[0, :] = jnp.maximum(
                opened, jnp.where(place, first + 1, 0)
            )
            placed_rows.append(place.astype(jnp.int32))

        placed_ref[pl.ds(base, _STEP_TILE), :] = jnp.stack(placed_rows, axis=0)
        return 0

    jax.lax.fori_loop(0, chunk // _STEP_TILE, tile_step, 0)


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "chunk", "group_block", "interpret"),
)
def _pallas_scan_aff(
    stream,        # [R, P_pad, G_pad] f32
    bit_stream,    # [3*TP, P_pad, G_pad] i32 (match, aff, anti plane groups)
    allocs_in,     # [R, G_pad] f32
    caps_row,      # [1, G_pad] i32
    nl_planes,     # [TP, G_pad] i32
    hl_planes,     # [TP, G_pad] i32
    sp_stream,     # [2, P_pad, G_pad] i32 (sp_of, sp_match bitsets) | None
    sp_stat,       # [8, S, G_pad] i32 statics | None
    max_nodes: int,
    chunk: int,
    group_block: int,
    interpret: bool,
):
    R, P_pad, G_pad = stream.shape
    TP = bit_stream.shape[0] // 3
    S = sp_stat.shape[1] if sp_stat is not None else 0
    NC = P_pad // chunk
    M_pad = max_nodes + (-max_nodes) % _STEP_TILE
    kernel = functools.partial(
        _scan_kernel_aff,
        num_resources=R, num_planes=TP, num_spread=S,
        chunk=chunk, max_nodes=max_nodes,
    )
    mb, ab, xb = (
        bit_stream[:TP], bit_stream[TP:2 * TP], bit_stream[2 * TP:]
    )
    chunk_spec = lambda n: pl.BlockSpec(  # noqa: E731
        (n, chunk, group_block), lambda g, c: (0, c, g)
    )
    row_spec = lambda n: pl.BlockSpec(  # noqa: E731
        (n, group_block), lambda g, c: (0, g)
    )
    carry_spec = lambda n: pl.BlockSpec(  # noqa: E731
        (n, M_pad, group_block), lambda g, c: (0, 0, g)
    )
    in_specs = [
        chunk_spec(R), chunk_spec(TP), chunk_spec(TP), chunk_spec(TP),
    ]
    operands = [stream, mb, ab, xb]
    if S:
        in_specs += [chunk_spec(1), chunk_spec(1)]
        operands += [sp_stream[:1], sp_stream[1:]]
    in_specs += [row_spec(1), row_spec(R), row_spec(TP), row_spec(TP)]
    operands += [caps_row, allocs_in, nl_planes, hl_planes]
    if S:
        in_specs.append(
            pl.BlockSpec((8, S, group_block), lambda g, c: (0, 0, g))
        )
        operands.append(sp_stat)
    out_specs = [
        carry_spec(R), row_spec(1),
        carry_spec(TP), carry_spec(TP), row_spec(TP), row_spec(TP),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((R, M_pad, G_pad), jnp.float32),
        jax.ShapeDtypeStruct((1, G_pad), jnp.int32),
        jax.ShapeDtypeStruct((TP, M_pad, G_pad), jnp.int32),
        jax.ShapeDtypeStruct((TP, M_pad, G_pad), jnp.int32),
        jax.ShapeDtypeStruct((TP, G_pad), jnp.int32),
        jax.ShapeDtypeStruct((TP, G_pad), jnp.int32),
    ]
    if S:
        out_specs += [carry_spec(S), row_spec(S)]
        out_shape += [
            jax.ShapeDtypeStruct((S, M_pad, G_pad), jnp.int32),
            jax.ShapeDtypeStruct((S, G_pad), jnp.int32),
        ]
    out_specs.append(
        pl.BlockSpec((chunk, group_block), lambda g, c: (c, g))
    )
    out_shape.append(jax.ShapeDtypeStruct((P_pad, G_pad), jnp.int32))
    outs = pl.pallas_call(
        kernel,
        grid=(G_pad // group_block, NC),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    # (free, opened, ..., placed) — callers use free, opened, placed
    return outs[0], outs[1], outs[-1]


@observed
def ffd_binpack_groups_affinity_pallas(
    pod_req,          # [P, R]
    pod_masks,        # [G, P] bool
    template_allocs,  # [G, R]
    max_nodes: int,
    match,            # [T, P] bool
    aff_of,           # [T, P] bool
    anti_of,          # [T, P] bool
    node_level,       # [T] bool
    has_label,        # [G, T] bool
    node_caps=None,   # [G] i32
    spread: tuple | None = None,  # SpreadTermTensors 11-tuple (ops/binpack)
    chunk: int | None = None,
    group_block: int = 0,
    interpret: bool | None = None,
    attribution: bool = False,
):
    """Drop-in twin of ffd_binpack_groups_affinity in Pallas, incl. the
    optional hard-topology-spread gates (count-plane carry; S <= 32).

    Same payload-sort / fused-grid / unsort structure as
    ffd_binpack_groups_pallas, with three extra sorted payload plane-groups
    carrying the pod's packed term bitsets (plus two spread bitset planes
    when spread terms exist). No SWAR/axis-compression here — the term
    state, not the resource planes, dominates the step.

    attribution=True returns ``(BinpackResult, reasons [G, P] i32)``: per-
    (pod, group) rejection reason codes (explain/reasons.py) from
    ops/binpack.attribute_unschedulable over the same operands, with the
    involvement mask derived from the term tensors — a pod matching or
    holding any (anti-)affinity or spread term attributes its leftover
    unschedulability to the dynamic gates, not the node cap."""
    if chunk is not None and chunk % _STEP_TILE != 0:
        raise ValueError(
            f"chunk must be a multiple of {_STEP_TILE} (sublane tile); got {chunk}"
        )
    pod_req = jnp.asarray(pod_req, jnp.float32)
    pod_masks = jnp.asarray(pod_masks)
    template_allocs = jnp.asarray(template_allocs, jnp.float32)
    match = jnp.asarray(match).astype(bool)
    aff_of = jnp.asarray(aff_of).astype(bool)
    anti_of = jnp.asarray(anti_of).astype(bool)
    attr_operands = (
        (pod_req, pod_masks, template_allocs) if attribution else None
    )
    node_level = jnp.asarray(node_level).astype(bool)
    has_label = jnp.asarray(has_label).astype(bool)
    P, R = pod_req.shape
    G = pod_masks.shape[0]
    T = match.shape[0]
    TP = max((T + 31) // 32, 1)
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(jnp.asarray(node_caps, jnp.int32), max_nodes)[None, :]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if group_block <= 0:
        group_block = 128 if not interpret else 8
    G_pad = G + (-G) % group_block
    if G_pad != G:
        pad = G_pad - G
        pod_masks = jnp.pad(pod_masks, ((0, pad), (0, 0)))
        template_allocs = jnp.pad(template_allocs, ((0, pad), (0, 0)))
        caps = jnp.pad(caps, ((0, 0), (0, pad)))
        has_label = jnp.pad(has_label, ((0, pad), (0, 0)))

    scores = jax.vmap(lambda alloc: ffd_scores(pod_req, alloc))(template_allocs)

    template_allocs = clamp_inf_allocs(pod_req, template_allocs)

    S_terms = spread[0].shape[1] if spread is not None else 0
    if chunk is None:
        chunk = 256
        for cand in (512,):
            if affinity_vmem_estimate(
                R, TP, max_nodes, cand, group_block, S=S_terms
            ) <= VMEM_BUDGET:
                chunk = cand
        while chunk > _STEP_TILE and chunk // 2 >= P:
            chunk //= 2

    P_pad = P + (-P) % chunk
    pad_cols = P_pad - P

    # term bitsets per pod: [TP, P] planes, sorted as i32 payloads
    mbits = _pack_term_bits(match, TP)
    abits = _pack_term_bits(aff_of, TP)
    xbits = _pack_term_bits(anti_of, TP)
    nl_plane = _pack_term_bits(node_level[:, None], TP)[:, 0]          # [TP]
    hl_planes = _pack_term_bits(has_label.T, TP)                       # [TP, G_pad]
    nl_planes = jnp.broadcast_to(nl_plane[:, None], (TP, G_pad))

    # optional spread state: pod bitset payloads + per-(term, group) statics
    sp_stat = None
    sp_of_col = sp_match_col = None
    if spread is not None:
        (sp_of_T, sp_match_T, sp_nl, sp_skew, sp_mind, sp_hl, sp_stc,
         sp_mino, sp_stmin, sp_stdom, sp_fz) = spread
        S = sp_of_T.shape[1]
        if S > 32:
            raise ValueError(
                f"spread bitset payload holds at most 32 terms; got {S} "
                "(route larger term sets to the XLA scan)"
            )
        sp_of_col = _pack_term_bits(jnp.asarray(sp_of_T).T.astype(bool), 1)[0]
        sp_match_col = _pack_term_bits(
            jnp.asarray(sp_match_T).T.astype(bool), 1
        )[0]                                                           # [P]
        g_extra = G_pad - jnp.asarray(sp_hl).shape[0]

        def _gpad(a):
            a = jnp.asarray(a, jnp.int32)
            return jnp.pad(a, ((0, g_extra), (0, 0))).T               # [S, G_pad]

        def _bcast(a):
            return jnp.broadcast_to(
                jnp.asarray(a, jnp.int32)[:, None], (S, G_pad)
            )

        # force_zero folds into the group-level min: min(0, cnt) == 0
        mino_eff = jnp.where(
            jnp.asarray(sp_fz, bool), 0, jnp.asarray(sp_mino, jnp.int32)
        )
        sp_stat = jnp.stack([
            _bcast(jnp.asarray(sp_nl, bool).astype(jnp.int32)),
            _gpad(jnp.asarray(sp_hl, bool).astype(jnp.int32)),
            _bcast(sp_skew), _bcast(sp_mind),
            _gpad(sp_stc), _gpad(mino_eff), _gpad(sp_stmin),
            _gpad(sp_stdom),
        ])                                                            # [8, S, G_pad]

    iota = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (G_pad, P))
    cols = [
        jnp.where(
            pod_masks,
            jnp.broadcast_to(pod_req[:, r][None, :], (G_pad, P)),
            jnp.inf,
        )
        for r in range(R)
    ]
    bit_cols = [
        jnp.broadcast_to(b[None, :], (G_pad, P))
        for planes in (mbits, abits, xbits)
        for b in planes
    ]
    if spread is not None:
        bit_cols += [
            jnp.broadcast_to(sp_of_col[None, :], (G_pad, P)),
            jnp.broadcast_to(sp_match_col[None, :], (G_pad, P)),
        ]
    sorted_ops = jax.lax.sort(
        [-scores, iota, *cols, *bit_cols],
        dimension=1, is_stable=True, num_keys=1,
    )
    sorted_iota = sorted_ops[1]
    stream = jnp.stack(
        [
            jnp.pad(c, ((0, 0), (0, pad_cols)), constant_values=jnp.inf).T
            for c in sorted_ops[2:2 + R]
        ]
    )
    bit_end = 2 + R + 3 * TP
    bit_stream = jnp.stack(
        [
            jnp.pad(c, ((0, 0), (0, pad_cols))).T
            for c in sorted_ops[2 + R:bit_end]
        ]
    )
    sp_stream = None
    if spread is not None:
        sp_stream = jnp.stack(
            [
                jnp.pad(c, ((0, 0), (0, pad_cols))).T
                for c in sorted_ops[bit_end:]
            ]
        )

    free, opened, placed = _pallas_scan_aff(
        stream, bit_stream, template_allocs.T, caps,
        nl_planes, hl_planes, sp_stream, sp_stat,
        max_nodes=max_nodes, chunk=chunk, group_block=group_block,
        interpret=interpret,
    )

    _, scheduled_i = jax.lax.sort(
        [sorted_iota, placed.T[:, :P].astype(jnp.uint8)],
        dimension=1, is_stable=False, num_keys=1,
    )
    scheduled = scheduled_i[:G] > 0

    used = allocs_to_used(template_allocs, free)
    node_used = jnp.transpose(used, (2, 1, 0))[:G, :max_nodes]
    result = BinpackResult(
        node_count=opened[0, :G],
        scheduled=scheduled,
        node_used=node_used,
    )
    if attr_operands is None:
        return result
    from autoscaler_tpu.ops.binpack import attribute_unschedulable

    a_req, a_masks, a_allocs = attr_operands
    involved = (match | aff_of | anti_of).any(axis=0)
    if spread is not None:
        involved = involved | (sp_of_col > 0) | (sp_match_col > 0)
    reasons = attribute_unschedulable(
        a_req, a_masks, a_allocs, scheduled, involved
    )
    return result, reasons
