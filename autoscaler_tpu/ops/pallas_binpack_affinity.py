"""Pallas FFD scan with dynamic inter-pod (anti-)affinity — the VMEM fast
path for the reference's single worst scalability case.

The reference documents inter-pod affinity as ~1000× the cost of every other
predicate combined (FAQ.md:151-153) because the InterPodAffinity plugin
re-runs after every simulated placement (binpacking_estimator.go:119-141).
The XLA scan twin (ops/binpack.ffd_binpack_groups_affinity) already turns
that into batched domain arithmetic, but it is HBM-bound the same way the
plain scan was (~50-80µs/step: the [G,T,M] count carries round-trip HBM on
every step, plus per-step gathers of the pod's term rows).

Key observation that makes a VMEM-resident Pallas twin fit: every affinity
gate consumes only the ZERO/NONZERO state of the count planes —
`dom_pm > 0`, `pm_tot == 0`, `ha_tot > 0` (ops/binpack._affinity_node_gates)
— never the magnitudes. So the carry packs T terms as BITS, 32 per i32
plane: `pm_bits/ha_bits [TP, M, GB]` (term t's bit set on node m ⇔ a
matching/anti-holding pod was scan-placed there) and `pm_tot/ha_tot
[TP, GB]` group-domain bitsets, TP = ceil(T/32). At T=64, M=1024, GB=128
that is ~4MB — resident in VMEM for the whole scan next to the free-capacity
carry, with the same nodes-on-sublanes layout as the plain kernel
(ops/pallas_binpack._scan_kernel): every per-step vector is a GB lane
vector, bit-plane ops are [M, GB] i32 elementwise, and the first-fit min is
a sublane reduction.

Gate algebra, transcribed bit-parallel from _affinity_node_gates (viol bits
nonzero ⇒ node vetoed; `dom` blends hostname-level planes with group totals
via the nl bitmask; `seed = m_p & ~pm_tot` is the Kubernetes self-match
seeding rule):

  dom_pm[m] = (pm_bits[m] & nl) | (pm_tot & ~nl)
  viol_aff[m]  = a_p & (~hl | ~(dom_pm[m] | seed))
  viol_anti[m] = x_p & dom_pm[m] & hl
  viol_sym[m]  = m_p & dom_ha[m] & hl
  gate_open[m] = (viol_aff | viol_anti | viol_sym) == 0

  new_viol = a_p & ~( (nl & seed) | (~nl & hl & (pm_tot | seed)) )
           | x_p & ~nl & pm_tot & hl
           | m_p & ~nl & ha_tot & hl
  new_ok   = new_viol == 0

The open-new-node rule folds into the one first-fit min exactly like the
plain kernel (closed nodes hold free == alloc): the per-node gate blends
`where(m < opened, gate_open[m], new_ok)`, so the min lands on the first
admitting open node, else on index `opened` when the pod may seed a fresh
node. Parity is locked against ffd_binpack_groups_affinity (itself
serial-oracle-locked) in tests/test_pallas_affinity.py.

Spread-carrying workloads stay on the XLA scan: hard topology spread needs
real COUNTS (maxSkew arithmetic), not bits — a count-plane variant is the
natural extension but is not built yet (estimator routing sends spread to
the XLA kernels).

Reference algorithm: binpacking_estimator.go:65-141 + the InterPodAffinity
filter semantics over scan-placed pods.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autoscaler_tpu.ops.binpack import BinpackResult, ffd_scores
from autoscaler_tpu.ops.pallas_binpack import BIG_I32, _STEP_TILE, allocs_to_used


VMEM_BUDGET = 15 * 1024 * 1024   # v5e has 16MB; leave Mosaic headroom


def affinity_vmem_estimate(
    R: int, TP: int, max_nodes: int, chunk: int, group_block: int = 128
) -> int:
    """Byte model for one grid program of the affinity kernel — the SINGLE
    source for both the kernel's chunk auto-sizer and the estimator's
    routing pre-check (so the gate cannot drift from the layout): Mosaic
    double-buffers the request + bit streams and the placed output; the
    free carry plus the 2·TP term-bit planes are revisited (resident)."""
    M_lanes = max_nodes + (-max_nodes) % 128
    return (
        2 * (R + 3 * TP) * chunk * group_block   # double-buffered streams
        + (R + 2 * TP) * group_block * M_lanes   # resident carry planes
        + 2 * chunk * group_block                # double-buffered placed
    ) * 4 + 3 * 1024 * 1024                      # Mosaic scratch


def _pack_term_bits(rows: jax.Array, TP: int) -> jax.Array:
    """[T, N] bool → [TP, N] i32 bitsets (term t → bit t%32 of plane t//32)."""
    T, N = rows.shape
    pad = TP * 32 - T
    r = jnp.pad(rows.astype(jnp.int32), ((0, pad), (0, 0)))
    r = r.reshape(TP, 32, N)
    weights = jnp.left_shift(
        jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32)
    )
    planes = jnp.sum(
        r.astype(jnp.uint32) * weights[None, :, None], axis=1, dtype=jnp.uint32
    )
    return jax.lax.bitcast_convert_type(planes, jnp.int32)


def _scan_kernel_aff(
    req_ref,       # [R, CHUNK, GB] f32 — sorted requests, +inf = inactive
    mbits_ref,     # [TP, CHUNK, GB] i32 — candidate pod's match bits
    abits_ref,     # [TP, CHUNK, GB] i32 — pod's required-affinity bits
    xbits_ref,     # [TP, CHUNK, GB] i32 — pod's anti-affinity bits
    caps_ref,      # [1, GB] i32
    allocs_ref,    # [R, GB] f32
    nl_ref,        # [TP, GB] i32 — node-level (hostname) term bitmask
    hl_ref,        # [TP, GB] i32 — group-template-has-label bitmask
    free_ref,      # [R, M, GB] f32 out — VMEM-resident carry
    opened_ref,    # [1, GB] i32 out
    pm_ref,        # [TP, M, GB] i32 out — match bits per node
    ha_ref,        # [TP, M, GB] i32 out — anti-holder bits per node
    pmt_ref,       # [TP, GB] i32 out — match bits anywhere in the group
    hat_ref,       # [TP, GB] i32 out — anti-holder bits anywhere
    placed_ref,    # [CHUNK, GB] i32 out
    *,
    num_resources: int,
    num_planes: int,
    chunk: int,
    max_nodes: int,
):
    gb = free_ref.shape[2]
    R = num_resources
    TP = num_planes
    M = free_ref.shape[1]
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (M, gb), 0)
    caps = caps_ref[0, :]

    @pl.when(pl.program_id(1) == 0)
    def _init():
        for r in range(R):
            free_ref[r, :, :] = jnp.broadcast_to(
                allocs_ref[r, :][None, :], (M, gb)
            )
        opened_ref[:] = jnp.zeros((1, gb), jnp.int32)
        for tp in range(TP):
            pm_ref[tp, :, :] = jnp.zeros((M, gb), jnp.int32)
            ha_ref[tp, :, :] = jnp.zeros((M, gb), jnp.int32)
        pmt_ref[:] = jnp.zeros((TP, gb), jnp.int32)
        hat_ref[:] = jnp.zeros((TP, gb), jnp.int32)

    def tile_step(t, _):
        base = t * _STEP_TILE
        req_tiles = [req_ref[r, pl.ds(base, _STEP_TILE), :] for r in range(R)]
        m_tiles = [mbits_ref[tp, pl.ds(base, _STEP_TILE), :] for tp in range(TP)]
        a_tiles = [abits_ref[tp, pl.ds(base, _STEP_TILE), :] for tp in range(TP)]
        x_tiles = [xbits_ref[tp, pl.ds(base, _STEP_TILE), :] for tp in range(TP)]
        placed_rows = []

        for s in range(_STEP_TILE):
            opened = opened_ref[0, :]                   # [GB]
            req = [req_tiles[r][s, :] for r in range(R)]
            m_p = [m_tiles[tp][s, :] for tp in range(TP)]   # [GB] i32 each
            a_p = [a_tiles[tp][s, :] for tp in range(TP)]
            x_p = [x_tiles[tp][s, :] for tp in range(TP)]

            fits = req[0][None, :] <= free_ref[0]       # [M, GB] capacity
            for r in range(1, R):
                fits &= req[r][None, :] <= free_ref[r]

            # --- bit-parallel affinity gates (module docstring algebra) ---
            bad = None          # [M, GB] i32 — any set bit vetoes the node
            new_viol = None     # [GB] i32 — any set bit vetoes a fresh node
            for tp in range(TP):
                nl = nl_ref[tp, :]                      # [GB] i32 masks
                hl = hl_ref[tp, :]
                pmt = pmt_ref[tp, :]
                hat = hat_ref[tp, :]
                seed = m_p[tp] & ~pmt
                dom_pm = (pm_ref[tp] & nl[None, :]) | (pmt & ~nl)[None, :]
                dom_ha = (ha_ref[tp] & nl[None, :]) | (hat & ~nl)[None, :]
                viol = (
                    (a_p[tp][None, :] & (~hl[None, :] | ~(dom_pm | seed[None, :])))
                    | (x_p[tp][None, :] & dom_pm & hl[None, :])
                    | (m_p[tp][None, :] & dom_ha & hl[None, :])
                )
                bad = viol if bad is None else (bad | viol)
                nv = (
                    (a_p[tp] & ~((nl & seed) | (~nl & hl & (pmt | seed))))
                    | (x_p[tp] & ~nl & pmt & hl)
                    | (m_p[tp] & ~nl & hat & hl)
                )
                new_viol = nv if new_viol is None else (new_viol | nv)

            gate_open = bad == 0                        # [M, GB]
            new_ok = new_viol == 0                      # [GB]
            is_open = node_iota < opened[None, :]
            gate = jnp.where(is_open, gate_open, new_ok[None, :])
            fits &= gate

            first = jnp.min(
                jnp.where(fits, node_iota, BIG_I32), axis=0
            )                                           # [GB]
            place = first < caps
            target = jnp.where(place, first, -1)

            hit = node_iota == target[None, :]          # [M, GB]
            for r in range(R):
                sub = jnp.where(place, req[r], 0.0)[None, :]
                free_ref[r, :, :] = free_ref[r] - jnp.where(hit, sub, 0.0)
            zero = jnp.int32(0)
            for tp in range(TP):
                m_add = jnp.where(place, m_p[tp], zero)
                x_add = jnp.where(place, x_p[tp], zero)
                pm_ref[tp, :, :] = pm_ref[tp] | jnp.where(hit, m_add[None, :], zero)
                ha_ref[tp, :, :] = ha_ref[tp] | jnp.where(hit, x_add[None, :], zero)
                pmt_ref[tp, :] = pmt_ref[tp, :] | m_add
                hat_ref[tp, :] = hat_ref[tp, :] | x_add
            opened_ref[0, :] = jnp.maximum(
                opened, jnp.where(place, first + 1, 0)
            )
            placed_rows.append(place.astype(jnp.int32))

        placed_ref[pl.ds(base, _STEP_TILE), :] = jnp.stack(placed_rows, axis=0)
        return 0

    jax.lax.fori_loop(0, chunk // _STEP_TILE, tile_step, 0)


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "chunk", "group_block", "interpret"),
)
def _pallas_scan_aff(
    stream,        # [R, P_pad, G_pad] f32
    bit_stream,    # [3*TP, P_pad, G_pad] i32 (match, aff, anti plane groups)
    allocs_in,     # [R, G_pad] f32
    caps_row,      # [1, G_pad] i32
    nl_planes,     # [TP, G_pad] i32
    hl_planes,     # [TP, G_pad] i32
    max_nodes: int,
    chunk: int,
    group_block: int,
    interpret: bool,
):
    R, P_pad, G_pad = stream.shape
    TP = bit_stream.shape[0] // 3
    NC = P_pad // chunk
    M_pad = max_nodes + (-max_nodes) % _STEP_TILE
    kernel = functools.partial(
        _scan_kernel_aff,
        num_resources=R, num_planes=TP, chunk=chunk, max_nodes=max_nodes,
    )
    mb, ab, xb = (
        bit_stream[:TP], bit_stream[TP:2 * TP], bit_stream[2 * TP:]
    )
    return pl.pallas_call(
        kernel,
        grid=(G_pad // group_block, NC),
        in_specs=[
            pl.BlockSpec((R, chunk, group_block), lambda g, c: (0, c, g)),
            pl.BlockSpec((TP, chunk, group_block), lambda g, c: (0, c, g)),
            pl.BlockSpec((TP, chunk, group_block), lambda g, c: (0, c, g)),
            pl.BlockSpec((TP, chunk, group_block), lambda g, c: (0, c, g)),
            pl.BlockSpec((1, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((R, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((TP, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((TP, group_block), lambda g, c: (0, g)),
        ],
        out_specs=[
            pl.BlockSpec((R, M_pad, group_block), lambda g, c: (0, 0, g)),
            pl.BlockSpec((1, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((TP, M_pad, group_block), lambda g, c: (0, 0, g)),
            pl.BlockSpec((TP, M_pad, group_block), lambda g, c: (0, 0, g)),
            pl.BlockSpec((TP, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((TP, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((chunk, group_block), lambda g, c: (c, g)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, M_pad, G_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, G_pad), jnp.int32),
            jax.ShapeDtypeStruct((TP, M_pad, G_pad), jnp.int32),
            jax.ShapeDtypeStruct((TP, M_pad, G_pad), jnp.int32),
            jax.ShapeDtypeStruct((TP, G_pad), jnp.int32),
            jax.ShapeDtypeStruct((TP, G_pad), jnp.int32),
            jax.ShapeDtypeStruct((P_pad, G_pad), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(stream, mb, ab, xb, caps_row, allocs_in, nl_planes, hl_planes)


def ffd_binpack_groups_affinity_pallas(
    pod_req,          # [P, R]
    pod_masks,        # [G, P] bool
    template_allocs,  # [G, R]
    max_nodes: int,
    match,            # [T, P] bool
    aff_of,           # [T, P] bool
    anti_of,          # [T, P] bool
    node_level,       # [T] bool
    has_label,        # [G, T] bool
    node_caps=None,   # [G] i32
    chunk: int | None = None,
    group_block: int = 0,
    interpret: bool | None = None,
) -> BinpackResult:
    """Drop-in twin of ffd_binpack_groups_affinity (no spread) in Pallas.

    Same payload-sort / fused-grid / unsort structure as
    ffd_binpack_groups_pallas, with three extra sorted payload plane-groups
    carrying the pod's packed term bitsets. No SWAR/axis-compression here —
    the affinity term state, not the resource planes, dominates the step."""
    if chunk is not None and chunk % _STEP_TILE != 0:
        raise ValueError(
            f"chunk must be a multiple of {_STEP_TILE} (sublane tile); got {chunk}"
        )
    pod_req = jnp.asarray(pod_req, jnp.float32)
    pod_masks = jnp.asarray(pod_masks)
    template_allocs = jnp.asarray(template_allocs, jnp.float32)
    match = jnp.asarray(match).astype(bool)
    aff_of = jnp.asarray(aff_of).astype(bool)
    anti_of = jnp.asarray(anti_of).astype(bool)
    node_level = jnp.asarray(node_level).astype(bool)
    has_label = jnp.asarray(has_label).astype(bool)
    P, R = pod_req.shape
    G = pod_masks.shape[0]
    T = match.shape[0]
    TP = max((T + 31) // 32, 1)
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(jnp.asarray(node_caps, jnp.int32), max_nodes)[None, :]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if group_block <= 0:
        group_block = 128 if not interpret else 8
    G_pad = G + (-G) % group_block
    if G_pad != G:
        pad = G_pad - G
        pod_masks = jnp.pad(pod_masks, ((0, pad), (0, 0)))
        template_allocs = jnp.pad(template_allocs, ((0, pad), (0, 0)))
        caps = jnp.pad(caps, ((0, 0), (0, pad)))
        has_label = jnp.pad(has_label, ((0, pad), (0, 0)))

    scores = jax.vmap(lambda alloc: ffd_scores(pod_req, alloc))(template_allocs)

    # inf allocs (unlimited CSI-attach virtual planes) clamp to a finite
    # always-fits stand-in AFTER scoring, for the same reason as the plain
    # twin (ops/pallas_binpack): the kernel carries FREE capacity, and
    # inf - used = inf would make node_used reconstruct as NaN.
    axis_total = jnp.sum(pod_req, axis=0)
    big = jnp.exp2(jnp.ceil(jnp.log2(jnp.maximum(axis_total * 2.0, 2.0**23))))
    template_allocs = jnp.where(
        jnp.isfinite(template_allocs), template_allocs, big[None, :]
    )

    if chunk is None:
        chunk = 256
        for cand in (512,):
            if affinity_vmem_estimate(
                R, TP, max_nodes, cand, group_block
            ) <= VMEM_BUDGET:
                chunk = cand
        while chunk > _STEP_TILE and chunk // 2 >= P:
            chunk //= 2

    P_pad = P + (-P) % chunk
    pad_cols = P_pad - P

    # term bitsets per pod: [TP, P] planes, sorted as i32 payloads
    mbits = _pack_term_bits(match, TP)
    abits = _pack_term_bits(aff_of, TP)
    xbits = _pack_term_bits(anti_of, TP)
    nl_plane = _pack_term_bits(node_level[:, None], TP)[:, 0]          # [TP]
    hl_planes = _pack_term_bits(has_label.T, TP)                       # [TP, G_pad]
    nl_planes = jnp.broadcast_to(nl_plane[:, None], (TP, G_pad))

    iota = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (G_pad, P))
    cols = [
        jnp.where(
            pod_masks,
            jnp.broadcast_to(pod_req[:, r][None, :], (G_pad, P)),
            jnp.inf,
        )
        for r in range(R)
    ]
    bit_cols = [
        jnp.broadcast_to(b[None, :], (G_pad, P))
        for planes in (mbits, abits, xbits)
        for b in planes
    ]
    sorted_ops = jax.lax.sort(
        [-scores, iota, *cols, *bit_cols],
        dimension=1, is_stable=True, num_keys=1,
    )
    sorted_iota = sorted_ops[1]
    stream = jnp.stack(
        [
            jnp.pad(c, ((0, 0), (0, pad_cols)), constant_values=jnp.inf).T
            for c in sorted_ops[2:2 + R]
        ]
    )
    bit_stream = jnp.stack(
        [
            jnp.pad(c, ((0, 0), (0, pad_cols))).T
            for c in sorted_ops[2 + R:]
        ]
    )

    free, opened, _pm, _ha, _pmt, _hat, placed = _pallas_scan_aff(
        stream, bit_stream, template_allocs.T, caps,
        nl_planes, hl_planes,
        max_nodes=max_nodes, chunk=chunk, group_block=group_block,
        interpret=interpret,
    )

    _, scheduled_i = jax.lax.sort(
        [sorted_iota, placed.T[:, :P].astype(jnp.uint8)],
        dimension=1, is_stable=False, num_keys=1,
    )
    scheduled = scheduled_i[:G] > 0

    used = allocs_to_used(template_allocs, free)
    node_used = jnp.transpose(used, (2, 1, 0))[:G, :max_nodes]
    return BinpackResult(
        node_count=opened[0, :G],
        scheduled=scheduled,
        node_used=node_used,
    )
