"""Pallas-tiled predicate fit with online reduction — the huge-cluster path.

Reference scale target: the in-tree snapshot benchmark grid runs to 100k
nodes (cluster-autoscaler/simulator/clustersnapshot/clustersnapshot_benchmark
_test.go:71), and the documented worst predicate (inter-pod affinity) is the
1000x outlier (FAQ.md:151-153). At 100k pods x 15k nodes the dense [P, N]
fit matrix is ~1.5G elements — too big to materialize in HBM per loop. This
kernel tiles the (pod x node) space and reduces *inside* each tile pass
(structurally the same blockwise-online trick as flash/ring attention,
SURVEY.md §5 "long-context analog"), emitting only [P]-sized outputs:

    any_fit[p], fit_count[p], first_fit[p]

Non-resource predicates enter as an equivalence-class factorization:
pod_class[P] x node_class[N] -> class_mask[CP, CN]. The [TP, TN] tile of the
mask is reconstructed on the MXU as onehot(pod_class) @ class_mask @
onehot(node_class)^T — two small matmuls instead of a 1.5GB boolean tensor.
(Taints/selectors/zones are class-structured; the few per-pod exceptions —
inter-pod affinity rows, placed host-port self-cells — are patched exactly
on top of the kernel output by fit_reduce_exact, so the tiled path keeps
full mask semantics at any scale.)
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.6 names the TPU compiler-params struct TPUCompilerParams; the
# rename to CompilerParams landed alongside jax.shard_map's promotion
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

BIG_I32 = np.int32(2**31 - 1)

# Machine-readable kernel contract (graftlint GL007, analysis/contracts.py):
# AST-extracted, never imported. Dim symbols tie across operands at every
# dispatch site; `static` constraints are mirrored by the runtime guards in
# the entry; `pad` rules must be witnessed by the exact-padding idiom; the
# `grid` must tile exactly under those pad facts.
KERNEL_CONTRACTS = {
    "pallas_fit_reduce": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "free": {"dims": ["N", "R"], "dtype": "f32"},
            "pod_class": {"dims": ["P"], "dtype": "i32"},
            "node_class": {"dims": ["N"], "dtype": "i32"},
            "class_mask": {"dims": ["CP", "CN"], "dtype": "bool"},
            "node_valid": {"dims": ["N"], "dtype": "bool"},
        },
        "static": {
            "tp": {"multiple_of": 8, "min": 8},
            "tn": {"multiple_of": 128, "min": 128},
        },
        "pad": {
            "P_pad": ["P", "tp"],
            "N_pad": ["N", "tn"],
            "R_pad": ["R", 8],
            "CP_pad": ["CP", 8],
            "CN_pad": ["CN", 128],
        },
        "grid": ["P_pad // tp", "N_pad // tn"],
        "pad_value": "+inf request row (padded pods fit nowhere); zero free",
    },
}


class FitReduction(NamedTuple):
    any_fit: jax.Array    # [P] bool
    fit_count: jax.Array  # [P] i32
    first_fit: jax.Array  # [P] i32 node index, -1 if none


def _kernel(
    req_ref,        # [TP, R_pad] f32
    free_t_ref,     # [R_pad, TN] f32 (transposed so rows are resources)
    pclass_ref,     # [TP, 1] i32
    nclass_ref,     # [1, TN] i32
    cmask_ref,      # [CP, CN] f32 (whole, small)
    nvalid_ref,     # [1, TN] f32 (1.0 = real node)
    any_ref,        # [TP, 1] i32 out
    count_ref,      # [TP, 1] i32 out
    first_ref,      # [TP, 1] i32 out
    *,
    num_resources: int,
    tn: int,
):
    j = pl.program_id(1)

    req = req_ref[:]            # [TP, R_pad]
    free_t = free_t_ref[:]      # [R_pad, TN]

    # resource fit: AND over the real resource rows
    fits = jnp.ones((req.shape[0], tn), dtype=jnp.bool_)
    for r in range(num_resources):
        req_col = req[:, r][:, None]          # [TP, 1]
        free_row = free_t[r][None, :]         # [1, TN]
        fits &= req_col <= free_row

    # class mask tile via two MXU matmuls
    cp = cmask_ref.shape[0]
    cn = cmask_ref.shape[1]
    pclass = pclass_ref[:]                      # [TP, 1]
    nclass = nclass_ref[:]                      # [1, TN]
    onehot_p = (
        pclass == jax.lax.broadcasted_iota(jnp.int32, (1, cp), 1)
    ).astype(jnp.float32)                       # [TP, CP]
    onehot_n = (
        nclass == jax.lax.broadcasted_iota(jnp.int32, (cn, 1), 0)
    ).astype(jnp.float32)                       # [CN, TN]
    allowed = jax.lax.dot(
        jax.lax.dot(onehot_p, cmask_ref[:], precision=jax.lax.Precision.HIGHEST),
        onehot_n,
        precision=jax.lax.Precision.HIGHEST,
    )                                           # [TP, TN]
    fits &= allowed > 0.5
    fits &= nvalid_ref[:] > 0.5

    # online reduction over this node tile
    tile_count = jnp.sum(fits, axis=1, dtype=jnp.int32)[:, None]     # [TP, 1]
    col = jax.lax.broadcasted_iota(jnp.int32, fits.shape, 1)
    global_col = col + j * tn
    first_here = jnp.min(
        jnp.where(fits, global_col, BIG_I32), axis=1
    )[:, None]                                                       # [TP, 1]

    @pl.when(j == 0)
    def _init():
        any_ref[:] = jnp.zeros_like(any_ref)
        count_ref[:] = jnp.zeros_like(count_ref)
        first_ref[:] = jnp.full_like(first_ref, BIG_I32)

    any_ref[:] = any_ref[:] | (tile_count > 0).astype(jnp.int32)
    count_ref[:] = count_ref[:] + tile_count
    first_ref[:] = jnp.minimum(first_ref[:], first_here)


@functools.partial(jax.jit, static_argnames=("tp", "tn", "interpret"))
def pallas_fit_reduce(
    pod_req: jax.Array,     # [P, R] f32
    free: jax.Array,        # [N, R] f32 (alloc - used; 0 rows for invalid)
    pod_class: jax.Array,   # [P] i32 (-1 = never schedulable)
    node_class: jax.Array,  # [N] i32 (-1 = invalid node)
    class_mask: jax.Array,  # [CP, CN] bool
    node_valid: jax.Array,  # [N] bool
    tp: int = 256,
    tn: int = 512,
    interpret: bool | None = None,  # None = interpret off-TPU (CPU tests)
) -> FitReduction:
    """Blockwise-tiled fit over (P x N) without materializing the matrix."""
    # tile divisibility guards (GL007 contract): P_pad // tp and
    # N_pad // tn must tile exactly, and Mosaic needs the sublane/lane
    # alignment — a bad explicit tile must fail loudly at trace time, not
    # silently drop the tail tile of the grid
    if tp <= 0 or tp % 8 != 0:
        raise ValueError(f"tp must be a positive multiple of 8 (sublane tile); got {tp}")
    if tn <= 0 or tn % 128 != 0:
        raise ValueError(f"tn must be a positive multiple of 128 (lane tile); got {tn}")
    P, R = pod_req.shape
    N = free.shape[0]
    # the resource axis pads to the sublane tile DYNAMICALLY: the fixed
    # R_pad = 8 this replaces rejected any world with more than 8 resource
    # axes (6 builtin + extended-resource/virtual planes overflow that at
    # scale) — the .at[:, :R] scatter clamped to 8 columns and raised
    R_pad = R + (-R) % 8
    P_pad = P + (-P) % tp
    N_pad = N + (-N) % tn
    CP, CN = class_mask.shape
    CP_pad = CP + (-CP) % 8
    CN_pad = CN + (-CN) % 128

    req = jnp.zeros((P_pad, R_pad), jnp.float32).at[:P, :R].set(pod_req)
    # padded pods: impossible request so they never fit
    if P_pad > P:
        req = req.at[P:, 0].set(jnp.inf)
    free_t = jnp.zeros((R_pad, N_pad), jnp.float32).at[:R, :N].set(free.T)
    pclass = jnp.full((P_pad, 1), -1, jnp.int32).at[:P, 0].set(pod_class)
    nclass = jnp.full((1, N_pad), -1, jnp.int32).at[0, :N].set(node_class)
    cmask = (
        jnp.zeros((CP_pad, CN_pad), jnp.float32)
        .at[:CP, :CN]
        .set(class_mask.astype(jnp.float32))
    )
    nvalid = (
        jnp.zeros((1, N_pad), jnp.float32)
        .at[0, :N]
        .set(node_valid.astype(jnp.float32))
    )

    grid = (P_pad // tp, N_pad // tn)
    kernel = functools.partial(_kernel, num_resources=R, tn=tn)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    any_o, count_o, first_o = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tp, R_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((R_pad, tn), lambda i, j: (0, j)),
            pl.BlockSpec((tp, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tn), lambda i, j: (0, j)),
            pl.BlockSpec((CP_pad, CN_pad), lambda i, j: (0, 0)),
            pl.BlockSpec((1, tn), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((tp, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tp, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tp, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((P_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((P_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((P_pad, 1), jnp.int32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(req, free_t, pclass, nclass, cmask, nvalid)

    any_fit = any_o[:P, 0] > 0
    first = first_o[:P, 0]
    return FitReduction(
        any_fit=any_fit,
        fit_count=count_o[:P, 0],
        first_fit=jnp.where(any_fit, first, -1),
    )


def fit_reduce_exact(snap, tp: int = 256, tn: int = 512, interpret=None) -> FitReduction:
    """Tiled (P × N) fit reduction over a SnapshotTensors with EXACT mask
    semantics. The Pallas kernel reduces the class-structured bulk; the few
    pods whose true rows deviate from the pure class factorization — affinity
    exception pods (exc_rows) and placed host-port pods carrying COO
    self-cell overrides — are re-reduced exactly from sched_row() and patched
    into the outputs. This is the huge-world entry point fit_matrix's guard
    points at: same verdicts as the dense path, never materializing [P, N].

    Dense-mask snapshots are handled too (direct XLA reduction — worlds small
    enough for a dense mask don't need the tiled kernel)."""
    free = snap.free()
    if snap.sched_mask is not None:
        fits = jnp.all(snap.pod_req[:, None, :] <= free[None, :, :], axis=-1)
        fits &= snap.sched_mask & snap.pod_valid[:, None] & snap.node_valid[None, :]
        any_fit = fits.any(axis=1)
        first = jnp.argmax(fits, axis=1).astype(jnp.int32)
        return FitReduction(
            any_fit=any_fit,
            fit_count=jnp.sum(fits, axis=1, dtype=jnp.int32),
            first_fit=jnp.where(any_fit, first, -1),
        )

    base = pallas_fit_reduce(
        snap.pod_req,
        free,
        snap.pod_class.astype(jnp.int32),
        snap.node_class.astype(jnp.int32),
        snap.class_mask,
        snap.node_valid,
        tp=tp,
        tn=tn,
        interpret=interpret,
    )

    # Pods the class factors get wrong: exception-row holders + COO override
    # targets. Both sets have static bounds (E rows, K cells), so the patch
    # is a fixed-size vmap + scatter, traceable under jit.
    E = snap.exc_rows.shape[0]
    exc_idx = jnp.nonzero(snap.pod_exc >= 0, size=E, fill_value=-1)[0]
    special = jnp.concatenate(
        [exc_idx.astype(jnp.int32), snap.cell_pod.astype(jnp.int32)]
    )
    node_ids = jnp.arange(snap.num_nodes, dtype=jnp.int32)

    def row_reduce(p):
        safe = jnp.maximum(p, 0)
        row = (
            snap.sched_row(safe)
            & snap.node_valid
            & (p >= 0)
            & snap.pod_valid[safe]
        )
        fitr = jnp.all(snap.pod_req[safe][None, :] <= free, axis=-1) & row
        cnt = jnp.sum(fitr, dtype=jnp.int32)
        first = jnp.min(jnp.where(fitr, node_ids, BIG_I32))
        return cnt > 0, cnt, jnp.where(cnt > 0, first, -1)

    s_any, s_cnt, s_first = jax.vmap(row_reduce)(special)
    idx = jnp.where(special >= 0, special, snap.num_pods)
    return FitReduction(
        any_fit=base.any_fit.at[idx].set(s_any, mode="drop"),
        fit_count=base.fit_count.at[idx].set(s_cnt, mode="drop"),
        first_fit=base.first_fit.at[idx].set(s_first, mode="drop"),
    )


def reference_fit_reduce(pod_req, free, pod_class, node_class, class_mask, node_valid):
    """Dense XLA/numpy oracle for parity tests."""
    P, N = pod_req.shape[0], free.shape[0]
    fits = np.all(pod_req[:, None, :] <= free[None, :, :], axis=-1)
    pc = np.asarray(pod_class)
    nc = np.asarray(node_class)
    cm = np.asarray(class_mask)
    ok_class = np.zeros((P, N), bool)
    valid_p = pc >= 0
    valid_n = (nc >= 0) & np.asarray(node_valid)
    ok_class[np.ix_(valid_p, valid_n)] = cm[np.ix_(pc[valid_p], nc[valid_n])]
    fits = fits & ok_class
    any_fit = fits.any(axis=1)
    count = fits.sum(axis=1).astype(np.int32)
    first = np.where(any_fit, fits.argmax(axis=1), -1).astype(np.int32)
    return any_fit, count, first
