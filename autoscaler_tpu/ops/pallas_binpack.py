"""Pallas FFD binpack scan — the VMEM-resident fast path for the north-star
multi-group estimator.

The XLA scan in ops/binpack.ffd_binpack_groups is HBM-bound: every pod step
reads and rewrites its usage carry (~12MB at G=500, M=1000), which costs
~50-80µs/step on a v5e. Here the carry lives in VMEM for the WHOLE scan: the
grid is (group-blocks, pod-chunks) with the chunk axis 'arbitrary' (serial),
so each group-block's [R, M, GB] FREE-capacity carry stays resident in VMEM
across all pod chunks and a step is pure VPU work (one compare pass + one-hot
update per resource plane).

Round-4 restructure, driven by the measured decomposition
(benchmarks/pallas_profile.py + captures/pallas_profile_tpu_r4.json): the
round-3 version spent only ~0.66s of its 2.7-2.9s inside the kernel
(1.6µs/step) — the rest was XLA glue with pathological gather/scatter
lowerings on TPU: argsort + take_along_axis (0.64s), per-chunk pod_req[idx]
gathers inside a host-side lax.scan (0.16s + dispatch), and the final
scheduled-bits scatter (0.45s). All three are gone, and the step itself
halved. 2026-07-31 e2e at the north-star shape: 2.68s → 1.02s incl. the
tunnel fetch.

  * ONE stable `lax.sort` carries the per-resource request columns and an
    original-index payload along the score sort (~0.2s at 100k x 512 — 3x
    cheaper than argsort + gathers, because TPU sorts are vectorized while
    row gathers are not).
  * The pod-chunk loop moved INTO the pallas grid: no per-chunk dispatch, no
    per-chunk carry HBM round-trip, no gathers — chunks slice a pre-sorted
    [R, P, G] stream via BlockSpec index maps.
  * The scheduled un-sort is a second `lax.sort` keyed on the sorted
    original-index payload, with the placement bits as a uint8 payload
    (sort cost tracks operand bytes; vs 0.45s for the scatter formulation).
  * NODES-ON-SUBLANES carry ([R, M, GB]): every per-step vector (request
    row, caps, opened, first-fit result) is a GB lane vector, so the
    request broadcast is a free sublane-direction broadcast and the
    first-fit min is a sublane reduction. The prior [R, GB, M] layout
    relayouted the request row lane→sublane on EVERY step — measured as
    half the step cost (const_req 0.685µs vs full 1.469µs/step in the
    profile capture). Kernel total at the north-star shape: 0.74s → 0.40s.
  * The resource-axis compression peek and the result fetch are each ONE
    host round-trip (a per-axis .any() probe and a separate counts fetch
    cost ~50-150ms of tunnel RTT apiece — ops/bits.pack_result_blob fuses
    counts + bit-packed scheduled into a single buffer).

Layout notes (Mosaic constraints): the request stream puts the step axis on
the sublane dimension ([R, CHUNK, GB]) and the kernel walks it in 8-step
tiles with an unrolled inner loop, so every dynamic offset is provably
8-aligned.
Inactive pods (mask-failed / pad) travel as +inf request rows — the mask is
folded into the columns BEFORE the sort (sorting permutes (key, payload)
tuples elementwise, so where(mask, col, inf) commutes with the sort) and no
separate active stream or mask payload exists at all. Closed nodes hold
free == alloc, letting one unmasked first-fit min implement both "first open
node that fits" and "open a new node" (see the kernel comment). Resource
axes nobody requests are dropped before the kernel (exact — see the
compression comment).

Semantics are bit-identical to ffd_binpack_groups (same FFD rules:
score-descending order, first-fit in node-open order, open-on-miss,
per-group dynamic caps) — parity-locked in tests/test_pallas_binpack.py.
Reference algorithm: cluster-autoscaler/estimator/binpacking_estimator.go:65.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.6 names the TPU compiler-params struct TPUCompilerParams; the
# rename to CompilerParams landed alongside jax.shard_map's promotion
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from autoscaler_tpu.ops.telemetry import observed
from autoscaler_tpu.ops.binpack import BinpackResult, ffd_scores

BIG_I32 = np.int32(2**31 - 1)
_STEP_TILE = 8  # sublane tile: dynamic offsets must be provably 8-aligned
VMEM_BUDGET = 15 * 1024 * 1024   # v5e has 16MB; leave Mosaic headroom

# Machine-readable kernel contract (graftlint GL007, analysis/contracts.py):
# AST-extracted, never imported. The lint proves the declared `grid` tiles
# exactly under the `pad` witnesses, that every `static` alignment has a
# matching runtime guard, and checks dims/statics at every dispatch site.
KERNEL_CONTRACTS = {
    "ffd_binpack_groups_pallas": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "pod_masks": {"dims": ["G", "P"], "dtype": "bool"},
            "template_allocs": {"dims": ["G", "R"], "dtype": "f32"},
            "node_caps": {"dims": ["G"], "dtype": "i32"},
        },
        "static": {
            "chunk": {"multiple_of": "_STEP_TILE", "min": 8, "optional": True},
            "max_nodes": {"min": 1},
        },
        "pad": {
            "P_pad": ["P", "chunk"],
            "G_pad": ["G", "group_block"],
            "M_pad": ["max_nodes", "_STEP_TILE"],
        },
        "grid": ["G_pad // group_block", "P_pad // chunk"],
        "pad_value": "+inf request rows (inactive pods sort last, fit nowhere)",
        "vmem": "plain_vmem_estimate",
    },
}


def plain_vmem_estimate(
    R: int, max_nodes: int, chunk: int, group_block: int = 128
) -> int:
    """Byte model for one grid program of the plain scan kernel — shared by
    the chunk auto-sizer below and the estimator's routing pre-check (a
    failed Mosaic compile is not cached, so gating beats retry-per-loop)."""
    M_lanes = max_nodes + (-max_nodes) % 128
    return (
        2 * R * chunk * group_block       # double-buffered req stream
        + R * group_block * M_lanes       # resident carry
        + 2 * chunk * group_block         # double-buffered placed out
    ) * 4 + 3 * 1024 * 1024               # Mosaic scratch


def clamp_inf_allocs(pod_req, template_allocs):
    """Replace +inf template capacities (unlimited CSI-attach virtual
    planes, estimator/binpacking._augment_virtual) with a finite
    always-fits stand-in. Both Pallas twins carry FREE capacity, so an inf
    alloc makes node_used reconstruct as inf - inf = NaN; a power of two
    >= 2x the axis's total request keeps "always fits" exact (used <= sum
    <= BIG/2, so free >= BIG/2 >= any request) and integer-request
    arithmetic exact in f32 for the unit-count planes this input actually
    is. Must run AFTER scoring (ffd_scores reads the raw caps)."""
    axis_total = jnp.sum(pod_req, axis=0)
    big = jnp.exp2(jnp.ceil(jnp.log2(jnp.maximum(axis_total * 2.0, 2.0**23))))
    return jnp.where(
        jnp.isfinite(template_allocs), template_allocs, big[None, :]
    )


def _scan_kernel(
    req_ref,      # [R, CHUNK, GB] f32 — sorted pod requests, +inf = inactive
    caps_ref,     # [1, GB] i32 (lane-resident, matching `first`'s layout)
    allocs_ref,   # [R, GB] f32 — template allocs (carry init at chunk 0)
    free_ref,     # [R, M, GB] f32 out — VMEM-resident across the chunk axis
    opened_ref,   # [1, GB] i32 out — resident likewise
    placed_ref,   # [CHUNK, GB] i32 out — flushed per chunk
    *,
    num_resources: int,
    chunk: int,
    max_nodes: int,
):
    # Layout: the capacity carry is NODES-ON-SUBLANES ([R, M, GB]: each
    # per-resource plane free_ref[r] is an [M sublanes × GB lanes] block)
    # and every per-step vector — requests, caps, opened, first — is a GB
    # LANE vector. That alignment is the round-4 step-cost fix: the prior
    # [R, GB, M] layout extracted req[r] as a lane vector but compared it
    # against a GB-sublane carry, forcing a cross-lane→sublane relayout of
    # every request row on every step; the measured decomposition
    # (captures/pallas_profile_tpu_r4.json: const_req 0.685µs vs full
    # 1.469µs/step) showed that relayout was HALF the step. Here the
    # request row broadcasts along sublanes (free in hardware), the
    # first-fit min is a sublane-axis reduction (rotate tree, ~130 tile
    # ops vs 512 tile compares — not dominant), and no relayout exists at
    # all. (The round-3 [GB, R, M] layout was worse still: R on sublanes
    # made every access a strided single-sublane RMW — 16.5s e2e.)
    # The carry holds FREE capacity (alloc - used), not usage: the fit
    # compare then reads it directly, saving R [M, GB] subtracts per step.
    gb = free_ref.shape[2]
    R = num_resources
    M = free_ref.shape[1]
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (M, gb), 0)
    caps = caps_ref[0, :]                               # [GB] lane vector

    # The carry blocks' index maps ignore the chunk grid axis, so Mosaic
    # keeps them VMEM-resident across chunks and writes back once per group
    # block (the standard revisited-block reduction pattern). Initialize at
    # the first chunk: every node (open or not) starts at free == alloc.
    @pl.when(pl.program_id(1) == 0)
    def _init():
        for r in range(R):
            free_ref[r, :, :] = jnp.broadcast_to(
                allocs_ref[r, :][None, :], (M, gb)
            )
        opened_ref[:] = jnp.zeros((1, gb), jnp.int32)

    def tile_step(t, _):
        base = t * _STEP_TILE
        req_tiles = [
            req_ref[r, pl.ds(base, _STEP_TILE), :] for r in range(R)
        ]                                               # R × [8, GB]
        placed_rows = []

        for s in range(_STEP_TILE):
            opened = opened_ref[0, :]                   # [GB]
            req = [req_tiles[r][s, :] for r in range(R)]  # R × [GB] lane vecs
            # inactive pods (mask-failed or pad slots) carry +inf requests:
            # they fit nowhere and so place nothing — no separate active
            # stream or gate needed.
            #
            # Closed nodes (m >= opened) hold free == alloc by construction,
            # so the UNMASKED first-fit min doubles as the open-new-node
            # rule: a pod that fits no open node but fits an empty template
            # lands exactly at index `opened` (all closed nodes compare
            # equal, the min picks the first). first > opened is impossible,
            # and first >= caps (capped group, or template too small: the
            # min landed past the cap or nowhere) means no placement. This
            # folds the open-mask compare, the fits_empty chain and the
            # can_open arithmetic into the one masked-min. Padded node rows
            # (M rounded up to the sublane tile) are permanently-closed
            # nodes ABOVE every real index: the min always prefers a real
            # row, and caps <= max_nodes gates placement past the cap.

            fits = req[0][None, :] <= free_ref[0]       # [M, GB]
            for r in range(1, R):
                fits &= req[r][None, :] <= free_ref[r]

            first = jnp.min(
                jnp.where(fits, node_iota, BIG_I32), axis=0
            )                                           # [GB]
            place = first < caps
            target = jnp.where(place, first, -1)        # -1: no hit row

            # The select (not a multiply by place) matters: inf * 0.0 = NaN
            # would poison the carry via the hit row.
            hit = node_iota == target[None, :]                      # [M, GB]
            for r in range(R):
                sub = jnp.where(place, req[r], 0.0)[None, :]        # [1, GB]
                free_ref[r, :, :] = free_ref[r] - jnp.where(hit, sub, 0.0)
            opened_ref[0, :] = jnp.maximum(
                opened, jnp.where(place, first + 1, 0)
            )
            placed_rows.append(place.astype(jnp.int32))

        placed_ref[pl.ds(base, _STEP_TILE), :] = jnp.stack(placed_rows, axis=0)
        return 0

    jax.lax.fori_loop(0, chunk // _STEP_TILE, tile_step, 0)


def _swar_plan(max_vals):
    """Greedy field-packing plan for the SWAR fast path: each resource axis
    becomes a (plane, shift, width) field, packed first-fit-decreasing into
    as few i32 planes as possible (<=31 bits per plane — the sign bit stays
    clear). width = bit_length(max_val) + 1: real values use width-1 bits,
    the top bit of each field is the GUARD bit for the borrow-free fit
    check, and the masked-pod sentinel sets the field to exactly
    2^(width-1) — one above any real value, so req_field <= 2^(width-1)
    always holds and a subtraction can never borrow across fields. Returns
    None when packing wins nothing (every axis needs its own plane)."""
    R = len(max_vals)
    widths = [max(int(v).bit_length(), 1) + 1 for v in max_vals]
    order = sorted(range(R), key=lambda r: -widths[r])
    planes = []   # list of [used_bits, [(r, shift, width), ...]]
    for r in order:
        w = widths[r]
        if w > 31:
            return None
        for pl_ in planes:
            if pl_[0] + w <= 31:
                pl_[1].append((r, pl_[0], w))
                pl_[0] += w
                break
        else:
            planes.append([w, [(r, 0, w)]])
    if len(planes) >= R:
        return None
    return [fields for _, fields in planes]


def _swar_masks(plan):
    """(guards, sentinels) per plane: guard = OR of each field's top bit;
    sentinel = OR of each field set to 2^(width-1) (same bits — the guard
    bit IS the sentinel value), kept separate for readability."""
    guards = tuple(
        sum(1 << (shift + width - 1) for _, shift, width in fields)
        for fields in plan
    )
    return guards, guards


def _swar_pack_cols(values, plan):
    """[N, R] f32 integer-valued -> list of [N] i32 packed planes."""
    vi = values.astype(jnp.int32)
    return [
        functools.reduce(
            lambda a, b: a + b,
            [vi[:, r] << shift for r, shift, _ in fields],
        )
        for fields in plan
    ]


def _swar_unpack_free(free_planes, plan, num_resources):
    """[NP, M, G] i32 packed free -> [R, M, G] f32 per-resource free."""
    outs = [None] * num_resources
    for p, fields in enumerate(plan):
        for r, shift, width in fields:
            outs[r] = (
                (free_planes[p] >> shift) & ((1 << (width - 1)) - 1)
            ).astype(jnp.float32)
    return jnp.stack(outs)


def _scan_kernel_swar(
    req_ref,      # [NP, CHUNK, GB] i32 — packed sorted requests
    caps_ref,     # [1, GB] i32
    allocs_ref,   # [NP, GB] i32 — packed template allocs
    free_ref,     # [NP, M, GB] i32 out — carry, VMEM-resident
    opened_ref,   # [1, GB] i32 out
    placed_ref,   # [CHUNK, GB] i32 out
    *,
    guards: tuple,
    chunk: int,
    max_nodes: int,
):
    """SWAR twin of _scan_kernel: the R f32 capacity planes collapse into
    NP <= ceil(31/width) i32 planes; one fit check per plane is the classic
    guard-bit trick — z = (free | guard) - req borrows OUT of exactly the
    fields where free < req, clearing their guard bits, and the field
    layout (req_field <= 2^(width-1), free guard bits clear) makes a
    cross-field borrow impossible. Same placement logic otherwise; plane
    traffic dominates the step (profile capture: const_req ~= swar), so
    halving the planes halves the step."""
    gb = free_ref.shape[2]
    NP = len(guards)
    M = free_ref.shape[1]
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (M, gb), 0)
    caps = caps_ref[0, :]

    @pl.when(pl.program_id(1) == 0)
    def _init():
        for p in range(NP):
            free_ref[p, :, :] = jnp.broadcast_to(
                allocs_ref[p, :][None, :], (M, gb)
            )
        opened_ref[:] = jnp.zeros((1, gb), jnp.int32)

    def tile_step(t, _):
        base = t * _STEP_TILE
        req_tiles = [
            req_ref[p, pl.ds(base, _STEP_TILE), :] for p in range(NP)
        ]
        placed_rows = []
        for s in range(_STEP_TILE):
            opened = opened_ref[0, :]
            req = [req_tiles[p][s, :] for p in range(NP)]
            fits = None
            for p in range(NP):
                g = guards[p]
                z = (free_ref[p] | g) - req[p][None, :]
                ok = (z & g) == g
                fits = ok if fits is None else (fits & ok)
            first = jnp.min(
                jnp.where(fits, node_iota, BIG_I32), axis=0
            )
            place = first < caps
            target = jnp.where(place, first, -1)
            hit = node_iota == target[None, :]
            for p in range(NP):
                sub = jnp.where(place, req[p], 0)[None, :]
                free_ref[p, :, :] = free_ref[p] - jnp.where(hit, sub, 0)
            opened_ref[0, :] = jnp.maximum(
                opened, jnp.where(place, first + 1, 0)
            )
            placed_rows.append(place.astype(jnp.int32))
        placed_ref[pl.ds(base, _STEP_TILE), :] = jnp.stack(placed_rows, axis=0)
        return 0

    jax.lax.fori_loop(0, chunk // _STEP_TILE, tile_step, 0)


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "chunk", "group_block", "interpret", "guards"),
)
def _pallas_scan_all(
    stream,           # [R, P_pad, G_pad] f32 (or [NP,...] i32 when guards set)
    allocs_in,        # [R, G_pad] f32 (i32 packed when guards set)
    caps_col,         # [1, G_pad] i32
    max_nodes: int,
    chunk: int,
    group_block: int,
    interpret: bool,
    guards: tuple | None = None,
):
    """One pallas_call covering the whole scan: grid (group-blocks, chunks),
    chunk axis 'arbitrary' (serial) with the free/opened carry blocks
    revisited — resident in VMEM across chunks, written back once per group
    block. No host-side chunk loop, no per-chunk gathers, no carry HBM
    round-trips. (Round 3 dispatched one pallas_call per chunk from a
    lax.scan with a pod_req[idx] gather per chunk; the glue cost ~3× the
    kernel itself — see the module docstring.)"""
    R, P_pad, G_pad = stream.shape
    NC = P_pad // chunk
    # nodes live on the SUBLANE axis of the carry — round up to the tile;
    # padded rows behave as permanently-closed nodes past every real index
    # (see the kernel comment) and are sliced away by the caller
    M_pad = max_nodes + (-max_nodes) % _STEP_TILE
    if guards is not None:
        kernel = functools.partial(
            _scan_kernel_swar, guards=guards, chunk=chunk, max_nodes=max_nodes
        )
        carry_dtype = jnp.int32
    else:
        kernel = functools.partial(
            _scan_kernel, num_resources=R, chunk=chunk, max_nodes=max_nodes
        )
        carry_dtype = jnp.float32
    return pl.pallas_call(
        kernel,
        grid=(G_pad // group_block, NC),
        in_specs=[
            pl.BlockSpec((R, chunk, group_block), lambda g, c: (0, c, g)),
            pl.BlockSpec((1, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((R, group_block), lambda g, c: (0, g)),
        ],
        out_specs=[
            pl.BlockSpec((R, M_pad, group_block), lambda g, c: (0, 0, g)),
            pl.BlockSpec((1, group_block), lambda g, c: (0, g)),
            pl.BlockSpec((chunk, group_block), lambda g, c: (c, g)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, M_pad, G_pad), carry_dtype),
            jax.ShapeDtypeStruct((1, G_pad), jnp.int32),
            jax.ShapeDtypeStruct((P_pad, G_pad), jnp.int32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(stream, caps_col, allocs_in)


@observed
def ffd_binpack_groups_pallas(
    pod_req,          # [P, R]
    pod_masks,        # [G, P] bool
    template_allocs,  # [G, R]
    max_nodes: int,
    node_caps=None,   # [G] i32
    chunk: int | None = None,   # None = auto-size against the VMEM budget
    group_block: int = 0,   # 0 = auto
    interpret: bool | None = None,
    attribution: bool = False,
):
    """Drop-in twin of ffd_binpack_groups running the scan in Pallas.

    The full scan runs in ONE device dispatch: a payload-carrying stable
    sort orders the requests per group, the pallas grid walks (group-block,
    chunk) cells with the capacity carry VMEM-resident, and a second sort
    restores original pod order for the scheduled bits. chunk=None picks the
    largest chunk the VMEM budget model admits; an explicit chunk is honored
    as-is.

    attribution=True returns ``(BinpackResult, reasons [G, P] i32)``: the
    per-(pod, group) rejection reason codes (explain/reasons.py) derived
    from the same operands by ops/binpack.attribute_unschedulable — the
    violated-constraint reduction is bandwidth-trivial next to the scan, so
    it rides the XLA path even when the FFD scan itself ran in Mosaic; one
    kernel family, one reason vocabulary."""
    if chunk is not None and chunk % _STEP_TILE != 0:
        raise ValueError(
            f"chunk must be a multiple of {_STEP_TILE} (sublane tile); got {chunk}"
        )
    pod_req = jnp.asarray(pod_req, jnp.float32)
    pod_masks = jnp.asarray(pod_masks)
    template_allocs = jnp.asarray(template_allocs, jnp.float32)
    # originals for the optional attribution output: the scan below pads
    # the group axis, clamps +inf allocs and may compress resource axes —
    # attribution must see the caller's semantics (+inf alloc = over-
    # admission impossible, so the raw allocs are exactly right)
    attr_operands = (
        (pod_req, pod_masks, template_allocs) if attribution else None
    )
    P, R_full = pod_req.shape
    G = pod_masks.shape[0]
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(jnp.asarray(node_caps, jnp.int32), max_nodes)[None, :]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if group_block <= 0:
        group_block = 128 if not interpret else 8
    # Pad the group axis to a block multiple (lane dims must be 128-wide on
    # TPU); padding groups carry zero caps/allocs and place nothing.
    G_pad = G + (-G) % group_block
    if G_pad != G:
        pad = G_pad - G
        pod_masks = jnp.pad(pod_masks, ((0, pad), (0, 0)))
        template_allocs = jnp.pad(template_allocs, ((0, pad), (0, 0)))
        caps = jnp.pad(caps, ((0, 0), (0, pad)))

    scores = jax.vmap(lambda alloc: ffd_scores(pod_req, alloc))(template_allocs)

    template_allocs = clamp_inf_allocs(pod_req, template_allocs)

    # Exact resource-axis compression (AFTER scoring, which indexes CPU/MEMORY
    # positionally): an axis nobody requests can never gate a fit (0 <= free
    # always) nor change the carry (usage += 0), so drop it from the kernel's
    # per-resource loop. At the north-star workload this removes the
    # always-zero ephemeral/tpu axes (R 6→4, ~1/3 of the VPU work). The tiny
    # host sync is amortized over the whole scan.
    # Under shard_map/jit the inputs are tracers — the host-side value peek
    # is impossible, so keep every axis (the sharded caller pays ~R/R_k more
    # VPU work; the single-chip dispatch path always has concrete inputs).
    swar_plan = None
    if isinstance(pod_req, jax.core.Tracer):
        keep = list(range(R_full))
    else:
        # ONE fused reduce + host fetch (a per-axis bool((col > 0).any())
        # costs a full tunnel round-trip each, ~50ms × R ≈ 0.3s measured —
        # round-4 decomposition): axis usage for the exact compression,
        # per-axis maxima and integrality for the SWAR packing decision
        axis_used, req_max, alloc_max, ints_ok = jax.device_get((
            (pod_req > 0).any(axis=0),
            jnp.max(pod_req, axis=0, initial=0.0),
            jnp.max(template_allocs, axis=0, initial=0.0),
            (pod_req >= 0).all()
            # non-finite requests never occur by construction, but an inf
            # would slip past the floor() integrality check and crash
            # _swar_plan, so guard explicitly (allocs are already finite:
            # the clamp above replaced every inf before this probe)
            & jnp.isfinite(pod_req).all()
            & (pod_req == jnp.floor(pod_req)).all()
            & (template_allocs == jnp.floor(template_allocs)).all(),
        ))
        axis_used = np.asarray(axis_used)
        keep = [r for r in range(R_full) if axis_used[r]] or [0]
        if bool(ints_ok):
            swar_plan = _swar_plan(
                [max(float(req_max[r]), float(alloc_max[r])) for r in keep]
            )
    compressed = len(keep) < R_full
    if compressed:
        pod_req = pod_req[:, jnp.asarray(keep)]
        template_allocs = template_allocs[:, jnp.asarray(keep)]
    R_k = len(keep)

    # Auto-size the chunk: bigger chunks mean fewer placed-block flushes and
    # request-stream fetches per group block, bounded by VMEM. Budget model
    # (bytes per grid program): Mosaic double-buffers the request stream and
    # placed blocks; the carry is revisited (single-buffered, resident).
    # With R=4, GB=128, M=1024, chunk=1024: 2·2MB req + 2MB carry + 2·0.5MB
    # placed + ~3MB scratch ≈ 10MB — compiles and runs on a 16MB-VMEM v5e.
    if chunk is None:
        chunk = 512
        n_planes = len(swar_plan) if swar_plan else R_k
        for cand in (1024,):
            if plain_vmem_estimate(
                n_planes, max_nodes, cand, group_block
            ) <= VMEM_BUDGET:
                chunk = cand
        # don't scan pure padding: a P=300 world needs one 304-slot chunk,
        # not a 1024-slot one
        while chunk > _STEP_TILE and chunk // 2 >= P:
            chunk //= 2

    P_pad = P + (-P) % chunk

    # ONE stable sort orders every group's stream by descending score and
    # carries the request columns plus the original pod index as payloads
    # (TPU sorts are fast and vectorized; the argsort + take_along_axis /
    # per-chunk-gather formulation this replaces cost ~3× the kernel). The
    # static mask folds into the columns first: where(mask, col, +inf)
    # commutes with the sort, and an all-inf row both fits nowhere in the
    # kernel and needs no separate active stream.
    iota = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (G_pad, P))
    pad_cols = P_pad - P
    if swar_plan is not None:
        # SWAR fast path (integer-valued requests/allocs, planes < axes):
        # the R_k f32 columns collapse into packed i32 planes BEFORE the
        # sort — the sort payload bytes, the stream, and the kernel's
        # per-step plane traffic all shrink together. Masked pods carry the
        # per-plane sentinel (each field at 2^(width-1): above every real
        # value, borrow-contained) instead of +inf.
        guards, sentinels = _swar_masks(swar_plan)
        plane_cols = _swar_pack_cols(pod_req, swar_plan)
        inactive = [jnp.int32(sent) for sent in sentinels]
        allocs_in = jnp.stack(_swar_pack_cols(template_allocs, swar_plan))
    else:
        guards = None
        plane_cols = [pod_req[:, r] for r in range(R_k)]
        inactive = [jnp.inf] * R_k
        allocs_in = template_allocs.T
    cols = [
        jnp.where(pod_masks, jnp.broadcast_to(pc[None, :], (G_pad, P)), sent)
        for pc, sent in zip(plane_cols, inactive)
    ]
    sorted_ops = jax.lax.sort(
        [-scores, iota, *cols], dimension=1, is_stable=True, num_keys=1
    )
    sorted_iota = sorted_ops[1]                                  # [G_pad, P]
    stream = jnp.stack(
        [
            jnp.pad(c, ((0, 0), (0, pad_cols)), constant_values=sent).T
            for c, sent in zip(sorted_ops[2:], inactive)
        ]
    )                                        # [NP or R, P_pad, G_pad]

    free, opened, placed = _pallas_scan_all(
        stream, allocs_in, caps,
        max_nodes=max_nodes, chunk=chunk, group_block=group_block,
        interpret=interpret, guards=guards,
    )
    if swar_plan is not None:
        free = _swar_unpack_free(free, swar_plan, R_k)

    # Un-sort the placement bits back to original pod order with a second
    # sort keyed on the carried original index (3× cheaper than the
    # equivalent scatter on TPU). Pad slots sit at sorted positions >= P and
    # are sliced away before the un-sort.
    # u8 payload: the sort's cost tracks operand bytes, and the placement
    # bit needs one byte, not four
    _, scheduled_i = jax.lax.sort(
        [sorted_iota, placed.T[:, :P].astype(jnp.uint8)],
        dimension=1, is_stable=False, num_keys=1,
    )
    scheduled = scheduled_i[:G] > 0

    used = allocs_to_used(template_allocs, free)
    node_used = jnp.transpose(used, (2, 1, 0))[:G, :max_nodes]   # [G, M, R]
    if compressed:
        node_used = (
            jnp.zeros((G, max_nodes, R_full), jnp.float32)
            .at[:, :, jnp.asarray(keep)]
            .set(node_used)
        )
    result = BinpackResult(
        node_count=opened[0, :G],
        scheduled=scheduled,
        node_used=node_used,
    )
    if attr_operands is None:
        return result
    from autoscaler_tpu.ops.binpack import attribute_unschedulable

    a_req, a_masks, a_allocs = attr_operands
    reasons = attribute_unschedulable(
        a_req, a_masks, a_allocs, scheduled,
        jnp.zeros((P,), bool),  # the plain family has no dynamic terms
    )
    return result, reasons


def allocs_to_used(template_allocs, free):
    """used[R, M, G] = alloc - free (free of padding groups is 0-alloc)."""
    return template_allocs.T[:, None, :] - free
