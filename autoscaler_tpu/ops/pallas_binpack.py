"""Pallas FFD binpack scan — the VMEM-resident fast path for the north-star
multi-group estimator.

The XLA scan in ops/binpack.ffd_binpack_groups is HBM-bound: every pod step
reads and rewrites the [G, R, M] usage carry (~12MB at G=500, M=1000), which
costs ~50-80µs/step on a v5e. Here the carry lives in VMEM for a whole chunk
of pods: the grid is (group-blocks,) and each program runs CHUNK scan steps
against its [GB, R, M] usage block without touching HBM, so a step is pure
VPU work (two [GB, M]-per-resource passes: compare and one-hot update).

Layout notes (Mosaic constraints): the per-step streams are shaped with the
step axis on the *sublane* dimension — requests [R, CHUNK, GB], actives and
placements [CHUNK, GB] — and the kernel walks them in 8-step tiles (sublane
tile size) with an unrolled inner loop, so every dynamic offset is provably
8-aligned; lane dimensions (GB, M) are full-width. The host driver
pre-gathers each chunk's score-sorted requests with one XLA gather and feeds
consecutive pallas_call invocations whose usage/opened carries are donated
(input_output_aliased), so chunk dispatch costs one HBM round-trip of the
carry instead of one per pod.

Semantics are bit-identical to ffd_binpack_groups (same FFD rules:
score-descending order, first-fit in node-open order, open-on-miss,
per-group dynamic caps) — parity-locked in tests/test_pallas_binpack.py.
Reference algorithm: cluster-autoscaler/estimator/binpacking_estimator.go:65.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autoscaler_tpu.ops.binpack import BinpackResult, ffd_scores

BIG_I32 = np.int32(2**31 - 1)
_STEP_TILE = 8  # sublane tile: dynamic offsets must be provably 8-aligned


def _scan_kernel(
    req_ref,      # [R, CHUNK, GB] f32 — pre-gathered sorted pod requests
    active_ref,   # [CHUNK, GB] i32 — pod passes the group's predicates
    alloc_ref,    # [1, GB, R] f32
    caps_ref,     # [1, GB] i32
    used_in_ref,  # [GB, R, M] f32 (aliased with used_out)
    opened_in_ref,  # [1, GB] i32 (aliased with opened_out)
    used_ref,     # [GB, R, M] f32 out
    opened_ref,   # [1, GB] i32 out
    placed_ref,   # [CHUNK, GB] i32 out
    *,
    num_resources: int,
    chunk: int,
    max_nodes: int,
):
    gb = used_ref.shape[0]
    R = num_resources
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (gb, max_nodes), 1)
    alloc = [alloc_ref[0, :, r] for r in range(R)]      # R × [GB]
    caps = caps_ref[0, :]                               # [GB]

    used_ref[:] = used_in_ref[:]
    opened_ref[:] = opened_in_ref[:]

    def tile_step(t, _):
        base = t * _STEP_TILE
        req_tiles = [
            req_ref[r, pl.ds(base, _STEP_TILE), :] for r in range(R)
        ]                                               # R × [8, GB]
        active_tile = active_ref[pl.ds(base, _STEP_TILE), :]        # [8, GB]
        placed_rows = []

        for s in range(_STEP_TILE):
            opened = opened_ref[0, :]                   # [GB]
            req = [req_tiles[r][s, :] for r in range(R)]  # R × [GB]
            active = active_tile[s, :] > 0              # [GB]

            fits = node_iota < opened[:, None]          # [GB, M]
            fits_empty = jnp.ones((gb,), jnp.bool_)
            for r in range(R):
                free_r = alloc[r][:, None] - used_ref[:, r, :]      # [GB, M]
                fits &= req[r][:, None] <= free_r
                fits_empty &= req[r] <= alloc[r]

            any_fit = fits.any(axis=1)                  # [GB]
            first = jnp.min(
                jnp.where(fits, node_iota, BIG_I32), axis=1
            )                                           # [GB]
            can_open = (~any_fit) & (opened < caps) & fits_empty
            place = active & (any_fit | can_open)
            target = jnp.where(any_fit, first, opened)  # [GB]

            # i1 [GB] -> [GB,1] reshapes are unsupported on TPU; broadcast
            # the placement gate through f32 instead
            hit = node_iota == target[:, None]                      # [GB, M]
            place_f = place.astype(jnp.float32)
            for r in range(R):
                add = (req[r] * place_f)[:, None]                   # [GB, 1]
                used_ref[:, r, :] = used_ref[:, r, :] + jnp.where(hit, add, 0.0)
            opened_ref[0, :] = opened + (place & can_open).astype(jnp.int32)
            placed_rows.append(place.astype(jnp.int32))

        placed_ref[pl.ds(base, _STEP_TILE), :] = jnp.stack(placed_rows, axis=0)
        return 0

    jax.lax.fori_loop(0, chunk // _STEP_TILE, tile_step, 0)


@functools.partial(
    jax.jit, static_argnames=("chunk", "max_nodes", "group_block", "interpret")
)
def _run_chunk(
    req_chunk,   # [R, CHUNK, G] f32
    active,      # [CHUNK, G] i32
    allocs,      # [1, G, R] f32
    caps,        # [1, G] i32
    used,        # [G, R, M] f32
    opened,      # [1, G] i32
    chunk: int,
    max_nodes: int,
    group_block: int,
    interpret: bool,
):
    R = req_chunk.shape[0]
    G = req_chunk.shape[2]
    grid = (G // group_block,)
    kernel = functools.partial(
        _scan_kernel, num_resources=R, chunk=chunk, max_nodes=max_nodes
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, chunk, group_block), lambda i: (0, 0, i)),
            pl.BlockSpec((chunk, group_block), lambda i: (0, i)),
            pl.BlockSpec((1, group_block, R), lambda i: (0, i, 0)),
            pl.BlockSpec((1, group_block), lambda i: (0, i)),
            pl.BlockSpec((group_block, R, max_nodes), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, group_block), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((group_block, R, max_nodes), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, group_block), lambda i: (0, i)),
            pl.BlockSpec((chunk, group_block), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G, R, max_nodes), jnp.float32),
            jax.ShapeDtypeStruct((1, G), jnp.int32),
            jax.ShapeDtypeStruct((chunk, G), jnp.int32),
        ],
        input_output_aliases={4: 0, 5: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(req_chunk, active, allocs, caps, used, opened)


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "chunk", "group_block", "interpret"),
)
def _pallas_scan_all(
    pod_req,          # [P_pad, R] (padded with an impossible sentinel row at 0? no — padding handled by active flags)
    order,            # [G_pad, P_pad] i32
    sorted_mask,      # [G_pad, P_pad] bool
    template_allocs,  # [G_pad, R]
    caps,             # [1, G_pad] i32
    max_nodes: int,
    chunk: int,
    group_block: int,
    interpret: bool,
):
    """One jit: lax.scan over pod chunks, each advancing the VMEM kernel.
    Keeping the loop on device avoids ~P/chunk host dispatch round-trips
    (which dominate wall-clock on a tunneled TPU)."""
    G_pad, P_pad = order.shape
    R = pod_req.shape[1]
    NC = P_pad // chunk
    order_c = order.reshape(G_pad, NC, chunk).transpose(1, 0, 2)       # [NC, G, C]
    active_c = sorted_mask.astype(jnp.int32).reshape(G_pad, NC, chunk).transpose(1, 0, 2)
    allocs_in = template_allocs[None, :, :]

    def chunk_step(carry, xs):
        used, opened = carry
        idx, active = xs                                   # [G, C]
        req_chunk = jnp.transpose(pod_req[idx], (2, 1, 0))  # [R, C, G]
        used, opened, placed = _run_chunk(
            req_chunk, active.T, allocs_in, caps, used, opened,
            chunk=chunk, max_nodes=max_nodes, group_block=group_block,
            interpret=interpret,
        )
        return (used, opened), placed.T                    # [G, C]

    init = (
        jnp.zeros((G_pad, R, max_nodes), jnp.float32),
        jnp.zeros((1, G_pad), jnp.int32),
    )
    (used, opened), placed = jax.lax.scan(chunk_step, init, (order_c, active_c))
    placed_sorted = placed.transpose(1, 0, 2).reshape(G_pad, P_pad) > 0
    return used, opened, placed_sorted


def ffd_binpack_groups_pallas(
    pod_req,          # [P, R]
    pod_masks,        # [G, P] bool
    template_allocs,  # [G, R]
    max_nodes: int,
    node_caps=None,   # [G] i32
    chunk: int = 512,
    group_block: int = 0,   # 0 = auto
    interpret: bool | None = None,
) -> BinpackResult:
    """Drop-in twin of ffd_binpack_groups running the scan in Pallas.

    The scan over pod chunks runs inside one jit (lax.scan), each iteration
    gathering the chunk's score-sorted requests and advancing the
    VMEM-resident usage carry via the kernel."""
    if chunk % _STEP_TILE != 0:
        raise ValueError(
            f"chunk must be a multiple of {_STEP_TILE} (sublane tile); got {chunk}"
        )
    # VMEM budget: XLA keeps the [G_pad, R, M] usage carry resident in VMEM
    # across the chunk scan (that residency IS the speedup), plus the chunk's
    # request/placement streams. At the north-star shape (G_pad=512, R=6,
    # M=1000→1024 lanes) the carry alone is ~12.6MB of the 16MB budget;
    # chunk=1024 overflowed it on a real v5e by 728KB (observed Mosaic
    # scoped-vmem OOM), chunk=512 fits. Callers raising chunk must leave
    # room for carry + chunk*(R+2)*G_pad*4 bytes.
    pod_req = jnp.asarray(pod_req, jnp.float32)
    pod_masks = jnp.asarray(pod_masks)
    template_allocs = jnp.asarray(template_allocs, jnp.float32)
    P, R = pod_req.shape
    G = pod_masks.shape[0]
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(jnp.asarray(node_caps, jnp.int32), max_nodes)[None, :]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if group_block <= 0:
        group_block = 128 if not interpret else 8
    # Pad the group axis to a block multiple (lane dims must be 128-wide on
    # TPU); padding groups carry zero caps/allocs and place nothing.
    G_pad = G + (-G) % group_block
    if G_pad != G:
        pad = G_pad - G
        pod_masks = jnp.pad(pod_masks, ((0, pad), (0, 0)))
        template_allocs = jnp.pad(template_allocs, ((0, pad), (0, 0)))
        caps = jnp.pad(caps, ((0, 0), (0, pad)))

    scores = jax.vmap(lambda alloc: ffd_scores(pod_req, alloc))(template_allocs)
    order = jnp.argsort(-scores, axis=1, stable=True)               # [G_pad, P]
    sorted_mask = jnp.take_along_axis(pod_masks, order, axis=1)

    # Pad the pod axis to a chunk multiple with inactive slots. The pad value
    # must be an index outside [0, P): the final scheduled scatter writes at
    # `order`, and zero-padding would send every padded (inactive, False)
    # slot to column 0, clobbering pod 0's real placement bit. P_pad-1 >= P
    # here, so padded writes land in columns sliced away by [:, :P].
    P_pad = P + (-P) % chunk
    if P_pad != P:
        order = jnp.pad(order, ((0, 0), (0, P_pad - P)), constant_values=P_pad - 1)
        sorted_mask = jnp.pad(sorted_mask, ((0, 0), (0, P_pad - P)))

    used, opened, placed_sorted = _pallas_scan_all(
        pod_req, order, sorted_mask, template_allocs, caps,
        max_nodes=max_nodes, chunk=chunk, group_block=group_block,
        interpret=interpret,
    )

    garange = jnp.arange(G_pad)
    scheduled = jnp.zeros((G_pad, P_pad), bool).at[
        garange[:, None], order
    ].set(placed_sorted)[:, :P]
    return BinpackResult(
        node_count=opened[0, :G],
        scheduled=scheduled[:G],
        node_used=jnp.swapaxes(used, 1, 2)[:G],
    )
