"""Pallas FFD binpack scan — the VMEM-resident fast path for the north-star
multi-group estimator.

The XLA scan in ops/binpack.ffd_binpack_groups is HBM-bound: every pod step
reads and rewrites its usage carry (~12MB at G=500, M=1000), which costs
~50-80µs/step on a v5e. Here the carry lives in VMEM for a whole chunk of
pods: the grid is (group-blocks,) and each program runs CHUNK scan steps
against its [R, GB, M] FREE-capacity block without touching HBM, so a step
is pure VPU work (one compare pass + one-hot update per resource plane).

Layout notes (Mosaic constraints): the carry is resource-major ([R, GB, M])
so each per-resource plane is a contiguous tile-aligned [GB sublanes × M
lanes] block; the request stream puts the step axis on the sublane
dimension ([R, CHUNK, GB]) and the kernel walks it in 8-step tiles with an
unrolled inner loop, so every dynamic offset is provably 8-aligned.
Inactive pods (mask-failed / pad) travel as +inf request rows — no separate
active stream. Closed nodes hold free == alloc, letting one unmasked
first-fit min implement both "first open node that fits" and "open a new
node" (see the kernel comment). The per-chunk pallas_call carries are
donated (input_output_aliased), so chunk dispatch costs one HBM round-trip
of the carry instead of one per pod; resource axes nobody requests are
dropped before the kernel (exact — see the compression comment).

Semantics are bit-identical to ffd_binpack_groups (same FFD rules:
score-descending order, first-fit in node-open order, open-on-miss,
per-group dynamic caps) — parity-locked in tests/test_pallas_binpack.py.
Reference algorithm: cluster-autoscaler/estimator/binpacking_estimator.go:65.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autoscaler_tpu.ops.binpack import BinpackResult, ffd_scores

BIG_I32 = np.int32(2**31 - 1)
_STEP_TILE = 8  # sublane tile: dynamic offsets must be provably 8-aligned


def _scan_kernel(
    req_ref,      # [R, CHUNK, GB] f32 — sorted pod requests, +inf = inactive
    caps_ref,     # [1, GB] i32
    free_in_ref,  # [R, GB, M] f32 (aliased with free_out)
    opened_in_ref,  # [1, GB] i32 (aliased with opened_out)
    free_ref,     # [R, GB, M] f32 out
    opened_ref,   # [1, GB] i32 out
    placed_ref,   # [CHUNK, GB] i32 out
    *,
    num_resources: int,
    chunk: int,
    max_nodes: int,
):
    # Layout: the capacity carry is resource-MAJOR ([R, GB, M]) so each
    # per-resource slice free_ref[r] is a contiguous, tile-aligned [GB, M]
    # block (GB sublanes × M lanes). The earlier [GB, R, M] layout put R on
    # the sublane axis, turning every read/update in the hot loop into a
    # strided single-sublane RMW across all GB tiles (~8× waste) — measured
    # 16.5s vs the XLA scan's 10.0s at the north-star shape on a real v5e.
    # The carry holds FREE capacity (alloc - used), not usage: the fit
    # compare then reads it directly, saving R [GB, M] subtracts per step.
    gb = free_ref.shape[1]
    R = num_resources
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (gb, max_nodes), 1)
    caps = caps_ref[0, :]                               # [GB]

    free_ref[:] = free_in_ref[:]
    opened_ref[:] = opened_in_ref[:]

    def tile_step(t, _):
        base = t * _STEP_TILE
        req_tiles = [
            req_ref[r, pl.ds(base, _STEP_TILE), :] for r in range(R)
        ]                                               # R × [8, GB]
        placed_rows = []

        for s in range(_STEP_TILE):
            opened = opened_ref[0, :]                   # [GB]
            req = [req_tiles[r][s, :] for r in range(R)]  # R × [GB]
            # inactive pods (mask-failed or pad slots) carry +inf requests:
            # they fit nowhere and so place nothing — no separate active
            # stream or gate needed.
            #
            # Closed nodes (m >= opened) hold free == alloc by construction,
            # so the UNMASKED first-fit min doubles as the open-new-node
            # rule: a pod that fits no open node but fits an empty template
            # lands exactly at index `opened` (all closed nodes compare
            # equal, the min picks the first). first > opened is impossible,
            # and first >= caps (capped group, or template too small: the
            # min landed past the cap or nowhere) means no placement. This
            # folds the open-mask compare, the fits_empty chain and the
            # can_open arithmetic into the one masked-min.

            fits = req[0][:, None] <= free_ref[0]       # [GB, M]
            for r in range(1, R):
                fits &= req[r][:, None] <= free_ref[r]

            first = jnp.min(
                jnp.where(fits, node_iota, BIG_I32), axis=1
            )                                           # [GB]
            place = first < caps
            target = jnp.where(place, first, -1)        # -1: no hit row

            # i1 [GB] -> [GB,1] reshapes are unsupported on TPU; broadcast
            # the placement gate through f32 [GB, 1] columns instead. The
            # select (not a multiply by place) matters: inf * 0.0 = NaN
            # would poison the carry via the hit row.
            hit = node_iota == target[:, None]                      # [GB, M]
            for r in range(R):
                sub = jnp.where(place, req[r], 0.0)[:, None]        # [GB, 1]
                free_ref[r, :, :] = free_ref[r] - jnp.where(hit, sub, 0.0)
            opened_ref[0, :] = jnp.maximum(
                opened, jnp.where(place, first + 1, 0)
            )
            placed_rows.append(place.astype(jnp.int32))

        placed_ref[pl.ds(base, _STEP_TILE), :] = jnp.stack(placed_rows, axis=0)
        return 0

    jax.lax.fori_loop(0, chunk // _STEP_TILE, tile_step, 0)


@functools.partial(
    jax.jit, static_argnames=("chunk", "max_nodes", "group_block", "interpret")
)
def _run_chunk(
    req_chunk,   # [R, CHUNK, G] f32 (+inf rows = inactive)
    caps,        # [1, G] i32
    free,        # [R, G, M] f32
    opened,      # [1, G] i32
    chunk: int,
    max_nodes: int,
    group_block: int,
    interpret: bool,
):
    R = req_chunk.shape[0]
    G = req_chunk.shape[2]
    grid = (G // group_block,)
    kernel = functools.partial(
        _scan_kernel, num_resources=R, chunk=chunk, max_nodes=max_nodes
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, chunk, group_block), lambda i: (0, 0, i)),
            pl.BlockSpec((1, group_block), lambda i: (0, i)),
            pl.BlockSpec((R, group_block, max_nodes), lambda i: (0, i, 0)),
            pl.BlockSpec((1, group_block), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((R, group_block, max_nodes), lambda i: (0, i, 0)),
            pl.BlockSpec((1, group_block), lambda i: (0, i)),
            pl.BlockSpec((chunk, group_block), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, G, max_nodes), jnp.float32),
            jax.ShapeDtypeStruct((1, G), jnp.int32),
            jax.ShapeDtypeStruct((chunk, G), jnp.int32),
        ],
        input_output_aliases={2: 0, 3: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(req_chunk, caps, free, opened)


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "chunk", "group_block", "interpret"),
)
def _pallas_scan_all(
    pod_req,          # [P_pad, R] (padded with an impossible sentinel row at 0? no — padding handled by active flags)
    order,            # [G_pad, P_pad] i32
    sorted_mask,      # [G_pad, P_pad] bool
    template_allocs,  # [G_pad, R]
    caps,             # [1, G_pad] i32
    max_nodes: int,
    chunk: int,
    group_block: int,
    interpret: bool,
):
    """One jit: lax.scan over pod chunks, each advancing the VMEM kernel.
    Keeping the loop on device avoids ~P/chunk host dispatch round-trips
    (which dominate wall-clock on a tunneled TPU). Inactive slots (mask
    failures and pad) travel as +inf requests, so the kernel needs no
    separate active stream. (A whole-stream pre-gather/transpose outside the
    scan was tried and crashed the AOT compile helper at the north-star
    shape; the per-chunk gather compiles everywhere and measures the same.)"""
    G_pad, P_pad = order.shape
    R = pod_req.shape[1]
    NC = P_pad // chunk
    order_c = order.reshape(G_pad, NC, chunk).transpose(1, 0, 2)       # [NC, G, C]
    active_c = sorted_mask.reshape(G_pad, NC, chunk).transpose(1, 0, 2)
    allocs_in = template_allocs.T                                      # [R, G]

    def chunk_step(carry, xs):
        free, opened = carry
        idx, active = xs                                   # [G, C]
        gathered = jnp.where(
            active[:, :, None], pod_req[idx], jnp.inf
        )                                                  # [G, C, R]
        req_chunk = jnp.transpose(gathered, (2, 1, 0))     # [R, C, G]
        free, opened, placed = _run_chunk(
            req_chunk, caps, free, opened,
            chunk=chunk, max_nodes=max_nodes, group_block=group_block,
            interpret=interpret,
        )
        return (free, opened), placed.T                    # [G, C]

    init = (
        jnp.broadcast_to(allocs_in[:, :, None], (R, G_pad, max_nodes)).astype(
            jnp.float32
        ),
        jnp.zeros((1, G_pad), jnp.int32),
    )
    (free, opened), placed = jax.lax.scan(chunk_step, init, (order_c, active_c))
    used = allocs_in[:, :, None] - free
    placed_sorted = placed.transpose(1, 0, 2).reshape(G_pad, P_pad) > 0
    return used, opened, placed_sorted


def ffd_binpack_groups_pallas(
    pod_req,          # [P, R]
    pod_masks,        # [G, P] bool
    template_allocs,  # [G, R]
    max_nodes: int,
    node_caps=None,   # [G] i32
    chunk: int | None = None,   # None = auto-size against the VMEM budget
    group_block: int = 0,   # 0 = auto
    interpret: bool | None = None,
) -> BinpackResult:
    """Drop-in twin of ffd_binpack_groups running the scan in Pallas.

    The scan over pod chunks runs inside one jit (lax.scan), each iteration
    gathering the chunk's score-sorted requests and advancing the
    VMEM-resident free-capacity carry via the kernel. chunk=None picks the
    largest chunk the VMEM budget model admits (see the calibrated estimate
    below); an explicit chunk is honored as-is."""
    if chunk is not None and chunk % _STEP_TILE != 0:
        raise ValueError(
            f"chunk must be a multiple of {_STEP_TILE} (sublane tile); got {chunk}"
        )
    pod_req = jnp.asarray(pod_req, jnp.float32)
    pod_masks = jnp.asarray(pod_masks)
    template_allocs = jnp.asarray(template_allocs, jnp.float32)
    P, R_full = pod_req.shape
    G = pod_masks.shape[0]
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(jnp.asarray(node_caps, jnp.int32), max_nodes)[None, :]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if group_block <= 0:
        group_block = 128 if not interpret else 8
    # Pad the group axis to a block multiple (lane dims must be 128-wide on
    # TPU); padding groups carry zero caps/allocs and place nothing.
    G_pad = G + (-G) % group_block
    if G_pad != G:
        pad = G_pad - G
        pod_masks = jnp.pad(pod_masks, ((0, pad), (0, 0)))
        template_allocs = jnp.pad(template_allocs, ((0, pad), (0, 0)))
        caps = jnp.pad(caps, ((0, 0), (0, pad)))

    scores = jax.vmap(lambda alloc: ffd_scores(pod_req, alloc))(template_allocs)
    order = jnp.argsort(-scores, axis=1, stable=True)               # [G_pad, P]
    sorted_mask = jnp.take_along_axis(pod_masks, order, axis=1)

    # Exact resource-axis compression (AFTER scoring, which indexes CPU/MEMORY
    # positionally): an axis nobody requests can never gate a fit (0 <= free
    # always) nor change the carry (usage += 0), so drop it from the kernel's
    # per-resource loop. At the north-star workload this removes the
    # always-zero ephemeral/tpu axes (R 6→4, ~1/3 of the VPU work). The tiny
    # host sync is amortized over the whole scan.
    keep = [r for r in range(R_full) if bool((pod_req[:, r] > 0).any())] or [0]
    compressed = len(keep) < R_full
    if compressed:
        pod_req = pod_req[:, jnp.asarray(keep)]
        template_allocs = template_allocs[:, jnp.asarray(keep)]

    # Auto-size the chunk: longer kernel invocations amortize per-chunk
    # dispatch and carry round-trips, bounded by VMEM. Budget model (bytes,
    # per grid program), calibrated on a real v5e: Mosaic double-buffers the
    # request stream and carry blocks, so scoped VMEM ≈
    # (2·req + 2·carry + placed)·4B + ~3MB scratch. With the [R, GB, M]
    # free-capacity carry at R=4, GB=128, M=1024: chunk=2048 overflowed by
    # 4.04MB (est 18.9MB), chunk=1024 (est 12.1MB) compiles and runs.
    # An explicit chunk is honored untouched; tiny worlds stay at the
    # smallest tile-aligned chunk covering P rather than padding up.
    if chunk is None:
        R_k = len(keep)
        M_lanes = max_nodes + (-max_nodes) % 128
        chunk = 512
        for cand in (1024,):
            est = (
                2 * R_k * cand * group_block      # double-buffered req stream
                + 2 * R_k * group_block * M_lanes  # carry in/out
                + cand * group_block              # placed out
            ) * 4 + 3 * 1024 * 1024               # Mosaic scratch
            if est <= 15 * 1024 * 1024:
                chunk = cand
        # don't scan pure padding: a P=300 world needs one 304-slot chunk,
        # not a 1024-slot one
        while chunk > _STEP_TILE and chunk // 2 >= P:
            chunk //= 2

    # Pad the pod axis to a chunk multiple with inactive slots. The pad value
    # must be an index outside [0, P): the final scheduled scatter writes at
    # `order`, and zero-padding would send every padded (inactive, False)
    # slot to column 0, clobbering pod 0's real placement bit. P_pad-1 >= P
    # here, so padded writes land in columns sliced away by [:, :P].
    P_pad = P + (-P) % chunk
    if P_pad != P:
        order = jnp.pad(order, ((0, 0), (0, P_pad - P)), constant_values=P_pad - 1)
        sorted_mask = jnp.pad(sorted_mask, ((0, 0), (0, P_pad - P)))

    used, opened, placed_sorted = _pallas_scan_all(
        pod_req, order, sorted_mask, template_allocs, caps,
        max_nodes=max_nodes, chunk=chunk, group_block=group_block,
        interpret=interpret,
    )

    garange = jnp.arange(G_pad)
    scheduled = jnp.zeros((G_pad, P_pad), bool).at[
        garange[:, None], order
    ].set(placed_sorted)[:, :P]
    node_used = jnp.transpose(used, (1, 2, 0))[:G]        # [G, M, R]
    if compressed:
        node_used = (
            jnp.zeros((G, max_nodes, R_full), jnp.float32)
            .at[:, :, jnp.asarray(keep)]
            .set(node_used)
        )
    return BinpackResult(
        node_count=opened[0, :G],
        scheduled=scheduled[:G],
        node_used=node_used,
    )
