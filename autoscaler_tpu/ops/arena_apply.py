"""Delta scatter-apply kernels for the resident device arena.

The arena (snapshot/arena.py) keeps the packed snapshot tensors
device-resident across reconcile ticks; the host ships only (row-index,
payload) batches for the rows the incremental packer dirtied. These
kernels apply one such batch to one resident buffer.

Donation (`donate_argnums=0`) is the point: the input buffer's device
memory is reused for the output, so a steady-state tick performs an
in-place row scatter — no fresh O(world) allocation, no host→device
re-transfer of the untouched rows (the pjit donation pattern of
SNIPPETS.md [1], applied to control-plane state instead of optimizer
state). On backends without donation support (CPU) XLA falls back to a
device-side copy; semantics are identical either way, which is what the
oracle twin (estimator/reference_impl.apply_row_deltas_reference) pins.

Index padding contract: delta batches are padded up to a power-of-EIGHT
K ladder (8, 64, 512, … — arena.delta_bucket; a small closed set of
traced shapes, the compile-cache key discipline of fleet/buckets.py
applied to the delta axis). Padding entries carry index == buffer
length, which is out of bounds and dropped by the scatter
(`mode="drop"`); real indices are UNIQUE and sorted (the packer emits
them from sets), so scatter-set determinism never depends on
duplicate-resolution order.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Machine-readable kernel contracts (graftlint GL007, analysis/contracts.py):
# AST-extracted, never imported. Operand names are arena_* on purpose — the
# buffers are dtype-polymorphic (f32 rows, bool masks, i32 vectors), so no
# dtype is declared for them and the names must not collide with the
# binpack family's typed operands. AK is the padded delta-batch axis (a
# power-of-eight ladder rung); out-of-range indices (== AN) are padding
# and drop.
KERNEL_CONTRACTS = {
    "arena_scatter_rows": {
        "args": {
            "arena_buf": {"dims": ["AN", "AR"]},
            "arena_idx": {"dims": ["AK"], "dtype": "i32"},
            "arena_rows": {"dims": ["AK", "AR"]},
        },
        "notes": "row scatter on axis 0; idx unique, padding idx == AN drops",
    },
    "arena_scatter_vec": {
        "args": {
            "arena_buf1": {"dims": ["AN"]},
            "arena_idx": {"dims": ["AK"], "dtype": "i32"},
            "arena_vals": {"dims": ["AK"]},
        },
        "notes": "element scatter on a rank-1 buffer; same index contract",
    },
    "arena_scatter_cols": {
        "args": {
            "arena_mat": {"dims": ["AP", "AN"]},
            "arena_idx": {"dims": ["AK"], "dtype": "i32"},
            "arena_cols": {"dims": ["AP", "AK"]},
        },
        "notes": "column scatter on axis 1 (mask node-column refresh)",
    },
}


@functools.partial(jax.jit, donate_argnums=0)
def arena_scatter_rows(
    arena_buf: jax.Array,   # [AN, AR] resident buffer (donated)
    arena_idx: jax.Array,   # [AK] i32 unique row indices; AN = padding
    arena_rows: jax.Array,  # [AK, AR] replacement rows
) -> jax.Array:
    arena_idx = jnp.asarray(arena_idx, jnp.int32)
    return arena_buf.at[arena_idx].set(arena_rows, mode="drop")


@functools.partial(jax.jit, donate_argnums=0)
def arena_scatter_vec(
    arena_buf1: jax.Array,  # [AN] resident rank-1 buffer (donated)
    arena_idx: jax.Array,   # [AK] i32 unique indices; AN = padding
    arena_vals: jax.Array,  # [AK] replacement elements
) -> jax.Array:
    arena_idx = jnp.asarray(arena_idx, jnp.int32)
    return arena_buf1.at[arena_idx].set(arena_vals, mode="drop")


@functools.partial(jax.jit, donate_argnums=0)
def arena_scatter_cols(
    arena_mat: jax.Array,   # [AP, AN] resident matrix (donated)
    arena_idx: jax.Array,   # [AK] i32 unique column indices; AN = padding
    arena_cols: jax.Array,  # [AP, AK] replacement columns
) -> jax.Array:
    arena_idx = jnp.asarray(arena_idx, jnp.int32)
    return arena_mat.at[:, arena_idx].set(arena_cols, mode="drop")
