"""First-fit-decreasing binpacking as a `lax.scan` — the device replacement
for the reference's BinpackingNodeEstimator inner loop.

Reference: cluster-autoscaler/estimator/binpacking_estimator.go:65 (Estimate):
pods are sorted by score = cpu_req/cpu_cap + mem_req/mem_cap descending
(:164-193), then a serial per-pod loop tries FitsAnyNodeMatching over the
newly-opened template nodes (:91) and opens another template node on miss
(:119-141). That loop is `#pods × #new_nodes × #filter_plugins` predicate
runs, serially, per node group.

Here the per-pod sequence is a lax.scan whose carry is the open-node usage
matrix `used[max_nodes, R]`; each step is a vectorized first-fit over all
open nodes at once, and the whole scan is vmapped over node groups (and,
higher up, over what-if scenarios), so the serial axis is amortized across
the batch — the TPU-native answer to FFD's inherent sequentiality
(SURVEY.md §7 hard-part #3).

FFD with identical bins is the same 11/9·OPT + 6/9 approximation the
reference already accepts (binpacking_estimator.go:58-62).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from autoscaler_tpu.explain.reasons import (
    NUM_REASONS,
    REASON_AFFINITY_SPREAD,
    REASON_CPU,
    REASON_MEMORY,
    REASON_NODE_CAP,
    REASON_NONE,
    REASON_POD_SLOT,
    REASON_RESOURCE,
    REASON_TOPOLOGY,
)
from autoscaler_tpu.kube.objects import CPU, MEMORY, PODS
from autoscaler_tpu.ops.telemetry import observed

BIG_I32 = jnp.int32(2**30)  # "no domain yet" sentinel in spread minimums

# Machine-readable kernel contracts (graftlint GL007, analysis/contracts.py):
# AST-extracted, never imported. The XLA scans have no pallas grid to prove,
# but the dim-symbol ties and dtypes are checked at every dispatch site, and
# shared operand names must agree with the Pallas twins on rank and dtype.
# (The run-compressed kernels rename the pod axis P to the run axis U.)
KERNEL_CONTRACTS = {
    "ffd_binpack": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "pod_mask": {"dims": ["P"], "dtype": "bool"},
            "template_alloc": {"dims": ["R"], "dtype": "f32"},
        },
        "static": {"max_nodes": {"min": 1}},
    },
    "ffd_binpack_groups": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "pod_masks": {"dims": ["G", "P"], "dtype": "bool"},
            "template_allocs": {"dims": ["G", "R"], "dtype": "f32"},
            "node_caps": {"dims": ["G"], "dtype": "i32"},
        },
        "static": {"max_nodes": {"min": 1}},
    },
    "ffd_binpack_groups_runs": {
        "args": {
            "run_req": {"dims": ["U", "R"], "dtype": "f32"},
            "run_counts": {"dims": ["U"], "dtype": "i32"},
            "run_masks": {"dims": ["G", "U"], "dtype": "bool"},
            "template_allocs": {"dims": ["G", "R"], "dtype": "f32"},
            "node_caps": {"dims": ["G"], "dtype": "i32"},
        },
        "static": {"max_nodes": {"min": 1}},
    },
    "ffd_binpack_groups_runs_affinity": {
        "args": {
            "run_req": {"dims": ["U", "R"], "dtype": "f32"},
            "run_counts": {"dims": ["U"], "dtype": "i32"},
            "run_masks": {"dims": ["G", "U"], "dtype": "bool"},
            "template_allocs": {"dims": ["G", "R"], "dtype": "f32"},
            "involved": {"dims": ["U"], "dtype": "bool"},
            "match": {"dims": ["T", "U"], "dtype": "bool"},
            "aff_of": {"dims": ["T", "U"], "dtype": "bool"},
            "anti_of": {"dims": ["T", "U"], "dtype": "bool"},
            "node_level": {"dims": ["T"], "dtype": "bool"},
            "has_label": {"dims": ["G", "T"], "dtype": "bool"},
            "node_caps": {"dims": ["G"], "dtype": "i32"},
        },
        "static": {"max_nodes": {"min": 1}},
    },
    "ffd_binpack_groups_affinity": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "pod_masks": {"dims": ["G", "P"], "dtype": "bool"},
            "template_allocs": {"dims": ["G", "R"], "dtype": "f32"},
            "match": {"dims": ["T", "P"], "dtype": "bool"},
            "aff_of": {"dims": ["T", "P"], "dtype": "bool"},
            "anti_of": {"dims": ["T", "P"], "dtype": "bool"},
            "node_level": {"dims": ["T"], "dtype": "bool"},
            "has_label": {"dims": ["G", "T"], "dtype": "bool"},
            "node_caps": {"dims": ["G"], "dtype": "i32"},
        },
        "static": {"max_nodes": {"min": 1}},
    },
    "attribute_unschedulable": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "pod_masks": {"dims": ["G", "P"], "dtype": "bool"},
            "template_allocs": {"dims": ["G", "R"], "dtype": "f32"},
            "scheduled": {"dims": ["G", "P"], "dtype": "bool"},
            "involved": {"dims": ["P"], "dtype": "bool"},
        },
    },
    # Scenario-batched fleet entry (autoscaler_tpu/fleet): the leading S
    # axis is independent what-if worlds — one coalesced multi-tenant batch.
    # Operand names are scen_* on purpose: each tenant ships its OWN pod
    # matrix, so the ranks differ from the single-snapshot family and the
    # cross-twin rank check must not tie them to pod_req/pod_masks.
    "ffd_binpack_scenarios": {
        "args": {
            "scen_req": {"dims": ["S", "P", "R"], "dtype": "f32"},
            "scen_masks": {"dims": ["S", "G", "P"], "dtype": "bool"},
            "scen_allocs": {"dims": ["S", "G", "R"], "dtype": "f32"},
            "scen_caps": {"dims": ["S", "G"], "dtype": "i32"},
        },
        "static": {"max_nodes": {"min": 1}},
    },
}


class BinpackResult(NamedTuple):
    node_count: jax.Array   # i32 scalar (or [G]) — template nodes opened
    scheduled: jax.Array    # [P] bool (or [G, P]) — pod was placed
    node_used: jax.Array    # [max_nodes, R] (or [G, max_nodes, R])


def ffd_scores(pod_req: jax.Array, template_alloc: jax.Array) -> jax.Array:
    """[P] f32 — the reference's pod score (binpacking_estimator.go:164-193):
    cpu/cpu_cap + mem/mem_cap against the group's template capacity,
    rescaled by the (positive, per-group-constant) product of the caps into
    the DIVISION-FREE order-equivalent `cpu·mem_cap + mem·cpu_cap`.

    The rescale is not cosmetic: XLA lowers f32 divide on TPU to a
    reciprocal-multiply approximation that is not correctly rounded, so the
    literal formula orders ulp-near scores differently on TPU than IEEE
    division does on the host — at the north-star bench shape that flipped
    score-sort order in every sampled group and diverged 4 scheduled bits
    vs the serial C++ baseline (round-4 capture). f32 multiply/add ARE
    IEEE-rounded on the VPU, so this form is bit-reproducible across TPU,
    numpy, and C++ (the C++ baseline compiles with -ffp-contract=off so no
    FMA re-rounds the sum). Every FFD order producer — this function, the
    numpy oracle (estimator/reference_impl.py), and native/ffd_serial.cpp —
    computes this same spec; a zero cap drops its term and leaves the other
    unscaled, preserving the original single-term order."""
    cpu_cap = template_alloc[CPU]
    mem_cap = template_alloc[MEMORY]
    c_scale = jnp.where(cpu_cap > 0, cpu_cap, 1.0)
    m_scale = jnp.where(mem_cap > 0, mem_cap, 1.0)
    s_cpu = jnp.where(cpu_cap > 0, pod_req[:, CPU] * m_scale, 0.0)
    s_mem = jnp.where(mem_cap > 0, pod_req[:, MEMORY] * c_scale, 0.0)
    return s_cpu + s_mem


@observed
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def ffd_binpack(
    pod_req: jax.Array,        # [P, R]
    pod_mask: jax.Array,       # [P] bool — pod passes the group's non-resource predicates
    template_alloc: jax.Array,  # [R]
    max_nodes: int,
    node_cap: jax.Array | None = None,  # dynamic per-call cap <= max_nodes
) -> BinpackResult:
    """Estimate how many template nodes are needed for the masked pods.

    Semantics mirror the reference serially: score-sort descending, first-fit
    over open nodes in open order, open a new node when none fit, skip pods
    that would not fit even an empty template node. `max_nodes` is the static
    carry size (compile-time); `node_cap` is the dynamic limiter threshold
    (per-group headroom), so differently-capped groups share one compiled
    kernel.
    """
    P = pod_req.shape[0]
    R = pod_req.shape[1]
    cap = jnp.int32(max_nodes) if node_cap is None else jnp.minimum(
        jnp.int32(node_cap), max_nodes
    )

    score = ffd_scores(pod_req, template_alloc)
    # Descending by score; ties keep original pod order (stable argsort).
    order = jnp.argsort(-score, stable=True)
    sorted_req = pod_req[order]
    sorted_mask = pod_mask[order]

    node_ids = jnp.arange(max_nodes)

    def step(carry, inp):
        used, opened = carry
        req, active = inp
        free = template_alloc[None, :] - used
        fits = jnp.all(req[None, :] <= free, axis=-1) & (node_ids < opened)
        has_fit = fits.any()
        first = jnp.argmax(fits)
        fits_empty = jnp.all(req <= template_alloc)
        can_open = (opened < cap) & fits_empty
        place = active & (has_fit | can_open)
        target = jnp.where(has_fit, first, opened)
        used = used.at[target].add(jnp.where(place, req, jnp.zeros((R,), req.dtype)))
        opened = opened + jnp.where(place & ~has_fit, 1, 0)
        return (used, opened), place

    init = (jnp.zeros((max_nodes, R), pod_req.dtype), jnp.int32(0))
    (used, opened), placed_sorted = jax.lax.scan(step, init, (sorted_req, sorted_mask))

    scheduled = jnp.zeros((P,), bool).at[order].set(placed_sorted)
    return BinpackResult(node_count=opened, scheduled=scheduled, node_used=used)


@observed
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def ffd_binpack_groups(
    pod_req: jax.Array,         # [P, R] shared pending-pod matrix
    pod_masks: jax.Array,       # [G, P] per-group schedulability
    template_allocs: jax.Array,  # [G, R]
    max_nodes: int,
    node_caps: jax.Array | None = None,  # [G] i32 dynamic per-group caps
) -> BinpackResult:
    """All node groups estimated in one dispatch — the batched replacement for
    the reference's serial FOR-EACH-nodeGroup expansion-option loop
    (core/scaleup/orchestrator/orchestrator.go:139-179). Returns [G]-leading
    results; the group axis is also the natural shard_map axis for multi-chip.

    Memory layout is deliberate for TPU tiling (minor dim pads to 128 lanes):
    the scan consumes per-group *pod indices* [P, G] into the shared pod_req
    (never materializing a [G, P, R] sorted copy — at 500 groups x 100k pods
    that padded copy alone is ~25GB), and the usage carry is [G, R, M] so the
    padded minor axis is the node axis, which is large anyway. Semantics are
    identical to vmapping ffd_binpack (parity-tested).
    """
    P, R = pod_req.shape
    G = pod_masks.shape[0]
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(node_caps.astype(jnp.int32), max_nodes)

    scores = jax.vmap(lambda alloc: ffd_scores(pod_req, alloc))(template_allocs)  # [G, P]
    order = jnp.argsort(-scores, axis=1, stable=True)                 # [G, P]
    sorted_mask = jnp.take_along_axis(pod_masks, order, axis=1)       # [G, P]

    alloc_t = template_allocs[:, :, None]                             # [G, R, 1]
    node_ids = jnp.arange(max_nodes)
    garange = jnp.arange(G)

    def step(carry, xs):
        used_t, opened = carry            # [G, R, M], [G]
        idx, active = xs                  # [G] i32, [G] bool
        req = pod_req[idx]                # [G, R] gather from shared matrix
        free_t = alloc_t - used_t         # [G, R, M]
        fits_n = jnp.all(req[:, :, None] <= free_t, axis=1)           # [G, M]
        fits_n &= node_ids[None, :] < opened[:, None]
        has_fit = fits_n.any(axis=1)
        first = jnp.argmax(fits_n, axis=1).astype(jnp.int32)
        fits_empty = jnp.all(req <= template_allocs, axis=1)
        can_open = (opened < caps) & fits_empty
        place = active & (has_fit | can_open)
        target = jnp.where(has_fit, first, opened)                    # [G]
        onehot = ((node_ids[None, :] == target[:, None]) & place[:, None]).astype(
            pod_req.dtype
        )                                                             # [G, M]
        used_t = used_t + req[:, :, None] * onehot[:, None, :]
        opened = opened + (place & ~has_fit).astype(jnp.int32)
        return (used_t, opened), place

    init = (
        jnp.zeros((G, R, max_nodes), pod_req.dtype),
        jnp.zeros((G,), jnp.int32),
    )
    (used_t, opened), placed = jax.lax.scan(
        step, init, (order.T, sorted_mask.T)
    )                                                                 # placed [P, G]

    scheduled = (
        jnp.zeros((G, P), bool).at[garange[:, None], order].set(placed.T)
    )
    return BinpackResult(
        node_count=opened,
        scheduled=scheduled,
        node_used=jnp.swapaxes(used_t, 1, 2),                         # [G, M, R]
    )


@observed
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def ffd_binpack_scenarios(
    scen_req: jax.Array,     # [S, P, R] per-scenario pending-pod matrices
    scen_masks: jax.Array,   # [S, G, P] per-scenario per-group schedulability
    scen_allocs: jax.Array,  # [S, G, R] per-scenario template capacities
    max_nodes: int,
    scen_caps: jax.Array | None = None,  # [S, G] i32 dynamic per-group caps
) -> BinpackResult:
    """The fleet-serving entry: a BATCH of independent estimate worlds in one
    dispatch (BASELINE config 5, ROADMAP item 1). Each scenario s is one
    tenant's coalesced request — its own pods, masks, templates, caps — and
    the whole operand set carries a leading scenario axis that shard_map
    splits across the mesh with the existing ``P("scenario", "group")``
    specs (parallel/mesh.fleet_batch_estimate); zero cross-scenario data
    flow, so per-tenant verdicts are bit-identical to solo dispatches of the
    same operands (the loadgen fairness certificate).

    Semantically this is exactly ``vmap(ffd_binpack_groups)`` over S —
    parity-locked against the serial per-scenario oracle twin
    (estimator/reference_impl.scenario_binpack_reference) in
    tests/test_fleet.py. ``max_nodes`` is the shared static carry size; a
    tenant's own node budget rides the dynamic ``scen_caps`` row (min'd with
    max_nodes inside the per-group kernel), which is what makes
    exact-padding a request into a (P, G, R) shape bucket answer-preserving:
    padded pods carry mask=False, padded groups carry alloc=0 ∧ cap=0,
    padded resource columns carry req=0 ≤ alloc=0, and the carry rows past a
    tenant's real cap can never open."""
    S, P, R = scen_req.shape
    G = scen_masks.shape[1]
    if scen_caps is None:
        scen_caps = jnp.full((S, G), max_nodes, jnp.int32)
    # the inner entry's @observed wrapper must not fire mid-trace (it would
    # clobber the perf observatory's parked record for THIS dispatch with
    # abstract tracers) — vmap the underlying jit entry
    inner = ffd_binpack_groups.__wrapped__
    return jax.vmap(
        lambda req, masks, allocs, caps: inner(
            req, masks, allocs, max_nodes=max_nodes, node_caps=caps
        )
    )(scen_req, scen_masks, scen_allocs, scen_caps)


def _max_fit(q, free):
    """[G, M] f32 — max k with k*q <= free elementwise over resources, exact
    under f32 multiply via floor-division + a ±1-ulp correction pass (shared
    by the run-fill kernels; parity-locked to the per-pod scan)."""
    pos = q > 0                                                  # [G, R]
    safe_q = jnp.where(pos, q, 1.0)
    per = jnp.where(
        pos[:, :, None], jnp.floor(free / safe_q[:, :, None]), jnp.float32(2**30)
    )
    cnt = jnp.maximum(per.min(axis=1), 0.0)                      # [G, M]

    def fits_k(k):
        return jnp.all(k[:, None, :] * q[:, :, None] <= free, axis=1)

    cnt = jnp.where(fits_k(cnt), cnt, jnp.maximum(cnt - 1, 0.0))
    return jnp.where(fits_k(cnt + 1), cnt + 1, cnt)


def _spread_state_init(G: int, S: int, max_nodes: int):
    return (
        jnp.zeros((G, S, max_nodes), jnp.int32),  # spc: per-node scan counts
        jnp.zeros((G, S), jnp.int32),             # spc_tot: group scan counts
    )


def _spread_gates(sp, spc, spc_tot, idx, opened, node_ids):
    """Within-wave topology-spread gating (closes the scan half of
    PREDICATES.md divergence 2; reference counts update per placement via
    schedulerbased.go:109-163) → (group_ok [G], node_ok [G, M], upd [G, S]).

    Group-level terms: every new node of a group shares the template's
    domain, so its count is static_count + scan placements; the global min
    is min(min over OTHER static domains, that count) — other domains'
    counts cannot change during the wave — with minDomains folding to a
    precomputed force_zero. One violated term blocks the whole group this
    step (both open-node placement and opening).

    Hostname-level terms: each opened node is a domain with its own scan
    count; the global min is min(static domain min, min over opened nodes),
    and minDomains compares against static domains + opened. A fresh node
    is a 0-count domain, so opening is never blocked by a hostname term
    (matching the reference: the candidate node's own empty domain is the
    global minimum)."""
    (sp_of_T, sp_match_T, nl, skew, mind, has_label, st_count,
     min_others, st_min, st_domnum, force_zero) = sp
    sp_o = sp_of_T[idx]                                          # [G, S]
    sp_m = sp_match_T[idx]                                       # [G, S]
    self_i = sp_m.astype(jnp.int32)
    # group-level
    cnt = st_count + spc_tot                                     # [G, S]
    min_eff_z = jnp.where(force_zero, 0, jnp.minimum(min_others, cnt))
    bad_z = (
        sp_o & ~nl[None, :] & has_label
        & (cnt + self_i - min_eff_z > skew[None, :])
    )
    group_ok = ~bad_z.any(axis=1)                                # [G]
    # hostname-level
    open_m = node_ids[None, None, :] < opened[:, None, None]     # [G, 1, M]
    dyn_min = jnp.min(jnp.where(open_m, spc, BIG_I32), axis=2)   # [G, S]
    domnum = st_domnum + opened[:, None]                         # [G, S]
    min_eff_h = jnp.where(
        mind[None, :] > domnum, 0, jnp.minimum(st_min, dyn_min)
    )
    bad_h = (
        sp_o[:, :, None] & nl[None, :, None]
        & (spc + self_i[:, :, None] - min_eff_h[:, :, None]
           > skew[None, :, None])
    )
    node_ok = ~bad_h.any(axis=1)                                 # [G, M]
    upd = sp_m & has_label   # placements on keyless templates never count
    return group_ok, node_ok, upd


def _affinity_node_gates(m_p, a_p, x_p, pm, pm_tot, ha, ha_tot, nl, has_label):
    """Shared dynamic-affinity gating (see ffd_binpack_groups_affinity's
    docstring for the rules) → (gate_open [G, M], new_ok [G]): which open
    nodes admit the candidate pod term-wise, and whether it may seed a fresh
    node. A node without the term's topology label has no domain there, so
    an anti term over it can never be violated (Kubernetes: the term simply
    does not match) — hence the has_label gate on both anti directions."""
    dom_pm = jnp.where(nl[None, :, None], pm, pm_tot[:, :, None])  # [G,T,M]
    dom_ha = jnp.where(nl[None, :, None], ha, ha_tot[:, :, None])
    self_seed = m_p & (pm_tot == 0)                              # [G, T]
    ok_t = ~a_p[:, :, None] | (
        has_label[:, :, None] & ((dom_pm > 0) | self_seed[:, :, None])
    )
    aff_ok = ok_t.all(axis=1)                                    # [G, M]
    hl = has_label[:, :, None]
    anti_blocked = (x_p[:, :, None] & (dom_pm > 0) & hl).any(axis=1)
    sym_blocked = (m_p[:, :, None] & (dom_ha > 0) & hl).any(axis=1)
    gate_open = aff_ok & ~anti_blocked & ~sym_blocked
    ok_new_t = ~a_p | jnp.where(
        nl[None, :], self_seed, has_label & ((pm_tot > 0) | self_seed)
    )
    new_ok = ok_new_t.all(axis=1)
    new_ok &= ~(x_p & ~nl[None, :] & (pm_tot > 0) & has_label).any(axis=1)
    new_ok &= ~(m_p & ~nl[None, :] & (ha_tot > 0) & has_label).any(axis=1)
    return gate_open, new_ok


class RunBinpackResult(NamedTuple):
    node_count: jax.Array     # [G] i32 — template nodes opened
    placed_counts: jax.Array  # [G, U] i32 — pods of run u placed in group g
    node_used: jax.Array      # [G, max_nodes, R]


@observed
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def ffd_binpack_groups_runs(
    run_req: jax.Array,         # [U, R] unique pod-requirement rows
    run_counts: jax.Array,      # [U] i32 — identical pods per run
    run_masks: jax.Array,       # [G, U] bool — run passes group's predicates
    template_allocs: jax.Array,  # [G, R]
    max_nodes: int,
    node_caps: jax.Array | None = None,  # [G] i32
) -> RunBinpackResult:
    """FFD over *equivalence runs*: one scan step per unique pod type instead
    of one per pod — the device-side twin of the reference's pod equivalence
    groups (core/scaleup/equivalence/groups.go:61), which dedups identical
    pods so one predicate evaluation covers many.

    Why a whole run collapses into one step: for identical pods the first-fit
    index is monotone within the run (nodes earlier than pod i's destination
    stay too full for pod i+1), so run placement ≡ greedy fill of nodes in
    open order. Each step therefore computes per-node capacity counts
    (floor(free/req), min over resources), a single cumulative sum in node
    order, and a clip against the remaining run count — no inner loop. New
    nodes continue the same cumsum with the empty-template capacity, bounded
    by the group cap, exactly reproducing the open-on-miss rule.

    Count arithmetic is float32 with a ±1-ulp correction pass so that
    `cnt = max k : k*req <= free` holds under f32 multiply — bit-parity with
    the per-pod kernel for the integer-valued requests the packer produces.
    Semantics match ffd_binpack_groups on the expanded pod list whenever
    distinct runs have distinct scores (ties across runs may interleave
    per-pod; any FFD tie-break is valid — parity-tested in
    tests/test_kernels.py).
    """
    U, R = run_req.shape
    G = run_masks.shape[0]
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(node_caps.astype(jnp.int32), max_nodes)

    scores = jax.vmap(lambda alloc: ffd_scores(run_req, alloc))(template_allocs)  # [G, U]
    order = jnp.argsort(-scores, axis=1, stable=True)                # [G, U]
    sorted_mask = jnp.take_along_axis(run_masks, order, axis=1)      # [G, U]

    alloc_t = template_allocs[:, :, None]                            # [G, R, 1]
    node_ids = jnp.arange(max_nodes)
    garange = jnp.arange(G)
    counts_f = run_counts.astype(jnp.float32)

    def step(carry, xs):
        used_t, opened = carry            # [G, R, M], [G]
        idx, active = xs                  # [G] i32, [G] bool
        q = run_req[idx]                  # [G, R]
        c = jnp.where(active, counts_f[idx], 0.0)                    # [G]
        free_t = alloc_t - used_t
        cnt_open = _max_fit(q, free_t)                                # [G, M]
        per_new = _max_fit(q, alloc_t)[:, 0]                          # [G]
        fits_empty = jnp.all(q <= template_allocs, axis=1)
        open_mask = node_ids[None, :] < opened[:, None]
        new_mask = ~open_mask & (node_ids[None, :] < caps[:, None])
        capvec = jnp.where(open_mask, cnt_open, 0.0) + jnp.where(
            new_mask & fits_empty[:, None], per_new[:, None], 0.0
        )                                                            # [G, M]
        prefix = jnp.cumsum(capvec, axis=1)
        take = jnp.clip(c[:, None] - (prefix - capvec), 0.0, capvec)  # [G, M]
        used_t = used_t + q[:, :, None] * take[:, None, :]
        newly = (take > 0) & new_mask
        high = jnp.max(
            jnp.where(newly, node_ids[None, :] + 1, 0), axis=1
        ).astype(jnp.int32)
        opened = jnp.maximum(opened, high)
        return (used_t, opened), take.sum(axis=1)

    init = (
        jnp.zeros((G, R, max_nodes), run_req.dtype),
        jnp.zeros((G,), jnp.int32),
    )
    (used_t, opened), placed = jax.lax.scan(
        step, init, (order.T, sorted_mask.T)
    )                                                                # placed [U, G]

    placed_counts = (
        jnp.zeros((G, U), jnp.int32)
        .at[garange[:, None], order]
        .set(placed.T.astype(jnp.int32))
    )
    return RunBinpackResult(
        node_count=opened,
        placed_counts=placed_counts,
        node_used=jnp.swapaxes(used_t, 1, 2),
    )


@observed
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def ffd_binpack_groups_runs_affinity(
    run_req: jax.Array,         # [U, R] unique pod-requirement rows
    run_counts: jax.Array,      # [U] i32 — identical pods per run
    run_masks: jax.Array,       # [G, U] bool — run passes group's predicates
    template_allocs: jax.Array,  # [G, R]
    max_nodes: int,
    involved: jax.Array,        # [U] bool — run touches any affinity term
    match: jax.Array,           # [T, U] bool — term selector matches run
    aff_of: jax.Array,          # [T, U] bool — run requires affinity term
    anti_of: jax.Array,         # [T, U] bool — run requires anti term
    node_level: jax.Array,      # [T] bool — hostname-level topology
    has_label: jax.Array,       # [G, T] bool — group template has topology label
    node_caps: jax.Array | None = None,  # [G] i32
    spread: tuple | None = None,  # SpreadTermTensors as an 11-array tuple
) -> RunBinpackResult:
    """Equivalence-run FFD that coexists with dynamic inter-pod affinity —
    the ROADMAP 'run-aware affinity kernel'. Hybrid step semantics:

    - A run with NO term involvement (matches no selector, holds no
      affinity/anti term) collapses into one greedy-fill step exactly like
      ffd_binpack_groups_runs: affinity state cannot change while it
      places, and nothing gates it (the symmetric rule only bites pods that
      match a held term).
    - An involved run is pre-expanded by the caller into singleton runs
      (count 1) and steps through the full affinity-gated placement of
      ffd_binpack_groups_affinity, carrying per-term counts (pm/ha).

    Both paths are computed vectorized each step and selected per group by
    `involved[idx]` (groups sort runs independently, so one step can be a
    plain fill for group A and an affinity placement for group B). Parity
    with ffd_binpack_groups_affinity on the expanded pod list is locked in
    tests/test_affinity_binpack.py. Reference semantics:
    estimator/binpacking_estimator.go:65 + equivalence groups.go:61.
    """
    U, R = run_req.shape
    G = run_masks.shape[0]
    T = match.shape[0]
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(node_caps.astype(jnp.int32), max_nodes)

    scores = jax.vmap(lambda alloc: ffd_scores(run_req, alloc))(template_allocs)  # [G, U]
    order = jnp.argsort(-scores, axis=1, stable=True)                # [G, U]
    sorted_mask = jnp.take_along_axis(run_masks, order, axis=1)      # [G, U]

    alloc_t = template_allocs[:, :, None]                            # [G, R, 1]
    node_ids = jnp.arange(max_nodes)
    garange = jnp.arange(G)
    counts_f = run_counts.astype(jnp.float32)
    inv_u = involved.astype(bool)
    match_t = match.T.astype(bool)                                   # [U, T]
    aff_t = aff_of.T.astype(bool)
    anti_t = anti_of.T.astype(bool)
    nl = node_level.astype(bool)                                     # [T]
    S = spread[2].shape[0] if spread is not None else 0  # node_level [S]

    def step(carry, xs):
        used_t, opened, pm, pm_tot, ha, ha_tot, spc, spc_tot = carry
        idx, active = xs                  # [G] i32, [G] bool
        q = run_req[idx]                  # [G, R]
        inv = inv_u[idx]                  # [G]
        c = jnp.where(active, counts_f[idx], 0.0)                    # [G]
        m_p = match_t[idx]                # [G, T]
        a_p = aff_t[idx]
        x_p = anti_t[idx]

        free_t = alloc_t - used_t
        fits_empty = jnp.all(q <= template_allocs, axis=1)           # [G]
        open_mask = node_ids[None, :] < opened[:, None]              # [G, M]

        # -- path A: plain greedy run fill (inv groups contribute zero) -----
        cnt_open = _max_fit(q, free_t)                                # [G, M]
        per_new = _max_fit(q, alloc_t)[:, 0]                          # [G]
        new_mask = ~open_mask & (node_ids[None, :] < caps[:, None])
        capvec = jnp.where(open_mask, cnt_open, 0.0) + jnp.where(
            new_mask & fits_empty[:, None], per_new[:, None], 0.0
        )
        prefix = jnp.cumsum(capvec, axis=1)
        c_a = jnp.where(inv, 0.0, c)
        take_a = jnp.clip(c_a[:, None] - (prefix - capvec), 0.0, capvec)  # [G, M]
        high_a = jnp.max(
            jnp.where((take_a > 0) & new_mask, node_ids[None, :] + 1, 0), axis=1
        ).astype(jnp.int32)

        # -- path B: affinity-gated single placement (non-inv contribute 0) -
        fits_n = jnp.all(q[:, :, None] <= free_t, axis=1) & open_mask
        gate_open, new_ok = _affinity_node_gates(
            m_p, a_p, x_p, pm, pm_tot, ha, ha_tot, nl, has_label
        )
        fits_b = fits_n & gate_open
        if spread is not None:
            # involved runs are singletons; spread-touching runs are always
            # involved (estimator routing), so path A never moves counts
            sp_group_ok, sp_node_ok, sp_upd = _spread_gates(
                spread, spc, spc_tot, idx, opened, node_ids
            )
            fits_b &= sp_node_ok & sp_group_ok[:, None]
            new_ok &= sp_group_ok
        has_fit = fits_b.any(axis=1)
        first = jnp.argmax(fits_b, axis=1).astype(jnp.int32)
        can_open = (opened < caps) & fits_empty & new_ok
        place_b = active & inv & (c > 0) & (has_fit | can_open)
        target = jnp.where(has_fit, first, opened)
        onehot_b = (node_ids[None, :] == target[:, None]) & place_b[:, None]  # [G, M]

        # -- combine (A and B are disjoint per group via the inv gate) ------
        take = take_a + onehot_b.astype(jnp.float32)
        used_t = used_t + q[:, :, None] * take[:, None, :]
        opened_b = opened + (place_b & ~has_fit).astype(jnp.int32)
        opened = jnp.maximum(opened_b, high_a)

        inc = onehot_b[:, None, :]
        pm = pm + (m_p[:, :, None] & inc).astype(jnp.int32)
        ha = ha + (x_p[:, :, None] & inc).astype(jnp.int32)
        pm_tot = pm_tot + (m_p & place_b[:, None]).astype(jnp.int32)
        ha_tot = ha_tot + (x_p & place_b[:, None]).astype(jnp.int32)
        if spread is not None:
            spc = spc + (sp_upd[:, :, None] & inc).astype(jnp.int32)
            spc_tot = spc_tot + (sp_upd & place_b[:, None]).astype(jnp.int32)
        return (
            (used_t, opened, pm, pm_tot, ha, ha_tot, spc, spc_tot),
            take.sum(axis=1),
        )

    init = (
        jnp.zeros((G, R, max_nodes), run_req.dtype),
        jnp.zeros((G,), jnp.int32),
        jnp.zeros((G, T, max_nodes), jnp.int32),
        jnp.zeros((G, T), jnp.int32),
        jnp.zeros((G, T, max_nodes), jnp.int32),
        jnp.zeros((G, T), jnp.int32),
        *_spread_state_init(G, S, max_nodes),
    )
    (used_t, opened, *_), placed = jax.lax.scan(
        step, init, (order.T, sorted_mask.T)
    )                                                                # placed [U, G]

    placed_counts = (
        jnp.zeros((G, U), jnp.int32)
        .at[garange[:, None], order]
        .set(placed.T.astype(jnp.int32))
    )
    return RunBinpackResult(
        node_count=opened,
        placed_counts=placed_counts,
        node_used=jnp.swapaxes(used_t, 1, 2),
    )


@observed
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def ffd_binpack_groups_affinity(
    pod_req: jax.Array,         # [P, R] shared pending-pod matrix
    pod_masks: jax.Array,       # [G, P] per-group schedulability (static mask)
    template_allocs: jax.Array,  # [G, R]
    max_nodes: int,
    match: jax.Array,           # [T, P] bool — term selector matches pod
    aff_of: jax.Array,          # [T, P] bool — pod requires affinity term
    anti_of: jax.Array,         # [T, P] bool — pod requires anti term
    node_level: jax.Array,      # [T] bool — hostname-level topology
    has_label: jax.Array,       # [G, T] bool — group template has topology label
    node_caps: jax.Array | None = None,  # [G] i32
    spread: tuple | None = None,  # SpreadTermTensors as an 11-array tuple
) -> BinpackResult:
    """FFD scan with *dynamic* inter-pod (anti-)affinity: pods placed during
    the scan constrain later pods, as the reference's per-placement filter
    re-run does (binpacking_estimator.go:119-141 → InterPodAffinity plugin).

    The carry adds per-term placement counts — `pm[G,T,M]` (pods matching
    term t on new node m) and `ha[G,T,M]` (pods *holding* anti-term t on m,
    for the symmetric anti-affinity rule) plus group totals — and each step
    gates candidate nodes on them. A hostname-level term's domain is the
    single node; any other key's domain is the whole group (all new nodes of
    a group share non-hostname topology labels — snapshot/affinity.py).

    Affinity-term satisfaction composes with the static mask: the mask
    handles terms vs pods already in the cluster (packer), this kernel
    handles terms vs scan-placed pods, including the Kubernetes self-match
    seeding rule (a pod matching its own required affinity term may open a
    fresh domain when no scan-placed pod matches the term yet).
    """
    P, R = pod_req.shape
    G = pod_masks.shape[0]
    T = match.shape[0]
    if node_caps is None:
        node_caps = jnp.full((G,), max_nodes, jnp.int32)
    caps = jnp.minimum(node_caps.astype(jnp.int32), max_nodes)

    scores = jax.vmap(lambda alloc: ffd_scores(pod_req, alloc))(template_allocs)  # [G, P]
    order = jnp.argsort(-scores, axis=1, stable=True)                 # [G, P]
    sorted_mask = jnp.take_along_axis(pod_masks, order, axis=1)       # [G, P]

    alloc_t = template_allocs[:, :, None]                             # [G, R, 1]
    node_ids = jnp.arange(max_nodes)
    garange = jnp.arange(G)
    match_t = match.T.astype(bool)                                    # [P, T]
    aff_t = aff_of.T.astype(bool)
    anti_t = anti_of.T.astype(bool)
    nl = node_level.astype(bool)                                      # [T]
    S = spread[2].shape[0] if spread is not None else 0  # node_level [S]

    def step(carry, xs):
        used_t, opened, pm, pm_tot, ha, ha_tot, spc, spc_tot = carry
        # used_t [G,R,M]; opened [G]; pm/ha [G,T,M] i32; *_tot [G,T] i32
        idx, active = xs                  # [G] i32, [G] bool
        req = pod_req[idx]                # [G, R]
        m_p = match_t[idx]                # [G, T]
        a_p = aff_t[idx]                  # [G, T]
        x_p = anti_t[idx]                 # [G, T]

        free_t = alloc_t - used_t
        fits_n = jnp.all(req[:, :, None] <= free_t, axis=1)           # [G, M]
        fits_n &= node_ids[None, :] < opened[:, None]

        # Per-term domain counts seen from node m: own node for hostname-level
        # terms, the whole group otherwise (_affinity_node_gates).
        gate_open, new_ok = _affinity_node_gates(
            m_p, a_p, x_p, pm, pm_tot, ha, ha_tot, nl, has_label
        )
        fits_n &= gate_open
        if spread is not None:
            sp_group_ok, sp_node_ok, sp_upd = _spread_gates(
                spread, spc, spc_tot, idx, opened, node_ids
            )
            fits_n &= sp_node_ok & sp_group_ok[:, None]
            new_ok &= sp_group_ok

        has_fit = fits_n.any(axis=1)
        first = jnp.argmax(fits_n, axis=1).astype(jnp.int32)
        fits_empty = jnp.all(req <= template_allocs, axis=1)
        can_open = (opened < caps) & fits_empty & new_ok

        place = active & (has_fit | can_open)
        target = jnp.where(has_fit, first, opened)                    # [G]
        onehot_b = (node_ids[None, :] == target[:, None]) & place[:, None]  # [G, M]
        onehot = onehot_b.astype(pod_req.dtype)
        used_t = used_t + req[:, :, None] * onehot[:, None, :]
        opened = opened + (place & ~has_fit).astype(jnp.int32)

        inc = onehot_b[:, None, :]                                    # [G,1,M]
        pm = pm + (m_p[:, :, None] & inc).astype(jnp.int32)
        ha = ha + (x_p[:, :, None] & inc).astype(jnp.int32)
        pm_tot = pm_tot + (m_p & place[:, None]).astype(jnp.int32)
        ha_tot = ha_tot + (x_p & place[:, None]).astype(jnp.int32)
        if spread is not None:
            spc = spc + (sp_upd[:, :, None] & inc).astype(jnp.int32)
            spc_tot = spc_tot + (sp_upd & place[:, None]).astype(jnp.int32)
        return (used_t, opened, pm, pm_tot, ha, ha_tot, spc, spc_tot), place

    init = (
        jnp.zeros((G, R, max_nodes), pod_req.dtype),
        jnp.zeros((G,), jnp.int32),
        jnp.zeros((G, T, max_nodes), jnp.int32),
        jnp.zeros((G, T), jnp.int32),
        jnp.zeros((G, T, max_nodes), jnp.int32),
        jnp.zeros((G, T), jnp.int32),
        *_spread_state_init(G, S, max_nodes),
    )
    (used_t, opened, *_), placed = jax.lax.scan(
        step, init, (order.T, sorted_mask.T)
    )                                                                 # placed [P, G]

    scheduled = (
        jnp.zeros((G, P), bool).at[garange[:, None], order].set(placed.T)
    )
    return BinpackResult(
        node_count=opened,
        scheduled=scheduled,
        node_used=jnp.swapaxes(used_t, 1, 2),
    )


# -- constraint attribution (decision provenance, autoscaler_tpu/explain) -----
#
# The fit reductions above compute per-constraint violation masks and then
# throw them away; these kernels keep them. Reason codes and their ordering
# come from explain/reasons.py — the ONE closed vocabulary the kernels, the
# serial oracle twin (estimator/reference_impl.attribute_unschedulable_
# reference) and the decision ledger share.


def _reason_codes_one(
    pod_req: jax.Array,   # [P, R]
    mask: jax.Array,      # [P] bool
    alloc: jax.Array,     # [R]
    scheduled: jax.Array,  # [P] bool
    involved: jax.Array,  # [P] bool — pod touches any affinity/spread term
) -> jax.Array:
    """[P] i32 — one group's reason per pod. Priority chain mirrors the
    reference's filter order (mask predicates → NodeResourcesFit per axis →
    dynamic affinity/spread → capacity): the FIRST violated constraint in
    that order is the recorded reason, built bottom-up with `where` so the
    highest-priority violation wins."""
    over = pod_req > alloc[None, :]                               # [P, R]
    R = pod_req.shape[1]
    base = jnp.where(
        involved,
        jnp.int32(REASON_AFFINITY_SPREAD),
        jnp.int32(REASON_NODE_CAP),
    )
    other_axes = [r for r in range(R) if r not in (CPU, MEMORY, PODS)]
    if other_axes:
        other_v = over[:, jnp.asarray(other_axes)].any(axis=1)
        base = jnp.where(other_v, REASON_RESOURCE, base)
    if R > PODS:
        base = jnp.where(over[:, PODS], REASON_POD_SLOT, base)
    base = jnp.where(over[:, MEMORY], REASON_MEMORY, base)
    base = jnp.where(over[:, CPU], REASON_CPU, base)
    base = jnp.where(~mask, REASON_TOPOLOGY, base)
    return jnp.where(scheduled, REASON_NONE, base).astype(jnp.int32)


@observed
@jax.jit
def attribute_unschedulable(
    pod_req: jax.Array,          # [P, R] shared pending-pod matrix
    pod_masks: jax.Array,        # [G, P] per-group schedulability
    template_allocs: jax.Array,  # [G, R]
    scheduled: jax.Array,        # [G, P] bool — the binpack verdict
    involved: jax.Array,         # [P] bool — pod touches any dynamic term
) -> jax.Array:
    """[G, P] i32 — machine-readable reason per (pod, node-group) pair the
    binpack left unschedulable, mirroring CA's PredicateError reasons: the
    vmap'd reduction over the violated-constraint mask the fit family
    otherwise discards. A pod the scan placed is REASON_NONE; an unplaced
    pod that passed the mask and fits an empty template was blocked either
    by the dynamic affinity/spread gates (when it holds any term) or by the
    group's node headroom. Pure function of its operands — identical on
    every ladder rung, byte-identical across replays."""
    return jax.vmap(
        lambda mask, alloc, sched: _reason_codes_one(
            pod_req, mask, alloc, sched, involved
        )
    )(pod_masks, template_allocs, scheduled)


@jax.jit
def attribution_summary(
    reasons: jax.Array,   # [G, P] i32 from attribute_unschedulable
    weights: jax.Array,   # [G, P] i32 — pods behind each slot (1, or the
                          # run's unplaced member count on the runs paths)
) -> tuple:
    """Device-side aggregation so the host never fetches the [G, P] reason
    matrix at 100k-pod scale: per-group reason histograms (weighted) and
    each pod's dominant reason — the MIN code across groups, i.e. the
    closest the pod came to scheduling anywhere (reasons.py orders codes by
    severity for exactly this reduction). The histogram is NUM_REASONS
    masked sums, never a [G, P, NUM_REASONS] one-hot (that intermediate is
    ~1.6GB at the north-star shape)."""
    hist = jnp.stack(
        [
            jnp.sum(
                jnp.where(reasons == code, weights, 0),
                axis=1, dtype=jnp.int32,
            )
            for code in range(NUM_REASONS)
        ],
        axis=1,
    )                                                             # [G, NR]
    dominant = jnp.min(reasons, axis=0).astype(jnp.int32)         # [P]
    return hist, dominant
