"""Priority-aware eviction packing — the `ffd_binpack_preempt` family.

Reference semantics: the CA itself never evicts for priority — preemption
lives in the scheduler (pkg/scheduler/framework/preemption) — but its
PriorityClass/preemptionPolicy model is the contract this kernel mirrors:
a pending pod whose preemptionPolicy is not Never may displace strictly-
lower-priority running pods when no node fits it outright, and victims are
chosen to minimize preemption cost. Here the whole pass is one lax.scan
over the pending pods against the EXISTING node set (not template nodes —
scale-up still owns capacity growth; this kernel answers "what could be
admitted onto the cluster as-is, and at what eviction cost").

Victim selection is a closed greedy spec shared bit-for-bit with the
serial numpy oracle (estimator/reference_impl.ffd_binpack_preempt_reference):
per candidate node, victims are taken in global (priority asc, pod row asc)
order until the pod fits — the minimal such prefix — and the node is chosen
by lexicographic (victim count, aggregate victim priority, node row). This
is the "fewest evictions, then lowest aggregate priority" cost order; like
the scheduler's own heuristic it approximates minimum-cost eviction (exact
minimality is a knapsack) but does so identically on every rung.

Each scan step materializes a [P, N, R] cumulative-free tensor, so the
pass is O(P²·N·R) — sized for control-loop worlds (the padded snapshot
buckets), not the 100k-pod fleet shapes; PREDICATES.md records the caveat.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from autoscaler_tpu.ops.binpack import ffd_scores
from autoscaler_tpu.ops.telemetry import observed

# "worse than any real cost" sentinel in the node-selection tie-break chain
_COST_INF = jnp.int32(2**30)

# Machine-readable kernel contracts (graftlint GL007, analysis/contracts.py).
# The P axis carries ALL pods — pending (pod_node < 0), resident, padding —
# so victim rows and evictor rows index one shared space; aggregate victim
# priority is summed in i32 (|priority|·P must stay under 2^31, true for
# any real PriorityClass world).
KERNEL_CONTRACTS = {
    "ffd_binpack_preempt": {
        "args": {
            "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
            "pod_valid": {"dims": ["P"], "dtype": "bool"},
            "pod_node": {"dims": ["P"], "dtype": "i32"},
            "pod_priority": {"dims": ["P"], "dtype": "i32"},
            "pod_can_preempt": {"dims": ["P"], "dtype": "bool"},
            "pod_evictable": {"dims": ["P"], "dtype": "bool"},
            "node_alloc": {"dims": ["N", "R"], "dtype": "f32"},
            "node_used": {"dims": ["N", "R"], "dtype": "f32"},
            "node_valid": {"dims": ["N"], "dtype": "bool"},
            "sched_mask": {"dims": ["P", "N"], "dtype": "bool"},
        },
        "notes": "O(P^2*N*R) scan; no Pallas twin (control-loop shapes only)",
    },
}


class PreemptResult(NamedTuple):
    scheduled: jax.Array    # [P] bool — pending pod admitted (direct or evicting)
    placed_node: jax.Array  # [P] i32 — node row it landed on, -1 otherwise
    victim_of: jax.Array    # [P] i32 — evictor's pod row, -1 = not evicted


@observed
@jax.jit
def ffd_binpack_preempt(
    pod_req: jax.Array,         # [P, R] — ALL pods (pending + resident)
    pod_valid: jax.Array,       # [P] bool
    pod_node: jax.Array,        # [P] i32 — resident's node row, -1 pending
    pod_priority: jax.Array,    # [P] i32
    pod_can_preempt: jax.Array,  # [P] bool — pending: policy != Never
    pod_evictable: jax.Array,    # [P] bool — resident: may be a victim
    node_alloc: jax.Array,      # [N, R] f32
    node_used: jax.Array,       # [N, R] f32 — includes residents' requests
    node_valid: jax.Array,      # [N] bool
    sched_mask: jax.Array,      # [P, N] bool — non-resource predicates
) -> PreemptResult:
    """Pack pending pods onto the existing nodes in (priority desc, FFD
    score desc, pod row asc) order; a pod that fits nowhere directly may
    evict strictly-lower-priority residents per the victim spec above.
    Pods admitted this pass occupy capacity but are never victims."""
    P = pod_req.shape[0]
    N = node_alloc.shape[0]

    # packing order: priority desc, then the ONE FFD score spec against the
    # elementwise-max valid allocatable row (heterogeneous nodes have no
    # single template; any fixed positive weights give a deterministic
    # order and max is exact in f32), then pod row asc (stable argsorts)
    cap_row = jnp.max(jnp.where(node_valid[:, None], node_alloc, 0.0), axis=0)
    score = ffd_scores(pod_req, cap_row)
    sorder = jnp.argsort(-score, stable=True)
    order = sorder[jnp.argsort(-pod_priority[sorder], stable=True)]
    # global victim order: priority asc, pod row asc
    vorder = jnp.argsort(pod_priority, stable=True)
    prio_sorted = pod_priority[vorder]
    req_sorted = pod_req[vorder]
    vnode_sorted = pod_node[vorder]
    evict_sorted = pod_evictable[vorder]
    node_ids = jnp.arange(N)
    positions = jnp.arange(P)

    def step(carry, i):
        used, alive, scheduled, placed, victim_of = carry
        req = pod_req[i]
        ok = sched_mask[i] & node_valid                             # [N]
        free = node_alloc - used                                    # [N, R]
        fits = ok & jnp.all(req[None, :] <= free, axis=1)           # [N]
        has_direct = fits.any()
        direct_n = jnp.argmax(fits)                                 # lowest row

        # victim candidacy in sorted space, restricted per node
        cand = alive[vorder] & evict_sorted & (prio_sorted < pod_priority[i])
        onnode = (vnode_sorted[:, None] == node_ids[None, :]) & cand[:, None]
        contrib = jnp.where(onnode[:, :, None], req_sorted[:, None, :], 0.0)
        cumfree = jnp.cumsum(contrib, axis=0)                       # [P, N, R]
        cap_ok = ok & jnp.all(req[None, :] <= node_alloc, axis=1)   # [N]
        fit_k = cap_ok[None, :] & jnp.all(
            req[None, None, :] <= free[None, :, :] + cumfree, axis=2
        )                                                           # [P, N]
        feasible = fit_k.any(axis=0)                                # [N]
        k_min = jnp.argmax(fit_k, axis=0)                           # [N]
        vict = onnode & (positions[:, None] <= k_min[None, :])      # [P, N]
        nvict = vict.sum(axis=0).astype(jnp.int32)                  # [N]
        aggprio = jnp.sum(
            jnp.where(vict, prio_sorted[:, None], 0), axis=0
        ).astype(jnp.int32)                                         # [N]
        # lexicographic (victim count, aggregate priority, node row) argmin
        key1 = jnp.where(feasible, nvict, _COST_INF)
        t2 = feasible & (nvict == key1.min())
        key2 = jnp.where(t2, aggprio, _COST_INF)
        t3 = t2 & (aggprio == key2.min())
        best_n = jnp.argmax(t3).astype(jnp.int32)

        is_pend = pod_valid[i] & (pod_node[i] < 0)
        do_direct = is_pend & has_direct
        do_preempt = (
            is_pend & ~has_direct & pod_can_preempt[i] & feasible.any()
        )
        place = do_direct | do_preempt
        target = jnp.where(do_direct, direct_n, best_n).astype(jnp.int32)
        vict_orig = (
            jnp.zeros((P,), bool).at[vorder].set(vict[:, best_n]) & do_preempt
        )
        freed = jnp.sum(jnp.where(vict_orig[:, None], pod_req, 0.0), axis=0)
        delta = jnp.where(place, req, 0.0) - jnp.where(do_preempt, freed, 0.0)
        used = used.at[target].add(delta)
        alive = alive & ~vict_orig
        victim_of = jnp.where(vict_orig, i.astype(jnp.int32), victim_of)
        scheduled = scheduled.at[i].set(place)
        placed = placed.at[i].set(jnp.where(place, target, jnp.int32(-1)))
        return (used, alive, scheduled, placed, victim_of), None

    init = (
        node_used,
        pod_valid & (pod_node >= 0),       # residents alive at entry
        jnp.zeros((P,), bool),
        jnp.full((P,), -1, jnp.int32),
        jnp.full((P,), -1, jnp.int32),
    )
    (_, _, scheduled, placed, victim_of), _ = jax.lax.scan(step, init, order)
    return PreemptResult(
        scheduled=scheduled, placed_node=placed, victim_of=victim_of
    )
