"""Scale-down device kernels: empty-node detection and batched
node-removal (drain) feasibility — the masked refit over the fit tensor.

Reference: cluster-autoscaler/simulator/cluster.go — FindNodesToRemove :116,
SimulateNodeRemoval :145 (GetPodsToMove → fork → findPlaceFor :220), and
FindEmptyNodesToRemove :187. The reference simulates one candidate at a time
on a forked snapshot; here every candidate's refit runs as an independent
vmap lane: lane j masks node j out of the fit tensor and greedily re-places
j's movable pods onto the remaining capacity (a short scan over the node's
pod slots). Independence across lanes matches the *categorization* semantics
(planner.go:252 categorizeNodes evaluates each candidate against the same
base state plus previously-moved pods; the final deletion set is re-validated
sequentially host-side, as NodesToDelete does).

BASELINE config #4: reschedule-feasibility over 5k nodes.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from autoscaler_tpu.kube.objects import PODS
from autoscaler_tpu.ops.schedule import spread_commit, spread_gate
from autoscaler_tpu.snapshot.tensors import SnapshotTensors


def empty_nodes(snap: SnapshotTensors, movable: jax.Array) -> jax.Array:
    """[N] bool — nodes whose only pods are unmovable-but-ignorable
    (daemonset/mirror), i.e. removable without any rescheduling
    (reference FindEmptyNodesToRemove, cluster.go:187). `movable` is the
    host-computed [P] drain-rules verdict: True = pod must be re-placed."""
    # Segment-sum over pod→node assignment: O(P), vs the [P, N] one-hot
    # matmul this replaced (~6GB of HBM at 100k pods × 15k nodes).
    w = (movable & snap.pod_valid & (snap.pod_node >= 0)).astype(jnp.float32)
    seg = jnp.clip(snap.pod_node, 0, snap.num_nodes - 1)
    movable_count = jax.ops.segment_sum(w, seg, num_segments=snap.num_nodes)
    return snap.node_valid & (movable_count == 0)


class RemovalFeasibility(NamedTuple):
    feasible: jax.Array      # [C] bool — all movable pods of the candidate re-place
    destinations: jax.Array  # [C, S] i32 — target node per pod slot, -1 if none
    moved_counts: jax.Array  # [C] i32 — pods that found a new home


BIG_I32 = jnp.int32(2**30)


def _place_pod_step(snap: SnapshotTensors, excluded: jax.Array, spread=None):
    """Shared greedy-placement scan step: place one movable pod onto the
    first allowed node (capacity + static mask + validity − excluded),
    updating the free-capacity carry. Used by both the per-candidate and the
    joint feasibility kernels so their placement semantics cannot drift.

    `spread` (affinity.build_spread_schedule_context minus static counts —
    the counts travel in the carry, per-candidate adjusted) makes hard
    topology-spread re-count per re-placement, the reference's findPlaceFor
    → TrySchedulePods behavior (cluster.go:220): moved pods leave the
    drained node's domain (the caller subtracts their static contribution)
    and raise their destination's counts for later moved pods. The carry is
    (free [N, R], counts [S, D])."""
    def step(carry, pod_idx):
        free, counts = carry
        valid_pod = pod_idx >= 0
        safe_idx = jnp.maximum(pod_idx, 0)
        req = snap.pod_req[safe_idx]
        ok = (
            jnp.all(req[None, :] <= free, axis=-1)
            & snap.sched_row(safe_idx)
            & snap.node_valid
            & ~excluded
        )
        if spread is not None:
            node_ok, m = spread_gate(spread, counts, safe_idx)
            ok &= node_ok
        has = ok.any()
        dest = jnp.where(has, jnp.argmax(ok).astype(jnp.int32), -1)
        place = valid_pod & has
        target = jnp.maximum(dest, 0)
        free = free.at[target].add(jnp.where(place, -req, jnp.zeros_like(req)))
        if spread is not None:
            counts = spread_commit(spread, counts, m, place, target)
        placed_needed = jnp.where(valid_pod, place, True)
        return (free, counts), (jnp.where(valid_pod, dest, -1), placed_needed, place)

    return step


@functools.partial(jax.jit, static_argnames=())
def removal_feasibility(
    snap: SnapshotTensors,
    candidate_nodes: jax.Array,   # [C] i32 node indices to evaluate
    pod_slots: jax.Array,         # [C, S] i32 pod indices on each candidate (-1 pad),
                                  #   already filtered to movable pods by drain rules
    blocked: jax.Array,           # [C] bool — drain rules forbid removal outright
) -> RemovalFeasibility:
    """Batched single-node removal refit. Each lane answers: if node j were
    drained, could each of its movable pods be placed on some other node
    (respecting current free capacity and the precomputed predicate mask),
    greedily in slot order with capacity updates between placements — the
    findPlaceFor semantics (cluster.go:220)."""
    return _removal_feasibility_impl(
        snap, candidate_nodes, pod_slots, blocked, None, None, None
    )


@functools.partial(jax.jit, static_argnames=())
def removal_feasibility_spread(
    snap: SnapshotTensors,
    candidate_nodes: jax.Array,
    pod_slots: jax.Array,
    blocked: jax.Array,
    spread: tuple,          # 8-array context (no static counts)
    static_counts: jax.Array,  # [S, D] live counts over ALL placed pods
    cand_sub: jax.Array,       # [C, S] candidate's movable matching pods
) -> RemovalFeasibility:
    """removal_feasibility with within-refit topology-spread re-counting:
    each lane starts from the live counts minus the candidate's own movable
    matching pods (the reference removes them from the forked snapshot
    before findPlaceFor) and carries placements' deltas."""
    return _removal_feasibility_impl(
        snap, candidate_nodes, pod_slots, blocked, spread, static_counts,
        cand_sub,
    )


def _removal_feasibility_impl(
    snap, candidate_nodes, pod_slots, blocked, spread, static_counts, cand_sub
):
    free0 = snap.free()  # [N, R]
    if spread is not None:
        node_dom, sp_elig, dom_valid = spread[2], spread[3], spread[4]

    def lane(j, slots, lane_blocked, sub):
        exclude = jnp.arange(snap.num_nodes) == j
        # The drained node's capacity is not a destination: zero its free row.
        free_start = jnp.where(exclude[:, None], 0.0, free0)
        if spread is not None:
            # counts minus the candidate's movable matching pods, at the
            # candidate's domain (only where it was eligible to count)
            dom_j = node_dom[:, j]                           # [S]
            gate = (dom_j >= 0) & sp_elig[:, j]
            counts0 = static_counts.at[
                jnp.arange(static_counts.shape[0]), jnp.maximum(dom_j, 0)
            ].add(-jnp.where(gate, sub, 0))
        else:
            counts0 = jnp.zeros((1, 1), jnp.int32)
        (_, _), (dests, placed_ok, placed) = jax.lax.scan(
            _place_pod_step(snap, exclude, spread), (free_start, counts0), slots
        )
        feasible = placed_ok.all() & ~lane_blocked
        return feasible, dests, placed.sum().astype(jnp.int32)

    if cand_sub is None:
        cand_sub = jnp.zeros((candidate_nodes.shape[0],), jnp.int32)
    return RemovalFeasibility(
        *jax.vmap(lane)(candidate_nodes, pod_slots, blocked, cand_sub)
    )


@functools.partial(jax.jit, static_argnames=())
def joint_removal_feasibility(
    snap: SnapshotTensors,
    candidate_nodes: jax.Array,   # [C] i32 node indices, in planner pick order
    pod_slots: jax.Array,         # [C, S] i32 movable-pod indices (-1 pad)
    excluded: jax.Array,          # [N] bool — every node leaving the cluster
                                  #   in this plan (all drains + empty deletes)
) -> RemovalFeasibility:
    """Sequential re-validation of a *set* of removals before actuation.

    removal_feasibility answers each candidate independently against the same
    base state — the reference's categorizeNodes semantics (planner.go:252).
    But the picked deletion set acts jointly: two drained nodes cannot both
    re-place pods into the same free capacity, and nothing may re-place onto
    a node that is itself being deleted (the reference re-simulates the set
    under a fresh snapshot inside NodesToDelete/actuation, actuator.go:371).
    Here candidates are scanned in pick order with a shared free-capacity
    carry; a candidate that no longer fits is reported infeasible and its
    trial placements are rolled back (later candidates see the state as if
    it stayed)."""
    return _joint_impl(snap, candidate_nodes, pod_slots, excluded, None, None, None)


@functools.partial(jax.jit, static_argnames=())
def joint_removal_feasibility_spread(
    snap: SnapshotTensors,
    candidate_nodes: jax.Array,
    pod_slots: jax.Array,
    excluded: jax.Array,
    spread: tuple,
    static_counts: jax.Array,  # [S, D]
    cand_sub: jax.Array,       # [C, S]
) -> RemovalFeasibility:
    """joint_removal_feasibility with within-plan spread re-counting: the
    counts carry is SHARED across candidates in pick order (as the
    reference's sequential set re-simulation is), each candidate first
    dropping its own movable matching pods from its domain; infeasible
    candidates roll back both capacity and counts."""
    return _joint_impl(
        snap, candidate_nodes, pod_slots, excluded, spread, static_counts,
        cand_sub,
    )


def _joint_impl(snap, candidate_nodes, pod_slots, excluded, spread,
                static_counts, cand_sub):
    free0 = snap.free()  # [N, R]
    if spread is not None:
        node_dom, sp_elig = spread[2], spread[3]

    def cand_step(carry, xs):
        free, counts = carry
        slots, j, sub = xs
        if spread is not None:
            dom_j = node_dom[:, j]
            gate = (dom_j >= 0) & sp_elig[:, j]
            counts_in = counts.at[
                jnp.arange(counts.shape[0]), jnp.maximum(dom_j, 0)
            ].add(-jnp.where(gate, sub, 0))
        else:
            counts_in = counts
        (trial_free, trial_counts), (dests, placed_ok, placed) = jax.lax.scan(
            _place_pod_step(snap, excluded, spread), (free, counts_in), slots
        )
        feasible = placed_ok.all()
        # commit this candidate's placements only if the whole node drains
        free = jnp.where(feasible, trial_free, free)
        counts = jnp.where(feasible, trial_counts, counts)
        moved = jnp.where(feasible, placed.sum(), 0).astype(jnp.int32)
        return (free, counts), (feasible, jnp.where(feasible, dests, -1), moved)

    # zero the free rows of every to-be-deleted node so nothing lands there;
    # each candidate's own row is already in `excluded`, set by the caller
    free_start = jnp.where(excluded[:, None], 0.0, free0)
    if spread is not None:
        counts_start = static_counts
        sub_xs = cand_sub
    else:
        counts_start = jnp.zeros((1, 1), jnp.int32)
        sub_xs = jnp.zeros((pod_slots.shape[0],), jnp.int32)
    (_, _), (feasible, dests, moved) = jax.lax.scan(
        cand_step, (free_start, counts_start),
        (pod_slots, candidate_nodes, sub_xs),
    )
    return RemovalFeasibility(
        feasible=feasible, destinations=dests, moved_counts=moved
    )
