"""Scale-down device kernels: empty-node detection and batched
node-removal (drain) feasibility — the masked refit over the fit tensor.

Reference: cluster-autoscaler/simulator/cluster.go — FindNodesToRemove :116,
SimulateNodeRemoval :145 (GetPodsToMove → fork → findPlaceFor :220), and
FindEmptyNodesToRemove :187. The reference simulates one candidate at a time
on a forked snapshot; here every candidate's refit runs as an independent
vmap lane: lane j masks node j out of the fit tensor and greedily re-places
j's movable pods onto the remaining capacity (a short scan over the node's
pod slots). Independence across lanes matches the *categorization* semantics
(planner.go:252 categorizeNodes evaluates each candidate against the same
base state plus previously-moved pods; the final deletion set is re-validated
sequentially host-side, as NodesToDelete does).

BASELINE config #4: reschedule-feasibility over 5k nodes.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from autoscaler_tpu.kube.objects import PODS
from autoscaler_tpu.snapshot.tensors import SnapshotTensors


def empty_nodes(snap: SnapshotTensors, movable: jax.Array) -> jax.Array:
    """[N] bool — nodes whose only pods are unmovable-but-ignorable
    (daemonset/mirror), i.e. removable without any rescheduling
    (reference FindEmptyNodesToRemove, cluster.go:187). `movable` is the
    host-computed [P] drain-rules verdict: True = pod must be re-placed."""
    # Segment-sum over pod→node assignment: O(P), vs the [P, N] one-hot
    # matmul this replaced (~6GB of HBM at 100k pods × 15k nodes).
    w = (movable & snap.pod_valid & (snap.pod_node >= 0)).astype(jnp.float32)
    seg = jnp.clip(snap.pod_node, 0, snap.num_nodes - 1)
    movable_count = jax.ops.segment_sum(w, seg, num_segments=snap.num_nodes)
    return snap.node_valid & (movable_count == 0)


class RemovalFeasibility(NamedTuple):
    feasible: jax.Array      # [C] bool — all movable pods of the candidate re-place
    destinations: jax.Array  # [C, S] i32 — target node per pod slot, -1 if none
    moved_counts: jax.Array  # [C] i32 — pods that found a new home


def _place_pod_step(snap: SnapshotTensors, excluded: jax.Array):
    """Shared greedy-placement scan step: place one movable pod onto the
    first allowed node (capacity + static mask + validity − excluded),
    updating the free-capacity carry. Used by both the per-candidate and the
    joint feasibility kernels so their placement semantics cannot drift."""

    def step(free, pod_idx):
        valid_pod = pod_idx >= 0
        safe_idx = jnp.maximum(pod_idx, 0)
        req = snap.pod_req[safe_idx]
        ok = (
            jnp.all(req[None, :] <= free, axis=-1)
            & snap.sched_row(safe_idx)
            & snap.node_valid
            & ~excluded
        )
        has = ok.any()
        dest = jnp.where(has, jnp.argmax(ok).astype(jnp.int32), -1)
        place = valid_pod & has
        target = jnp.maximum(dest, 0)
        free = free.at[target].add(jnp.where(place, -req, jnp.zeros_like(req)))
        placed_needed = jnp.where(valid_pod, place, True)
        return free, (jnp.where(valid_pod, dest, -1), placed_needed, place)

    return step


@functools.partial(jax.jit, static_argnames=())
def removal_feasibility(
    snap: SnapshotTensors,
    candidate_nodes: jax.Array,   # [C] i32 node indices to evaluate
    pod_slots: jax.Array,         # [C, S] i32 pod indices on each candidate (-1 pad),
                                  #   already filtered to movable pods by drain rules
    blocked: jax.Array,           # [C] bool — drain rules forbid removal outright
) -> RemovalFeasibility:
    """Batched single-node removal refit. Each lane answers: if node j were
    drained, could each of its movable pods be placed on some other node
    (respecting current free capacity and the precomputed predicate mask),
    greedily in slot order with capacity updates between placements — the
    findPlaceFor semantics (cluster.go:220)."""
    free0 = snap.free()  # [N, R]

    def lane(j, slots, lane_blocked):
        exclude = jnp.arange(snap.num_nodes) == j
        # The drained node's capacity is not a destination: zero its free row.
        free_start = jnp.where(exclude[:, None], 0.0, free0)
        _, (dests, placed_ok, placed) = jax.lax.scan(
            _place_pod_step(snap, exclude), free_start, slots
        )
        feasible = placed_ok.all() & ~lane_blocked
        return feasible, dests, placed.sum().astype(jnp.int32)

    return RemovalFeasibility(*jax.vmap(lane)(candidate_nodes, pod_slots, blocked))


@functools.partial(jax.jit, static_argnames=())
def joint_removal_feasibility(
    snap: SnapshotTensors,
    candidate_nodes: jax.Array,   # [C] i32 node indices, in planner pick order
    pod_slots: jax.Array,         # [C, S] i32 movable-pod indices (-1 pad)
    excluded: jax.Array,          # [N] bool — every node leaving the cluster
                                  #   in this plan (all drains + empty deletes)
) -> RemovalFeasibility:
    """Sequential re-validation of a *set* of removals before actuation.

    removal_feasibility answers each candidate independently against the same
    base state — the reference's categorizeNodes semantics (planner.go:252).
    But the picked deletion set acts jointly: two drained nodes cannot both
    re-place pods into the same free capacity, and nothing may re-place onto
    a node that is itself being deleted (the reference re-simulates the set
    under a fresh snapshot inside NodesToDelete/actuation, actuator.go:371).
    Here candidates are scanned in pick order with a shared free-capacity
    carry; a candidate that no longer fits is reported infeasible and its
    trial placements are rolled back (later candidates see the state as if
    it stayed)."""
    free0 = snap.free()  # [N, R]

    def cand_step(free, slots):
        trial_free, (dests, placed_ok, placed) = jax.lax.scan(
            _place_pod_step(snap, excluded), free, slots
        )
        feasible = placed_ok.all()
        # commit this candidate's placements only if the whole node drains
        free = jnp.where(feasible, trial_free, free)
        moved = jnp.where(feasible, placed.sum(), 0).astype(jnp.int32)
        return free, (feasible, jnp.where(feasible, dests, -1), moved)

    # zero the free rows of every to-be-deleted node so nothing lands there;
    # candidate_nodes fixes the row order of pod_slots (each candidate's own
    # row is already in `excluded`, set by the caller)
    del candidate_nodes
    free_start = jnp.where(excluded[:, None], 0.0, free0)
    _, (feasible, dests, moved) = jax.lax.scan(cand_step, free_start, pod_slots)
    return RemovalFeasibility(
        feasible=feasible, destinations=dests, moved_counts=moved
    )
