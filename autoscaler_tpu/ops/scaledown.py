"""Scale-down device kernels: empty-node detection and batched
node-removal (drain) feasibility — the masked refit over the fit tensor.

Reference: cluster-autoscaler/simulator/cluster.go — FindNodesToRemove :116,
SimulateNodeRemoval :145 (GetPodsToMove → fork → findPlaceFor :220), and
FindEmptyNodesToRemove :187. The reference simulates one candidate at a time
on a forked snapshot; here every candidate's refit runs as an independent
vmap lane: lane j masks node j out of the fit tensor and greedily re-places
j's movable pods onto the remaining capacity (a short scan over the node's
pod slots). Independence across lanes matches the *categorization* semantics
(planner.go:252 categorizeNodes evaluates each candidate against the same
base state plus previously-moved pods; the final deletion set is re-validated
sequentially host-side, as NodesToDelete does).

BASELINE config #4: reschedule-feasibility over 5k nodes.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from autoscaler_tpu.kube.objects import PODS
from autoscaler_tpu.snapshot.tensors import SnapshotTensors


def empty_nodes(snap: SnapshotTensors, movable: jax.Array) -> jax.Array:
    """[N] bool — nodes whose only pods are unmovable-but-ignorable
    (daemonset/mirror), i.e. removable without any rescheduling
    (reference FindEmptyNodesToRemove, cluster.go:187). `movable` is the
    host-computed [P] drain-rules verdict: True = pod must be re-placed."""
    pod_on_node = jax.nn.one_hot(
        snap.pod_node, snap.num_nodes, dtype=jnp.float32
    )  # [P, N]; pod_node=-1 rows are all-zero
    movable_count = jnp.einsum(
        "pn,p->n", pod_on_node, (movable & snap.pod_valid).astype(jnp.float32)
    )
    return snap.node_valid & (movable_count == 0)


class RemovalFeasibility(NamedTuple):
    feasible: jax.Array      # [C] bool — all movable pods of the candidate re-place
    destinations: jax.Array  # [C, S] i32 — target node per pod slot, -1 if none
    moved_counts: jax.Array  # [C] i32 — pods that found a new home


@functools.partial(jax.jit, static_argnames=())
def removal_feasibility(
    snap: SnapshotTensors,
    candidate_nodes: jax.Array,   # [C] i32 node indices to evaluate
    pod_slots: jax.Array,         # [C, S] i32 pod indices on each candidate (-1 pad),
                                  #   already filtered to movable pods by drain rules
    blocked: jax.Array,           # [C] bool — drain rules forbid removal outright
) -> RemovalFeasibility:
    """Batched single-node removal refit. Each lane answers: if node j were
    drained, could each of its movable pods be placed on some other node
    (respecting current free capacity and the precomputed predicate mask),
    greedily in slot order with capacity updates between placements — the
    findPlaceFor semantics (cluster.go:220)."""
    free0 = snap.free()  # [N, R]

    def lane(j, slots, lane_blocked):
        exclude = jnp.arange(snap.num_nodes) == j

        def step(carry, pod_idx):
            free = carry
            valid_pod = pod_idx >= 0
            safe_idx = jnp.maximum(pod_idx, 0)
            req = snap.pod_req[safe_idx]
            ok = (
                jnp.all(req[None, :] <= free, axis=-1)
                & snap.sched_mask[safe_idx]
                & snap.node_valid
                & ~exclude
            )
            has = ok.any()
            dest = jnp.where(has, jnp.argmax(ok).astype(jnp.int32), -1)
            place = valid_pod & has
            target = jnp.maximum(dest, 0)
            free = free.at[target].add(
                jnp.where(place, -req, jnp.zeros_like(req))
            )
            placed_needed = jnp.where(valid_pod, place, True)
            return free, (jnp.where(valid_pod, dest, -1), placed_needed, place)

        # The drained node's capacity is not a destination: zero its free row.
        free_start = jnp.where(exclude[:, None], 0.0, free0)
        _, (dests, placed_ok, placed) = jax.lax.scan(step, free_start, slots)
        feasible = placed_ok.all() & ~lane_blocked
        return feasible, dests, placed.sum().astype(jnp.int32)

    return RemovalFeasibility(*jax.vmap(lane)(candidate_nodes, pod_slots, blocked))
