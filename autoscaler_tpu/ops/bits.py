"""Device-side boolean bit-packing for cheap host fetches.

The north-star estimator's `scheduled` output is a [G, P] bool — 50MB at
100k pods × 500 groups. Fetched raw over the axon tunnel it costs ~1.2s,
an order of magnitude more than the node_count fetch; packed 8:1 on device
it rides home in ~150ms and unpacks host-side with np.unpackbits at memory
speed. Layout matches np.unpackbits' default big-endian bit order so the
host side is a single library call.

TPU-design note: this is the "minimize host↔device transfers" rule applied
to the decision path — the control plane consumes booleans, so ship bits,
not bytes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_WEIGHTS = np.array([128, 64, 32, 16, 8, 4, 2, 1], np.int32)  # MSB-first


@jax.jit
def pack_bool_bits(x: jax.Array) -> jax.Array:
    """[..., P] bool → [..., ceil(P/8)] uint8 (np.unpackbits-compatible)."""
    P = x.shape[-1]
    pad = (-P) % 8
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    grouped = x.reshape(*x.shape[:-1], (P + pad) // 8, 8).astype(jnp.int32)
    return jnp.tensordot(grouped, jnp.asarray(_WEIGHTS), axes=1).astype(jnp.uint8)


def unpack_bool_bits(packed: np.ndarray, length: int) -> np.ndarray:
    """Host-side inverse: [..., B] uint8 → [..., length] bool."""
    flat = np.unpackbits(np.ascontiguousarray(packed), axis=-1)
    return flat[..., :length].astype(bool)
