"""Device-side boolean bit-packing for cheap host fetches.

The north-star estimator's `scheduled` output is a [G, P] bool — 50MB at
100k pods × 500 groups. Fetched raw over the axon tunnel it costs ~1.2s,
an order of magnitude more than the node_count fetch; packed 8:1 on device
it rides home in ~150ms and unpacks host-side with np.unpackbits at memory
speed. Layout matches np.unpackbits' default big-endian bit order so the
host side is a single library call.

TPU-design note: this is the "minimize host↔device transfers" rule applied
to the decision path — the control plane consumes booleans, so ship bits,
not bytes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_WEIGHTS = np.array([128, 64, 32, 16, 8, 4, 2, 1], np.int32)  # MSB-first


@jax.jit
def pack_bool_bits(x: jax.Array) -> jax.Array:
    """[..., P] bool → [..., ceil(P/8)] uint8 (np.unpackbits-compatible)."""
    P = x.shape[-1]
    pad = (-P) % 8
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    grouped = x.reshape(*x.shape[:-1], (P + pad) // 8, 8).astype(jnp.int32)
    return jnp.tensordot(grouped, jnp.asarray(_WEIGHTS), axes=1).astype(jnp.uint8)


def unpack_bool_bits(packed: np.ndarray, length: int) -> np.ndarray:
    """Host-side inverse: [..., B] uint8 → [..., length] bool."""
    flat = np.unpackbits(np.ascontiguousarray(packed), axis=-1)
    return flat[..., :length].astype(bool)


_count_byte_order_ok = False


def _check_count_byte_order() -> None:
    """One-per-process runtime proof that bitcast_convert_type(int32→uint8)
    yields little-endian bytes on the ACTIVE backend, so unpack_result_blob's
    '<i4' view is sound. The byte order of bitcast is backend-defined; the
    contract test only covers CPU, so a sentinel round-trip guards the real
    device path (advisor r4)."""
    global _count_byte_order_ok
    if _count_byte_order_ok:
        return
    sentinel = jax.lax.bitcast_convert_type(
        jnp.asarray([0x01020304], jnp.int32), jnp.uint8
    )
    got = list(np.asarray(sentinel)[0])
    if got != [0x04, 0x03, 0x02, 0x01]:
        raise AssertionError(
            "bitcast_convert_type(int32->uint8) is not little-endian on "
            f"backend {jax.default_backend()!r} (sentinel bytes {got}); "
            "unpack_result_blob's '<i4' decode would corrupt counts"
        )
    _count_byte_order_ok = True


@jax.jit
def _pack_result_blob_impl(node_count: jax.Array, scheduled: jax.Array) -> jax.Array:
    cnt_bytes = jax.lax.bitcast_convert_type(
        node_count.astype(jnp.int32), jnp.uint8
    )                                                    # [G, 4] LE (checked)
    packed = pack_bool_bits(scheduled)                   # [G, B] u8
    return jnp.concatenate([cnt_bytes.ravel(), packed.ravel()])


def pack_result_blob(node_count: jax.Array, scheduled: jax.Array) -> jax.Array:
    """Fuse an estimator result (counts [G] i32 + scheduled [G, P] bool) into
    ONE flat uint8 buffer: [G*4 little-endian count bytes][G*ceil(P/8)
    packed bits]. One buffer = one host fetch = one tunnel round-trip — a
    separate counts fetch costs a full RTT (~50-150ms over a remoted
    backend), comparable to shipping the whole bit plane.

    The first call per process proves the backend's bitcast byte order with
    a sentinel (raises if not LE) — see _check_count_byte_order."""
    _check_count_byte_order()
    return _pack_result_blob_impl(node_count, scheduled)


def unpack_result_blob(buf: np.ndarray, G: int, P: int):
    """Host-side inverse of pack_result_blob → (counts [G] i32 int array,
    scheduled [G, P] bool)."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    counts = buf[: 4 * G].view("<i4").copy()
    B = (P + 7) // 8
    bits = unpack_bool_bits(buf[4 * G : 4 * G + G * B].reshape(G, B), P)
    return counts, bits
