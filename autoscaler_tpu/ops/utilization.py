"""Node utilization — vectorized over the whole cluster in one op.

Reference: cluster-autoscaler/simulator/utilization/info.go:35,49,83 —
utilization of a node is max(cpu, mem) of (requested / allocatable), except
GPU nodes where the GPU fraction alone decides (GPU-dominant rule); DaemonSet
and mirror pods can be excluded from the numerator via config. The reference
computes this per node inside the eligibility loop; here it is one [N]
reduction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from autoscaler_tpu.kube.objects import CPU, GPU, MEMORY
from autoscaler_tpu.snapshot.tensors import SnapshotTensors


def node_utilization(
    snap: SnapshotTensors,
    exclude_used: jax.Array | None = None,  # [N, R] usage to subtract (daemonset/mirror)
) -> jax.Array:
    """[N] f32 — per-node utilization under the reference's dominant-resource
    rule. Padding rows are 0."""
    used = snap.node_used if exclude_used is None else snap.node_used - exclude_used
    alloc = snap.node_alloc

    def frac(axis):
        return jnp.where(alloc[:, axis] > 0, used[:, axis] / alloc[:, axis], 0.0)

    cpu_mem = jnp.maximum(frac(CPU), frac(MEMORY))
    gpu_util = frac(GPU)
    is_gpu_node = alloc[:, GPU] > 0
    util = jnp.where(is_gpu_node, gpu_util, cpu_mem)
    return jnp.where(snap.node_valid, util, 0.0)


node_utilization_jit = jax.jit(node_utilization)
