"""Kernel dispatch-boundary observation seam.

The perf observatory (autoscaler_tpu/perf) needs the concrete call —
kernel function, arrays, statics — of every device dispatch to derive
shape signatures, operand footprints, and the XLA cost model. The
estimator must NOT rewrite its kernel call sites to thread that through:
graftlint GL007 proves kernel contracts at every *syntactic* dispatch
site, so ``ffd_binpack_groups(...)`` has to stay a direct call.

Instead, each ``ops/`` kernel entry is wrapped with :func:`observed`
(outside the jit boundary — the wrapper is host Python, never traced),
and the estimator installs an ambient observer around each ladder rung
via :func:`kernel_observer`. The observer is a contextvar, not a module
global: concurrently running autoscalers (the loadgen driver inside a
test process, an rpc sidecar thread) each see only their own
observatory, and the seam is free when nobody is observing.

Dependency-free: stdlib only.
"""
from __future__ import annotations

import contextvars
import functools
from contextlib import contextmanager
from typing import Any, Callable, Iterator, Optional

# the ambient observer for THIS context: called (fn, args, kwargs) just
# before every observed kernel entry runs; fn is the outermost compiled
# callable (jit-wrapped entries expose .lower for AOT cost capture)
_OBSERVER: contextvars.ContextVar[
    Optional[Callable[[Any, tuple, dict], None]]
] = contextvars.ContextVar("autoscaler_tpu_kernel_observer", default=None)


@contextmanager
def kernel_observer(
    observer: Optional[Callable[[Any, tuple, dict], None]],
) -> Iterator[None]:
    """Install ``observer`` as the ambient kernel observer for the dynamic
    extent of the block (None = explicitly nothing, shadowing any outer
    observer). The estimator wraps each ladder-rung dispatch in this."""
    token = _OBSERVER.set(observer)
    try:
        yield
    finally:
        _OBSERVER.reset(token)


def observed(fn: Any) -> Any:
    """Wrap a kernel entry point so the ambient observer (when installed)
    sees every call's (fn, args, kwargs) before dispatch. The wrapper runs
    on the host outside any jit trace; with no observer installed it costs
    one contextvar read. The wrapped entry is exposed as ``__wrapped__``
    (functools.wraps), so AOT surfaces like ``.lower`` remain reachable on
    ``fn`` itself — the observer receives the *compiled* callable."""

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        observer = _OBSERVER.get()
        if observer is not None:
            observer(fn, args, kwargs)
        return fn(*args, **kwargs)

    return wrapper
