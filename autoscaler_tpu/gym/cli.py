"""gym CLI.

    python -m autoscaler_tpu.gym tune benchmarks/scenarios/gym_suite.json \\
        --generations 4 --population 8 --ledger tune.jsonl
    python -m autoscaler_tpu.gym replay benchmarks/scenarios/gym_suite.json \\
        --ledger tune.jsonl
    python -m autoscaler_tpu.gym apply tune.jsonl
    python -m autoscaler_tpu.gym validate tune.jsonl

``tune`` runs the population tuner over a suite and prints one summary
JSON object (winner policy, score trajectory, improvement over the
all-defaults baseline); ``--ledger`` writes the byte-stable tuning ledger
(one sorted-key JSON line per generation — two runs of the same tune are
byte-identical). ``replay`` re-runs a tune with the config recorded in an
existing ledger and byte-compares — exit 1 on any divergence (the
determinism gate). ``apply`` renders a ledger's winning PolicySpec as a
production flags snippet, a ``loadgen run --set`` snippet, and a
deploy/chart values.yaml fragment. ``validate`` checks a ledger's schema
and the improvement invariant without re-running anything.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.gym import ledger as gym_ledger
from autoscaler_tpu.gym.policy import PolicyError, PolicySpec
from autoscaler_tpu.loadgen.score import ObjectiveWeights
from autoscaler_tpu.loadgen.suite import SuiteSpec
from autoscaler_tpu.loadgen.spec import SpecError


def build_arg_parser() -> argparse.ArgumentParser:
    defaults = AutoscalingOptions()
    p = argparse.ArgumentParser(
        prog="python -m autoscaler_tpu.gym", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = p.add_subparsers(dest="command", required=True)

    tune = sub.add_parser("tune", help="tune policies over a scenario suite")
    tune.add_argument("suite", help="path to a suite JSON "
                      "(benchmarks/scenarios/gym_suite.json)")
    tune.add_argument("--generations", type=int, default=4)
    tune.add_argument("--population", type=int, default=8,
                      help="candidates sampled per generation (the "
                           "all-defaults control rides along in gen 0)")
    tune.add_argument("--seed", type=int, default=0,
                      help="tune seed: drives ALL candidate sampling "
                           "(scenario seeds come from the suite)")
    tune.add_argument("--ledger", default="",
                      help="write the tuning ledger here (JSONL, one "
                           "generation per line; byte-identical across "
                           "runs of the same tune)")
    tune.add_argument("--workers", type=int,
                      default=defaults.gym_rollout_workers,
                      help="concurrent candidate rollouts "
                           "(--gym-rollout-workers)")
    tune.add_argument("--weights", default=defaults.gym_objective_weights,
                      help='objective weights, "slo=1,cost=8,churn=0.25" '
                           "(--gym-objective-weights; empty = scorer "
                           "defaults)")
    tune.add_argument("--no-fleet", action="store_true",
                      help="solo rollout dispatches (skip the shared fleet "
                           "coalescer; scores are identical either way — "
                           "this is the parity-test lane)")

    rep = sub.add_parser("replay", help="re-run a recorded tune and "
                         "byte-compare the ledgers")
    rep.add_argument("suite")
    rep.add_argument("--ledger", required=True,
                     help="the existing tuning ledger to reproduce")

    app = sub.add_parser("apply", help="render a ledger's winning policy")
    app.add_argument("ledger")

    val = sub.add_parser("validate", help="validate a tuning ledger "
                         "(schema + improvement invariant)")
    val.add_argument("ledger")
    return p


def _options_for(args: argparse.Namespace) -> AutoscalingOptions:
    """The --gym-* flag surface, CLI-shaped: the same AutoscalingOptions
    fields main.py wires (GL009) back a standalone tune."""
    return AutoscalingOptions(
        gym_rollout_workers=args.workers,
        gym_objective_weights=args.weights,
        gym_fleet_coalesce=not args.no_fleet,
    )


def _run_tune(args, ledger_path: str):
    from autoscaler_tpu.gym.tune import TuneConfig, tune_suite

    suite = SuiteSpec.load(args.suite)
    config = TuneConfig.from_options(
        _options_for(args),
        generations=args.generations,
        population=args.population,
        seed=args.seed,
    )
    result = tune_suite(suite, config)
    if ledger_path:
        with open(ledger_path, "w") as f:
            f.write(result.ledger_lines())
    return result


def _tune(args) -> int:
    result = _run_tune(args, args.ledger)
    summary = gym_ledger.summarize(result.records)
    print(json.dumps({
        "metric": f"gym_tune_{result.suite}",
        "suite": result.suite,
        "seed": args.seed,
        **summary,
        "winner_flags": result.best_policy.render_flags(),
    }, indent=2, sort_keys=True))
    return 0


def _replay(args) -> int:
    from autoscaler_tpu.gym.tune import TuneConfig, tune_suite
    from autoscaler_tpu.loadgen.suite import SuiteSpec

    original = gym_ledger.load_jsonl(args.ledger)
    errors = gym_ledger.validate_records(original)
    if errors:
        print("ledger invalid before replay:", file=sys.stderr)
        for err in errors[:20]:
            print(f"  {err}", file=sys.stderr)
        return 2
    head = original[0]
    suite = SuiteSpec.load(args.suite)
    if suite.name != head["suite"] or suite.scenario_names() != head["scenarios"]:
        # a mismatched suite would re-tune different worlds and read as a
        # (false) determinism violation after burning a whole tune
        print(
            f"error: suite {suite.name!r} ({suite.scenario_names()}) does "
            f"not match the ledger's recorded suite {head['suite']!r} "
            f"({head['scenarios']})",
            file=sys.stderr,
        )
        return 2
    # the recorded weights pass through VERBATIM (a string re-encoding
    # would round them and replay a tune nobody ran)
    w = head["weights"]
    config = TuneConfig(
        generations=head["generations"],
        population=head["population"],
        seed=head["seed"],
        weights=ObjectiveWeights(
            w_slo=w["slo"], w_cost=w["cost"], w_churn=w["churn"]
        ),
        fleet_coalesce=head.get("fleet_coalesced", True),
    )
    result = tune_suite(suite, config)
    replayed = result.ledger_lines()
    original_text = "".join(
        gym_ledger.record_line(rec) for rec in original
    )
    if replayed != original_text:
        print(
            "ERROR: replayed tuning ledger diverges from the recorded one "
            "(determinism violation)",
            file=sys.stderr,
        )
        for i, (a, b) in enumerate(
            zip(original_text.splitlines(), replayed.splitlines())
        ):
            if a != b:
                print(f"  first divergence at line {i + 1}", file=sys.stderr)
                break
        return 1
    print(f"replay ok: {len(original)} generations byte-identical")
    return 0


def _apply(args) -> int:
    records = gym_ledger.load_jsonl(args.ledger)
    errors = gym_ledger.validate_records(records)
    if errors:
        print("ledger invalid:", file=sys.stderr)
        for err in errors[:20]:
            print(f"  {err}", file=sys.stderr)
        return 2
    winner = PolicySpec.from_dict(records[-1]["best_so_far"]["policy"])
    summary = gym_ledger.summarize(records)
    print(f"# winner of {args.ledger} "
          f"(score {summary['winner']['total']:g} vs baseline "
          f"{summary['baseline_total']:g})")
    print("# autoscaler flags:")
    print(winner.render_flags() or "# (all defaults)")
    print("# loadgen --set form:")
    print(winner.render_set_args() or "# (all defaults)")
    print("# deploy/chart values.yaml fragment:")
    print(winner.render_values_yaml(), end="")
    return 0


def _validate(args) -> int:
    records = gym_ledger.load_jsonl(args.ledger)
    errors = gym_ledger.validate_records(records)
    if errors:
        for err in errors[:20]:
            print(f"error: {err}", file=sys.stderr)
        return 1
    print(json.dumps(gym_ledger.summarize(records), indent=2, sort_keys=True))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    try:
        if args.command == "tune":
            return _tune(args)
        if args.command == "replay":
            return _replay(args)
        if args.command == "apply":
            return _apply(args)
        if args.command == "validate":
            return _validate(args)
    except (SpecError, PolicyError, ValueError, FileNotFoundError,
            json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 2
