"""Tuning ledger: one sorted-key JSON line per tuner generation.

Schema ``autoscaler_tpu.gym.generation/1``. Every value in a record is a
pure function of (suite, tune seed, weights): candidate policies come from
the seeded PolicyRng, scores from deterministic rollouts — so two runs of
the same tune write byte-identical JSONL files (hack/verify.sh diffs
them), and ``bench.py --gym-ledger`` machine-checks the schema plus the
improvement invariant: ``best_so_far`` (the score column is a reward —
higher is better) never decreases across generations, and the final
winner strictly beats the recorded all-defaults baseline.

``record_line`` serializes STRICTLY (same contract as the explain ledger):
a non-JSON value leaking in fails at the writer, not as a silently quoted
string that passes the byte-diff gate with the wrong type.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List

from autoscaler_tpu.gym.policy import PolicyError, PolicySpec

SCHEMA = "autoscaler_tpu.gym.generation/1"

# the machine-readable field contract (graftlint GL017): change the
# field set → update this AND bump the version tag above
SCHEMA_FIELDS = {
    SCHEMA: {
        "required": (
            "suite",
            "generation",
            "generations",
            "seed",
            "population",
            "weights",
            "scenarios",
            "fleet_coalesced",
            "candidates",
            "pruned",
            "best",
            "best_so_far",
        ),
        "optional": (),
    },
}

# the reserved candidate id of the all-defaults control: evaluated on the
# FULL suite in generation 0, never pruned — the improvement gate's
# denominator
BASELINE_ID = "defaults"


def stable_json(doc: Any) -> str:
    """Byte-stable one-line JSON (sorted keys, tight separators)."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def record_line(rec: Dict[str, Any]) -> str:
    """One ledger line (newline-terminated) for one generation record."""
    return stable_json(rec) + "\n"


def dump_jsonl(records: Iterable[Dict[str, Any]], path: str) -> int:
    n = 0
    with open(path, "w") as f:
        for rec in records:
            f.write(record_line(rec))
            n += 1
    return n


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from None
    return records


def _num(value: Any) -> bool:
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def _check_candidate(
    i: int, j: int, cand: Any, scenario_names: List[str], errors: List[str]
) -> None:
    where = f"record {i} candidate {j}"
    if not isinstance(cand, dict):
        errors.append(f"{where}: not an object")
        return
    cid = cand.get("id")
    if not isinstance(cid, str) or not cid:
        errors.append(f"{where}: missing/empty id")
    policy = cand.get("policy")
    if not isinstance(policy, dict):
        errors.append(f"{where}: policy must be an object")
    else:
        try:
            PolicySpec.from_dict(policy)
        except PolicyError as e:
            errors.append(f"{where}: policy outside the knob space: {e}")
    scores = cand.get("scores")
    if not isinstance(scores, dict):
        errors.append(f"{where}: scores must map scenario -> score")
        return
    for scen, val in scores.items():
        if scen not in scenario_names:
            errors.append(f"{where}: score for unknown scenario {scen!r}")
        if not _num(val):
            errors.append(f"{where}: score for {scen!r} is not a number")
    eliminated = cand.get("eliminated_after")
    if eliminated is not None and eliminated not in scenario_names:
        errors.append(
            f"{where}: eliminated_after names unknown scenario {eliminated!r}"
        )
    total = cand.get("total")
    if eliminated is None:
        # a full-suite candidate must carry every scenario score and the
        # comparable total
        missing = [s for s in scenario_names if s not in scores]
        if missing:
            errors.append(f"{where}: surviving candidate missing {missing}")
        if not _num(total):
            errors.append(f"{where}: surviving candidate needs a numeric total")
    elif total is not None:
        errors.append(
            f"{where}: eliminated candidate must not carry a total "
            "(partial scores are not comparable)"
        )


def validate_records(records: Iterable[Any]) -> List[str]:
    """→ error strings ([] = valid). Checks the schema, generation
    monotonicity, candidate/score shapes, that generation 0 carries the
    all-defaults baseline on the full suite, that each record's ``best``
    is the max over its surviving candidates, and the improvement
    invariant (best_so_far non-decreasing)."""
    errors: List[str] = []
    prev_gen = -1
    prev_best = None
    config_keys = None
    declared_generations = None
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            errors.append(f"record {i}: not an object")
            continue
        if rec.get("schema") != SCHEMA:
            errors.append(
                f"record {i}: schema {rec.get('schema')!r} != {SCHEMA!r}"
            )
            continue
        gen = rec.get("generation")
        if not isinstance(gen, int) or gen != prev_gen + 1:
            errors.append(
                f"record {i}: generation {gen!r} not monotonic "
                f"(expected {prev_gen + 1})"
            )
        prev_gen = gen if isinstance(gen, int) else prev_gen + 1
        if not isinstance(rec.get("suite"), str) or not rec.get("suite"):
            errors.append(f"record {i}: missing suite name")
        if not isinstance(rec.get("fleet_coalesced"), bool):
            errors.append(f"record {i}: fleet_coalesced must be a bool")
        scen = rec.get("scenarios")
        if not isinstance(scen, list) or not scen:
            errors.append(f"record {i}: scenarios must be a non-empty list")
            continue
        key = (
            tuple(scen), rec.get("seed"), rec.get("population"),
            rec.get("generations"), stable_json(rec.get("weights")),
        )
        if config_keys is None:
            config_keys = key
        elif key != config_keys:
            errors.append(
                f"record {i}: tune config drifted mid-ledger (seed/"
                "population/scenarios/weights must be constant)"
            )
        cands = rec.get("candidates")
        if not isinstance(cands, list) or not cands:
            errors.append(f"record {i}: candidates must be a non-empty list")
            continue
        for j, cand in enumerate(cands):
            _check_candidate(i, j, cand, list(scen), errors)
        pruned = rec.get("pruned")
        eliminated = sum(
            1
            for c in cands
            if isinstance(c, dict) and c.get("eliminated_after") is not None
        )
        if not isinstance(pruned, int) or pruned < 0:
            errors.append(f"record {i}: pruned must be a non-negative int")
        elif pruned != eliminated:
            errors.append(
                f"record {i}: pruned={pruned} disagrees with the "
                f"{eliminated} candidates carrying eliminated_after"
            )
        if i == 0 and not any(
            isinstance(c, dict) and c.get("id") == BASELINE_ID for c in cands
        ):
            errors.append(
                f"record 0: no {BASELINE_ID!r} baseline candidate — the "
                "improvement gate has no denominator"
            )
        totals = [
            c["total"] for c in cands
            if isinstance(c, dict) and _num(c.get("total"))
        ]
        best = rec.get("best")
        if not isinstance(best, dict) or not _num(best.get("total")):
            errors.append(f"record {i}: best must carry a numeric total")
        elif totals and best["total"] != max(totals):
            errors.append(
                f"record {i}: best.total {best['total']} != max candidate "
                f"total {max(totals)}"
            )
        bsf = rec.get("best_so_far")
        if not isinstance(bsf, dict) or not _num(bsf.get("total")):
            errors.append(f"record {i}: best_so_far must carry a numeric total")
            continue
        if not isinstance(bsf.get("policy"), dict):
            errors.append(f"record {i}: best_so_far must carry its policy")
        if prev_best is not None and bsf["total"] < prev_best:
            errors.append(
                f"record {i}: improvement invariant violated — best_so_far "
                f"{bsf['total']} < previous {prev_best}"
            )
        prev_best = bsf["total"]
        declared = rec.get("generations")
        if declared_generations is None and isinstance(declared, int):
            declared_generations = declared
    if prev_gen < 0:
        errors.append("empty ledger")
    elif declared_generations is not None and prev_gen + 1 != declared_generations:
        # a truncated (or over-long) ledger must not validate clean: its
        # mid-tune best would masquerade as the winner, and a replay
        # would read as a false determinism violation
        errors.append(
            f"ledger holds {prev_gen + 1} generation records but the "
            f"tune config declares {declared_generations} (truncated?)"
        )
    return errors


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a VALID ledger: the winner, the per-generation best
    trajectory, and the improvement over the all-defaults baseline (the
    number hack/verify.sh gates on)."""
    baseline_total = None
    for cand in records[0].get("candidates", []):
        if cand.get("id") == BASELINE_ID and _num(cand.get("total")):
            baseline_total = cand["total"]
    trajectory = [rec["best_so_far"]["total"] for rec in records]
    final = records[-1]["best_so_far"]
    rollouts = sum(
        len(c.get("scores", {})) for rec in records
        for c in rec.get("candidates", [])
    )
    out: Dict[str, Any] = {
        "generations": len(records),
        "scenarios": records[0]["scenarios"],
        "rollouts": rollouts,
        "best_trajectory": trajectory,
        "winner": final,
        "baseline_total": baseline_total,
    }
    if baseline_total is not None:
        out["improvement"] = round(final["total"] - baseline_total, 6)
        out["beats_baseline"] = final["total"] > baseline_total
    return out
