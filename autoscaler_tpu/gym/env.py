"""PolicyGymEnv: a gym-style reset/step/rollout wrapper over the REAL
loadgen ScenarioDriver.

The env does not reimplement anything: ``step()`` drives the exact
``ScenarioDriver.tick_once`` body ``run()`` loops over (loadgen/driver.py
exposes the tick loop for precisely this), on the driver's simulated
clock. Rollout-vs-direct decision parity is therefore structural — the
identity policy's decision log is byte-identical to ``run_scenario``'s
(tests/test_gym.py locks it).

The *action* is a typed :class:`PolicySpec` (gym/policy.py), applied at
episode start through the AutoscalingOptions override seam (the ``--set``
machinery): its overrides merge into a copy of the scenario spec's
``options`` and the driver's schema gate validates them. Mid-episode
policy changes are rejected loudly — half the knob space (expander
strategy, breaker cooldowns) is consumed at construction, and silently
half-applying a policy would score a candidate nobody proposed.

Reward: the NEGATION of the scorer's per-tick objective contribution
(``loadgen.score.tick_objective``), so Σ step rewards ≈ −(the report's
``objective.weighted_total``) — the gym and the human report read the same
number by construction.

Fleet coalescing (Podracer batching): pass a shared ``FleetCoalescer`` and
every rollout's estimator routes its plain batched dispatches through the
coalescer's admission queue (estimator/binpacking.py ``fleet_client``
seam) — concurrent candidate rollouts then coalesce their estimator calls
into shared mesh dispatches. Answers are certified batch-invariant (the
PR-8 fairness property), so scores are identical with or without the
coalescer; the coalescer buys dispatch amortization, never different
decisions.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from autoscaler_tpu.gym.policy import DEFAULT_POLICY, PolicyError, PolicySpec
from autoscaler_tpu.loadgen.score import (
    DEFAULT_WEIGHTS,
    ObjectiveWeights,
    build_report,
    tick_objective,
)
from autoscaler_tpu.loadgen.spec import ScenarioSpec


class GymError(RuntimeError):
    """Env protocol misuse (step before reset, mid-episode policy swap)."""


@dataclass
class RolloutResult:
    """One full episode's verdict: the score the tuner ranks on plus the
    artifacts the parity tests byte-compare."""

    scenario: str
    seed: int
    policy: PolicySpec
    objective: float                 # the scorer's weighted_total (minimize)
    score: float                     # -objective (maximize; ledger column)
    report: Dict[str, Any] = field(default_factory=dict)
    decision_log: List[Dict[str, Any]] = field(default_factory=list)
    step_rewards: List[float] = field(default_factory=list)


class FleetEstimatorClient:
    """The estimator-side adapter of the shared coalescer: turns one plain
    packed estimate dispatch into a FleetRequest ticket and blocks for the
    demuxed answer. Lives here (not in fleet/) because the tenant identity
    and the blocking-rollout semantics are gym concerns."""

    def __init__(self, coalescer, tenant_id: str, timeout_s: float = 60.0):
        self.coalescer = coalescer
        self.tenant_id = tenant_id
        self.timeout_s = float(timeout_s)

    def estimate_groups(self, req, masks, allocs, caps, max_nodes: int):
        """[P,R]/[G,P]/[G,R]/[G] packed operands → (counts [G], scheduled
        [G,P]) numpy, via one coalesced (possibly co-batched) dispatch."""
        from autoscaler_tpu.fleet.coalescer import FleetRequest

        ticket = self.coalescer.submit(FleetRequest(
            tenant_id=self.tenant_id,
            pod_req=req,
            pod_masks=masks,
            template_allocs=allocs,
            node_caps=caps,
            max_nodes=int(max_nodes),
        ))
        answer = ticket.result(timeout=self.timeout_s)
        return answer.node_counts, answer.scheduled


class PolicyGymEnv:
    """reset/step/rollout over one loadgen scenario.

    Episodes are deterministic: same (seed, policy) → same observation and
    reward streams, byte-identical decision logs (the loadgen contract)."""

    def __init__(
        self,
        spec: ScenarioSpec,
        weights: ObjectiveWeights = DEFAULT_WEIGHTS,
        coalescer=None,
        rollout_timeout_s: float = 60.0,
    ):
        if spec.fleet is not None:
            raise GymError(
                "PolicyGymEnv drives the control loop; fleet scenarios "
                "have no policy knobs to tune"
            )
        self.spec = spec
        self.weights = weights
        self.coalescer = coalescer
        self.rollout_timeout_s = rollout_timeout_s
        self._driver = None
        self._policy: PolicySpec = DEFAULT_POLICY
        self._seed: int = spec.seed
        self._tick = 0

    # -- the gym protocol ------------------------------------------------------
    def reset(
        self,
        seed: Optional[int] = None,
        policy: Optional[PolicySpec] = None,
    ) -> Dict[str, Any]:
        """Start a fresh episode: rebuild the driver from a copy of the
        scenario spec with the policy's overrides merged into ``options``
        (the sanctioned --set seam; out-of-range knobs raise PolicyError
        here, schema mismatches raise SpecError in the driver)."""
        from autoscaler_tpu.loadgen.driver import ScenarioDriver

        policy = policy if policy is not None else DEFAULT_POLICY
        policy.validate()
        self._seed = self.spec.seed if seed is None else int(seed)
        self._policy = policy
        episode = ScenarioSpec.from_dict(self.spec.to_dict())  # exact copy
        episode.seed = self._seed
        episode.options = dict(episode.options)
        episode.options.update(policy.to_overrides())
        self._driver = ScenarioDriver(episode)
        if self.coalescer is not None:
            est = self._driver.autoscaler.scale_up_orchestrator.estimator
            est.fleet_client = FleetEstimatorClient(
                self.coalescer,
                tenant_id=f"gym:{episode.name}:{self._seed}",
                timeout_s=self.rollout_timeout_s,
            )
        self._driver.begin()
        self._tick = 0
        return self._observe_initial()

    def step(self, action: Optional[PolicySpec] = None):
        """Advance one scan interval → (observation, reward, done, info).

        ``action`` must be the episode's policy (or None): policies bind at
        episode start through the options seam, so a first-step action
        rebinds by rebuilding the driver, and a MID-episode change raises
        — half the knobs are construction-time and a silent partial apply
        would be a lie."""
        if self._driver is None:
            raise GymError("step() before reset()")
        if self._tick >= self.spec.ticks:
            # stepping past done would silently extend the episode beyond
            # the scenario (extra ticks, extra reward, a decision log
            # longer than the spec declares — breaking rollout-vs-direct
            # parity); fail loudly like every other protocol misuse
            raise GymError(
                f"episode is done (tick {self._tick} == spec.ticks); "
                "reset() to start a new one"
            )
        if action is not None and action != self._policy:
            if self._tick == 0:
                self.reset(seed=self._seed, policy=action)
            else:
                raise PolicyError(
                    "mid-episode policy change: knobs like the expander "
                    "and breaker cooldowns bind at episode start (the "
                    "AutoscalingOptions seam) — reset() to change policy"
                )
        rec = self._driver.tick_once(self._tick)
        self._tick += 1
        reward = -tick_objective(
            rec, self.spec.tick_interval_s, self.weights
        )
        done = self._tick >= self.spec.ticks
        obs = {
            "tick": rec.tick,
            "pending": rec.pending_after,
            "nodes_ready": rec.nodes_ready,
            "nodes_total": rec.nodes_total,
            "demand_nodes": rec.demand_nodes,
            "degraded": bool(rec.degraded),
        }
        return obs, reward, done, {"record": rec.to_dict()}

    def rollout(
        self,
        policy: Optional[PolicySpec] = None,
        seed: Optional[int] = None,
    ) -> RolloutResult:
        """One full episode under ``policy`` → the tuner's scoring unit."""
        self.reset(seed=seed, policy=policy)
        rewards: List[float] = []
        done = self._tick >= self.spec.ticks
        while not done:
            _, reward, done, _ = self.step()
            rewards.append(reward)
        result = self._driver.finish()
        report = build_report(result, weights=self.weights)
        objective = float(report["objective"]["weighted_total"])
        return RolloutResult(
            scenario=self.spec.name,
            seed=self._seed,
            policy=self._policy,
            objective=objective,
            score=round(-objective, 6),
            report=report,
            decision_log=result.decision_log(),
            step_rewards=rewards,
        )

    # -- helpers ---------------------------------------------------------------
    def _observe_initial(self) -> Dict[str, Any]:
        api = self._driver.api
        return {
            "tick": -1,     # before the first scan interval
            "pending": sum(1 for p in api.list_pods() if not p.node_name),
            "nodes_ready": sum(1 for n in api.list_nodes() if n.ready),
            "nodes_total": len(api.nodes),
            "demand_nodes": 0,
            "degraded": False,
        }
