"""Cross-entropy / successive-halving population tuner over the policy gym.

Podracer shape (PAPERS.md): a *population* of candidate policies is one
more batch axis on infrastructure that already batches — per-candidate
rollouts run concurrently on a thread pool, and every rollout's estimator
routes its packed dispatches through ONE shared fleet coalescer
(fleet/coalescer.py admission queue), so estimator calls from parallel
rollouts coalesce into shared mesh dispatches exactly as fleet tenants do.
Answers are batch-invariant (the PR-8 fairness certificate), so the
coalescer buys dispatch amortization without ever touching a score.

Determinism: ALL randomness flows from one seeded :class:`PolicyRng`
(``np.random.default_rng`` keyed on the tune seed — the loadgen idiom;
GL001/GL010 clean, no ambient RNG). Candidate sampling happens in the
coordinator thread BEFORE any evaluation, scores are pure functions of
(scenario seed, policy), and ledger records are assembled in candidate
order — so concurrency changes wall time, never a byte of the tuning
ledger. Two runs of the same tune are byte-identical (hack/verify.sh
diffs them).

The search itself:

- generation 0 = the all-defaults control (id ``defaults``, never pruned
  — the improvement gate's denominator) + K seeded-random candidates;
- each generation runs *successive halving* across the suite: candidates
  are scored scenario by scenario and the worse half is pruned after each
  stage, so hopeless candidates never pay for the full suite;
- survivors get comparable full-suite totals; the elite set feeds a
  cross-entropy update (numeric knobs: clipped gaussians around the elite
  mean; categorical knobs: the elite empirical distribution with an
  exploration floor) for the next generation;
- the best-so-far candidate is retained verbatim (elitism), which is what
  makes the ledger's best-of-generation score non-decreasing — the
  invariant ``bench.py --gym-ledger`` enforces.
"""
from __future__ import annotations

import math
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from autoscaler_tpu import trace
from autoscaler_tpu.gym import ledger as gym_ledger
from autoscaler_tpu.gym.env import PolicyGymEnv
from autoscaler_tpu.gym.policy import (
    DEFAULT_POLICY,
    KNOB_SPACE,
    Knob,
    PolicySpec,
)
from autoscaler_tpu.loadgen.suite import SuiteSpec
from autoscaler_tpu.loadgen.score import DEFAULT_WEIGHTS, ObjectiveWeights
from autoscaler_tpu.metrics import metrics as metrics_mod
from autoscaler_tpu.metrics.metrics import AutoscalerMetrics

# the tuner's INITIAL sampling window per knob — a practical sub-range of
# the declared bounds (sampling uniformly over [0, 3600] cooldown seconds
# would spend generations on obviously-absurd policies); CE then moves
# wherever the elites point, still bounds-checked by PolicySpec.
_INIT_WINDOW: Dict[str, Tuple[float, float]] = {
    "scale_down_utilization_threshold": (0.3, 0.9),
    "scale_down_unneeded_time_s": (0.0, 120.0),
    "scale_down_delay_after_add_s": (0.0, 120.0),
    "kernel_breaker_cooldown_s": (10.0, 300.0),
    "kernel_breaker_failure_threshold": (1, 5),
}


class PolicyRng:
    """The tune's one randomness source: a seeded numpy Generator behind
    the few draw shapes sampling needs. Threaded through explicitly — the
    GL001 seam — and only ever touched from the coordinator thread, so the
    draw sequence (hence the ledger) is independent of rollout timing."""

    def __init__(self, seed: int):
        self._rng = np.random.default_rng((int(seed), 15485863))

    def uniform(self, lo: float, hi: float) -> float:
        return float(lo + (hi - lo) * self._rng.random())

    def gauss(self, mu: float, sigma: float) -> float:
        return float(mu + sigma * self._rng.standard_normal())

    def choice(self, seq):
        return seq[int(self._rng.integers(0, len(seq)))]

    def coin(self, p: float) -> bool:
        return bool(self._rng.random() < p)


@dataclass
class TuneConfig:
    generations: int = 4
    population: int = 8
    seed: int = 0
    weights: ObjectiveWeights = field(default_factory=lambda: DEFAULT_WEIGHTS)
    # concurrent rollouts (the population axis; AutoscalingOptions
    # --gym-rollout-workers)
    workers: int = 4
    # route rollout estimator dispatches through one shared fleet
    # coalescer (--gym-fleet-coalesce); scores are identical either way
    fleet_coalesce: bool = True
    elite_count: int = 2
    # successive halving never prunes below this many candidates
    min_alive: int = 2
    rollout_timeout_s: float = 60.0

    def __post_init__(self):
        if self.generations < 1 or self.population < 1:
            raise ValueError("generations and population must be >= 1")

    @classmethod
    def from_options(cls, options, **kwargs) -> "TuneConfig":
        """The --gym-* flag surface (config/options.py gym_* fields)."""
        kwargs.setdefault("workers", options.gym_rollout_workers)
        kwargs.setdefault("fleet_coalesce", options.gym_fleet_coalesce)
        kwargs.setdefault(
            "weights", ObjectiveWeights.parse(options.gym_objective_weights)
        )
        return cls(**kwargs)


@dataclass
class TuneResult:
    suite: str
    records: List[Dict[str, Any]]            # the ledger, in order
    best_policy: PolicySpec
    best_total: float
    baseline_total: float
    rollouts: int

    def ledger_lines(self) -> str:
        return "".join(gym_ledger.record_line(rec) for rec in self.records)

    def improvement(self) -> float:
        return round(self.best_total - self.baseline_total, 6)


@dataclass
class _Candidate:
    cid: str
    policy: PolicySpec
    scores: Dict[str, float] = field(default_factory=dict)
    eliminated_after: Optional[str] = None
    total: Optional[float] = None


def _window_sleep(seconds: float) -> None:
    """Wall pacing for the coalescing window thread without the time.sleep
    sanitizer trap (gym/ is replay-scoped; Event.wait is not a replay
    artifact input — it paces dispatch, the answers are batch-invariant)."""
    threading.Event().wait(max(float(seconds), 0.0))


class PopulationTuner:
    def __init__(
        self,
        suite: SuiteSpec,
        config: Optional[TuneConfig] = None,
        metrics: Optional[AutoscalerMetrics] = None,
    ):
        self.suite = suite
        self.config = config or TuneConfig()
        self.metrics = metrics or AutoscalerMetrics()
        self._coalescer = None
        # (policy JSON, scenario) → score, filled coordinator-side only
        self._score_cache: Dict[Tuple[str, str], float] = {}

    # -- sampling --------------------------------------------------------------
    def _sample_initial(self, rng: PolicyRng) -> PolicySpec:
        kw: Dict[str, Any] = {}
        for knob in KNOB_SPACE:
            if not rng.coin(0.75):
                continue        # leave at default: near-baseline diversity
            kw[knob.name] = self._draw_initial(rng, knob)
        return PolicySpec(**kw)

    @staticmethod
    def _draw_initial(rng: PolicyRng, knob: Knob):
        if knob.kind == "choice":
            return rng.choice(knob.choices)
        lo, hi = _INIT_WINDOW.get(knob.name, (knob.lo, knob.hi))
        if knob.kind == "int":
            return int(round(rng.uniform(lo, hi)))
        return round(rng.uniform(lo, hi), 4)

    def _sample_ce(
        self, rng: PolicyRng, elites: List[PolicySpec]
    ) -> PolicySpec:
        """Cross-entropy step: numeric knobs get a clipped gaussian around
        the elite mean (σ = elite spread with a floor so the search never
        collapses), categorical knobs draw from the elite empirical
        distribution with a 25% exploration coin."""
        kw: Dict[str, Any] = {}
        for knob in KNOB_SPACE:
            values = [e.resolved(knob.name) for e in elites]
            if knob.kind == "choice":
                kw[knob.name] = (
                    rng.choice(values) if rng.coin(0.75)
                    else rng.choice(knob.choices)
                )
                continue
            mu = sum(values) / len(values)
            spread = max(values) - min(values)
            lo, hi = _INIT_WINDOW.get(knob.name, (knob.lo, knob.hi))
            sigma = max(spread / 2.0, (hi - lo) * 0.15)
            drawn = min(max(rng.gauss(mu, sigma), knob.lo), knob.hi)
            kw[knob.name] = (
                int(round(drawn)) if knob.kind == "int" else round(drawn, 4)
            )
        return PolicySpec(**kw)

    # -- evaluation ------------------------------------------------------------
    def _rollout_score(self, policy: PolicySpec, scenario) -> float:
        env = PolicyGymEnv(
            scenario,
            weights=self.config.weights,
            coalescer=self._coalescer,
            rollout_timeout_s=self.config.rollout_timeout_s,
        )
        with trace.span(
            metrics_mod.GYM_ROLLOUT, metrics=self.metrics,
            scenario=scenario.name,
        ):
            result = env.rollout(policy=policy)
        self.metrics.gym_rollouts_total.inc(scenario=scenario.name)
        return result.score

    def _evaluate_stage(
        self, executor: ThreadPoolExecutor, alive: List[_Candidate], scenario
    ) -> None:
        """Score every live candidate on one scenario, concurrently;
        results land keyed by candidate, so completion order is invisible.
        Scores are pure functions of (scenario seed, policy) — the
        determinism contract — so a (policy, scenario) pair already
        evaluated this tune (the elitism carry-over, CE re-draws) reuses
        its score instead of re-paying a full rollout; ledger bytes are
        identical either way."""
        futures = {}
        for cand in alive:
            key = (gym_ledger.stable_json(cand.policy.to_dict()), scenario.name)
            if key in self._score_cache:
                cand.scores[scenario.name] = self._score_cache[key]
            else:
                futures[cand.cid] = (
                    key,
                    executor.submit(self._rollout_score, cand.policy, scenario),
                )
        for cand in alive:
            if cand.cid not in futures:
                continue
            key, fut = futures[cand.cid]
            score = fut.result(
                timeout=self.config.rollout_timeout_s * (scenario.ticks + 1)
            )
            cand.scores[scenario.name] = score
            self._score_cache[key] = score

    # -- the tune --------------------------------------------------------------
    def tune(self) -> TuneResult:
        cfg = self.config
        scenarios = self.suite.scenarios
        names = self.suite.scenario_names()
        rng = PolicyRng(cfg.seed)
        if cfg.fleet_coalesce:
            from autoscaler_tpu.fleet.coalescer import FleetCoalescer

            # perf_counter (the sanctioned measurement clock) + Event-wait
            # pacing: the window thread must not touch the replay-trapped
            # clocks. Breaker cooldowns on the fleet ladder run on this
            # wall clock — fleet answers are batch- and rung-invariant, so
            # nothing score-visible depends on it.
            self._coalescer = FleetCoalescer(
                window_s=0.002,
                metrics=self.metrics,
                clock=time.perf_counter,
                sleep=_window_sleep,
            )
            self._coalescer.start()
        executor = ThreadPoolExecutor(
            max_workers=max(cfg.workers, 1),
            thread_name_prefix="gym-rollout",
        )
        try:
            return self._tune_inner(executor, rng, scenarios, names)
        finally:
            executor.shutdown(wait=True)
            if self._coalescer is not None:
                self._coalescer.stop()
                self._coalescer = None

    def _tune_inner(self, executor, rng, scenarios, names) -> TuneResult:
        cfg = self.config
        records: List[Dict[str, Any]] = []
        pool: List[_Candidate] = []      # fully-evaluated, all generations
        best_so_far: Optional[_Candidate] = None
        rollouts = 0
        for g in range(cfg.generations):
            with trace.span(
                metrics_mod.GYM_GENERATION, metrics=self.metrics,
                generation=g, population=cfg.population,
            ):
                cands = self._generation_candidates(g, rng, pool)
                pruned = self._halving(executor, cands, scenarios)
                survivors = [c for c in cands if c.eliminated_after is None]
                for cand in survivors:
                    cand.total = round(
                        sum(cand.scores[n] for n in names) / len(names), 6
                    )
                pool.extend(survivors)
                best = max(
                    survivors, key=lambda c: (c.total, c.cid)
                )
                if best_so_far is None or best.total > best_so_far.total:
                    best_so_far = best
                rollouts += sum(len(c.scores) for c in cands)
                self.metrics.gym_generation_best_score.set(
                    float(best_so_far.total)
                )
                if pruned:
                    self.metrics.gym_candidates_pruned_total.inc(
                        float(pruned)
                    )
                records.append(self._record(g, names, cands, best, best_so_far))
        baseline = next(
            c for c in pool if c.cid == gym_ledger.BASELINE_ID
        )
        return TuneResult(
            suite=self.suite.name,
            records=records,
            best_policy=best_so_far.policy,
            best_total=best_so_far.total,
            baseline_total=baseline.total,
            rollouts=rollouts,
        )

    def _generation_candidates(
        self, g: int, rng: PolicyRng, pool: List[_Candidate]
    ) -> List[_Candidate]:
        cfg = self.config
        if g == 0:
            cands = [_Candidate(gym_ledger.BASELINE_ID, DEFAULT_POLICY)]
            cands.extend(
                _Candidate(f"g0c{i}", self._sample_initial(rng))
                for i in range(cfg.population)
            )
            return cands
        elites = [
            c.policy
            for c in sorted(pool, key=lambda c: (-c.total, c.cid))
        ][: max(cfg.elite_count, 1)]
        cands = []
        seen = set()
        for i in range(cfg.population):
            if i == 0:
                policy = elites[0]      # elitism: best-so-far re-enters
            else:
                policy = self._sample_ce(rng, elites)
            # a resampled duplicate would waste a full-suite evaluation
            # AND create ambiguous ledger rows; nudge via fresh draws,
            # RE-CHECKING each (a collapsed CE distribution keeps handing
            # back the elite) — bounded so sampling always terminates
            for _ in range(8):
                if gym_ledger.stable_json(policy.to_dict()) not in seen:
                    break
                policy = self._sample_initial(rng)
            seen.add(gym_ledger.stable_json(policy.to_dict()))
            cands.append(_Candidate(f"g{g}c{i}", policy))
        return cands

    def _halving(
        self, executor, cands: List[_Candidate], scenarios
    ) -> int:
        """Successive halving across the suite; returns how many
        candidates were pruned. The ``defaults`` control is exempt — its
        full-suite total is the improvement gate's denominator."""
        cfg = self.config
        alive = list(cands)
        pruned = 0
        for si, scenario in enumerate(scenarios):
            self._evaluate_stage(executor, alive, scenario)
            last = si == len(scenarios) - 1
            prunable = [
                c for c in alive if c.cid != gym_ledger.BASELINE_ID
            ]
            if last or len(prunable) <= cfg.min_alive:
                continue
            keep = max(
                int(math.ceil(len(prunable) / 2.0)), cfg.min_alive
            )
            cum = lambda c: sum(c.scores.values())  # noqa: E731
            ranked = sorted(prunable, key=lambda c: (-cum(c), c.cid))
            for cand in ranked[keep:]:
                cand.eliminated_after = scenario.name
                pruned += 1
            dropped = {c.cid for c in ranked[keep:]}
            alive = [c for c in alive if c.cid not in dropped]
        return pruned

    def _record(
        self, g: int, names, cands: List[_Candidate], best, best_so_far
    ) -> Dict[str, Any]:
        cfg = self.config
        return {
            "schema": gym_ledger.SCHEMA,
            "suite": self.suite.name,
            "generation": g,
            "generations": cfg.generations,
            "seed": cfg.seed,
            "population": cfg.population,
            "weights": cfg.weights.to_dict(),
            "scenarios": list(names),
            "fleet_coalesced": bool(cfg.fleet_coalesce),
            "candidates": [
                {
                    "id": c.cid,
                    "policy": c.policy.to_dict(),
                    "scores": {k: c.scores[k] for k in sorted(c.scores)},
                    "eliminated_after": c.eliminated_after,
                    **({"total": c.total} if c.total is not None else {}),
                }
                for c in cands
            ],
            "pruned": sum(1 for c in cands if c.eliminated_after is not None),
            "best": {"id": best.cid, "total": best.total},
            "best_so_far": {
                "id": best_so_far.cid,
                "total": best_so_far.total,
                "policy": best_so_far.policy.to_dict(),
            },
        }


def tune_suite(
    suite: SuiteSpec,
    config: Optional[TuneConfig] = None,
    metrics: Optional[AutoscalerMetrics] = None,
) -> TuneResult:
    return PopulationTuner(suite, config, metrics=metrics).tune()
