"""PolicySpec: the typed action space of the policy gym.

An *action* is not a flag string — it is a declared point in a bounded
knob space (the knobs ROADMAP item 4 names as hand-tuned today: expander
strategy, scale-down aggressiveness, breaker/ladder cooldowns). The spec
is applied through the existing AutoscalingOptions override seam (the
loadgen ``--set`` machinery): ``to_overrides()`` yields the exact dict a
``--set KEY=VALUE`` series would, and the driver's
``config.options.validate_overrides`` schema gate runs on top. Bounds are
enforced HERE, before any rollout: an out-of-range candidate raises
:class:`PolicyError` naming the knob — it never silently clamps, because a
clamped candidate would score as a policy nobody proposed.

Stdlib only: the tuner, the CLI renderers and the ledger all round-trip
PolicySpec through plain dicts.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple


class PolicyError(ValueError):
    """A PolicySpec outside the declared knob space (unknown knob or
    out-of-bounds value) — candidates fail loudly, never clamp."""


@dataclass(frozen=True)
class Knob:
    """One tunable dimension: its kind, bounds/choices, the nominal
    baseline (the driver-default value CE sampling centers on when a
    candidate leaves the knob unset), and the production flag it renders
    to in ``gym apply``."""

    name: str
    kind: str                       # "float" | "int" | "choice"
    lo: float = 0.0
    hi: float = 0.0
    choices: Tuple[str, ...] = ()
    baseline: Any = None
    flag: str = ""
    values_key: str = ""            # deploy/chart values.yaml key


# THE knob space — the single declaration validation, sampling (gym/tune),
# the docs knob table and the apply renderers all read.
KNOB_SPACE: Tuple[Knob, ...] = (
    Knob(
        "expander", "choice",
        choices=("least-waste", "most-pods", "price", "random"),
        baseline="least-waste", flag="--expander", values_key="expander",
    ),
    Knob(
        "scale_down_utilization_threshold", "float", lo=0.05, hi=0.95,
        baseline=0.5, flag="--scale-down-utilization-threshold",
        values_key="scaleDownUtilizationThreshold",
    ),
    Knob(
        "scale_down_unneeded_time_s", "float", lo=0.0, hi=3600.0,
        baseline=20.0, flag="--scale-down-unneeded-time",
        values_key="scaleDownUnneededTime",
    ),
    Knob(
        "scale_down_delay_after_add_s", "float", lo=0.0, hi=3600.0,
        baseline=0.0, flag="--scale-down-delay-after-add",
        values_key="scaleDownDelayAfterAdd",
    ),
    Knob(
        "kernel_breaker_cooldown_s", "float", lo=1.0, hi=3600.0,
        baseline=120.0, flag="--kernel-breaker-cooldown",
        values_key="kernelBreakerCooldown",
    ),
    Knob(
        "kernel_breaker_failure_threshold", "int", lo=1, hi=10,
        baseline=3, flag="--kernel-breaker-failure-threshold",
        values_key="kernelBreakerFailureThreshold",
    ),
    # expander churn penalty per planned eviction an option leaves
    # uncovered (0 = churn-blind); only bites when the scenario enables
    # preemption_enabled — on priority-flat scenarios the filter
    # disengages and any value scores identically
    Knob(
        "preemption_churn_weight", "float", lo=0.0, hi=100.0,
        baseline=0.0, flag="--preemption-churn-weight",
        values_key="preemptionChurnWeight",
    ),
)

KNOBS: Dict[str, Knob] = {k.name: k for k in KNOB_SPACE}


def _check_value(knob: Knob, value: Any) -> None:
    if knob.kind == "choice":
        if value not in knob.choices:
            raise PolicyError(
                f"knob {knob.name!r}: {value!r} not one of {knob.choices}"
            )
        return
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise PolicyError(
            f"knob {knob.name!r}: wants a number in [{knob.lo}, {knob.hi}], "
            f"got {type(value).__name__} ({value!r})"
        )
    if knob.kind == "int" and int(value) != value:
        raise PolicyError(
            f"knob {knob.name!r}: wants an integer in "
            f"[{int(knob.lo)}, {int(knob.hi)}], got {value!r}"
        )
    if not knob.lo <= value <= knob.hi:
        raise PolicyError(
            f"knob {knob.name!r}: {value!r} outside [{knob.lo}, {knob.hi}] "
            "(candidates fail loudly, never clamp)"
        )


@dataclass(frozen=True)
class PolicySpec:
    """One candidate policy. ``None`` leaves the knob at the environment's
    default — the all-``None`` spec IS the all-defaults baseline candidate
    every tune must beat."""

    expander: Optional[str] = None
    scale_down_utilization_threshold: Optional[float] = None
    scale_down_unneeded_time_s: Optional[float] = None
    scale_down_delay_after_add_s: Optional[float] = None
    kernel_breaker_cooldown_s: Optional[float] = None
    kernel_breaker_failure_threshold: Optional[int] = None
    preemption_churn_weight: Optional[float] = None

    def __post_init__(self):
        self.validate()

    def validate(self) -> None:
        for knob in KNOB_SPACE:
            value = getattr(self, knob.name)
            if value is not None:
                _check_value(knob, value)

    def is_default(self) -> bool:
        return all(getattr(self, k.name) is None for k in KNOB_SPACE)

    def resolved(self, name: str) -> Any:
        """The knob's effective nominal value (set value, else baseline) —
        what CE sampling and the apply renderers read."""
        value = getattr(self, name)
        return KNOBS[name].baseline if value is None else value

    # -- the AutoscalingOptions seam ------------------------------------------
    def to_overrides(self) -> Dict[str, Any]:
        """→ the ``--set``-shaped override dict (set knobs only); merged
        into ScenarioSpec.options and schema-checked by the driver's
        validate_overrides gate like any other override."""
        out: Dict[str, Any] = {}
        for k in KNOB_SPACE:
            value = getattr(self, k.name)
            if value is None:
                continue
            if k.kind == "int":
                value = int(value)      # 3.0 from a sampler is the int knob 3
            elif k.kind == "float":
                value = float(value)
            out[k.name] = value
        return out

    # -- round-trip ------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return self.to_overrides()

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "PolicySpec":
        if not isinstance(doc, dict):
            raise PolicyError(f"policy must be an object, got {type(doc)}")
        unknown = set(doc) - set(KNOBS)
        if unknown:
            raise PolicyError(
                f"unknown policy knobs {sorted(unknown)} "
                f"(the space is {sorted(KNOBS)})"
            )
        return cls(**doc)

    # -- production renderers (gym apply) --------------------------------------
    def render_flags(self) -> str:
        """The winning policy as a main.py flag snippet."""
        parts: List[str] = []
        for knob in KNOB_SPACE:
            value = getattr(self, knob.name)
            if value is None:
                continue
            parts.append(f"{knob.flag}={_render_scalar(knob, value)}")
        return " ".join(parts)

    def render_set_args(self) -> str:
        """The winning policy as a ``loadgen run --set`` snippet."""
        return " ".join(
            f"--set {k.name}={_render_scalar(k, getattr(self, k.name))}"
            for k in KNOB_SPACE
            if getattr(self, k.name) is not None
        )

    def render_values_yaml(self) -> str:
        """The winning policy as a deploy/chart values.yaml fragment
        (camelCase keys under ``autoscaling:``, the chart's convention)."""
        lines = ["autoscaling:"]
        for knob in KNOB_SPACE:
            value = getattr(self, knob.name)
            if value is None:
                continue
            lines.append(f"  {knob.values_key}: {_render_scalar(knob, value)}")
        if len(lines) == 1:
            lines.append("  {}  # all-defaults policy: nothing to override")
        return "\n".join(lines) + "\n"


def _render_scalar(knob: Knob, value: Any) -> str:
    if knob.kind == "choice":
        return str(value)
    if knob.kind == "int":
        return str(int(value))
    # .10g: enough digits that the rendered flag/--set reproduces the
    # winning candidate EXACTLY (%g's 6 significant digits would round a
    # tuned 117.6293 to 117.629 — a policy nobody evaluated)
    return f"{float(value):.10g}"


DEFAULT_POLICY = PolicySpec()
