import sys

from autoscaler_tpu.gym.cli import main

sys.exit(main())
