"""Policy gym (ROADMAP item 4): loadgen as a tuning environment.

Three layers:

- ``gym/env.py`` — :class:`PolicyGymEnv`: gym-style reset/step/rollout
  over the real loadgen ``ScenarioDriver`` (its own tick loop, exposed
  tick-at-a-time), rewarded by the scorer's deterministic objective;
- ``gym/tune.py`` — :class:`PopulationTuner`: a seeded cross-entropy /
  successive-halving population search whose concurrent rollouts coalesce
  estimator dispatches through the fleet admission queue;
- ``gym/ledger.py`` — the byte-stable tuning ledger
  (``autoscaler_tpu.gym.generation/1``) ``bench.py --gym-ledger`` gates.

CLI: ``python -m autoscaler_tpu.gym tune benchmarks/scenarios/gym_suite.json``.
"""
from autoscaler_tpu.gym.env import (
    FleetEstimatorClient,
    GymError,
    PolicyGymEnv,
    RolloutResult,
)
from autoscaler_tpu.gym.ledger import (
    BASELINE_ID,
    SCHEMA,
    dump_jsonl,
    load_jsonl,
    record_line,
    stable_json,
    summarize,
    validate_records,
)
from autoscaler_tpu.gym.policy import (
    DEFAULT_POLICY,
    KNOB_SPACE,
    KNOBS,
    PolicyError,
    PolicySpec,
)
from autoscaler_tpu.loadgen.suite import SuiteSpec, is_suite_doc
from autoscaler_tpu.gym.tune import (
    PolicyRng,
    PopulationTuner,
    TuneConfig,
    TuneResult,
    tune_suite,
)

__all__ = [
    "BASELINE_ID",
    "DEFAULT_POLICY",
    "FleetEstimatorClient",
    "GymError",
    "KNOBS",
    "KNOB_SPACE",
    "PolicyError",
    "PolicyGymEnv",
    "PolicyRng",
    "PolicySpec",
    "PopulationTuner",
    "RolloutResult",
    "SCHEMA",
    "SuiteSpec",
    "TuneConfig",
    "TuneResult",
    "dump_jsonl",
    "is_suite_doc",
    "load_jsonl",
    "record_line",
    "stable_json",
    "summarize",
    "tune_suite",
    "validate_records",
]
