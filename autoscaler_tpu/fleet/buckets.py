"""Shape buckets: the compile-cost contract of the fleet service.

Production fleet traffic means arbitrary (P, G, R) request shapes, and
every distinct shape is a distinct XLA compile (ROADMAP item 5). The fleet
service therefore admits requests into a SMALL closed set of power-of-two
shape buckets: each request is exact-padded up to the smallest configured
bucket that fits it, so the steady-state compile-cache key set is bounded
by ``len(buckets)`` and ladder-rung pre-warm can touch every key at
startup — the first real request never compiles.

Exact-pad safety (the GL007 contract argument, restated for the fleet
operand set): a padded POD row carries ``mask=False`` in every group (the
scan's ``active`` gate — it can never place); a padded GROUP carries
``alloc=0`` and ``cap=0`` (``can_open = opened < 0`` is false, so it opens
nothing and schedules nothing); a padded RESOURCE column carries ``req=0``
against ``alloc=0`` (``0 <= 0`` fits — the column gates nothing, including
``ffd_scores``, which reads only the CPU/MEMORY axes). The scenario axis
pads with all-zero worlds. Demux is therefore a pure slice: the first
(P, G) block of scenario ``s`` IS tenant ``s``'s solo answer, byte for
byte — the property tests/test_fleet.py locks on randomized worlds.

Stdlib + numpy only; jax stays on the dispatch side (fleet/coalescer.py →
parallel/mesh.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

# the default bucket ladder: small interactive requests and a medium tier;
# deploy sites size their own via --fleet-shape-buckets
DEFAULT_BUCKETS = "64x8x8,256x16x16"

# the resident-arena prewarm ladder (snapshot/arena.py), same PxGxR grammar
# read as (pods, nodes, resources-cap); lives HERE so config/options.py can
# import the default without pulling jax (ONE source, like DEFAULT_BUCKETS).
# Deploy sites size their own via --arena-buckets.
DEFAULT_ARENA_BUCKETS = "64x16x8,1024x256x8"


class BucketError(ValueError):
    """A bucket spec string that doesn't describe a usable ladder."""


@dataclass(frozen=True, order=True)
class BucketSpec:
    """One (P, G, R) shape bucket. Ordering is lexicographic on (P, G, R),
    which makes "smallest fitting bucket" a min() over the fitting set.
    The static scan carry is ``max_nodes = P``: a node only opens when a
    pod is placed on it, so a tenant can never need more carry rows than
    it has pods — its own node budget rides the dynamic caps row."""

    pods: int
    groups: int
    resources: int

    def fits(self, P: int, G: int, R: int) -> bool:
        return P <= self.pods and G <= self.groups and R <= self.resources

    def cells(self) -> int:
        """Mask cells per scenario slot — the padding-waste denominator."""
        return self.pods * self.groups

    @property
    def key(self) -> str:
        return f"{self.pods}x{self.groups}x{self.resources}"


def pow2ceil(n: int) -> int:
    """Smallest power of two >= max(n, 1)."""
    return 1 << max(int(n) - 1, 0).bit_length()


def parse_buckets(spec: str) -> List[BucketSpec]:
    """``"64x8x8,256x16x16"`` → sorted BucketSpecs. Dimensions must be
    positive powers of two (the exact-pad rules and mesh divisibility both
    lean on it); duplicates collapse."""
    out = set()
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        dims = part.split("x")
        if len(dims) != 3:
            raise BucketError(
                f"bucket {part!r} must be PxGxR (e.g. 64x8x8)"
            )
        try:
            p, g, r = (int(d) for d in dims)
        except ValueError:
            raise BucketError(f"bucket {part!r} has non-integer dims") from None
        for name, v in (("P", p), ("G", g), ("R", r)):
            if v <= 0 or v != pow2ceil(v):
                raise BucketError(
                    f"bucket {part!r}: {name}={v} must be a positive power "
                    "of two (exact-pad + mesh divisibility)"
                )
        out.add(BucketSpec(p, g, r))
    if not out:
        raise BucketError(f"no buckets in spec {spec!r}")
    return sorted(out)


def format_buckets(buckets: Sequence[BucketSpec]) -> str:
    return ",".join(b.key for b in sorted(buckets))


def select_bucket(
    buckets: Sequence[BucketSpec], P: int, G: int, R: int
) -> Optional[BucketSpec]:
    """Smallest configured bucket admitting a (P, G, R) request; None when
    the request exceeds every bucket (the coalescer then mints an ad-hoc
    pow2 bucket — served correctly, just never pre-warmed)."""
    fitting = [b for b in buckets if b.fits(P, G, R)]
    return min(fitting) if fitting else None


def adhoc_bucket(P: int, G: int, R: int) -> BucketSpec:
    """The exact-pow2 envelope of an over-sized request."""
    return BucketSpec(pow2ceil(P), pow2ceil(G), pow2ceil(R))


def pad_operands(
    bucket: BucketSpec,
    pod_req: np.ndarray,     # [P, R] f32
    pod_masks: np.ndarray,   # [G, P] bool
    allocs: np.ndarray,      # [G, R] f32
    caps: np.ndarray,        # [G] i32
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """One tenant's exact operands → the bucket shape, zero-padded per the
    exact-pad rules above. Caller has already clamped ``caps`` with the
    tenant's own max_nodes (that clamp is what keeps bucket-carry padding
    answer-preserving)."""
    P, R = pod_req.shape
    G = pod_masks.shape[0]
    if not bucket.fits(P, G, R):
        raise BucketError(
            f"request (P={P}, G={G}, R={R}) exceeds bucket {bucket.key}"
        )
    req = np.zeros((bucket.pods, bucket.resources), np.float32)
    req[:P, :R] = pod_req
    masks = np.zeros((bucket.groups, bucket.pods), bool)
    masks[:G, :P] = pod_masks
    al = np.zeros((bucket.groups, bucket.resources), np.float32)
    al[:G, :R] = allocs
    cp = np.zeros((bucket.groups,), np.int32)
    cp[:G] = caps
    return req, masks, al, cp


def padding_waste(
    bucket: BucketSpec, shapes: Sequence[Tuple[int, int, int]], batch_slots: int
) -> float:
    """Fraction of the batch's (S × P × G) mask cells that are padding —
    the fleet's efficiency tax, reported per batch (metrics + scorer).
    ``shapes`` are the real (P, G, R) triples of the coalesced requests;
    empty scenario slots count fully."""
    total = float(batch_slots * bucket.cells())
    if total <= 0:
        return 0.0
    real = sum(min(p, bucket.pods) * min(g, bucket.groups) for p, g, _ in shapes)
    return max(0.0, 1.0 - real / total)
