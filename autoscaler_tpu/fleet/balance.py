"""Health-weighted multi-endpoint balancing for the fleet client.

PR 14 gave ``TpuSimulationClient`` a *static* failover list: endpoint
order was fixed, first attempts always went to the current endpoint, and
a flapping replica kept eating first-attempt traffic until it happened to
fail at the exact moment a call went through it. This module replaces the
rotation with a per-endpoint **health scorer** feeding a
**power-of-two-choices** weighted picker with breaker-style outlier
ejection (ARCHITECTURE.md "Fleet HA"):

- **Scorer inputs** (per endpoint, mutated only under the balancer lock):
  EWMA of successful-call latency, windowed error rate over the last
  ``ERROR_WINDOW`` outcomes, the consecutive-UNAVAILABLE streak, and a
  drain-observed bit (the endpoint said "I am shutting down"). The score
  is seconds-shaped — latency plus penalty terms — so "healthier" is
  simply "lower".
- **Pick policy**: power-of-two-choices — draw two distinct candidates
  from the eligible set, keep the lower score (ties break on index, so
  picks are a pure function of the rng stream). P2C gives most traffic to
  healthy endpoints without the herd-to-the-single-best behavior a full
  argmin would have the instant one endpoint's EWMA dips.
- **Ejection + cooldown**: each endpoint owns a
  :class:`~autoscaler_tpu.utils.circuit.CircuitBreaker`. Consecutive
  failures trip it OPEN and the endpoint leaves the eligible set; after
  the cooldown at most ONE pick per cooldown window is admitted as the
  half-open probe (the breaker's single-flight slot), whose outcome
  decides recovery vs. another OPEN window. When every endpoint is
  ejected the picker degrades to least-bad-score — the client must still
  send somewhere.

Determinism: the balancer holds no ambient state — ``clock`` and ``rng``
are injected-parameter seams (GL001), so the pick sequence is a pure
function of the (pick, record) call order, the clock readings, and the
rng stream. The loadgen fleet driver seeds both from the scenario seed,
which is what makes the fleet ledger's endpoint-choice column replay
byte-identically (hack/verify.sh diffs it).
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Set

from autoscaler_tpu.utils.circuit import BreakerState, CircuitBreaker

# sliding outcome window per endpoint (1 = failure, 0 = success): short
# enough that recovery shows within tens of calls, long enough that one
# blip doesn't read as a 100% error rate
ERROR_WINDOW = 32
# EWMA smoothing for successful-call latency
EWMA_ALPHA = 0.3
# score penalty terms, seconds-shaped so they compose with the EWMA:
# a fully erroring endpoint reads as +1s, each consecutive UNAVAILABLE
# adds half a second (capped), a drain-observed endpoint is effectively
# last-resort until a success clears the bit
ERROR_RATE_PENALTY_S = 1.0
UNAVAILABLE_PENALTY_S = 0.5
UNAVAILABLE_PENALTY_CAP = 8
DRAIN_PENALTY_S = 30.0


class EndpointHealth:
    """One endpoint's scorer inputs plus its ejection breaker. NOT
    thread-safe by itself: every mutation happens under the owning
    balancer's lock (the GL004 discipline — verdicts and state move
    together)."""

    def __init__(
        self, name: str, failure_threshold: int, cooldown_s: float
    ) -> None:
        self.name = name
        self.ewma_latency_s = 0.0
        self.outcomes: deque = deque(maxlen=ERROR_WINDOW)
        self.consecutive_unavailable = 0
        self.drain_observed = False
        self.breaker = CircuitBreaker(
            failure_threshold=failure_threshold,
            cooldown_s=cooldown_s,
            name=f"endpoint:{name}",
        )

    def error_rate(self) -> float:
        if not self.outcomes:
            return 0.0
        return sum(self.outcomes) / len(self.outcomes)

    def score(self) -> float:
        """Seconds-shaped health score — lower is healthier. A fresh
        endpoint scores 0.0 (cold endpoints look attractive, which is how
        a recovered replica earns traffic back)."""
        s = self.ewma_latency_s
        s += self.error_rate() * ERROR_RATE_PENALTY_S
        s += UNAVAILABLE_PENALTY_S * min(
            self.consecutive_unavailable, UNAVAILABLE_PENALTY_CAP
        )
        if self.drain_observed:
            s += DRAIN_PENALTY_S
        return s

    def note_success(self, latency_s: float) -> None:
        if self.ewma_latency_s == 0.0:
            self.ewma_latency_s = latency_s
        else:
            self.ewma_latency_s += EWMA_ALPHA * (
                latency_s - self.ewma_latency_s
            )
        self.outcomes.append(0)
        self.consecutive_unavailable = 0
        # a served request IS the evidence the drain completed (restart
        # finished, new process admitting) — clear the bit
        self.drain_observed = False

    def note_failure(self, unavailable: bool, drain: bool) -> None:
        self.outcomes.append(1)
        if unavailable:
            self.consecutive_unavailable += 1
        if drain:
            self.drain_observed = True


class EndpointBalancer:
    """Health-weighted P2C picker over a fixed endpoint set.

    ``clock``/``rng`` are injected-parameter seams (GL001): production
    clients take the wall defaults; replay drivers inject the sim clock
    and a seeded uniform so pick sequences replay byte-identically.
    ``rng`` returns uniforms in [0, 1).

    Thread safety: all state moves under one lock — the client's worker
    threads pick/record concurrently with a failover rewriting health."""

    def __init__(
        self,
        endpoints: Sequence[str],
        clock: Callable[[], float] = time.monotonic,
        rng: Callable[[], float] = random.random,
        eject_failure_threshold: int = 3,
        eject_cooldown_s: float = 5.0,
    ) -> None:
        names = [str(e) for e in endpoints]
        if not names:
            raise ValueError("EndpointBalancer needs at least one endpoint")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate endpoints in {names}")
        self._clock = clock
        self._rng = rng
        self._lock = threading.Lock()
        self._order: List[str] = names
        self._health: Dict[str, EndpointHealth] = {
            n: EndpointHealth(n, eject_failure_threshold, eject_cooldown_s)
            for n in names
        }

    @property
    def endpoints(self) -> List[str]:
        return list(self._order)

    # -- picking --------------------------------------------------------------
    def pick(
        self,
        exclude: Sequence[str] = (),
        healthy_only: bool = False,
    ) -> Optional[str]:
        """Pick one endpoint by health-weighted power-of-two-choices.

        ``exclude`` removes endpoints already tried this call (failover).
        ``healthy_only`` additionally refuses ejected and drain-observed
        endpoints outright and returns None when no healthy candidate
        remains — the hedge-leg mode: a hedge fired at a draining sidecar
        burns deadline budget for a guaranteed UNAVAILABLE, so no hedge
        beats a doomed hedge. Without ``healthy_only`` the picker always
        returns SOMETHING when any non-excluded endpoint exists (the
        primary attempt must go somewhere, even in a full outage)."""
        skip: Set[str] = set(exclude)
        with self._lock:
            now = self._clock()
            candidates = [n for n in self._order if n not in skip]
            if not candidates:
                return None
            eligible = [
                n for n in candidates
                if self._health[n].breaker.state is BreakerState.CLOSED
            ]
            if healthy_only:
                eligible = [
                    n for n in eligible
                    if not self._health[n].drain_observed
                    and self._health[n].consecutive_unavailable == 0
                ]
                if not eligible:
                    return None
                return self._p2c_locked(eligible)
            # a cooled-down ejected endpoint takes the pick OUTRIGHT as
            # its half-open probe: a probe that had to win a score
            # contest against a healthy peer would never run (its score
            # is exactly what ejected it), and the breaker's
            # single-flight slot already bounds probe traffic to one in
            # flight per cooldown window — a recovering replica is never
            # stampeded, and never starved of its comeback either.
            for n in candidates:
                h = self._health[n]
                if (
                    h.breaker.state is not BreakerState.CLOSED
                    and h.breaker.allow(now)
                ):
                    return n
            if eligible:
                return self._p2c_locked(eligible)
            # everything ejected and still cooling down: least-bad by
            # score — the call has to go somewhere
            return self._p2c_locked(candidates)

    def _p2c_locked(self, pool: List[str]) -> str:
        """Power-of-two-choices over ``pool`` (caller holds the lock):
        draw two DISTINCT candidates from the rng stream, keep the lower
        score; a tie keeps the FIRST draw — the first draw is uniform, so
        a fully-healthy (all-tied) fleet spreads picks evenly instead of
        herding onto the lowest index, and the choice stays a pure
        function of the rng stream. One candidate short-circuits without
        an rng draw, keeping the stream alignment predictable."""
        if len(pool) == 1:
            return pool[0]
        n = len(pool)
        i = min(int(self._rng() * n), n - 1)
        # second draw over the remaining n-1 slots, offset past i: always
        # distinct, exactly two rng draws per pick
        j = (i + 1 + min(int(self._rng() * (n - 1)), n - 2)) % n
        a, b = pool[i], pool[j]
        return b if self._health[b].score() < self._health[a].score() else a

    def pick_hedge(self, primary: str) -> Optional[str]:
        """The hedge-leg target: a HEALTHY endpoint other than the
        primary, or None (skip the hedge — see pick(healthy_only))."""
        return self.pick(exclude=(primary,), healthy_only=True)

    # -- outcome reporting ----------------------------------------------------
    def record_success(self, endpoint: str, latency_s: float) -> None:
        with self._lock:
            h = self._health.get(endpoint)
            if h is None:
                return
            h.note_success(max(float(latency_s), 0.0))
            h.breaker.record_success(self._clock())

    def record_failure(
        self, endpoint: str, unavailable: bool = True, drain: bool = False
    ) -> None:
        """One failed call at ``endpoint``. ``unavailable`` marks the
        UNAVAILABLE statuses (connection refused, dead process, drain) that
        feed the consecutive-streak input; a deadline blowout passes
        False — it is a slowness signal, not an outage signal. ``drain``
        sets the drain-observed bit (the endpoint SAID it is shutting
        down) so hedges and healthy-only picks route around it until a
        success clears it."""
        with self._lock:
            h = self._health.get(endpoint)
            if h is None:
                return
            h.note_failure(unavailable, drain)
            h.breaker.record_failure(self._clock())

    def record_drain(self, endpoint: str) -> None:
        self.record_failure(endpoint, unavailable=True, drain=True)

    def record_response(self, endpoint: str) -> None:
        """The endpoint ANSWERED, but with a status that is neither
        success-shaped nor outage-shaped (quota shed, invalid argument,
        internal error): the process is alive at the transport level.
        Resolves a held half-open probe (record_neutral) and clears the
        UNAVAILABLE streak — an answering endpoint is not mid-outage —
        but touches neither the EWMA nor the error window (an admission
        shed says nothing about latency) nor the drain bit (only a real
        success clears that). Without this, a probe that came back
        RESOURCE_EXHAUSTED would hold the single-flight slot forever and
        wedge the endpoint out of rotation permanently."""
        with self._lock:
            h = self._health.get(endpoint)
            if h is None:
                return
            h.consecutive_unavailable = 0
            h.breaker.record_neutral(self._clock())

    def release(self, endpoint: str) -> None:
        """The picked endpoint was never driven to an outcome (its hedge
        leg was cancelled after the other leg won): return a held
        half-open probe slot so a later pick can probe. Without it a
        cancelled probe leg wedges the endpoint HALF_OPEN forever — no
        outcome will ever arrive to resolve it."""
        with self._lock:
            h = self._health.get(endpoint)
            if h is None:
                return
            h.breaker.release_probe(self._clock())

    # -- observability --------------------------------------------------------
    def healthy(self, endpoint: str) -> bool:
        """Hedge-grade health: not ejected, no drain observed, no live
        UNAVAILABLE streak."""
        with self._lock:
            h = self._health.get(endpoint)
            if h is None:
                return False
            return (
                h.breaker.state is BreakerState.CLOSED
                and not h.drain_observed
                and h.consecutive_unavailable == 0
            )

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Per-endpoint scorer inputs + verdicts, sorted-key-safe for
        reports (consumed through sorted() only)."""
        with self._lock:
            out: Dict[str, Dict[str, object]] = {}
            for name in self._order:
                h = self._health[name]
                out[name] = {
                    "score": round(h.score(), 6),
                    "ewma_latency_s": round(h.ewma_latency_s, 6),
                    "error_rate": round(h.error_rate(), 4),
                    "consecutive_unavailable": h.consecutive_unavailable,
                    "drain_observed": h.drain_observed,
                    "breaker": h.breaker.state.value,
                }
            return out
