"""The coalescing multi-tenant estimator service.

Many independent estimate/what-if requests → one sharded mesh dispatch.
The pipeline (ARCHITECTURE.md "Fleet serving"):

    admission → bucket → dispatch → demux

1. **Admission**: ``submit()`` parks a request (lock-disciplined queue,
   graftlint GL004) and returns a ticket. The RPC path runs a window
   thread that flushes the queue every coalescing window; deterministic
   drivers (loadgen, tests) call ``flush()`` themselves — batch formation
   is a pure function of submission order, which is what makes fleet
   decision ledgers byte-identical across replays.
2. **Bucketing**: each request is exact-padded to the smallest configured
   power-of-two (P, G, R) bucket (fleet/buckets.py carries the safety
   argument), same-bucket requests are chunked into batches of
   ``batch_scenarios`` scenario slots, and empty slots pad with zero
   worlds — one compiled kernel shape per bucket, pre-warmable.
3. **Dispatch**: one ``ffd_binpack_scenarios`` mesh dispatch per batch
   (parallel/mesh.fleet_batch_estimate), walked down a circuit-broken
   two-rung ladder — the batched device kernel, then the serial
   per-scenario oracle twin (estimator/reference_impl). Every rung shares
   the one FFD order spec, so a faulted batch degrades with IDENTICAL
   per-tenant verdicts: batch isolation means a device fault costs the
   batch latency, never a co-batched tenant's answer.
4. **Demux**: tenant ``s``'s answer is the ``[:G, :P]`` slice of scenario
   ``s`` — plus what-if cost ranking when the request carried prices.

Time is injected (``clock``/``sleep`` parameter defaults — the GL001
sanctioned seam; ``tick(now)`` feeds the breaker cooldowns) so fault
scenarios replay byte-for-byte on the loadgen driver's simulated clock.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from autoscaler_tpu import trace
from autoscaler_tpu.estimator.ladder import RUNG_PYTHON, RUNG_XLA, KernelLadder
from autoscaler_tpu.fleet.admission import AdmissionController, partition_expired
from autoscaler_tpu.fleet.buckets import (
    DEFAULT_BUCKETS,
    BucketSpec,
    adhoc_bucket,
    pad_operands,
    padding_waste,
    parse_buckets,
    select_bucket,
)
from autoscaler_tpu.fleet.tiers import parse_tiers
from autoscaler_tpu.fleet.errors import (
    SHED_DEADLINE,
    SHED_DRAINING,
    SHED_QUEUE_FULL,
    TICKET_ABANDONED,
    TICKET_EXPIRED,
    TICKET_FAILED,
    TICKET_RESOLVED,
    FleetDeadlineError,
    FleetDrainError,
    FleetError,
    FleetOverloadError,
)
from autoscaler_tpu.metrics import metrics as metrics_mod

# route labels on the estimator_kernel_route vocabulary pattern: which lane
# served a coalesced batch (perf-observatory records key on these)
ROUTE_BATCHED = "fleet_batched"
ROUTE_ORACLE = "fleet_oracle"

# the aggregate tenant label past --fleet-max-tenant-labels: a misbehaving
# fleet (or an abusive tenant-id generator) collapses into ONE series
# instead of exploding /metrics exposition
OVERFLOW_TENANT = "__overflow__"


@dataclass
class FleetRequest:
    """One tenant's estimate question, in packed-tensor form (the same
    operand set rpc Estimate carries, plus identity and optional what-if
    prices)."""

    tenant_id: str
    pod_req: np.ndarray          # [P, R] f32
    pod_masks: np.ndarray        # [G, P] bool
    template_allocs: np.ndarray  # [G, R] f32
    node_caps: np.ndarray        # [G] i32
    max_nodes: int
    prices: Optional[np.ndarray] = None  # [G] f32 — present = what-if ranking
    # origin trace context ("<trace_id>:<span_id>", trace.current_context):
    # the RPC path decodes it from the wire, programmatic submitters inside
    # a traced tick get it captured automatically at submit() — it parents
    # the shared fleetDispatch span's links and the SLI exemplars
    trace_context: str = ""
    # remaining deadline budget in seconds at submission (the RPC path
    # passes gRPC's context.time_remaining(), driver paths pass the
    # request's own budget; None = no deadline). The coalescer converts it
    # to an absolute instant on ITS injected clock, so expiry shedding is
    # deterministic under the loadgen sim clock.
    deadline_s: Optional[float] = None

    def shape(self) -> Tuple[int, int, int]:
        P, R = self.pod_req.shape
        return P, self.pod_masks.shape[0], R


@dataclass
class FleetAnswer:
    """One tenant's demuxed verdict plus batch provenance (observability
    fields — everything above ``bucket`` is byte-compared against solo)."""

    node_counts: np.ndarray      # [G] i32
    scheduled: np.ndarray        # [G, P] bool
    bucket: str = ""
    batch_size: int = 0          # co-batched real requests
    padding_waste: float = 0.0   # padded-cell fraction of the batch
    route: str = ROUTE_BATCHED   # which ladder rung served the batch
    best_group: int = -1         # what-if: argmin cost (prices present)
    best_cost: float = 0.0


class FleetTicket:
    """The demux hand-back: admission returns immediately, the answer
    arrives when the request's batch dispatches."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self._answer: Optional[FleetAnswer] = None
        self._error: Optional[BaseException] = None
        # wall stamps (time.perf_counter — the sanctioned measurement
        # clock, never a replay artifact): admission, dispatch, and
        # resolution, so a caller can split its true service latency into
        # queue wait vs service even when its batch dispatched before
        # other buckets in the same flush
        self.submitted_wall: float = 0.0
        self.dispatched_wall: float = 0.0
        self.resolved_wall: float = 0.0
        # lifecycle stamps on the submitter's timeline clock (captured at
        # submit via trace.timeline_clock) — DETERMINISTIC under the
        # loadgen drivers' synthetic clocks, so the queue/service
        # decomposition can ride ledgers and SLO windows byte-stably:
        # submit → admit (queued) → dispatch (batch walk begins) → demux
        # (this ticket's slice cut) → resolve (answer/error visible).
        # ONE clock serves all five stamps even when dispatch happens on
        # the (untraced) window thread — mixing the submitter's timeline
        # with the bare-monotonic fallback would make the deltas garbage.
        self.t_submit: float = 0.0
        self.t_admit: float = 0.0
        self.t_dispatch: float = 0.0
        self.t_demux: float = 0.0
        self.t_resolve: float = 0.0
        # the captured stamp clock (seated by submit(); the coalescer's
        # injected clock when the submitter ran outside any trace)
        self.stamp_clock: Callable[[], float] = time.monotonic
        # origin trace context (copied from the request at submit) — the
        # span-link + exemplar identity of this ticket
        self.trace_context: str = ""
        # quota tier of the submitting tenant ("" when tiers are off) —
        # the tier label on the lifecycle SLI series and ledger rows
        self.tier: str = ""
        # absolute expiry instant on the COALESCER's injected clock (seated
        # by submit from FleetRequest.deadline_s; None = no deadline) —
        # flush/_dispatch_batch shed past-deadline tickets typed instead of
        # spending batch slots on answers nobody is waiting for
        self.deadline_ts: Optional[float] = None
        # abandonment: result(timeout) raising TimeoutError marks the
        # caller DEPARTED. A late resolve still completes the ticket (a
        # polling retry must never hang) but its lifecycle is counted
        # `abandoned`, not stamped into SLIs/exemplars as a fake good event
        self._state_lock = threading.Lock()
        self._abandoned = False

    @property
    def abandoned(self) -> bool:
        with self._state_lock:
            return self._abandoned

    def done(self) -> bool:
        """True once the ticket reached a terminal state (answer, typed
        failure, or typed shed) — the zero-hung-tickets audit reads this."""
        return self._done.is_set()

    def resolve(self, answer: FleetAnswer) -> bool:
        """Deliver the answer. Returns True when the caller was still
        waiting (lifecycle SLIs may be stamped), False when the ticket was
        abandoned — taken under the state lock so a ``result`` timing out
        concurrently cannot be half-counted on both sides."""
        with self._state_lock:
            abandoned = self._abandoned
            self._answer = answer
            self.resolved_wall = time.perf_counter()
            self._done.set()
        return not abandoned

    def fail(self, error: BaseException) -> bool:
        with self._state_lock:
            abandoned = self._abandoned
            self._error = error
            self.resolved_wall = time.perf_counter()
            self._done.set()
        return not abandoned

    def result(self, timeout: Optional[float] = None) -> FleetAnswer:
        if not self._done.wait(timeout):
            # atomic vs a concurrent resolve(): only a ticket that is
            # STILL unresolved is marked abandoned — if the answer landed
            # between the wait and here, the caller can still read it on
            # a retry and the lifecycle observation stays honest
            with self._state_lock:
                if not self._done.is_set():
                    self._abandoned = True
                    raise TimeoutError(
                        "fleet answer not ready within the deadline"
                    )
        with self._state_lock:
            error, answer = self._error, self._answer
        if error is not None:
            raise error
        assert answer is not None
        return answer


class FleetCoalescer:
    """One coalescer per serving process. ``mesh`` is the device mesh the
    batched dispatches shard over (None = single-device). ``ladder`` is a
    KernelLadder whose ``xla``/``python`` breakers guard the two fleet
    rungs; loadgen installs its fault hook there. ``observatory`` (a
    perf.PerfObservatory) sees every batch dispatch, which is where the
    per-bucket compile cache hit/miss telemetry comes from — each bucket is
    one (route, shape-signature) key."""

    def __init__(
        self,
        buckets: str = DEFAULT_BUCKETS,
        window_s: float = 0.005,
        batch_scenarios: int = 8,
        mesh: Any = None,
        metrics: Any = None,
        observatory: Any = None,
        ladder: Optional[KernelLadder] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        slo: Any = None,
        max_tenant_labels: int = 64,
        max_queue_depth: int = 0,
        tenant_qps: float = 0.0,
        tenant_burst: float = 0.0,
        tenant_tiers: str = "",
        latency_hook: Optional[Callable[[str], float]] = None,
    ) -> None:
        if batch_scenarios < 1:
            raise ValueError(f"batch_scenarios must be >= 1, got {batch_scenarios}")
        self.buckets: List[BucketSpec] = parse_buckets(buckets)
        self.window_s = float(window_s)
        self.batch_scenarios = int(batch_scenarios)
        self.mesh = mesh
        self.metrics = metrics
        self.observatory = observatory
        # slo (an slo.SloEngine, optional): every resolved/failed ticket
        # feeds one fleet_e2e SLI event on its timeline stamps
        self.slo = slo
        # tenant label cardinality bound for the per-tenant metric series
        # (--fleet-max-tenant-labels): the first N distinct tenants keep
        # their own label, the rest aggregate into OVERFLOW_TENANT.
        # 0 = unbounded (trusted closed fleets only).
        self.max_tenant_labels = int(max_tenant_labels)
        self.ladder = ladder or KernelLadder()
        self.ladder.bind_metrics(metrics)
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[Tuple[FleetRequest, FleetTicket]] = []
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # drain bit (GL004: flipped only under the queue lock): True from
        # the moment stop() begins until a start() re-arms — a submit that
        # loses the race against a drain gets the typed FleetDrainError,
        # never a ticket that nothing will ever flush
        self._draining = False
        self._prewarmed: List[str] = []
        self._configured = frozenset(self.buckets)
        # tenant id → metric label, insertion-ordered admission (GL004:
        # written only under the queue lock)
        self._tenant_labels: Dict[str, str] = {}
        # tenant quota tiers (--fleet-tenant-tiers JSON → TierPolicy;
        # None = tiers off, the global per-tenant quota stands). Tiers
        # supersede tenant_qps: per-tier shared buckets, queue-share
        # slices, default deadlines, and tier-priority flush ordering.
        self.tiers = parse_tiers(tenant_tiers)
        # per-tier queued counts (GL004: mutated only under the queue
        # lock, always in step with _pending) — the queue-share input
        self._tier_pending: Dict[str, int] = {}
        # deadline-aware admission: queue-depth bound + per-tenant token
        # buckets on the injected clock (fleet/admission.py; all state
        # mutated under the queue lock). Defaults keep both gates off.
        self.admission = AdmissionController(
            max_queue_depth=max_queue_depth,
            tenant_qps=tenant_qps,
            tenant_burst=tenant_burst,
            window_s=self.window_s,
            # same bound AND same semantics as the metric-label guard:
            # 0 = unbounded (every tenant gets its own quota bucket)
            max_tenants=self.max_tenant_labels,
            tiers=self.tiers,
        )
        # chaos seam (loadgen rpc_slow): tenant_id → extra service seconds
        # folded into the demux/resolve timeline stamps — simulated RPC
        # slowness that reaches the SLIs/SLO deterministically
        self.latency_hook = latency_hook

    # -- wiring ---------------------------------------------------------------
    @classmethod
    def from_options(cls, options, **kwargs) -> "FleetCoalescer":
        """Build (and pre-warm, per ``fleet_prewarm``) a coalescer from
        AutoscalingOptions — the --fleet-* flag surface."""
        co = cls(
            buckets=options.fleet_shape_buckets,
            window_s=options.fleet_coalesce_window_ms / 1000.0,
            batch_scenarios=options.fleet_batch_scenarios,
            max_tenant_labels=options.fleet_max_tenant_labels,
            max_queue_depth=options.fleet_max_queue_depth,
            tenant_qps=options.fleet_tenant_qps,
            tenant_burst=options.fleet_tenant_burst,
            tenant_tiers=options.fleet_tenant_tiers,
            **kwargs,
        )
        if options.fleet_prewarm:
            co.prewarm()
        return co

    def tick(self, now: float) -> None:
        """Advance the ladder clock (wall in production, simulated under
        loadgen — breaker cooldowns replay byte-for-byte)."""
        self.ladder.tick(now)

    def degraded(self) -> List[str]:
        return self.ladder.degraded()

    def prewarmed(self) -> List[str]:
        with self._lock:
            return list(self._prewarmed)

    # -- admission ------------------------------------------------------------
    def submit(self, request: FleetRequest) -> FleetTicket:
        """Park one request for the next coalesced dispatch. The queue is
        the only cross-thread state; tickets are resolved outside the lock.

        Admission is deadline-aware and typed: a draining coalescer raises
        :class:`FleetDrainError` (fail over, don't wait), a full queue or
        an over-quota tenant raises :class:`FleetOverloadError` carrying
        ``retry_after_s``, and a request whose deadline budget is already
        spent raises :class:`FleetDeadlineError` — a caller NEVER gets a
        ticket that nothing will resolve.

        Trace-context capture: a request that arrived without an explicit
        origin context (the RPC path decodes one from the wire) inherits
        the ambient one — a submitter inside a traced tick (loadgen fleet
        driver, gym rollouts) gets its span linked from the shared
        fleetDispatch span for free."""
        ticket = FleetTicket()
        if not request.trace_context:
            ctx = trace.current_context()
            if ctx is not None:
                request.trace_context = ctx
        ticket.trace_context = request.trace_context
        # capture the submitter's clock domain ONCE: every later stamp —
        # including those taken on the window thread, which has no active
        # trace — reads this same clock, so the queue/service deltas are
        # real durations in one domain (synthetic under loadgen, the
        # serving tracer's wall clock on the RPC path)
        ticket.stamp_clock = trace.timeline_clock() or self._clock
        ticket.t_submit = ticket.stamp_clock()
        ticket.submitted_wall = time.perf_counter()
        tier = (
            self.tiers.tier_for(request.tenant_id)
            if self.tiers is not None else None
        )
        if tier is not None:
            ticket.tier = tier.name
            if request.deadline_s is None and tier.default_deadline_s > 0:
                # the tier's latency contract binds even clients that
                # submitted without a budget of their own
                request.deadline_s = tier.default_deadline_s
        now = self._clock()
        if request.deadline_s is not None:
            ticket.deadline_ts = now + max(float(request.deadline_s), 0.0)
        with self._lock:
            if ticket.deadline_ts is not None and now >= ticket.deadline_ts:
                # a dead-on-arrival budget: shed typed BEFORE the
                # drain/depth/quota gates — a request nobody can answer in
                # time must not burn a quota token or count twice in the
                # admission tallies
                verdict = self.admission.admit_expired(request.tenant_id)
            else:
                verdict = self.admission.admit(
                    request.tenant_id, len(self._pending), now,
                    draining=self._draining,
                    tier_depth=(
                        self._tier_pending.get(tier.name, 0)
                        if tier is not None else 0
                    ),
                )
            tenant = self._tenant_label_locked(request.tenant_id)
            if verdict.admitted:
                self._pending.append((request, ticket))
                if tier is not None:
                    self._tier_pending[tier.name] = (
                        self._tier_pending.get(tier.name, 0) + 1
                    )
                if self.metrics is not None:
                    # published under the queue lock so a concurrent
                    # flush() can't interleave its set(0) with a stale
                    # depth — the gauge and the queue move together
                    # (metric series take their own inner lock; the order
                    # is always queue → series)
                    self.metrics.fleet_queue_depth.set(
                        float(len(self._pending))
                    )
                self._cond.notify()
        if self.metrics is not None:
            # the tier label only exists when a tier policy is configured
            # (tier names are a closed small set — the cardinality bound
            # stands); tierless deployments keep the PR-14 series shape
            labels = dict(outcome=verdict.outcome, tenant=tenant)
            if self.tiers is not None:
                labels["tier"] = verdict.tier
            self.metrics.fleet_admission_total.inc(**labels)
        if not verdict.admitted:
            raise self._shed_error(verdict, request.tenant_id)
        ticket.t_admit = ticket.stamp_clock()
        return ticket

    @staticmethod
    def _shed_error(verdict, tenant_id: str) -> Exception:
        """Admission verdict → the typed rejection the RPC layer maps to
        a gRPC status (errors.py documents the mapping)."""
        if verdict.outcome == SHED_DRAINING:
            return FleetDrainError(
                "fleet coalescer draining: sidecar shutting down, fail "
                "over to another endpoint"
            )
        if verdict.outcome == SHED_DEADLINE:
            return FleetDeadlineError(
                f"tenant {tenant_id} request deadline already expired at "
                "admission"
            )
        detail = (
            "coalescing queue full"
            if verdict.outcome == SHED_QUEUE_FULL
            else f"tenant {tenant_id} over quota"
        )
        return FleetOverloadError(
            f"{detail}; retry after {verdict.retry_after_s:.3f}s",
            retry_after_s=verdict.retry_after_s,
            outcome=verdict.outcome,
        )

    def _tenant_label_locked(self, tenant_id: str) -> str:
        """The cardinality bound (caller holds the queue lock): the first
        ``max_tenant_labels`` distinct tenants keep their own metric label;
        later arrivals aggregate into OVERFLOW_TENANT. First-come admission
        is deterministic under replay (submission order IS the ledger
        order). Overflow tenants are NOT memoized — once the admission set
        is full it stays full, so membership answers every later lookup
        and recording each abusive tenant id would grow this dict without
        bound (the exact attack the label bound exists to stop)."""
        label = self._tenant_labels.get(tenant_id)
        if label is not None:
            return label
        if (
            self.max_tenant_labels > 0
            and len(self._tenant_labels) >= self.max_tenant_labels
        ):
            return OVERFLOW_TENANT
        self._tenant_labels[tenant_id] = tenant_id
        return tenant_id

    def tenant_label(self, tenant_id: str) -> str:
        with self._lock:
            return self._tenant_label_locked(tenant_id)

    def tier_name(self, tenant_id: str) -> str:
        """The tenant's quota tier ("" when tiers are off) — ledger rows
        and reports key sheds on it."""
        if self.tiers is None:
            return ""
        return self.tiers.tier_for(tenant_id).name

    def admission_snapshot(self) -> Dict[str, int]:
        """Lifetime admission-outcome tallies, read under the queue lock
        (the controller itself is lock-free by contract)."""
        with self._lock:
            return self.admission.snapshot()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- the coalescing window (RPC path) -------------------------------------
    def start(self) -> None:
        """EXPLICIT start: re-arms a drained coalescer (the one way out of
        the drain state) and runs the window thread. Per-request revival
        paths must use :meth:`ensure_running` instead — it refuses to
        un-drain."""
        with self._lock:
            self._draining = False
            if self.metrics is not None:
                self.metrics.fleet_draining.set(0.0)
        self.ensure_running()

    def ensure_running(self) -> bool:
        """Run the window thread UNLESS draining (atomic with the drain
        bit): whenever the queue is non-empty it waits one coalescing
        window (letting co-tenant requests pile in), then flushes. A
        thread that died (it should not — the loop absorbs flush errors)
        is revived, not treated as running. Returns False while draining —
        a racing RPC must NOT resurrect a stopping coalescer (its submit
        gets the typed drain rejection instead)."""
        with self._lock:
            if self._draining:
                return False
            if self._thread is not None and self._thread.is_alive():
                return True
            self._running = True
            self._thread = threading.Thread(
                target=self._window_loop, name="fleet-coalescer", daemon=True
            )
            thread = self._thread
        thread.start()
        return True

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def stop(self) -> None:
        """The drain sequence: (1) flip the drain bit under the queue lock
        — from this instant every submit, including one racing this very
        call, gets the typed FleetDrainError instead of a ticket nothing
        will flush; (2) stop and join the window thread; (3) flush every
        in-flight ticket so the queue empties with answers, not hangs."""
        with self._lock:
            self._draining = True
            if self.metrics is not None:
                # order queue-state → series, same as the depth gauge rule
                self.metrics.fleet_draining.set(1.0)
            self._running = False
            thread = self._thread
            self._thread = None
            self._cond.notify_all()
        if thread is not None:
            thread.join(timeout=5.0)
        self.flush()  # drain stragglers so no ticket hangs

    def _window_loop(self) -> None:
        import logging

        while True:
            with self._lock:
                if not self._running:
                    return
                if not self._pending:
                    self._cond.wait(timeout=0.1)
                    continue
            self._sleep(self.window_s)
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — the window thread IS the
                # service: an escaping flush error (per-batch errors already
                # resolve their tickets) must not kill it, or every later
                # request hangs until deadline for the process lifetime
                logging.getLogger("fleet").exception(
                    "fleet window flush failed; the loop continues"
                )

    # -- bucket + dispatch + demux --------------------------------------------
    def flush(self, limit: Optional[int] = None) -> int:
        """Dispatch pending requests; returns the request count served.
        Deterministic: batches form per bucket in submission order, buckets
        dispatch in sorted key order — replaying the same submission
        sequence forms the same batches.

        Expired tickets are shed FIRST, typed (FleetDeadlineError), before
        they consume batch slots — shedding runs on the injected clock so
        it replays byte-identically. ``limit`` bounds how many live
        requests this flush serves (submission order; the rest stay
        queued) — the overload bench uses it to model a service slower
        than its arrival rate; production flushes pass None.

        With tiers configured the live queue is served in
        (tier shed_priority, submission order): gold dispatches first and
        under bounded capacity the bronze tail is what stays queued (and
        eventually expires) — "shed order under queue pressure prefers low
        tiers". The sort key is a pure function of submission order plus
        the static tier table, so replays stay byte-identical."""
        now = self._clock()
        with self._lock:
            live, expired = partition_expired(self._pending, now)
            if self.tiers is not None and len(live) > 1:
                # sorted() is stable: within a tier, submission order holds
                live = sorted(
                    live,
                    key=lambda rt: self.tiers.tier_for(
                        rt[0].tenant_id
                    ).shed_priority,
                )
            if limit is not None and limit < len(live):
                drained, rest = live[:limit], live[limit:]
            else:
                drained, rest = live, []
            self._pending = rest
            if self.tiers is not None:
                counts: Dict[str, int] = {}
                for req, _ in rest:
                    name = self.tiers.tier_for(req.tenant_id).name
                    counts[name] = counts.get(name, 0) + 1
                self._tier_pending = counts
            if self.metrics is not None:
                self.metrics.fleet_queue_depth.set(float(len(rest)))
        for req, ticket in expired:
            self._shed_expired(req, ticket, now)
        if not drained:
            return 0
        by_bucket: Dict[BucketSpec, List[Tuple[FleetRequest, FleetTicket]]] = {}
        for req, ticket in drained:
            P, G, R = req.shape()
            bucket = select_bucket(self.buckets, P, G, R) or adhoc_bucket(P, G, R)
            by_bucket.setdefault(bucket, []).append((req, ticket))
        for bucket in sorted(by_bucket, key=lambda b: b.key):
            entries = by_bucket[bucket]
            for i in range(0, len(entries), self.batch_scenarios):
                self._dispatch_batch(bucket, entries[i : i + self.batch_scenarios])
        return len(drained)

    def _batch_slots(self, bucket: BucketSpec, n: int) -> int:
        """Scenario slots for one batch. Configured buckets always dispatch
        the full ``batch_scenarios`` so each holds ONE compiled shape
        (pre-warmable, cache-coherent). Ad-hoc buckets are one-off by
        definition — never pre-warmed, compile-on-arrival — so padding them
        to the full width would multiply the kernel work for nothing;
        they get the pow2 envelope of the actual request count."""
        from autoscaler_tpu.fleet.buckets import pow2ceil

        if bucket in self._configured:
            return self.batch_scenarios
        return min(pow2ceil(max(n, 1)), self.batch_scenarios)

    def _batch_operands(
        self,
        bucket: BucketSpec,
        entries: Sequence[Tuple[FleetRequest, FleetTicket]],
        S: int,
    ):
        scen_req = np.zeros((S, bucket.pods, bucket.resources), np.float32)
        scen_masks = np.zeros((S, bucket.groups, bucket.pods), bool)
        scen_allocs = np.zeros((S, bucket.groups, bucket.resources), np.float32)
        scen_caps = np.zeros((S, bucket.groups), np.int32)
        for s, (req, _) in enumerate(entries):
            # the tenant's own node budget becomes a dynamic cap (min with
            # its declared caps) so the shared static carry (= bucket P)
            # reproduces the solo max_nodes semantics exactly
            caps = np.minimum(
                req.node_caps.astype(np.int64), int(req.max_nodes)
            ).astype(np.int32)
            r, m, a, c = pad_operands(
                bucket, req.pod_req, req.pod_masks, req.template_allocs, caps
            )
            scen_req[s], scen_masks[s], scen_allocs[s], scen_caps[s] = r, m, a, c
        return scen_req, scen_masks, scen_allocs, scen_caps

    def _shed_expired(self, req: FleetRequest, ticket: FleetTicket,
                      now: float) -> None:
        """Fail one past-deadline ticket typed (DEADLINE_EXCEEDED — never a
        silent hang) and charge the bad-budget event on the injected clock
        so the shed replays byte-identically. Queue expiry is a TICKET
        outcome, not an admission verdict — the ticket was already counted
        `admitted`, so only fleet_ticket_outcomes_total moves here (an
        admission_total row too would make the verdicts stop summing to
        submits)."""
        ticket.t_resolve = ticket.stamp_clock()
        if self.slo is not None:
            from autoscaler_tpu.slo import SLI_FLEET_E2E

            self.slo.observe_event(SLI_FLEET_E2E, bad=True, now=now)
        delivered = ticket.fail(
            FleetDeadlineError(
                "fleet ticket deadline expired before its batch dispatched"
            )
        )
        self._count_outcome(
            TICKET_EXPIRED if delivered else TICKET_ABANDONED,
            req.tenant_id,
        )

    def _count_outcome(self, outcome: str, tenant_id: str) -> None:
        if self.metrics is not None:
            self.metrics.fleet_ticket_outcomes_total.inc(
                outcome=outcome, tenant=self.tenant_label(tenant_id)
            )

    def _dispatch_batch(
        self, bucket: BucketSpec, entries: Sequence[Tuple[FleetRequest, FleetTicket]]
    ) -> None:
        # second expiry gate (the first runs in flush): on the RPC path
        # the clock advances between flush partition and dispatch, and a
        # ticket that died waiting for earlier buckets in this same flush
        # must not consume a batch slot either
        now = self._clock()
        entries, expired = partition_expired(entries, now)
        for req, ticket in expired:
            self._shed_expired(req, ticket, now)
        if not entries:
            return
        try:
            slots = self._batch_slots(bucket, len(entries))
            scen_req, scen_masks, scen_allocs, scen_caps = self._batch_operands(
                bucket, entries, slots
            )
            waste = padding_waste(
                bucket, [req.shape() for req, _ in entries], slots
            )
            if self.metrics is not None:
                self.metrics.fleet_batch_size.observe(
                    float(len(entries)), bucket=bucket.key
                )
                self.metrics.fleet_padding_waste_ratio.observe(
                    waste, bucket=bucket.key
                )
                for req, _ in entries:
                    self.metrics.fleet_requests_total.inc(
                        bucket=bucket.key,
                        tenant=self.tenant_label(req.tenant_id),
                    )
            # the dispatch moment is shared by the batch (one walk serves
            # them all) but each ticket stamps it from its OWN captured
            # clock: bucket-wait = t_dispatch − t_admit per ticket
            dispatch_wall = time.perf_counter()
            for _, ticket in entries:
                ticket.t_dispatch = ticket.stamp_clock()
                ticket.dispatched_wall = dispatch_wall
            counts, scheduled, route = self._walk_ladder(
                bucket, scen_req, scen_masks, scen_allocs, scen_caps,
                batch=len(entries),
                # one batch, many traces: the shared fleetDispatch span
                # links every co-batched ticket's origin context
                links=[t.trace_context for _, t in entries if t.trace_context],
            )
        except Exception as e:  # noqa: BLE001 — whatever failed (operand
            # build, every rung), the batch's tickets must still resolve:
            # the RPC handlers are blocked on them, and an unresolved
            # ticket is a hang-until-deadline. The typed error rides each
            # ticket out.
            err = FleetError(f"no fleet rung served bucket {bucket.key}: {e}")
            err.__cause__ = e
            for req, ticket in entries:
                ticket.t_resolve = ticket.stamp_clock()
                if self.slo is not None:
                    # a failed batch is bad budget regardless of latency;
                    # the event timestamp rides the coalescer's injected
                    # clock (the burn windows' time base), not the
                    # timeline stamps (the latency measurement)
                    from autoscaler_tpu.slo import SLI_FLEET_E2E

                    self.slo.observe_event(
                        SLI_FLEET_E2E, bad=True, now=self._clock()
                    )
                delivered = ticket.fail(err)
                self._count_outcome(
                    TICKET_FAILED if delivered else TICKET_ABANDONED,
                    req.tenant_id,
                )
            return
        if self.metrics is not None:
            self.metrics.fleet_batches_total.inc(bucket=bucket.key, route=route)
        for s, (req, ticket) in enumerate(entries):
            answer = self._demux(
                req, counts[s], scheduled[s], bucket, len(entries), waste,
                route,
            )
            # chaos seam: injected rpc_slow latency lands in the timeline
            # stamps (deterministic under the sim clock) so slow service
            # reaches the SLIs/SLO exactly as real slowness would
            extra = (
                self.latency_hook(req.tenant_id)
                if self.latency_hook is not None else 0.0
            )
            ticket.t_demux = ticket.stamp_clock() + extra
            # resolve is stamped BEFORE the event fires so a caller
            # unblocked by result() always reads a complete stamp set
            ticket.t_resolve = ticket.stamp_clock() + extra
            delivered = ticket.resolve(answer)
            if delivered:
                # lifecycle SLIs fire only for a caller that was still
                # there — an abandoned ticket's late answer must not stamp
                # exemplars/SLO good events for a departed caller
                self._observe_lifecycle(req, ticket, bucket)
                self._count_outcome(TICKET_RESOLVED, req.tenant_id)
            else:
                self._count_outcome(TICKET_ABANDONED, req.tenant_id)

    def _observe_lifecycle(
        self, req: FleetRequest, ticket: FleetTicket, bucket: BucketSpec
    ) -> None:
        """Per-ticket request-lifecycle SLIs on the timeline stamps:
        queue wait (submit→dispatch: admission + coalescing window + bucket
        queue), service (dispatch→resolve: batched kernel + demux), and
        end-to-end — per-tenant histograms with OpenMetrics exemplars
        naming the origin trace, plus one fleet_e2e SLO event."""
        queue_wait = max(ticket.t_dispatch - ticket.t_submit, 0.0)
        service = max(ticket.t_resolve - ticket.t_dispatch, 0.0)
        e2e = max(ticket.t_resolve - ticket.t_submit, 0.0)
        if self.metrics is not None:
            tenant = self.tenant_label(req.tenant_id)
            parsed = trace.parse_context(ticket.trace_context)
            # quota-tier label only when a policy is configured (closed
            # small vocabulary — the SLI cardinality bound stands)
            extra = {"tier": ticket.tier} if self.tiers is not None else {}
            rows = (
                (self.metrics.fleet_queue_wait_seconds, queue_wait),
                (self.metrics.fleet_service_seconds, service),
                (self.metrics.fleet_e2e_seconds, e2e),
            )
            for series, value in rows:
                if parsed is None:
                    series.observe(
                        value, tenant=tenant, bucket=bucket.key, **extra
                    )
                else:
                    series.observe_with_exemplar(
                        value, str(parsed[0]), tenant=tenant,
                        bucket=bucket.key, **extra,
                    )
        if self.slo is not None:
            # latency judged from the timeline stamps; the event timestamp
            # rides the coalescer's injected clock — the same time base
            # the engine's burn windows (and the breaker cooldowns) use,
            # simulated under loadgen so the ledger replays byte-for-byte
            from autoscaler_tpu.slo import SLI_FLEET_E2E

            self.slo.observe(SLI_FLEET_E2E, e2e, now=self._clock())

    def _walk_ladder(
        self, bucket, scen_req, scen_masks, scen_allocs, scen_caps,
        batch: int, links: Sequence[str] = (),
    ):
        """Two-rung fleet ladder: the batched mesh kernel (``xla`` breaker),
        then the serial oracle twin (``python`` breaker). Same protocol as
        the estimator's walk — begin/record per rung, one fleetDispatch
        span per engagement — shrunk to the two routes a coalesced batch
        has. ``links`` carries the co-batched tickets' origin trace
        contexts (one batch, many traces): /tracez joins the tree from
        either side."""
        from autoscaler_tpu.parallel.mesh import fleet_batch_estimate

        # advance the breaker clock from the injected clock on EVERY walk:
        # the RPC serving path has no run_once to tick the ladder, and a
        # tripped batched rung must recover once cooldown_s of (wall or
        # simulated) time elapses — loadgen injects its sim clock here, so
        # trip→degrade→recover replays byte-for-byte
        self.ladder.tick(self._clock())

        M = bucket.pods  # static carry: a pod can open at most one node

        def batched():
            return fleet_batch_estimate(
                self.mesh, scen_req, scen_masks, scen_allocs, scen_caps, M
            )

        def oracle():
            from autoscaler_tpu.estimator.reference_impl import (
                scenario_binpack_reference,
            )

            return scenario_binpack_reference(
                scen_req, scen_masks, scen_allocs, M, scen_caps
            )

        last = None
        for rung, route, fn in (
            (RUNG_XLA, ROUTE_BATCHED, batched),
            (RUNG_PYTHON, ROUTE_ORACLE, oracle),
        ):
            span_attrs = dict(rung=rung, bucket=bucket.key, batch=batch)
            if links:
                # span links, comma-joined "<trace>:<span>" contexts in
                # submission order — deterministic under replay
                span_attrs["links"] = ",".join(links)
            with trace.span(
                metrics_mod.FLEET_DISPATCH, metrics=self.metrics,
                **span_attrs,
            ) as sp:
                engaged = self.ladder.begin(rung)
                if engaged == "breaker_open":
                    sp.set_attrs(outcome="skipped", reason="breaker_open")
                    last = FleetError(f"{rung} rung breaker open")
                    continue
                if engaged is not None:  # injected device-fault kind
                    sp.set_attrs(outcome="fault", reason=engaged)
                    last = FleetError(f"injected {engaged} on {rung} rung")
                    continue
                try:
                    counts, scheduled = self._observed_dispatch(route, fn, sp)
                except Exception as e:  # noqa: BLE001 — any rung failure descends
                    self.ladder.record_failure(rung)
                    sp.set_attrs(outcome="fault", reason="kernel_raised")
                    last = e
                    continue
                self.ladder.record_success(rung)
                sp.set_attrs(outcome="ok", route=route)
                return counts, scheduled, route
        raise last if last is not None else FleetError("no fleet rungs configured")

    def _observed_dispatch(self, route: str, fn, sp):
        """Run one rung under the perf observatory (when attached): the
        batched rung's kernel entry is @observed, so the observatory sees
        the concrete call — per-bucket shape signature, operand bytes,
        compile-cache verdict — exactly as estimator dispatches do."""
        obs = self.observatory
        if obs is None:
            return fn()
        from autoscaler_tpu.ops.telemetry import kernel_observer

        obs.clear_pending()
        t0 = trace.timeline_now()
        with kernel_observer(obs.note_kernel):
            out = fn()
        obs.on_dispatch(route, trace.timeline_now() - t0, span=sp)
        return out

    @staticmethod
    def _demux(
        req: FleetRequest, counts, scheduled, bucket: BucketSpec,
        batch: int, waste: float, route: str,
    ) -> FleetAnswer:
        P, G, R = req.shape()
        node_counts = np.asarray(counts[:G], np.int32).copy()
        sched = np.asarray(scheduled[:G, :P], bool).copy()
        answer = FleetAnswer(
            node_counts=node_counts,
            scheduled=sched,
            bucket=bucket.key,
            batch_size=batch,
            padding_waste=round(float(waste), 6),
            route=route,
        )
        if req.prices is not None and G > 0:
            # the what-if reduction of parallel/mesh.whatif_best_options,
            # host-side over the demuxed slice: price·count plus the
            # unscheduled penalty per group
            from autoscaler_tpu.parallel.mesh import UNSCHEDULED_PENALTY

            pending = P - sched.sum(axis=1)
            cost = (
                np.asarray(req.prices, np.float64) * node_counts.astype(np.float64)
                + UNSCHEDULED_PENALTY * pending.astype(np.float64)
            )
            answer.best_group = int(np.argmin(cost))
            answer.best_cost = float(cost[answer.best_group])
        return answer

    # -- pre-warm -------------------------------------------------------------
    def prewarm(self) -> List[str]:
        """Ladder-rung pre-warm: push one all-zero batch through every
        configured bucket so each (route, shape signature) compiles at
        startup — the first real request is a compile-cache hit (the perf
        observatory's per-bucket hit/miss series proves it). Returns the
        bucket keys warmed."""
        warmed: List[str] = []
        with trace.span(
            metrics_mod.FLEET_PREWARM, metrics=self.metrics,
            buckets=len(self.buckets),
        ):
            for bucket in self.buckets:
                S = self.batch_scenarios
                self._walk_ladder(
                    bucket,
                    np.zeros((S, bucket.pods, bucket.resources), np.float32),
                    np.zeros((S, bucket.groups, bucket.pods), bool),
                    np.zeros((S, bucket.groups, bucket.resources), np.float32),
                    np.zeros((S, bucket.groups), np.int32),
                    batch=0,
                )
                warmed.append(bucket.key)
        with self._lock:
            self._prewarmed = warmed
        if self.metrics is not None:
            self.metrics.fleet_prewarmed_buckets.set(float(len(warmed)))
        return warmed
