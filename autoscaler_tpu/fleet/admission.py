"""Deadline-aware admission control for the fleet coalescer.

The overload story (ARCHITECTURE.md "Fleet overload & drain"): ``submit``
used to accept unboundedly — a tenant storm grew the queue without limit,
every queued ticket eventually resolved (late), and the only backpressure
was the caller's own deadline silently expiring while the ticket still
consumed a batch slot. This module makes every rejection *typed* and
*priced*:

- :class:`FleetOverloadError` — the queue is full or the tenant is over
  its token-bucket quota; carries ``retry_after_s`` so the RPC layer can
  surface RESOURCE_EXHAUSTED with a concrete retry hint and the client
  can pace itself instead of hammering a drowning server.
- :class:`FleetDrainError` — the coalescer is draining (sidecar shutting
  down); maps to UNAVAILABLE with a drain detail, the client's signal to
  fail over to another endpoint rather than retry here.
- :class:`FleetDeadlineError` — the ticket's deadline expired while it
  was queued; the coalescer sheds it *before* it consumes a batch slot
  (typed DEADLINE_EXCEEDED, never a silent hang).

Determinism (graftlint GL001/GL010): the token buckets run on the
coalescer's injected clock — under the loadgen drivers that is the
simulated scenario clock, so quota sheds (and their retry-after values)
replay byte-identically. All controller state is mutated ONLY under the
coalescer's queue lock (GL004: the admission verdict and the queue move
together — a verdict computed outside the lock could admit into a queue
that a concurrent drain already closed).

Closed admission-outcome vocabulary (metric labels + ledger fields):
``admitted``, ``shed_queue_full``, ``shed_quota``, ``shed_draining``,
``shed_deadline``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from autoscaler_tpu.fleet.tiers import TierPolicy, TierSpec
from autoscaler_tpu.fleet.errors import (
    ADMIT_OK,
    SHED_DEADLINE,
    SHED_DRAINING,
    SHED_QUEUE_FULL,
    SHED_QUOTA,
    FleetDeadlineError,
    FleetDrainError,
    FleetOverloadError,
)

__all__ = [
    "ADMIT_OK",
    "SHED_DEADLINE",
    "SHED_DRAINING",
    "SHED_QUEUE_FULL",
    "SHED_QUOTA",
    "AdmissionController",
    "FleetDeadlineError",
    "FleetDrainError",
    "FleetOverloadError",
    "TokenBucket",
]

# the shared quota bucket tenants past the per-tenant bound fall into —
# same overflow discipline as the metric-label bound (coalescer
# OVERFLOW_TENANT): once the admission set is full it stays full, so an
# abusive tenant-id generator costs bounded memory AND shares one quota
OVERFLOW_BUCKET = "__overflow__"


class TokenBucket:
    """One tenant's request budget: ``rate`` tokens/second, ``burst``
    capacity. ``try_take`` runs on the injected clock (the caller passes
    ``now``) so refill arithmetic is a pure function of event times —
    replayable under the loadgen sim clock."""

    def __init__(self, rate: float, burst: float) -> None:
        if rate <= 0:
            raise ValueError(f"token rate must be positive, got {rate}")
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._tokens = self.burst
        self._last: Optional[float] = None

    def try_take(self, now: float) -> float:
        """Take one token. Returns 0.0 on success, else the seconds until
        the next token becomes available (the retry-after hint).

        ``_last`` only ever advances: callers may present out-of-order
        timestamps (the coalescer reads its clock before taking the queue
        lock, so two racing submits can arrive swapped), and rewinding
        would re-credit the interval between the stamps — a quota leak
        under exactly the concurrency quotas exist to police."""
        if self._last is None:
            self._last = now
        elif now > self._last:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return 0.0
        return (1.0 - self._tokens) / self.rate


@dataclass(frozen=True)
class AdmissionVerdict:
    """One submit's fate: the closed outcome label plus the retry hint
    (0.0 for admitted/draining — drain has no useful retry-here time).
    ``tier`` names the judged tenant's quota tier ("" when tiers are off)
    — the metric/ledger label."""

    outcome: str
    retry_after_s: float = 0.0
    tier: str = ""

    @property
    def admitted(self) -> bool:
        return self.outcome == ADMIT_OK


class AdmissionController:
    """Queue-depth + per-tenant-quota gate in front of the coalescing
    queue. NOT thread-safe by itself: every method is called under the
    coalescer's queue lock (the GL004 discipline documented in the module
    docstring), which also makes verdict order = submission order —
    deterministic under replay.

    ``max_queue_depth`` 0 disables the depth gate; ``tenant_qps`` 0
    disables quotas (both default off so embedders opt in via the
    --fleet-* surface).

    ``tiers`` (a fleet.tiers.TierPolicy, optional) supersedes the global
    per-tenant quota with per-TIER budgets: one shared token bucket per
    tier (tier.qps/burst; 0 = the tier is unmetered) plus a queue-share
    slice of ``max_queue_depth`` — a storming bronze tier fills its slice
    and sheds while gold's slice stays open, which is how "low tiers shed
    first under queue pressure" holds at admission time."""

    def __init__(
        self,
        max_queue_depth: int = 0,
        tenant_qps: float = 0.0,
        tenant_burst: float = 0.0,
        window_s: float = 0.005,
        max_tenants: int = 64,
        tiers: Optional[TierPolicy] = None,
    ) -> None:
        self.max_queue_depth = int(max_queue_depth)
        self.tenant_qps = float(tenant_qps)
        self.tenant_burst = float(tenant_burst) if tenant_burst > 0 else max(
            self.tenant_qps, 1.0
        )
        self.window_s = float(window_s)
        self.max_tenants = int(max_tenants)
        self.tiers = tiers
        self._buckets: Dict[str, TokenBucket] = {}
        # one shared bucket per TIER (tiers mode): the tier's tenants draw
        # from one budget, which is the whole point of a tier
        self._tier_buckets: Dict[str, TokenBucket] = {}
        # lifetime admission tallies by outcome (report/debug surface —
        # the per-series truth lives in fleet_admission_total)
        self.tallies: Dict[str, int] = {}

    def _bucket_for(self, tenant_id: str) -> TokenBucket:
        bucket = self._buckets.get(tenant_id)
        if bucket is not None:
            return bucket
        if self.max_tenants > 0 and len(self._buckets) >= self.max_tenants:
            overflow = self._buckets.get(OVERFLOW_BUCKET)
            if overflow is None:
                overflow = self._buckets[OVERFLOW_BUCKET] = TokenBucket(
                    self.tenant_qps, self.tenant_burst
                )
            return overflow
        bucket = self._buckets[tenant_id] = TokenBucket(
            self.tenant_qps, self.tenant_burst
        )
        return bucket

    def tier_for(self, tenant_id: str) -> Optional[TierSpec]:
        return self.tiers.tier_for(tenant_id) if self.tiers else None

    def _tier_bucket(self, tier: TierSpec) -> TokenBucket:
        bucket = self._tier_buckets.get(tier.name)
        if bucket is None:
            bucket = self._tier_buckets[tier.name] = TokenBucket(
                tier.qps, tier.burst if tier.burst > 0 else max(tier.qps, 1.0)
            )
        return bucket

    def admit(
        self, tenant_id: str, queue_depth: int, now: float,
        draining: bool = False, tier_depth: int = 0,
    ) -> AdmissionVerdict:
        """Judge one submit (caller holds the queue lock). Order matters
        and is part of the contract: drain first (an over-quota tenant
        hitting a draining sidecar must hear "go elsewhere", not "slow
        down"), then queue depth — global bound, then the tier's
        queue-share slice (``tier_depth`` = this tier's queued count) —
        then quota (the tier's shared bucket when tiers are configured,
        else the global per-tenant bucket)."""
        tier = self.tier_for(tenant_id)
        label = tier.name if tier is not None else ""
        if draining:
            return self._tally(AdmissionVerdict(SHED_DRAINING, tier=label))
        if self.max_queue_depth > 0:
            if queue_depth >= self.max_queue_depth:
                # the queue will not shrink before the next flush window
                # at the earliest — that is the honest retry hint
                return self._tally(AdmissionVerdict(
                    SHED_QUEUE_FULL, max(self.window_s, 1e-3), tier=label,
                ))
            if tier is not None and tier.queue_share < 1.0:
                share = max(1, int(tier.queue_share * self.max_queue_depth))
                if tier_depth >= share:
                    return self._tally(AdmissionVerdict(
                        SHED_QUEUE_FULL, max(self.window_s, 1e-3),
                        tier=label,
                    ))
        if tier is not None:
            if tier.qps > 0:
                wait = self._tier_bucket(tier).try_take(now)
                if wait > 0.0:
                    return self._tally(
                        AdmissionVerdict(SHED_QUOTA, wait, tier=label)
                    )
        elif self.tenant_qps > 0:
            wait = self._bucket_for(tenant_id).try_take(now)
            if wait > 0.0:
                return self._tally(AdmissionVerdict(SHED_QUOTA, wait))
        return self._tally(AdmissionVerdict(ADMIT_OK, tier=label))

    def admit_expired(self, tenant_id: str = "") -> AdmissionVerdict:
        """A request whose deadline budget was already spent at submit:
        shed typed (DEADLINE_EXCEEDED) — queueing it would burn a batch
        slot on an answer nobody can receive in time."""
        tier = self.tier_for(tenant_id)
        return self._tally(AdmissionVerdict(
            SHED_DEADLINE, tier=tier.name if tier is not None else "",
        ))

    def _tally(self, verdict: AdmissionVerdict) -> AdmissionVerdict:
        self.tallies[verdict.outcome] = self.tallies.get(verdict.outcome, 0) + 1
        return verdict

    def snapshot(self) -> Dict[str, int]:
        """Lifetime outcome tallies (caller holds the queue lock) —
        consumed by reports through sorted() only."""
        return dict(self.tallies)


def partition_expired(
    entries, now: float
) -> Tuple[list, list]:
    """Split (request, ticket) pairs into (live, expired) by ticket
    deadline at ``now``, preserving submission order — the shared shed
    step of ``flush`` and ``_dispatch_batch`` (an expired ticket must
    never consume a batch slot)."""
    live, expired = [], []
    for req, ticket in entries:
        deadline = getattr(ticket, "deadline_ts", None)
        if deadline is not None and now >= deadline:
            expired.append((req, ticket))
        else:
            live.append((req, ticket))
    return live, expired
