"""Fleet decision-ledger schema: the ``validate_records`` twin the
``autoscaler_tpu.fleet.round`` tag never had.

One sorted-key JSON line per fleet round (FleetRoundRecord.to_dict in
loadgen/fleetdrive.py is the producer). /2 added the overload-armor
columns (typed ``shed`` rows + the ``outcomes`` tally); /3 added the
fleet-HA columns (per-verdict ``endpoint`` + ``failovers``, quota
``tier``). The tag and SCHEMA_FIELDS manifest live here — graftlint
GL017 cross-checks every producer, this validator, and the summarizer
against the manifest, so a field drifting in any of the three without a
version bump fails the lint gate, not a replay three PRs later.

``validate_records`` also machine-checks the two accounting identities
the chaos gate used to assert ad hoc:

- ``len(shed) == outcomes["shed"] + outcomes["expired"]`` — every shed
  row is tallied exactly once;
- ``outcomes["unresolved"] == 0`` — the zero-hung-tickets audit: a
  ticket the coalescer admitted but never resolved/failed/shed is the
  deadline-deadlock bug class, and a ledger carrying one must never
  validate clean.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List

# re-exported serialization helpers — one stable_json for every ledger
from autoscaler_tpu.perf.ledger import (  # noqa: F401 — re-exported API
    dump_jsonl,
    load_jsonl,
    record_line,
    stable_json,
)

FLEET_SCHEMA = "autoscaler_tpu.fleet.round/3"

SCHEMA_FIELDS = {
    FLEET_SCHEMA: {
        "required": (
            "tick",
            "now_ts",
            "tenants",
            "degraded",
            "errors",
            "shed",
            "outcomes",
        ),
        "optional": (),
    },
}

# every FleetTenantVerdict column (loadgen/fleetdrive.py dataclass);
# asdict() serializes them all, so a row missing one is a drifted writer
_VERDICT_KEYS = (
    "tenant",
    "bucket",
    "batch_size",
    "padding_waste",
    "route",
    "node_counts",
    "scheduled_pods",
    "verdict_sha256",
    "match_solo",
    "best_group",
    "endpoint",
    "failovers",
    "tier",
)

_OUTCOME_KEYS = ("resolved", "shed", "expired", "failed", "unresolved")


def _num(value: Any) -> bool:
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def _check_tenants(i: int, rec: Dict[str, Any], errors: List[str]) -> None:
    tenants = rec.get("tenants")
    if not isinstance(tenants, list):
        errors.append(f"record {i}: tenants must be a list")
        return
    for j, row in enumerate(tenants):
        at = f"record {i} tenant {j}"
        if not isinstance(row, dict):
            errors.append(f"{at}: not an object")
            continue
        missing = [k for k in _VERDICT_KEYS if k not in row]
        if missing:
            errors.append(f"{at}: verdict row missing {missing}")
        if not isinstance(row.get("tenant"), str) or not row.get("tenant"):
            errors.append(f"{at}: missing tenant name")
        if not isinstance(row.get("verdict_sha256"), str):
            errors.append(f"{at}: verdict_sha256 must be a string")
        if not isinstance(row.get("match_solo"), bool):
            errors.append(f"{at}: match_solo must be a bool")
        if not isinstance(row.get("failovers"), int) or row.get("failovers", 0) < 0:
            errors.append(f"{at}: failovers must be a non-negative int")


def _check_outcomes(i: int, rec: Dict[str, Any], errors: List[str]) -> None:
    outcomes = rec.get("outcomes")
    if not isinstance(outcomes, dict):
        errors.append(f"record {i}: outcomes must be an object")
        return
    for k, v in outcomes.items():
        if k not in _OUTCOME_KEYS:
            errors.append(f"record {i}: unknown outcome {k!r}")
        elif not isinstance(v, int) or v < 0:
            errors.append(f"record {i}: outcome {k} must be a non-negative int")
    shed = rec.get("shed")
    if isinstance(shed, list):
        tallied = outcomes.get("shed", 0) + outcomes.get("expired", 0)
        if isinstance(tallied, int) and tallied != len(shed):
            errors.append(
                f"record {i}: {len(shed)} shed rows but outcomes tally "
                f"{tallied} (shed+expired) — a shed request went uncounted"
            )
    unresolved = outcomes.get("unresolved", 0)
    if unresolved:
        errors.append(
            f"record {i}: {unresolved} unresolved ticket(s) — the "
            "zero-hung-tickets audit fails (an admitted request reached "
            "no terminal outcome)"
        )


def validate_records(records: Iterable[Any]) -> List[str]:
    """Validate a fleet decision ledger; returns error strings (empty =
    valid). Checks the round-record schema, tick monotonicity, verdict
    row shape, and the shed/outcome accounting identities."""
    errors: List[str] = []
    last_tick = None
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            errors.append(f"record {i}: not an object")
            continue
        if rec.get("schema") != FLEET_SCHEMA:
            errors.append(
                f"record {i}: schema {rec.get('schema')!r} != {FLEET_SCHEMA!r}"
            )
        tick = rec.get("tick")
        if not isinstance(tick, int):
            errors.append(f"record {i}: tick must be an int")
        elif last_tick is not None and tick <= last_tick:
            errors.append(
                f"record {i}: tick {tick} not increasing (prev {last_tick})"
            )
        if isinstance(tick, int):
            last_tick = tick
        if not _num(rec.get("now_ts")):
            errors.append(f"record {i}: now_ts must be a number")
        degraded = rec.get("degraded")
        if not isinstance(degraded, list) or any(
            not isinstance(s, str) for s in degraded
        ):
            errors.append(f"record {i}: degraded must be a list of strings")
        errs = rec.get("errors")
        if not isinstance(errs, list) or any(
            not isinstance(s, str) for s in errs
        ):
            errors.append(f"record {i}: errors must be a list of strings")
        shed = rec.get("shed")
        if not isinstance(shed, list) or any(
            not isinstance(row, dict) for row in shed
        ):
            errors.append(f"record {i}: shed must be a list of objects")
        _check_tenants(i, rec, errors)
        _check_outcomes(i, rec, errors)
    return errors


def summarize(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a fleet ledger into the figures bench.py reports: round
    count, terminal-outcome totals, shed volume, per-endpoint verdict
    counts, total failovers, and the solo-match certificate ratio."""
    rounds = 0
    outcome_totals: Dict[str, int] = {}
    shed_rows = 0
    endpoints: Dict[str, int] = {}
    failovers = 0
    verdicts = 0
    solo_matches = 0
    for rec in records:
        rounds += 1
        for k, v in rec.get("outcomes", {}).items():
            outcome_totals[k] = outcome_totals.get(k, 0) + int(v)
        shed_rows += len(rec.get("shed", ()))
        for row in rec.get("tenants", ()):
            verdicts += 1
            if row.get("match_solo"):
                solo_matches += 1
            ep = row.get("endpoint", "")
            if ep:
                endpoints[ep] = endpoints.get(ep, 0) + 1
            failovers += int(row.get("failovers", 0))
    return {
        "rounds": rounds,
        "outcomes": {k: outcome_totals[k] for k in sorted(outcome_totals)},
        "shed_rows": shed_rows,
        "verdicts": verdicts,
        "solo_matches": solo_matches,
        "endpoints": {k: endpoints[k] for k in sorted(endpoints)},
        "failovers": failovers,
    }
