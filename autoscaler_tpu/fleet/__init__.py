"""Fleet serving: a coalescing multi-tenant batched-estimator service.

Many autoscalers (tenants) post independent scale-up questions; the fleet
service pads them into power-of-two shape buckets, coalesces same-bucket
requests inside a window, answers one scenario-sharded mesh dispatch per
batch, and demuxes per-tenant verdicts that are BYTE-IDENTICAL to solo
dispatches of the same operands — the "one TPU slice serving a fleet of
autoscalers" story (ROADMAP item 1 / BASELINE config 5), certified by the
loadgen fleet driver and tests/test_fleet.py.

Layers: fleet/buckets.py (shape buckets + exact-pad safety argument),
fleet/coalescer.py (admission queue, batching, circuit-broken dispatch,
demux, pre-warm), rpc/service.py BatchEstimate (the wire surface).
"""
from autoscaler_tpu.fleet.buckets import (
    DEFAULT_BUCKETS,
    BucketError,
    BucketSpec,
    adhoc_bucket,
    format_buckets,
    pad_operands,
    padding_waste,
    parse_buckets,
    pow2ceil,
    select_bucket,
)
from autoscaler_tpu.fleet.admission import AdmissionController, TokenBucket
from autoscaler_tpu.fleet.balance import EndpointBalancer, EndpointHealth
from autoscaler_tpu.fleet.tiers import (
    DEFAULT_TIER,
    TierError,
    TierPolicy,
    TierSpec,
    parse_tiers,
)
from autoscaler_tpu.fleet.coalescer import (
    OVERFLOW_TENANT,
    ROUTE_BATCHED,
    ROUTE_ORACLE,
    FleetAnswer,
    FleetCoalescer,
    FleetRequest,
    FleetTicket,
)
from autoscaler_tpu.fleet.ledger import (
    FLEET_SCHEMA,
    summarize as summarize_fleet_ledger,
    validate_records as validate_fleet_records,
)
from autoscaler_tpu.fleet.errors import (
    ADMIT_OK,
    SHED_DEADLINE,
    SHED_DRAINING,
    SHED_OUTCOMES,
    SHED_QUEUE_FULL,
    SHED_QUOTA,
    TICKET_ABANDONED,
    TICKET_EXPIRED,
    TICKET_FAILED,
    TICKET_OUTCOMES,
    TICKET_RESOLVED,
    FleetAdmissionError,
    FleetDeadlineError,
    FleetDrainError,
    FleetError,
    FleetOverloadError,
)

__all__ = [
    "ADMIT_OK",
    "DEFAULT_BUCKETS",
    "OVERFLOW_TENANT",
    "ROUTE_BATCHED",
    "ROUTE_ORACLE",
    "SHED_DEADLINE",
    "SHED_DRAINING",
    "SHED_OUTCOMES",
    "SHED_QUEUE_FULL",
    "SHED_QUOTA",
    "TICKET_ABANDONED",
    "TICKET_EXPIRED",
    "TICKET_FAILED",
    "TICKET_OUTCOMES",
    "TICKET_RESOLVED",
    "AdmissionController",
    "BucketError",
    "BucketSpec",
    "DEFAULT_TIER",
    "EndpointBalancer",
    "EndpointHealth",
    "FLEET_SCHEMA",
    "TierError",
    "TierPolicy",
    "TierSpec",
    "parse_tiers",
    "FleetAdmissionError",
    "FleetAnswer",
    "FleetCoalescer",
    "FleetDeadlineError",
    "FleetDrainError",
    "FleetError",
    "FleetOverloadError",
    "FleetRequest",
    "FleetTicket",
    "TokenBucket",
    "adhoc_bucket",
    "format_buckets",
    "pad_operands",
    "padding_waste",
    "parse_buckets",
    "pow2ceil",
    "select_bucket",
    "summarize_fleet_ledger",
    "validate_fleet_records",
]
