"""Typed fleet admission/overload errors and the closed outcome vocabulary.

Separate module so both the admission controller and the coalescer can
import them without a cycle, and so the RPC layer's status mapping
(rpc/service.py: FleetOverloadError → RESOURCE_EXHAUSTED + retry-after,
FleetDrainError → UNAVAILABLE + drain detail, FleetDeadlineError →
DEADLINE_EXCEEDED) reads from one source of truth.
"""
from __future__ import annotations

# closed admission-outcome vocabulary (metric labels, ledger fields,
# report keys — GL010: these strings reach replay artifacts)
ADMIT_OK = "admitted"
SHED_QUEUE_FULL = "shed_queue_full"
SHED_QUOTA = "shed_quota"
SHED_DRAINING = "shed_draining"
SHED_DEADLINE = "shed_deadline"
SHED_OUTCOMES = (SHED_QUEUE_FULL, SHED_QUOTA, SHED_DRAINING, SHED_DEADLINE)

# closed ticket terminal-outcome vocabulary (every ticket ends in exactly
# one of these — the "zero tickets hang to deadline" audit counts them)
TICKET_RESOLVED = "resolved"
TICKET_FAILED = "failed"
TICKET_EXPIRED = "expired"
TICKET_ABANDONED = "abandoned"
TICKET_OUTCOMES = (
    TICKET_RESOLVED, TICKET_FAILED, TICKET_EXPIRED, TICKET_ABANDONED,
)


class FleetError(RuntimeError):
    """No rung could serve a coalesced batch."""


class FleetAdmissionError(FleetError):
    """Base of the typed admission rejections: ``outcome`` is the closed
    vocabulary label, ``retry_after_s`` the server's pacing hint (0 =
    no useful retry-here time)."""

    outcome: str = "rejected"

    def __init__(self, message: str, retry_after_s: float = 0.0) -> None:
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class FleetOverloadError(FleetAdmissionError):
    """Queue full or tenant over quota — the server is alive but
    shedding; honor ``retry_after_s`` before retrying HERE."""

    def __init__(
        self, message: str, retry_after_s: float, outcome: str = SHED_QUOTA
    ) -> None:
        super().__init__(message, retry_after_s)
        self.outcome = outcome


class FleetDrainError(FleetAdmissionError):
    """The coalescer is draining (sidecar shutting down): fail over to
    another endpoint; retrying here buys nothing."""

    outcome = SHED_DRAINING


class FleetDeadlineError(FleetAdmissionError):
    """The ticket's deadline expired in the queue — shed before it
    consumed a batch slot. Retrying a timed-out estimate doubles load
    exactly when the server is drowning, so the client must NOT resend."""

    outcome = SHED_DEADLINE
