"""Tenant quota tiers: typed per-tier admission budgets.

PR 14's admission control ran ONE global per-tenant qps knob
(``--fleet-tenant-qps``): every tenant got the same budget, so a paying
"gold" autoscaler and a best-effort batch tenant shed at the same depth.
``--fleet-tenant-tiers`` replaces that with a declarative tier table
(JSON on the flag / chart ``values.fleet.tenantTiers``):

    {
      "gold":    {"qps": 50, "burst": 100, "queue_share": 0.75,
                  "default_deadline_s": 30, "shed_priority": 0,
                  "tenants": ["vip-a", "vip-b"]},
      "default": {"qps": 1, "burst": 2, "queue_share": 0.25,
                  "default_deadline_s": 10, "shed_priority": 10}
    }

Semantics (consumed by fleet/admission.py + the coalescer):

- ``qps``/``burst``  — ONE token bucket per tier, shared by the tier's
  tenants (0 = the tier is unmetered). This is the "quota configs per
  tenant tier rather than one global qps" gap ROADMAP item 1 names.
- ``queue_share``    — the fraction of ``--fleet-max-queue-depth`` this
  tier may occupy; a storming low tier fills its slice and sheds
  ``shed_queue_full`` while gold's slice stays open. This is how "shed
  order under queue pressure prefers low tiers" holds at admission.
- ``default_deadline_s`` — applied to tickets submitted without their own
  deadline, so a tier's latency contract binds even lazy clients.
- ``shed_priority``  — service order under bounded capacity: LOWER serves
  first, HIGHER sheds/waits first (the coalescer orders each flush by it,
  so when ``flush(limit=)`` models a saturated service the bronze tail is
  what stays queued and expires).
- ``tenants``        — exact tenant ids pinned to the tier. Every policy
  MUST declare a ``default`` tier (the catch-all for unlisted tenants;
  it must not pin tenants itself) — an implicit default would silently
  unmeter unknown tenants, the opposite of what quotas are for.

Tier names are a closed, small vocabulary by construction, so the
``tier`` label they put on ``fleet_admission_total`` and the lifecycle
SLI histograms stays inside the existing cardinality bound.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

# the mandatory catch-all tier name
DEFAULT_TIER = "default"


class TierError(ValueError):
    """A tier table that doesn't describe a usable policy."""


@dataclass(frozen=True)
class TierSpec:
    """One tier's typed admission budget (see module docstring)."""

    name: str
    qps: float = 0.0
    burst: float = 0.0
    queue_share: float = 1.0
    default_deadline_s: float = 0.0
    shed_priority: int = 0
    tenants: Tuple[str, ...] = ()

    def __post_init__(self):
        if not self.name:
            raise TierError("tier name must be non-empty")
        if self.qps < 0:
            raise TierError(f"tier {self.name!r} qps must be >= 0")
        if self.burst < 0:
            raise TierError(f"tier {self.name!r} burst must be >= 0")
        if not 0.0 < self.queue_share <= 1.0:
            raise TierError(
                f"tier {self.name!r} queue_share must be in (0, 1], got "
                f"{self.queue_share}"
            )
        if self.default_deadline_s < 0:
            raise TierError(
                f"tier {self.name!r} default_deadline_s must be >= 0"
            )
        if self.shed_priority < 0:
            raise TierError(
                f"tier {self.name!r} shed_priority must be >= 0"
            )


class TierPolicy:
    """The resolved tier table: name → spec, tenant → tier."""

    def __init__(self, tiers: Sequence[TierSpec]) -> None:
        names = [t.name for t in tiers]
        if len(set(names)) != len(names):
            raise TierError(f"duplicate tier names in {sorted(names)}")
        self.by_name: Dict[str, TierSpec] = {t.name: t for t in tiers}
        if DEFAULT_TIER not in self.by_name:
            raise TierError(
                "tier policy must declare a 'default' tier (the catch-all "
                "for unlisted tenants — an implicit default would silently "
                "unmeter unknown tenants)"
            )
        if self.by_name[DEFAULT_TIER].tenants:
            raise TierError(
                "the 'default' tier must not pin tenants — it is the "
                "catch-all"
            )
        self.default = self.by_name[DEFAULT_TIER]
        self._tenant_tier: Dict[str, TierSpec] = {}
        for t in tiers:
            for tenant in t.tenants:
                if tenant in self._tenant_tier:
                    raise TierError(
                        f"tenant {tenant!r} pinned to both "
                        f"{self._tenant_tier[tenant].name!r} and {t.name!r}"
                    )
                self._tenant_tier[tenant] = t

    def tier_for(self, tenant_id: str) -> TierSpec:
        return self._tenant_tier.get(tenant_id, self.default)

    def names(self) -> Tuple[str, ...]:
        return tuple(sorted(self.by_name))


# the JSON keys a tier entry may carry (anything else is a typo — fail
# loudly, the flag configures production shedding behavior)
_TIER_FIELDS = (
    "qps", "burst", "queue_share", "default_deadline_s", "shed_priority",
    "tenants",
)


def parse_tiers(text: str) -> Optional[TierPolicy]:
    """``--fleet-tenant-tiers`` JSON → :class:`TierPolicy` (None when the
    flag is empty — tiers off, the PR-14 global-quota behavior stands)."""
    if not text or not text.strip():
        return None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise TierError(f"tenant tiers are not valid JSON: {e}") from None
    if not isinstance(doc, dict) or not doc:
        raise TierError(
            "tenant tiers must be a non-empty JSON object of "
            "{tier name: spec}"
        )
    tiers = []
    for name in sorted(doc):
        entry = doc[name]
        if not isinstance(entry, dict):
            raise TierError(f"tier {name!r} spec must be an object")
        unknown = set(entry) - set(_TIER_FIELDS)
        if unknown:
            raise TierError(
                f"tier {name!r} has unknown fields {sorted(unknown)} "
                f"(known: {list(_TIER_FIELDS)})"
            )
        tenants = entry.get("tenants", [])
        if not isinstance(tenants, list) or not all(
            isinstance(t, str) and t for t in tenants
        ):
            raise TierError(
                f"tier {name!r} tenants must be a list of tenant ids"
            )
        try:
            tiers.append(TierSpec(
                name=name,
                qps=float(entry.get("qps", 0.0)),
                burst=float(entry.get("burst", 0.0)),
                queue_share=float(entry.get("queue_share", 1.0)),
                default_deadline_s=float(entry.get("default_deadline_s", 0.0)),
                shed_priority=int(entry.get("shed_priority", 0)),
                tenants=tuple(tenants),
            ))
        except (TypeError, ValueError) as e:
            if isinstance(e, TierError):
                raise
            raise TierError(f"tier {name!r}: {e}") from None
    return TierPolicy(tiers)
