"""RemovalSimulator: object-level orchestration of the scale-down kernels.

Reference: cluster-autoscaler/simulator/cluster.go — RemovalSimulator,
FindNodesToRemove :116, SimulateNodeRemoval :145, FindEmptyNodesToRemove
:187, UnremovableReason enum :56-90. Candidates are batched into one
removal_feasibility dispatch instead of per-node fork/refit/revert.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from autoscaler_tpu.kube.objects import Node, Pod, PodDisruptionBudget
from autoscaler_tpu.ops.scaledown import empty_nodes as empty_nodes_kernel
from autoscaler_tpu.ops.scaledown import (
    joint_removal_feasibility,
    joint_removal_feasibility_spread,
    removal_feasibility,
    removal_feasibility_spread,
)
from autoscaler_tpu.simulator.drain import (
    BlockingPod,
    DrainabilityRules,
    daemonset_pods_of,
    get_pods_to_move,
)
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot

import jax.numpy as jnp


class UnremovableReason(enum.Enum):
    """reference: simulator/cluster.go:56-90 (subset exercised here)."""

    NO_REASON = "NoReason"
    BLOCKED_BY_POD = "BlockedByPod"
    NO_PLACE_TO_MOVE_PODS = "NoPlaceToMovePods"
    NOT_UNNEEDED_LONG_ENOUGH = "NotUnneededLongEnough"
    NOT_UNREADY_LONG_ENOUGH = "NotUnreadyLongEnough"
    NODE_GROUP_MIN_SIZE_REACHED = "NodeGroupMinSizeReached"
    MINIMAL_RESOURCE_LIMIT_EXCEEDED = "MinimalResourceLimitExceeded"
    SCALE_DOWN_DISABLED_ANNOTATION = "ScaleDownDisabledAnnotation"
    NOT_UTILIZED_ENOUGH = "NotUnderutilized"
    UNREADY_NOT_ALLOWED = "UnreadyNotAllowed"
    RECENTLY_UNREMOVABLE = "RecentlyUnremovable"


@dataclass
class NodeToRemove:
    node: Node
    pods_to_reschedule: List[Pod] = field(default_factory=list)
    destinations: Dict[str, str] = field(default_factory=dict)  # pod key → node name
    # DaemonSet pods riding on the node: never simulated for rescheduling
    # (the controller recreates them elsewhere), optionally evicted
    # best-effort at actuation (reference actuation/drain.go:177-188).
    daemonset_pods: List[Pod] = field(default_factory=list)


@dataclass
class UnremovableNode:
    node: Node
    reason: UnremovableReason
    blocking_pod: Optional[BlockingPod] = None



def _spread_refit_context(meta, tensors, moving_pods):
    """→ (spread8, static_counts, sp_match_np) or (None, None, None): the
    within-refit topology-spread context. Static counts cover ALL placed
    pods (candidates' movable pods included — the kernels subtract each
    candidate's own contribution, matching findPlaceFor's remove-then-place
    order, cluster.go:220)."""
    from autoscaler_tpu.snapshot.affinity import (
        build_spread_context_from_meta,
        has_hard_spread,
    )

    if not has_hard_spread(moving_pods):
        return None, None, None
    ctx = build_spread_context_from_meta(moving_pods, meta, tensors)
    if ctx is None:
        return None, None, None
    (sp_of, sp_match, node_dom, sp_elig, dom_valid,
     static_counts, skew, min_dom, domnum) = ctx
    spread8 = (sp_of, sp_match, node_dom, sp_elig, dom_valid,
               skew, min_dom, domnum)
    return spread8, static_counts, np.asarray(sp_match)


def _cand_sub_matrix(sp_match_np, meta, pods_per_cand):
    """[C, S] — per candidate, how many of its moving pods match each term.
    Terminating movers are EXCLUDED: static_counts never counted them
    (countPodsMatchSelector skips deletion-stamped pods, #87621), so
    subtracting them would drive the domain count negative and over-admit."""
    S = sp_match_np.shape[1]
    out = np.zeros((len(pods_per_cand), S), np.int32)
    for ci, pods in enumerate(pods_per_cand):
        for p in pods:
            if p.deletion_ts is None:
                out[ci] += sp_match_np[meta.pod_index[p.key()]]
    return out


class RemovalSimulator:
    def __init__(self, rules: Optional[DrainabilityRules] = None):
        self.rules = rules or DrainabilityRules()

    def find_empty_nodes(
        self, snapshot: ClusterSnapshot, candidates: Sequence[str]
    ) -> List[str]:
        """Nodes among candidates with no pods needing rescheduling
        (reference cluster.go:187)."""
        tensors, meta = snapshot.tensors()
        movable = np.zeros(tensors.num_pods, bool)
        for i, pod in enumerate(meta.pods):
            movable[i] = not (pod.mirror or pod.daemonset)
        empty = np.asarray(empty_nodes_kernel(tensors, jnp.asarray(movable)))
        out = []
        for name in candidates:
            j = meta.node_index.get(name)
            if j is not None and empty[j]:
                out.append(name)
        return out

    def find_nodes_to_remove(
        self,
        snapshot: ClusterSnapshot,
        candidates: Sequence[str],
        pdbs: Sequence[PodDisruptionBudget] = (),
        max_pods_per_node: int = 128,
    ) -> Tuple[List[NodeToRemove], List[UnremovableNode]]:
        """Batched FindNodesToRemove (reference cluster.go:116): drain rules
        per candidate on host, then ONE removal_feasibility dispatch for all
        candidates."""
        tensors, meta = snapshot.tensors()
        cand_names = [c for c in candidates if c in meta.node_index]
        if not cand_names:
            return [], []

        C = len(cand_names)
        S = max_pods_per_node
        cand_idx = np.zeros(C, np.int32)
        pod_slots = np.full((C, S), -1, np.int32)
        blocked = np.zeros(C, bool)
        blocking: Dict[str, BlockingPod] = {}
        movable_pods: Dict[str, List[Pod]] = {}
        ds_pods: Dict[str, List[Pod]] = {}

        # controller → live replica count, the MinReplicas drain-rule input
        # (built once per dispatch; None disables the check)
        owner_counts = None
        if self.rules.min_replica_count > 0:
            from autoscaler_tpu.simulator.drain import count_owner_replicas

            owner_counts = count_owner_replicas(snapshot.pods())
        for ci, name in enumerate(cand_names):
            cand_idx[ci] = meta.node_index[name]
            pods_on = snapshot.pods_on_node(name)
            ds_pods[name] = daemonset_pods_of(pods_on)
            to_move, block = get_pods_to_move(
                pods_on, self.rules, pdbs, owner_counts
            )
            if block is not None:
                blocked[ci] = True
                blocking[name] = block
                continue
            movable_pods[name] = to_move
            for si, pod in enumerate(to_move[:S]):
                pod_slots[ci, si] = meta.pod_index[pod.key()]
            if len(to_move) > S:
                blocked[ci] = True  # too many pods to evaluate — conservative

        all_moving = [p for pods in movable_pods.values() for p in pods]
        spread8, static_counts, sp_match_np = _spread_refit_context(
            meta, tensors, all_moving
        )
        if spread8 is not None:
            pods_per_cand = [
                movable_pods.get(name, [])[:S] for name in cand_names
            ]
            res = removal_feasibility_spread(
                tensors,
                jnp.asarray(cand_idx),
                jnp.asarray(pod_slots),
                jnp.asarray(blocked),
                spread8,
                static_counts,
                jnp.asarray(_cand_sub_matrix(sp_match_np, meta, pods_per_cand)),
            )
        else:
            res = removal_feasibility(
                tensors,
                jnp.asarray(cand_idx),
                jnp.asarray(pod_slots),
                jnp.asarray(blocked),
            )
        feasible = np.asarray(res.feasible)
        dests = np.asarray(res.destinations)

        to_remove: List[NodeToRemove] = []
        unremovable: List[UnremovableNode] = []
        for ci, name in enumerate(cand_names):
            node = snapshot.get_node(name)
            if blocked[ci]:
                unremovable.append(
                    UnremovableNode(
                        node, UnremovableReason.BLOCKED_BY_POD, blocking.get(name)
                    )
                )
            elif feasible[ci]:
                moves = movable_pods.get(name, [])
                destinations = {
                    pod.key(): meta.nodes[dests[ci, si]].name
                    for si, pod in enumerate(moves[:S])
                    if dests[ci, si] >= 0
                }
                to_remove.append(
                    NodeToRemove(node, moves, destinations, ds_pods.get(name, []))
                )
            else:
                unremovable.append(
                    UnremovableNode(node, UnremovableReason.NO_PLACE_TO_MOVE_PODS)
                )
        return to_remove, unremovable

    def validate_removal_set(
        self,
        snapshot: ClusterSnapshot,
        drains: Sequence[NodeToRemove],
        also_removed: Sequence[str] = (),
        max_pods_per_node: int = 128,
    ) -> Tuple[List[NodeToRemove], List[UnremovableNode]]:
        """Joint re-simulation of the picked deletion set, in pick order.

        Per-candidate feasibility (find_nodes_to_remove) evaluates every
        candidate against the same base state; this pass replays the chosen
        drains sequentially over ONE shared capacity state, with every node
        leaving the cluster (the drains themselves plus `also_removed`, e.g.
        empty nodes picked for deletion) excluded as a destination — the
        joint check the reference gets from re-simulating against a fresh
        snapshot during actuation (actuator.go:371, cluster.go:145). Returns
        (validated drains with updated destinations, rejected)."""
        tensors, meta = snapshot.tensors()
        # Guard against drains computed from an older snapshot: a drain whose
        # node or pods have since vanished cannot be validated — reject it
        # rather than crash (find_nodes_to_remove filters the same way).
        rejected: List[UnremovableNode] = []
        current: List[NodeToRemove] = []
        for r in drains:
            known = r.node.name in meta.node_index and all(
                p.key() in meta.pod_index for p in r.pods_to_reschedule
            )
            if known:
                current.append(r)
            else:
                rejected.append(
                    UnremovableNode(r.node, UnremovableReason.NO_PLACE_TO_MOVE_PODS)
                )
        drains = current
        if not drains:
            return [], rejected
        C, S = len(drains), max_pods_per_node
        cand_idx = np.zeros(C, np.int32)
        pod_slots = np.full((C, S), -1, np.int32)
        excluded = np.zeros(tensors.num_nodes, bool)
        for name in also_removed:
            j = meta.node_index.get(name)
            if j is not None:
                excluded[j] = True
        for ci, r in enumerate(drains):
            j = meta.node_index[r.node.name]
            cand_idx[ci] = j
            excluded[j] = True
            for si, pod in enumerate(r.pods_to_reschedule[:S]):
                pod_slots[ci, si] = meta.pod_index[pod.key()]

        all_moving = [p for r in drains for p in r.pods_to_reschedule]
        spread8, static_counts, sp_match_np = _spread_refit_context(
            meta, tensors, all_moving
        )
        if spread8 is not None:
            pods_per_cand = [r.pods_to_reschedule[:S] for r in drains]
            res = joint_removal_feasibility_spread(
                tensors,
                jnp.asarray(cand_idx),
                jnp.asarray(pod_slots),
                jnp.asarray(excluded),
                spread8,
                static_counts,
                jnp.asarray(_cand_sub_matrix(sp_match_np, meta, pods_per_cand)),
            )
        else:
            res = joint_removal_feasibility(
                tensors,
                jnp.asarray(cand_idx),
                jnp.asarray(pod_slots),
                jnp.asarray(excluded),
            )
        feasible = np.asarray(res.feasible)
        dests = np.asarray(res.destinations)

        valid: List[NodeToRemove] = []
        for ci, r in enumerate(drains):
            if feasible[ci]:
                destinations = {
                    pod.key(): meta.nodes[dests[ci, si]].name
                    for si, pod in enumerate(r.pods_to_reschedule[:S])
                    if dests[ci, si] >= 0
                }
                valid.append(
                    NodeToRemove(
                        r.node, r.pods_to_reschedule, destinations, r.daemonset_pods
                    )
                )
            else:
                rejected.append(
                    UnremovableNode(r.node, UnremovableReason.NO_PLACE_TO_MOVE_PODS)
                )
        return valid, rejected
