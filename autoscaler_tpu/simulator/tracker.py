"""UsageTracker: cross-loop memory of simulated pod moves between nodes.

Reference: cluster-autoscaler/simulator/tracker.go — UsageTracker :38 records,
per drain simulation, which destination nodes received pods from which
removal candidate (RegisterUsage), and on actual deletion of a candidate
reports the destinations so their "unneeded since" timers reset (their
utilization is about to rise when the evicted pods really land there);
stale records expire via CleanUp.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Set


@dataclass
class UsageRecord:
    """Per-node view of the simulated-move graph (reference tracker.go:25)."""

    # nodes this node's simulated pods were placed onto → last sim timestamp
    using: Dict[str, float] = field(default_factory=dict)
    # nodes whose simulated pods landed on this node → last sim timestamp
    used_by: Dict[str, float] = field(default_factory=dict)


class UsageTracker:
    def __init__(self) -> None:
        self._records: Dict[str, UsageRecord] = {}

    def _record(self, name: str) -> UsageRecord:
        rec = self._records.get(name)
        if rec is None:
            rec = self._records[name] = UsageRecord()
        return rec

    def register_usage(self, using: str, used: str, now_ts: float) -> None:
        """Candidate `using`'s simulated pods were placed on node `used`
        (reference tracker.go:51)."""
        self._record(using).using[used] = now_ts
        self._record(used).used_by[using] = now_ts

    def get(self, name: str) -> UsageRecord:
        return self._records.get(name, UsageRecord())

    def remove_node(self, name: str) -> List[str]:
        """Node `name` was actually deleted: drop its records and return the
        destinations its simulation used — callers reset those nodes'
        unneeded-since timers (reference tracker.go:67 Unmark semantics)."""
        rec = self._records.pop(name, None)
        if rec is None:
            return []
        destinations: Set[str] = set(rec.using)
        for other in rec.using:
            other_rec = self._records.get(other)
            if other_rec:
                other_rec.used_by.pop(name, None)
        for other in rec.used_by:
            other_rec = self._records.get(other)
            if other_rec:
                other_rec.using.pop(name, None)
        return sorted(destinations)

    def cleanup(self, cutoff_ts: float) -> None:
        """Expire entries last touched before cutoff (reference tracker.go:89)."""
        empty = []
        for name, rec in self._records.items():
            rec.using = {k: t for k, t in rec.using.items() if t >= cutoff_ts}
            rec.used_by = {k: t for k, t in rec.used_by.items() if t >= cutoff_ts}
            if not rec.using and not rec.used_by:
                empty.append(name)
        for name in empty:
            del self._records[name]
