"""Hinting scheduling simulator — host wrapper over the greedy kernel with a
generational hint map.

Reference: cluster-autoscaler/simulator/scheduling/ — hinting_simulator.go:58
(TrySchedulePods), hints.go:39,68 (generational hint map: successful
placements remembered across loops, stale entries dropped by generation GC),
similar_pods.go (memoized verdicts for equivalent pods — subsumed here
because the whole batch is one dispatch).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from autoscaler_tpu.kube.objects import Pod
from autoscaler_tpu.ops.schedule import greedy_schedule
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot

import jax.numpy as jnp


class Hints:
    """pod key → node name, with generation-based eviction (hints.go:39)."""

    def __init__(self, max_generations: int = 2):
        self._store: Dict[str, Tuple[str, int]] = {}
        self._generation = 0
        self.max_generations = max_generations

    def get(self, pod_key: str) -> Optional[str]:
        entry = self._store.get(pod_key)
        return entry[0] if entry else None

    def set(self, pod_key: str, node_name: str) -> None:
        self._store[pod_key] = (node_name, self._generation)

    def next_generation(self) -> None:
        self._generation += 1
        cutoff = self._generation - self.max_generations
        self._store = {k: v for k, v in self._store.items() if v[1] > cutoff}


class HintingSimulator:
    def __init__(self) -> None:
        self.hints = Hints()

    def try_schedule_pods(
        self,
        snapshot: ClusterSnapshot,
        pods: Sequence[Pod],
        commit: bool = True,
    ) -> Tuple[List[Pod], Dict[str, str]]:
        """→ (scheduled_pods, assignments pod key → node name). When commit,
        the placements are applied to the snapshot (as TrySchedulePods does on
        its working snapshot)."""
        if not pods:
            return [], {}
        tensors, meta = snapshot.tensors()
        K = len(pods)
        slots = np.full(K, -1, np.int32)
        hint_idx = np.full(K, -1, np.int32)
        for i, pod in enumerate(pods):
            slots[i] = meta.pod_index[pod.key()]
            hinted = self.hints.get(pod.key())
            if hinted is not None and hinted in meta.node_index:
                hint_idx[i] = meta.node_index[hinted]
        # within-wave topology spread: placements in THIS wave raise their
        # domain's count for later pods (PREDICATES.md divergence 2, closed)
        from autoscaler_tpu.snapshot.affinity import build_spread_context_from_meta

        spread_ctx = build_spread_context_from_meta(pods, meta, tensors)
        res = greedy_schedule(
            tensors, jnp.asarray(slots), jnp.asarray(hint_idx), spread=spread_ctx
        )
        placed = np.asarray(res.placed)
        dest = np.asarray(res.dest)

        scheduled: List[Pod] = []
        assignments: Dict[str, str] = {}
        for i, pod in enumerate(pods):
            if placed[i]:
                node_name = meta.nodes[dest[i]].name
                scheduled.append(pod)
                assignments[pod.key()] = node_name
                self.hints.set(pod.key(), node_name)
                if commit:
                    snapshot.schedule_pod(pod.key(), node_name)
        self.hints.next_generation()
        return scheduled, assignments
