"""Drain policy: which pods may move, which block node removal.

Reference: cluster-autoscaler/utils/drain/drain.go:76
(GetPodsForDeletionOnNodeDrain: mirror/DaemonSet/kube-system/local-storage/
unreplicated/safe-to-evict rules, BlockingPod + reasons :44-50) and
cluster-autoscaler/simulator/drain.go:50 (GetPodsToMove = policy + PDB check
:73). Pure host-side policy — the feasibility arithmetic runs on device
(ops/scaledown.py); this module decides which pods even enter it.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from autoscaler_tpu.kube.objects import (
    SAFE_TO_EVICT_ANNOTATION,
    Pod,
    PodDisruptionBudget,
)


class BlockingReason(enum.Enum):
    """reference: utils/drain/drain.go:50-73."""

    NO_REASON = "NoReason"
    CONTROLLER_NOT_FOUND = "ControllerNotFound"
    NOT_REPLICATED = "NotReplicated"
    LOCAL_STORAGE_REQUESTED = "LocalStorageRequested"
    NOT_SAFE_TO_EVICT_ANNOTATION = "NotSafeToEvictAnnotation"
    UNMOVABLE_KUBE_SYSTEM_POD = "UnmovableKubeSystemPod"
    NOT_ENOUGH_PDB = "NotEnoughPdb"
    MIN_REPLICAS_REACHED = "MinReplicasReached"


@dataclass
class BlockingPod:
    pod: Pod
    reason: BlockingReason


@dataclass
class DrainabilityRules:
    """Knobs mirroring the reference flags (main.go / drain.go callers)."""

    skip_nodes_with_system_pods: bool = True
    skip_nodes_with_local_storage: bool = True
    skip_nodes_with_custom_controller_pods: bool = True
    # a replicated pod whose controller runs fewer than this many replicas
    # blocks drain (reference drain.go:131 MinReplicasReached; replica count
    # approximated by the controller's live pod count, supplied by the
    # caller via owner_replica_counts)
    min_replica_count: int = 0


def _safe_to_evict(pod: Pod) -> Optional[bool]:
    v = pod.annotations.get(SAFE_TO_EVICT_ANNOTATION)
    if v is None:
        return None
    return v.lower() == "true"


def owner_key(pod: Pod) -> Optional[Tuple[str, str, str]]:
    """(namespace, kind, name) of the pod's controller, or None."""
    if pod.owner_ref is None:
        return None
    return (pod.namespace, pod.owner_ref.kind, pod.owner_ref.name)


def count_owner_replicas(all_pods: Sequence[Pod]) -> dict:
    """controller → live pod count, the replica proxy for the MinReplicas
    drain rule (built once per loop from the full pod list)."""
    counts: dict = {}
    for p in all_pods:
        k = owner_key(p)
        if k is not None:
            counts[k] = counts.get(k, 0) + 1
    return counts


def get_pods_for_deletion_on_node_drain(
    pods: Sequence[Pod],
    rules: DrainabilityRules,
    pdbs: Sequence[PodDisruptionBudget] = (),
    owner_replica_counts: Optional[dict] = None,
) -> Tuple[List[Pod], Optional[BlockingPod]]:
    """→ (pods_to_move, first_blocking_pod). Mirror pods are ignored entirely;
    DaemonSet pods are not "moved" (they are evicted best-effort at the end of
    a drain, reference actuation/drain.go:178) so they never appear in either
    output. The first blocking pod aborts, as the reference does."""
    to_move: List[Pod] = []
    for pod in pods:
        if pod.mirror:
            continue
        if pod.daemonset:
            continue
        safe = _safe_to_evict(pod)
        if safe is False:
            return [], BlockingPod(pod, BlockingReason.NOT_SAFE_TO_EVICT_ANNOTATION)
        if safe is not True:
            # controller / replication checks apply unless explicitly safe
            if pod.owner_ref is None or not pod.owner_ref.controller:
                if rules.skip_nodes_with_custom_controller_pods or pod.owner_ref is None:
                    return [], BlockingPod(pod, BlockingReason.NOT_REPLICATED)
            if not pod.restartable:
                return [], BlockingPod(pod, BlockingReason.CONTROLLER_NOT_FOUND)
            if rules.min_replica_count > 0 and owner_replica_counts is not None:
                k = owner_key(pod)
                if (
                    k is not None
                    and owner_replica_counts.get(k, 0) < rules.min_replica_count
                ):
                    return [], BlockingPod(
                        pod, BlockingReason.MIN_REPLICAS_REACHED
                    )
            if rules.skip_nodes_with_local_storage and pod.local_storage:
                return [], BlockingPod(pod, BlockingReason.LOCAL_STORAGE_REQUESTED)
            if rules.skip_nodes_with_system_pods and pod.namespace == "kube-system":
                if not _has_pdb(pod, pdbs):
                    return [], BlockingPod(pod, BlockingReason.UNMOVABLE_KUBE_SYSTEM_POD)
        to_move.append(pod)
    return to_move, None


def _has_pdb(pod: Pod, pdbs: Sequence[PodDisruptionBudget]) -> bool:
    return any(
        pdb.namespace == pod.namespace and pdb.selector.matches(pod.labels)
        for pdb in pdbs
    )


def check_pdbs(
    pods: Sequence[Pod], pdbs: Sequence[PodDisruptionBudget]
) -> Optional[BlockingPod]:
    """PDB gate for a set of pods being moved together (reference
    simulator/drain.go:73): each matching PDB must allow >= 1 disruption per
    matched pod (conservative per-pod accounting, as the reference's
    RemainingPdbTracker does)."""
    remaining = {id(p): p.disruptions_allowed for p in pdbs}
    for pod in pods:
        for pdb in pdbs:
            if pdb.namespace == pod.namespace and pdb.selector.matches(pod.labels):
                if remaining[id(pdb)] <= 0:
                    return BlockingPod(pod, BlockingReason.NOT_ENOUGH_PDB)
                remaining[id(pdb)] -= 1
    return None


def get_pods_to_move(
    pods_on_node: Sequence[Pod],
    rules: DrainabilityRules,
    pdbs: Sequence[PodDisruptionBudget] = (),
    owner_replica_counts: Optional[dict] = None,
) -> Tuple[List[Pod], Optional[BlockingPod]]:
    """Full GetPodsToMove: drain policy then PDB check (simulator/drain.go:50)."""
    to_move, blocking = get_pods_for_deletion_on_node_drain(
        pods_on_node, rules, pdbs, owner_replica_counts
    )
    if blocking is not None:
        return [], blocking
    pdb_block = check_pdbs(to_move, pdbs)
    if pdb_block is not None:
        return [], pdb_block
    return to_move, None


def daemonset_pods_of(pods: Sequence[Pod]) -> List[Pod]:
    """DaemonSet pods eligible for best-effort eviction when their node is
    removed (reference actuation/drain.go:177-188). Mirror pods are managed
    by the kubelet and never evicted. Shared by the empty-node and drained-
    node paths so their eviction sets cannot drift."""
    return [p for p in pods if p.daemonset and not p.mirror]
