"""Human-readable autoscaler status — the status-ConfigMap payload.

Reference: cluster-autoscaler/clusterstate/clusterstate.go:701 (GetStatus →
api/ ClusterAutoscalerStatus written to a ConfigMap every loop,
static_autoscaler.go:389-393): cluster-wide and per-node-group Health /
ScaleUp / ScaleDown conditions with readiness counts and timestamps.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List

from autoscaler_tpu.clusterstate.registry import ClusterStateRegistry


@dataclass
class GroupStatus:
    group_id: str
    health: str
    ready: int
    unready: int
    registered: int
    target: int
    min_size: int
    max_size: int
    scale_up_status: str


@dataclass
class ClusterStatus:
    time_ts: float
    cluster_health: str
    total_ready: int
    total_registered: int
    groups: List[GroupStatus] = field(default_factory=list)
    cluster_name: str = ""  # --cluster-name, shown in the header when set
    # kernel-ladder rungs whose circuit breaker is open/half-open: the
    # autoscaler is still deciding, on a lower rung (degraded mode)
    degraded_rungs: List[str] = field(default_factory=list)
    # last scale-up decision summary (explain.DecisionExplainer
    # last_decision_summary): chosen group, winning expander score, top
    # rejection reasons — the "why" next to the "what" the groups show
    last_decision: Dict = field(default_factory=dict)

    @property
    def degraded(self) -> bool:
        return bool(self.degraded_rungs)

    def render(self) -> str:
        name = f" [{self.cluster_name}]" if self.cluster_name else ""
        lines = [
            f"Cluster-autoscaler status{name} at {time.strftime('%Y-%m-%d %H:%M:%S', time.gmtime(self.time_ts))}:",
            f"Cluster-wide: Health: {self.cluster_health} "
            f"(ready={self.total_ready} registered={self.total_registered})",
        ]
        if self.degraded_rungs:
            lines.append(
                "Degraded: kernel ladder rungs tripped: "
                + ",".join(self.degraded_rungs)
            )
        if self.last_decision:
            d = self.last_decision
            chosen = d.get("chosen") or "none"
            score = d.get("score")
            score_s = f" score={score}" if score is not None else ""
            top = ",".join(d.get("top_rejections", ())) or "none"
            lines.append(
                f"LastDecision (tick {d.get('tick')}): chosen={chosen}"
                f"{score_s} topRejections={top}"
            )
        for g in self.groups:
            lines.append(
                f"  NodeGroup {g.group_id}: Health: {g.health} "
                f"(ready={g.ready}/{g.registered} target={g.target} "
                f"minSize={g.min_size} maxSize={g.max_size}) "
                f"ScaleUp: {g.scale_up_status}"
            )
        return "\n".join(lines)


def build_status(
    csr: ClusterStateRegistry,
    now_ts: float,
    cluster_name: str = "",
    degraded_rungs=(),
    last_decision=None,
) -> ClusterStatus:
    total = csr.total_readiness()
    status = ClusterStatus(
        time_ts=now_ts,
        cluster_health="Healthy" if csr.is_cluster_healthy() else "Unhealthy",
        total_ready=total.ready,
        total_registered=total.registered,
        cluster_name=cluster_name,
        degraded_rungs=list(degraded_rungs),
        last_decision=dict(last_decision or {}),
    )
    for group in csr.provider.node_groups():
        gid = group.id()
        r = csr.readiness(gid)
        if gid in csr.scale_up_requests:
            up = "InProgress"
        elif csr.backoff.is_backed_off(gid, now_ts):
            up = "Backoff"
        else:
            up = "NoActivity"
        status.groups.append(
            GroupStatus(
                group_id=gid,
                health="Healthy" if csr.is_node_group_healthy(gid) else "Unhealthy",
                ready=r.ready,
                unready=r.unready,
                registered=r.registered,
                target=group.target_size(),
                min_size=group.min_size(),
                max_size=group.max_size(),
                scale_up_status=up,
            )
        )
    return status
