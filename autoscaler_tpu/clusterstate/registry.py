"""Cluster state registry: readiness accounting, scale-up request tracking,
acceptable ranges, health gates, upcoming nodes, unregistered-node detection.

Reference: cluster-autoscaler/clusterstate/clusterstate.go — struct :112,
UpdateNodes :290, updateScaleRequests :232 (fulfillment = no upcoming nodes,
timeout → RegisterFailedScaleUp), updateAcceptableRanges :493 (target minus
in-flight scale-up increases / plus in-flight scale-downs, minus
long-unregistered), updateReadinessStats :543 (ready/unready/not-started/
deleted + unregistered/long-unregistered buckets, MaxNodeStartupTime :44),
updateIncorrectNodeGroupSizes :616 (registered outside the acceptable range,
first-observed preserved for fixNodeGroupSize), GetUpcomingNodes :921,
IsClusterHealthy :353, IsNodeGroupHealthy :368, IsNodeGroupSafeToScaleUp
:419, instance-error handling :1015-1099.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from autoscaler_tpu.cloudprovider.interface import (
    CloudProvider,
    Instance,
    InstanceState,
)
from autoscaler_tpu.clusterstate.backoff import ExponentialBackoff
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.kube.objects import Node

# reference clusterstate.go:44 — registration → ready grace period
MAX_NODE_STARTUP_TIME_S = 15 * 60.0
# reference clusterstate.go:48 MaxCloudProviderNodeDeletionTime
MAX_NODE_DELETION_TIME_S = 5 * 60.0


@dataclass
class ScaleUpRequest:
    group_id: str
    start_ts: float
    expected_delta: int
    expected_target: int


@dataclass
class ScaleDownRequest:
    """One in-flight node deletion (reference clusterstate.go ScaleDownRequest):
    widens the group's acceptable range until the cloud finishes deleting."""

    group_id: str
    node_name: str
    start_ts: float
    expected_delete_ts: float


@dataclass
class ScaleUpFailure:
    group_id: str
    reason: str
    ts: float


@dataclass
class AcceptableRange:
    """reference clusterstate.go:479 — how many registered nodes a group may
    legitimately have right now. A recent scale-up of 5 puts the group
    between target-5 and target; 3 in-flight deletions put it between
    target and target+3."""

    min_nodes: int = 0
    max_nodes: int = 0
    current_target: int = 0


@dataclass
class IncorrectNodeGroupSize:
    """reference clusterstate.go:616 — registered count outside the
    acceptable range; first_observed feeds fixNodeGroupSize's timeout."""

    current_size: int
    expected_size: int
    first_observed: float


@dataclass
class Readiness:
    ready: int = 0
    unready: int = 0
    not_started: int = 0
    deleted: int = 0
    registered: int = 0
    unregistered: int = 0        # cloud instance exists, no Node object yet
    long_unregistered: int = 0   # unregistered past the provision timeout

    @property
    def total(self) -> int:
        return self.registered


class ClusterStateRegistry:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        backoff: Optional[ExponentialBackoff] = None,
    ):
        self.provider = provider
        self.options = options
        self.backoff = backoff or ExponentialBackoff(
            initial_s=options.initial_node_group_backoff_duration_s,
            max_s=options.max_node_group_backoff_duration_s,
            reset_timeout_s=options.node_group_backoff_reset_timeout_s,
        )
        self.scale_up_requests: Dict[str, ScaleUpRequest] = {}
        self.scale_down_requests: List[ScaleDownRequest] = []
        self.scale_up_failures: List[ScaleUpFailure] = []
        self.last_scale_down_ts: float = 0.0
        self._readiness: Dict[str, Readiness] = {}
        self._total: Readiness = Readiness()
        self._acceptable: Dict[str, AcceptableRange] = {}
        self._incorrect: Dict[str, IncorrectNodeGroupSize] = {}
        self._unregistered_since: Dict[str, float] = {}  # instance id → first seen
        self._deleted_node_names: set = set()
        self._nodes: List[Node] = []
        self._last_update_ts: float = 0.0

    # -- scale-up lifecycle (reference clusterstate.go:232-288) --------------
    def register_or_update_scale_up(self, group_id: str, delta: int, now_ts: float) -> None:
        group = self._group(group_id)
        target = group.target_size() if group else delta
        req = self.scale_up_requests.get(group_id)
        if req is None:
            if delta <= 0:
                return
            self.scale_up_requests[group_id] = ScaleUpRequest(
                group_id=group_id,
                start_ts=now_ts,
                expected_delta=delta,
                expected_target=target,
            )
            return
        if req.expected_delta + delta <= 0:
            # no remaining scale-up intent (clusterstate.go:210)
            del self.scale_up_requests[group_id]
            return
        req.expected_delta += delta
        req.expected_target = target
        if delta > 0:
            # actually adding nodes restarts the provision clock
            req.start_ts = now_ts

    def register_failed_scale_up(self, group_id: str, reason: str, now_ts: float) -> None:
        self.scale_up_failures.append(ScaleUpFailure(group_id, reason, now_ts))
        self.backoff.backoff(group_id, now_ts)
        self.scale_up_requests.pop(group_id, None)

    def register_scale_down(
        self, now_ts: float, group_id: str = "", node_name: str = ""
    ) -> None:
        self.last_scale_down_ts = now_ts
        if group_id:
            self.scale_down_requests.append(
                ScaleDownRequest(
                    group_id=group_id,
                    node_name=node_name,
                    start_ts=now_ts,
                    expected_delete_ts=now_ts + MAX_NODE_DELETION_TIME_S,
                )
            )

    def register_deleted_nodes(self, node_names: Sequence[str]) -> None:
        """Nodes mid cloud-deletion: still registered in the control plane
        but no longer counted toward target (clusterstate.go:675)."""
        self._deleted_node_names = set(node_names)

    # -- per-loop state update (reference clusterstate.go:290) ---------------
    def update_nodes(self, nodes: Sequence[Node], now_ts: float) -> None:
        self._nodes = list(nodes)
        self._last_update_ts = now_ts
        # drop backoff entries idle past the reset timeout: they can never
        # influence is_backed_off again (a new failure restarts at the
        # initial duration), so keeping them only grows the map without
        # bound across group churn on long-lived processes
        self.backoff.remove_stale(now_ts)
        self._update_unregistered(now_ts)
        self._recalculate_readiness(now_ts)
        # acceptable ranges feed the scale-request fulfillment check, then
        # get recomputed once timed-out requests are gone (the reference
        # updates them twice for the same reason, clusterstate.go:317-323)
        self._update_acceptable_ranges()
        self._update_scale_requests(now_ts)
        self._update_acceptable_ranges()
        self._update_incorrect_sizes(now_ts)

    def _update_unregistered(self, now_ts: float) -> None:
        """Track when each cloud instance without a Node object was first
        seen (clusterstate.go:650 keeps the earlier observation)."""
        registered_ids = {n.provider_id for n in self._nodes if n.provider_id}
        registered_names = {n.name for n in self._nodes}
        current: Dict[str, float] = {}
        for group in self.provider.node_groups():
            for inst in group.nodes():
                if (
                    inst.id not in registered_ids
                    and inst.id not in registered_names
                    and inst.state != InstanceState.DELETING
                ):
                    current[inst.id] = self._unregistered_since.get(inst.id, now_ts)
        self._unregistered_since = current

    def _recalculate_readiness(self, now_ts: float) -> None:
        per_group: Dict[str, Readiness] = {}
        total = Readiness()

        def bucket(r: Readiness, node: Node) -> None:
            r.registered += 1
            if node.name in self._deleted_node_names:
                r.deleted += 1
            elif node.ready:
                r.ready += 1
            elif now_ts - node.creation_ts < MAX_NODE_STARTUP_TIME_S:
                r.not_started += 1
            else:
                r.unready += 1

        for node in self._nodes:
            group = self.provider.node_group_for_node(node)
            gid = group.id() if group else ""
            bucket(per_group.setdefault(gid, Readiness()), node)
            bucket(total, node)

        # unregistered buckets come from the cloud side (clusterstate.go:583)
        id_to_group: Dict[str, str] = {}
        for group in self.provider.node_groups():
            for inst in group.nodes():
                id_to_group[inst.id] = group.id()
        provision_timeout = self.options.max_node_provision_time_s
        for inst_id, since in self._unregistered_since.items():
            gid = id_to_group.get(inst_id, "")
            r = per_group.setdefault(gid, Readiness())
            if now_ts - since > provision_timeout:
                r.long_unregistered += 1
                total.long_unregistered += 1
            else:
                r.unregistered += 1
                total.unregistered += 1
        self._readiness = per_group
        self._total = total

    def _update_acceptable_ranges(self) -> None:
        """clusterstate.go:493."""
        result: Dict[str, AcceptableRange] = {}
        for group in self.provider.node_groups():
            gid = group.id()
            target = group.target_size()
            r = self._readiness.get(gid, Readiness())
            result[gid] = AcceptableRange(
                min_nodes=target - r.long_unregistered,
                max_nodes=target,
                current_target=target,
            )
        for gid, req in self.scale_up_requests.items():
            if gid in result:
                result[gid].min_nodes -= req.expected_delta
        for sdr in self.scale_down_requests:
            if sdr.group_id in result:
                result[sdr.group_id].max_nodes += 1
        self._acceptable = result

    def _update_incorrect_sizes(self, now_ts: float) -> None:
        """clusterstate.go:616 — keep first_observed stable while the same
        discrepancy persists, so fixNodeGroupSize can time it out."""
        result: Dict[str, IncorrectNodeGroupSize] = {}
        for gid, acceptable in self._acceptable.items():
            r = self._readiness.get(gid)
            if r is None:
                continue
            if r.registered > acceptable.max_nodes or r.registered < acceptable.min_nodes:
                incorrect = IncorrectNodeGroupSize(
                    current_size=r.registered,
                    expected_size=acceptable.current_target,
                    first_observed=now_ts,
                )
                existing = self._incorrect.get(gid)
                if (
                    existing is not None
                    and existing.current_size == incorrect.current_size
                    and existing.expected_size == incorrect.expected_size
                ):
                    incorrect = existing
                result[gid] = incorrect
        self._incorrect = result

    def _update_scale_requests(self, now_ts: float) -> None:
        """clusterstate.go:232 — a scale-up is fulfilled when the group has
        no upcoming nodes left; it fails (→ backoff) on provision timeout.
        Expired scale-down requests just age out."""
        provision_timeout = self.options.max_node_provision_time_s
        for gid, req in list(self.scale_up_requests.items()):
            if not self.are_there_upcoming_nodes(gid):
                del self.scale_up_requests[gid]
                self.backoff.remove_backoff(gid)
            elif now_ts - req.start_ts > provision_timeout:
                self.register_failed_scale_up(gid, "timeout", now_ts)
        self.scale_down_requests = [
            sdr for sdr in self.scale_down_requests if sdr.expected_delete_ts > now_ts
        ]

    # -- sizing queries ------------------------------------------------------
    def _provisioned_and_target(self, group_id: str) -> Optional[tuple]:
        acceptable = self._acceptable.get(group_id)
        if acceptable is None:
            group = self._group(group_id)
            if group is None:
                return None
            return 0, group.target_size()
        r = self._readiness.get(group_id, Readiness())
        provisioned = r.registered - r.not_started
        return provisioned, acceptable.current_target

    def are_there_upcoming_nodes(self, group_id: str) -> bool:
        """clusterstate.go:452."""
        pt = self._provisioned_and_target(group_id)
        return pt is not None and pt[1] > pt[0]

    def is_node_group_at_target_size(self, group_id: str) -> bool:
        pt = self._provisioned_and_target(group_id)
        return pt is not None and pt[1] == pt[0]

    def is_node_group_scaling_up(self, group_id: str) -> bool:
        return self.are_there_upcoming_nodes(group_id) and group_id in self.scale_up_requests

    def acceptable_range(self, group_id: str) -> Optional[AcceptableRange]:
        return self._acceptable.get(group_id)

    def incorrect_node_group_size(self, group_id: str) -> Optional[IncorrectNodeGroupSize]:
        return self._incorrect.get(group_id)

    # -- health gates --------------------------------------------------------
    def is_cluster_healthy(self) -> bool:
        """reference clusterstate.go:353 — too many unready nodes halts
        autoscaling."""
        t = self._total
        unready = t.unready
        if unready <= self.options.ok_total_unready_count:
            return True
        if t.registered == 0:
            return True
        return unready * 100.0 / t.registered <= self.options.max_total_unready_percentage

    def is_node_group_healthy(self, group_id: str) -> bool:
        """reference clusterstate.go:368."""
        r = self._readiness.get(group_id, Readiness())
        unready = r.unready
        if unready <= self.options.ok_total_unready_count:
            return True
        if r.registered == 0:
            return True
        return unready * 100.0 / r.registered <= self.options.max_total_unready_percentage

    def is_node_group_safe_to_scale_up(self, group_id: str, now_ts: float) -> bool:
        """healthy + not backed off (reference clusterstate.go:419)."""
        return self.is_node_group_healthy(group_id) and not self.backoff.is_backed_off(
            group_id, now_ts
        )

    # -- upcoming / unregistered (reference :921, :479) ----------------------
    def get_upcoming_nodes(self) -> Dict[str, int]:
        """Per group: target minus everything provisioned-or-hopeless
        (ready + unready + long-unregistered, clusterstate.go:931) —
        injected as virtual nodes during simulation
        (reference static_autoscaler.go:484-519)."""
        upcoming: Dict[str, int] = {}
        for group in self.provider.node_groups():
            gid = group.id()
            r = self._readiness.get(gid, Readiness())
            acceptable = self._acceptable.get(gid)
            target = acceptable.current_target if acceptable else group.target_size()
            ahead = target - (r.ready + r.unready + r.long_unregistered)
            if ahead > 0:
                upcoming[gid] = ahead
        return upcoming

    def unregistered_instances(self) -> Dict[str, List[Instance]]:
        """Cloud instances with no matching registered Node (candidates for
        removeOldUnregisteredNodes, reference static_autoscaler.go:732)."""
        registered_ids = {n.provider_id for n in self._nodes if n.provider_id}
        registered_names = {n.name for n in self._nodes}
        out: Dict[str, List[Instance]] = {}
        for group in self.provider.node_groups():
            missing = [
                inst
                for inst in group.nodes()
                if inst.id not in registered_ids
                and inst.id not in registered_names
                and inst.state != InstanceState.DELETING
            ]
            if missing:
                out[group.id()] = missing
        return out

    def long_unregistered_instances(self) -> Dict[str, List[Instance]]:
        """Unregistered past the provision timeout — the subset
        removeOldUnregisteredNodes may delete."""
        cutoff = self.options.max_node_provision_time_s
        out: Dict[str, List[Instance]] = {}
        for gid, instances in self.unregistered_instances().items():
            stale = [
                i
                for i in instances
                if self._last_update_ts - self._unregistered_since.get(i.id, self._last_update_ts)
                > cutoff
            ]
            if stale:
                out[gid] = stale
        return out

    def instances_with_errors(self) -> Dict[str, List[Instance]]:
        """Creating instances that reported a cloud error — to be deleted and
        re-tried (reference deleteCreatedNodesWithErrors,
        static_autoscaler.go:773 + clusterstate.go:1015-1099)."""
        out: Dict[str, List[Instance]] = {}
        for group in self.provider.node_groups():
            errored = [i for i in group.nodes() if i.error_info is not None]
            if errored:
                out[group.id()] = errored
        return out

    def registered_nodes(self) -> List[Node]:
        """The node list the current iteration's accounting ran against."""
        return list(self._nodes)

    def readiness(self, group_id: str) -> Readiness:
        return self._readiness.get(group_id, Readiness())

    def total_readiness(self) -> Readiness:
        return self._total

    def _group(self, group_id: str):
        for g in self.provider.node_groups():
            if g.id() == group_id:
                return g
        return None
