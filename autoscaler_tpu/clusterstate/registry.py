"""Cluster state registry: readiness accounting, scale-up request tracking,
health gates, upcoming nodes, unregistered-node detection.

Reference: cluster-autoscaler/clusterstate/clusterstate.go — struct :112,
UpdateNodes :290, readiness/acceptable-range accounting :479-613,
GetUpcomingNodes :921, IsClusterHealthy :353, IsNodeGroupHealthy :368,
IsNodeGroupSafeToScaleUp :419, scale-up expiry → RegisterFailedScaleUp
:232-288, instance-error handling :1015-1099.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from autoscaler_tpu.cloudprovider.interface import (
    CloudProvider,
    Instance,
    InstanceState,
)
from autoscaler_tpu.clusterstate.backoff import ExponentialBackoff
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.kube.objects import Node


@dataclass
class ScaleUpRequest:
    group_id: str
    start_ts: float
    expected_delta: int
    expected_target: int


@dataclass
class ScaleUpFailure:
    group_id: str
    reason: str
    ts: float


@dataclass
class Readiness:
    ready: int = 0
    unready: int = 0
    not_started: int = 0
    deleted: int = 0
    registered: int = 0

    @property
    def total(self) -> int:
        return self.registered


class ClusterStateRegistry:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        backoff: Optional[ExponentialBackoff] = None,
    ):
        self.provider = provider
        self.options = options
        self.backoff = backoff or ExponentialBackoff()
        self.scale_up_requests: Dict[str, ScaleUpRequest] = {}
        self.scale_up_failures: List[ScaleUpFailure] = []
        self.last_scale_down_ts: float = 0.0
        self._readiness: Dict[str, Readiness] = {}
        self._total: Readiness = Readiness()
        self._nodes: List[Node] = []
        self._last_update_ts: float = 0.0

    # -- scale-up lifecycle (reference clusterstate.go:232-288) --------------
    def register_or_update_scale_up(self, group_id: str, delta: int, now_ts: float) -> None:
        group = self._group(group_id)
        target = group.target_size() if group else delta
        req = self.scale_up_requests.get(group_id)
        if req is None:
            self.scale_up_requests[group_id] = ScaleUpRequest(
                group_id=group_id,
                start_ts=now_ts,
                expected_delta=delta,
                expected_target=target,
            )
        else:
            req.expected_delta += delta
            req.expected_target = target
            req.start_ts = now_ts

    def register_failed_scale_up(self, group_id: str, reason: str, now_ts: float) -> None:
        self.scale_up_failures.append(ScaleUpFailure(group_id, reason, now_ts))
        self.backoff.backoff(group_id, now_ts)
        self.scale_up_requests.pop(group_id, None)

    def register_scale_down(self, now_ts: float) -> None:
        self.last_scale_down_ts = now_ts

    # -- per-loop state update (reference clusterstate.go:290) ---------------
    def update_nodes(self, nodes: Sequence[Node], now_ts: float) -> None:
        self._nodes = list(nodes)
        self._last_update_ts = now_ts
        self._recalculate_readiness(now_ts)
        self._expire_scale_up_requests(now_ts)

    def _recalculate_readiness(self, now_ts: float) -> None:
        per_group: Dict[str, Readiness] = {}
        total = Readiness()
        for node in self._nodes:
            group = self.provider.node_group_for_node(node)
            gid = group.id() if group else ""
            r = per_group.setdefault(gid, Readiness())
            r.registered += 1
            total.registered += 1
            if node.ready:
                r.ready += 1
                total.ready += 1
            elif now_ts - node.creation_ts < 120.0:
                r.not_started += 1
                total.not_started += 1
            else:
                r.unready += 1
                total.unready += 1
        self._readiness = per_group
        self._total = total

    def _expire_scale_up_requests(self, now_ts: float) -> None:
        provision_timeout = self.options.max_node_provision_time_s
        for gid, req in list(self.scale_up_requests.items()):
            group = self._group(gid)
            ready = self._readiness.get(gid, Readiness()).ready
            if group is not None and ready >= req.expected_target:
                # fulfilled
                del self.scale_up_requests[gid]
                self.backoff.remove_backoff(gid)
            elif now_ts - req.start_ts > provision_timeout:
                self.register_failed_scale_up(gid, "timeout", now_ts)

    # -- health gates --------------------------------------------------------
    def is_cluster_healthy(self) -> bool:
        """reference clusterstate.go:353 — too many unready nodes halts
        autoscaling."""
        t = self._total
        unready = t.unready
        if unready <= self.options.ok_total_unready_count:
            return True
        if t.registered == 0:
            return True
        return unready * 100.0 / t.registered <= self.options.max_total_unready_percentage

    def is_node_group_healthy(self, group_id: str) -> bool:
        """reference clusterstate.go:368."""
        r = self._readiness.get(group_id, Readiness())
        unready = r.unready
        if unready <= self.options.ok_total_unready_count:
            return True
        if r.registered == 0:
            return True
        return unready * 100.0 / r.registered <= self.options.max_total_unready_percentage

    def is_node_group_safe_to_scale_up(self, group_id: str, now_ts: float) -> bool:
        """healthy + not backed off (reference clusterstate.go:419)."""
        return self.is_node_group_healthy(group_id) and not self.backoff.is_backed_off(
            group_id, now_ts
        )

    # -- upcoming / unregistered (reference :921, :479) ----------------------
    def get_upcoming_nodes(self) -> Dict[str, int]:
        """Per group: nodes requested/being created but not yet ready —
        injected as virtual nodes during simulation
        (reference static_autoscaler.go:484-519)."""
        upcoming: Dict[str, int] = {}
        for group in self.provider.node_groups():
            gid = group.id()
            r = self._readiness.get(gid, Readiness())
            ahead = group.target_size() - r.registered
            if ahead > 0:
                upcoming[gid] = ahead
        return upcoming

    def unregistered_instances(self) -> Dict[str, List[Instance]]:
        """Cloud instances with no matching registered Node (candidates for
        removeOldUnregisteredNodes, reference static_autoscaler.go:732)."""
        registered_ids = {n.provider_id for n in self._nodes if n.provider_id}
        registered_names = {n.name for n in self._nodes}
        out: Dict[str, List[Instance]] = {}
        for group in self.provider.node_groups():
            missing = [
                inst
                for inst in group.nodes()
                if inst.id not in registered_ids
                and inst.id not in registered_names
                and inst.state != InstanceState.DELETING
            ]
            if missing:
                out[group.id()] = missing
        return out

    def instances_with_errors(self) -> Dict[str, List[Instance]]:
        """Creating instances that reported a cloud error — to be deleted and
        re-tried (reference deleteCreatedNodesWithErrors,
        static_autoscaler.go:773 + clusterstate.go:1015-1099)."""
        out: Dict[str, List[Instance]] = {}
        for group in self.provider.node_groups():
            errored = [i for i in group.nodes() if i.error_info is not None]
            if errored:
                out[group.id()] = errored
        return out

    def readiness(self, group_id: str) -> Readiness:
        return self._readiness.get(group_id, Readiness())

    def total_readiness(self) -> Readiness:
        return self._total

    def _group(self, group_id: str):
        for g in self.provider.node_groups():
            if g.id() == group_id:
                return g
        return None
