"""Per-node-group exponential backoff after failed scale-ups.

Reference: cluster-autoscaler/utils/backoff/backoff.go (interface) and
exponential_backoff.go:28,69 (initial 5m, max 30m, doubling, reset after
3h idle).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict


@dataclass
class _Entry:
    until_ts: float = 0.0
    duration_s: float = 0.0
    last_failure_ts: float = 0.0


@dataclass
class ExponentialBackoff:
    initial_s: float = 300.0       # 5m  (--initial-node-group-backoff-duration)
    max_s: float = 1800.0          # 30m (--max-node-group-backoff-duration)
    reset_timeout_s: float = 10800.0  # 3h (--node-group-backoff-reset-timeout)
    _entries: Dict[str, _Entry] = field(default_factory=dict)

    def backoff(self, group_id: str, now_ts: float) -> float:
        """Record a failure; returns the timestamp the group is backed off
        until (reference exponential_backoff.go:69 Backoff)."""
        e = self._entries.get(group_id)
        if e is None or now_ts - e.last_failure_ts > self.reset_timeout_s:
            duration = self.initial_s
        else:
            duration = min(e.duration_s * 2, self.max_s) if e.duration_s else self.initial_s
        self._entries[group_id] = _Entry(
            until_ts=now_ts + duration, duration_s=duration, last_failure_ts=now_ts
        )
        return now_ts + duration

    def is_backed_off(self, group_id: str, now_ts: float) -> bool:
        e = self._entries.get(group_id)
        return e is not None and now_ts < e.until_ts

    def remove_backoff(self, group_id: str) -> None:
        self._entries.pop(group_id, None)

    def remove_stale(self, now_ts: float) -> None:
        """Drop entries that are both idle past the reset timeout AND no
        longer backing anything off. The second condition matters when an
        operator configures reset_timeout below the backoff duration:
        an entry can be 'stale' by idle time while its until_ts is still in
        the future, and deleting it would lift an active backoff early."""
        stale = [
            g
            for g, e in self._entries.items()
            if now_ts - e.last_failure_ts > self.reset_timeout_s
            and now_ts >= e.until_ts
        ]
        for g in stale:
            del self._entries[g]
