"""Pod list processing before scale-up: drop pods that already fit existing
capacity.

Reference: cluster-autoscaler/core/podlistprocessor/ — the default pipeline
is currently-drained-nodes injection + filter-out-schedulable
(filter_out_schedulable.go:46,95: priority-sorted hinted packing of pending
pods onto existing free capacity; whatever fits is removed from the scale-up
trigger list). The packing runs as one greedy-schedule dispatch on device.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from autoscaler_tpu.kube.objects import Pod
from autoscaler_tpu.simulator.hinting import HintingSimulator
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot


class FilterOutSchedulablePodListProcessor:
    def __init__(self, hinting: HintingSimulator | None = None):
        self.hinting = hinting or HintingSimulator()

    def process(
        self, snapshot: ClusterSnapshot, pending: Sequence[Pod]
    ) -> Tuple[List[Pod], List[Pod]]:
        """→ (still_pending, filtered_as_schedulable). Pods are packed in
        priority order, highest first (filter_out_schedulable.go:95), onto a
        fork of the snapshot; placements are committed to the fork so later
        pods see the consumed capacity."""
        if not pending:
            return [], []
        # stable total order: priority alone leaves equal-priority pods in
        # caller-list order, and the caller assembles that list from an API
        # listing whose order is not a replay invariant — the pod key breaks
        # ties deterministically so hinted packing (and therefore which pods
        # trigger scale-up) is a pure function of the pod SET
        ordered = sorted(pending, key=lambda p: (-p.priority, p.key()))
        scheduled, _ = self.hinting.try_schedule_pods(snapshot, ordered, commit=True)
        scheduled_keys = {p.key() for p in scheduled}
        still_pending = [p for p in pending if p.key() not in scheduled_keys]
        return still_pending, scheduled
