"""Scale-up orchestration: from pending pods to cloud IncreaseSize calls,
with all per-group estimation in one batched device dispatch.

Reference: cluster-autoscaler/core/scaleup/orchestrator/orchestrator.go —
ScaleUp :81, ComputeExpansionOption :444, ExecuteScaleUps :550,
GetCappedNewNodeCount :536, ScaleUpToNodeGroupMinSize :348. The reference
iterates node groups serially, forking the snapshot per group
(:139-179 + :455-484); here every viable group's (predicate mask, FFD
estimate) is computed in a single ffd_binpack_groups dispatch via
BinpackingNodeEstimator.estimate_many, and only the chosen option crosses
back into the (host-side, cloud-API) actuation boundary.
"""
from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from autoscaler_tpu.cloudprovider.interface import CloudProvider, NodeGroup
from autoscaler_tpu.clusterstate.registry import ClusterStateRegistry
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.scaleup.equivalence import build_pod_groups
from autoscaler_tpu.explain.reasons import SkipReason
from autoscaler_tpu.snapshot.affinity import has_hard_spread
from autoscaler_tpu.core.scaleup.resource_manager import ScaleUpResourceManager
from autoscaler_tpu.estimator.binpacking import BinpackingNodeEstimator
from autoscaler_tpu.estimator.limiter import ThresholdBasedEstimationLimiter
from autoscaler_tpu.expander.core import Option, Strategy
from autoscaler_tpu.kube.objects import Node, Pod
from autoscaler_tpu.utils.errors import to_autoscaler_error


@dataclass
class ScaleUpResult:
    """reference: processors/status ScaleUpStatus."""

    scaled_up: bool = False
    chosen_group: Optional[str] = None
    new_nodes: int = 0
    extra_scale_ups: List[tuple] = field(default_factory=list)  # balancing
    # the ACTUAL executed (group, delta) list, first entry included: with
    # balancing the chosen group can receive zero nodes (balance_scale_up
    # grows the smallest similar group), so deriving the plan from
    # chosen_group + extra_scale_ups misattributes nodes — consumers that
    # record the plan (decision ledger, loadgen log) read this
    executed: List[tuple] = field(default_factory=list)
    pods_triggered: List[Pod] = field(default_factory=list)
    pods_remain_unschedulable: List[Pod] = field(default_factory=list)
    # closed SkipReason enum (explain/reasons.py), promoted from free-text
    # strings: the decision ledger and the scaleup_skipped_groups_total
    # gauge need a finite vocabulary (CA parity: skipped_scale_events_count)
    skipped_groups: Dict[str, SkipReason] = field(default_factory=dict)
    options_considered: int = 0
    error: Optional[str] = None
    # decision provenance (autoscaler_tpu/explain): the expander's full
    # scoring table (ALL candidates, not just the winner), the winning
    # score, and the estimator's constraint attribution for this pass
    expander_table: List[dict] = field(default_factory=list)
    chosen_score: Optional[float] = None
    estimator_explain: Dict = field(default_factory=dict)


class ScaleUpOrchestrator:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        csr: ClusterStateRegistry,
        estimator: Optional[BinpackingNodeEstimator] = None,
        expander: Optional[Strategy] = None,
        balancing_processor=None,
        template_provider=None,
        node_group_list_processor=None,
        node_info_processor=None,
        binpacking_limiter=None,
        metrics=None,
        priorities_fetch=None,
        observatory=None,  # perf.PerfObservatory, threaded to the estimator
        operand_arena=None,  # snapshot/arena.OperandArena, ditto
    ):
        from autoscaler_tpu.expander.core import build_strategy

        self.provider = provider
        self.options = options
        self.csr = csr
        if estimator is None:
            from autoscaler_tpu.estimator.ladder import KernelLadder

            estimator = BinpackingNodeEstimator(
                limiter=ThresholdBasedEstimationLimiter(
                    max_nodes=options.max_nodes_per_scaleup,
                    max_duration_s=options.max_nodegroup_binpacking_duration_s,
                ),
                metrics=metrics,
                # circuit-broken degradation ladder around the kernel rungs
                ladder=KernelLadder(
                    failure_threshold=options.kernel_breaker_failure_threshold,
                    cooldown_s=options.kernel_breaker_cooldown_s,
                ),
                observatory=observatory,
                operand_arena=operand_arena,
            )
        self.estimator = estimator
        self.expander = expander or build_strategy(
            [n.strip() for n in options.expander.split(",") if n.strip()],
            seed=options.expander_random_seed,
            priorities=options.expander_priorities,
            priorities_path=options.priority_config_file or None,
            priorities_fetch=priorities_fetch,
            grpc_target=options.grpc_expander_url or None,
            rpc_deadline_s=options.rpc_default_deadline_s,
            # sidecar failover endpoints + hedging for the expander's
            # client (--rpc-address / --rpc-hedge)
            rpc_failover_targets=options.rpc_addresses,
            rpc_hedge=options.rpc_hedge,
            # the price filter scores against the provider's pricing model
            # (expander/price/price.go); absent model → build_strategy
            # rejects the 'price' entry loudly
            pricing=provider.pricing(),
        )
        # eviction-churn penalty column (--preemption-churn-weight): leads
        # the chain so churn-heavy options are pruned before the tie-break
        # filters; run_once rebinds it to each tick's PreemptionPlan via the
        # scale_up preemption_churn seam. Weight 0 (default) builds nothing
        # — the option table stays byte-identical to pre-preemption ledgers.
        self.churn_filter = None
        if options.preemption_churn_weight > 0:
            from autoscaler_tpu.expander.core import (
                ChainStrategy,
                PreemptionChurnFilter,
            )

            self.churn_filter = PreemptionChurnFilter(
                options.preemption_churn_weight
            )
            if isinstance(self.expander, ChainStrategy):
                self.expander.filters.insert(0, self.churn_filter)
        self.resource_manager = ScaleUpResourceManager(provider.get_resource_limiter())
        self.balancing_processor = balancing_processor
        # TemplateNodeInfoProvider (processors/nodeinfos.py): prefer a
        # sanitized real node over the cloud's synthetic template
        self.template_provider = template_provider
        # NAP (reference orchestrator.go:124): may extend the candidate list
        # with not-yet-existing autoprovisioned groups
        self.node_group_list_processor = node_group_list_processor
        self.node_info_processor = node_info_processor
        self.binpacking_limiter = binpacking_limiter
        self.metrics = metrics

    # -- main entry (reference orchestrator.go:81) ---------------------------
    def scale_up(
        self,
        pending_pods: Sequence[Pod],
        cluster_nodes: Sequence[Node],
        now_ts: float,
        pods_of_node=None,
        pending_daemonsets=(),
        preemption_churn=None,
    ) -> ScaleUpResult:
        if not pending_pods:
            return ScaleUpResult()
        # rebind the churn column to this tick's preemption plan (a
        # callable: covered pod keys → evictions left standing); None —
        # preemption off or nothing planned — disengages the filter so the
        # scoring table carries no churn column at all
        if self.churn_filter is not None:
            self.churn_filter.churn_of = preemption_churn

        # Re-read the limiter every pass: providers may fetch it remotely
        # (external gRPC) and a limiter captured once at construction would
        # pin a transient startup failure's unlimited fallback for the
        # process lifetime (reference reads it per loop via
        # context.NewResourceLimiterFromAutoscalingOptions / Refresh).
        self.resource_manager.limiter = self.provider.get_resource_limiter()

        # Equivalence groups shrink reporting/mask work (orchestrator.go:103).
        pod_groups = build_pod_groups(pending_pods)

        nodes_by_group: Dict[str, List[Node]] = {}
        if self.template_provider is not None:
            for node in cluster_nodes:
                g = self.provider.node_group_for_node(node)
                if g is not None:
                    nodes_by_group.setdefault(g.id(), []).append(node)

        all_groups: List[NodeGroup] = list(self.provider.node_groups())
        if self.node_group_list_processor is not None:
            all_groups += self.node_group_list_processor.process(
                self.provider, list(pending_pods), all_groups
            )

        viable: Dict[str, NodeGroup] = {}
        templates: Dict[str, Node] = {}
        headrooms: Dict[str, int] = {}
        skipped: Dict[str, SkipReason] = {}
        for group in all_groups:
            gid = group.id()
            # NAP candidates go through the same gate: they are healthy by
            # default (no readiness history) but a failed create()/increase
            # registered under their deterministic id backs them off too,
            # preventing a per-loop retry storm against the cloud API.
            if not self.csr.is_node_group_safe_to_scale_up(gid, now_ts):
                skipped[gid] = SkipReason.NOT_SAFE
                continue
            headroom = group.max_size() - group.target_size()
            if headroom <= 0:
                skipped[gid] = SkipReason.MAX_SIZE_REACHED
                continue
            template: Optional[Node] = None
            if self.template_provider is not None:
                template = self.template_provider.template_for(
                    group, nodes_by_group.get(gid, []), now_ts,
                    pods_of_node=pods_of_node,
                    pending_daemonsets=pending_daemonsets,
                )
            else:
                try:
                    template = group.template_node_info()
                except Exception as e:  # no template → skip (orchestrator.go:157)
                    # the closed enum cannot carry the exception text the
                    # old free-form string did — log it (typed, so the
                    # error class survives alongside the message) and keep
                    # the diagnostic detail behind a persistent
                    # no_template skip
                    logging.getLogger("scaleup").info(
                        "node group %s skipped: no template (%s)",
                        gid,
                        to_autoscaler_error(e),
                    )
                    skipped[gid] = SkipReason.NO_TEMPLATE
                    continue
            if template is None:
                skipped[gid] = SkipReason.NO_TEMPLATE
                continue
            viable[gid] = group
            templates[gid] = template
            headrooms[gid] = min(headroom, self.options.max_nodes_per_scaleup)

        if not viable:
            return ScaleUpResult(
                pods_remain_unschedulable=list(pending_pods), skipped_groups=skipped
            )

        # NodeInfoProcessor seam (reference processors/nodeinfos): last-touch
        # transform of the template set before estimation.
        if self.node_info_processor is not None:
            templates = self.node_info_processor.process(templates)
        # BinpackingLimiter seam: pre-bound the batched dispatch (the
        # reference's serial StopBinpacking early-exit, adapted to one-shot
        # estimation — see processors/pipeline.py BinpackingLimiter).
        if self.binpacking_limiter is not None:
            viable, templates, headrooms = self.binpacking_limiter.limit_groups(
                viable, templates, headrooms, pending_pods
            )
            if not viable:
                return ScaleUpResult(
                    pods_remain_unschedulable=list(pending_pods),
                    skipped_groups=skipped,
                )

        # Static spread context: topology-spread estimation needs the live
        # cluster's domain counts (the reference's PreFilter runs over the
        # full snapshot, podtopologyspread/common.go:289). Built only when a
        # pending pod actually carries a hard constraint — it is O(world).
        cluster_ctx = None
        if pods_of_node is not None and has_hard_spread(pending_pods):
            cl_pods: List[Pod] = []
            cl_node_of: List[int] = []
            for j, node in enumerate(cluster_nodes):
                for q in pods_of_node(node.name):
                    cl_pods.append(q)
                    cl_node_of.append(j)
            cluster_ctx = (list(cluster_nodes), cl_pods, cl_node_of)

        # ONE batched device dispatch for every group's expansion option
        # (replaces the serial ComputeExpansionOption loop).
        estimates = self.estimator.estimate_many(
            list(pending_pods), templates, headrooms, pod_groups=pod_groups,
            cluster=cluster_ctx,
        )
        # constraint attribution for this pass (estimator/binpacking
        # _finish_explain): per-group rejection-reason histograms + each
        # pod's dominant reason, carried on the result so run_once can
        # assemble the tick's DecisionRecord without re-reaching in
        explain = dict(getattr(self.estimator, "last_explain", None) or {})

        options: List[Option] = []
        for gid, (count, scheduled) in estimates.items():
            if count <= 0 or not scheduled:
                continue
            options.append(Option(node_group=viable[gid], node_count=count, pods=scheduled))

        if not options:
            return ScaleUpResult(
                pods_remain_unschedulable=list(pending_pods),
                skipped_groups=skipped,
                estimator_explain=explain,
            )

        best = self.expander.best_option(options)
        # the expander's scoring table (ChainStrategy publishes it per
        # call; strategies without one leave the provenance fields empty)
        expander_table = list(getattr(self.expander, "last_table", ()) or ())
        chosen_score = getattr(self.expander, "last_score", None)
        if best is None:
            return ScaleUpResult(
                pods_remain_unschedulable=list(pending_pods),
                skipped_groups=skipped,
                estimator_explain=explain,
                expander_table=expander_table,
            )

        # Cap: group headroom, cluster node total, cluster resource limits
        # (GetCappedNewNodeCount :536 + ApplyLimits path :277).
        new_count = min(best.node_count, headrooms[best.node_group.id()])
        if self.options.max_nodes_total > 0:
            room = self.options.max_nodes_total - len(cluster_nodes)
            new_count = min(new_count, max(room, 0))
        left = self.resource_manager.resources_left(cluster_nodes)
        new_count = self.resource_manager.apply_limits(
            new_count, left, templates[best.node_group.id()]
        )
        if new_count <= 0:
            return ScaleUpResult(
                pods_remain_unschedulable=list(pending_pods),
                skipped_groups=skipped,
                options_considered=len(options),
                estimator_explain=explain,
                expander_table=expander_table,
                chosen_score=chosen_score,
            )

        # Balance across similar groups (orchestrator.go:277-318) when enabled.
        scale_ups: List[tuple] = [(best.node_group, new_count)]
        if self.balancing_processor is not None and self.options.balance_similar_node_groups:
            similar = self.balancing_processor.find_similar_node_groups(
                best.node_group, templates, list(viable.values())
            )
            if similar:
                scale_ups = self.balancing_processor.balance_scale_up(
                    [best.node_group] + similar, new_count
                )

        # ExecuteScaleUps (orchestrator.go:550) — the cloud-API boundary.
        executed: List[tuple] = []
        for group, delta in scale_ups:
            if delta <= 0:
                continue
            try:
                if not group.exist():
                    # a NAP candidate won: create the group for real
                    # (orchestrator.go:217 CreateNodeGroup)
                    group = group.create()
                    if self.metrics is not None:
                        self.metrics.created_node_groups_total.inc()
                group.increase_size(delta)
                self.csr.register_or_update_scale_up(group.id(), delta, now_ts)
                executed.append((group.id(), delta))
            except Exception as e:
                # typed wrapping preserves str(e) for non-empty messages,
                # so the decision record and CSR backoff text are unchanged
                err = to_autoscaler_error(e)
                self.csr.register_failed_scale_up(group.id(), str(err), now_ts)
                return ScaleUpResult(
                    error=f"scale-up of {group.id()} failed: {err}",
                    # provenance: the expander DID choose (the cloud then
                    # refused) — the decision record names the winner, the
                    # executed prefix, and every pod left pending, so a
                    # failed tick still explains itself
                    chosen_group=best.node_group.id(),
                    executed=list(executed),
                    pods_remain_unschedulable=list(pending_pods),
                    skipped_groups=skipped,
                    options_considered=len(options),
                    estimator_explain=explain,
                    expander_table=expander_table,
                    chosen_score=chosen_score,
                )

        helped = {p.key() for p in best.pods}
        return ScaleUpResult(
            scaled_up=True,
            chosen_group=best.node_group.id(),
            new_nodes=sum(d for _, d in executed),
            extra_scale_ups=executed[1:],
            executed=list(executed),
            pods_triggered=best.pods,
            pods_remain_unschedulable=[
                p for p in pending_pods if p.key() not in helped
            ],
            skipped_groups=skipped,
            options_considered=len(options),
            estimator_explain=explain,
            expander_table=expander_table,
            chosen_score=chosen_score,
        )

    # -- min-size enforcement (reference orchestrator.go:348) ----------------
    def scale_up_to_node_group_min_size(self, now_ts: float) -> List[tuple]:
        """Raise any group below its min size (--enforce-node-group-min-size)."""
        executed = []
        if not self.options.enforce_node_group_min_size:
            return executed
        for group in self.provider.node_groups():
            delta = group.min_size() - group.target_size()
            if delta > 0 and self.csr.is_node_group_safe_to_scale_up(group.id(), now_ts):
                try:
                    group.increase_size(delta)
                    self.csr.register_or_update_scale_up(group.id(), delta, now_ts)
                    executed.append((group.id(), delta))
                except Exception as e:
                    self.csr.register_failed_scale_up(
                        group.id(), str(to_autoscaler_error(e)), now_ts
                    )
        return executed
