"""Pod equivalence groups — dedup pods by controller + scheduling-relevant
spec so one predicate evaluation covers many identical pods.

Reference: cluster-autoscaler/core/scaleup/equivalence/groups.go:32,39,61
(PodGroup, BuildPodGroups, groupPodsBySchedulingProperties: same controller
owner-ref + equivalent spec → one group). In the TPU design this shrinks the
host-side mask computation (one mask row per exemplar, broadcast to members);
the device kernels are indifferent (they take per-pod rows either way).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

from autoscaler_tpu.kube.objects import Pod


@dataclass
class PodEquivalenceGroup:
    pods: List[Pod] = field(default_factory=list)

    @property
    def exemplar(self) -> Pod:
        return self.pods[0]


def _spec_fingerprint(pod: Pod) -> Tuple:
    aff = pod.affinity
    # CSI volumes enter as per-driver unique-handle COUNTS, not handles: the
    # NodeVolumeLimits verdict (packer._csi_fits) depends only on counts, and
    # counts keep StatefulSet replicas (same shape, distinct PVC handles) in
    # one equivalence group while splitting pods with different volume shapes.
    csi_counts: dict = {}
    for driver, handle in pod.csi_volumes:
        csi_counts.setdefault(driver, set()).add(handle)
    return (
        pod.namespace,
        pod.requests.as_tuple(),
        pod.requests.extended,  # named extended resources are fit dimensions
        tuple(sorted(pod.node_selector.items())),
        tuple(pod.tolerations),
        tuple(sorted(pod.labels.items())),
        pod.host_ports,
        tuple(sorted((d, len(h)) for d, h in csi_counts.items())),
        (aff.node_selector_terms, aff.pod_affinity, aff.pod_anti_affinity)
        if aff
        else None,
        pod.topology_spread,  # the spread scan gate reads run exemplars
        pod.volume_node_affinity,  # bound-PV placement constraints
        pod.rwop_handles,
        pod.legacy_volumes,  # same-volume node conflicts are per-identity
        pod.priority,
        # Never-policy pods pack differently under preemption (they may not
        # evict), so they must not share an exemplar with default-policy
        # twins of the same priority
        pod.preemption_policy,
    )


def build_pod_groups(pods: Sequence[Pod]) -> List[PodEquivalenceGroup]:
    """Pods with a controller owner and identical scheduling spec share a
    group; controller-less pods get singleton groups (reference groups.go:61)."""
    groups: Dict[Tuple, PodEquivalenceGroup] = {}
    out: List[PodEquivalenceGroup] = []
    for pod in pods:
        if pod.owner_ref is None or not pod.owner_ref.controller:
            g = PodEquivalenceGroup([pod])
            out.append(g)
            continue
        key = (pod.owner_ref.kind, pod.owner_ref.name) + _spec_fingerprint(pod)
        if key in groups:
            groups[key].pods.append(pod)
        else:
            g = PodEquivalenceGroup([pod])
            groups[key] = g
            out.append(g)
    return out
