"""Cluster-wide resource limits for scale-up.

Reference: cluster-autoscaler/core/scaleup/resource/manager.go —
DeltaForNode :62, ResourcesLeft :88, ApplyLimits :146,
CheckDeltaWithinLimits :184. Limits come from the cloud provider's
ResourceLimiter (cores/memory/GPU cluster caps) plus max_nodes_total.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence

from autoscaler_tpu.cloudprovider.interface import ResourceLimiter
from autoscaler_tpu.kube.objects import Node

CPU_RES = "cpu"
MEM_RES = "memory"
GPU_RES = "gpu"

_INF = float("inf")


@dataclass
class ResourceDelta:
    """Per-node resource footprint. cpu in millicores, memory in MiB."""

    resources: Dict[str, float] = field(default_factory=dict)

    @staticmethod
    def for_node(node: Node) -> "ResourceDelta":
        a = node.allocatable
        d = {CPU_RES: a.cpu_m, MEM_RES: a.memory / (1024.0 * 1024.0)}
        if a.gpu:
            d[GPU_RES] = a.gpu
        return ResourceDelta(d)

    def times(self, count: int) -> "ResourceDelta":
        return ResourceDelta({k: v * count for k, v in self.resources.items()})


@dataclass
class ResourcesLeft:
    left: Dict[str, float] = field(default_factory=dict)

    def exceeded_by(self, delta: ResourceDelta) -> List[str]:
        """reference CheckDeltaWithinLimits (manager.go:184)."""
        return [
            r
            for r, v in delta.resources.items()
            if v > 0 and self.left.get(r, _INF) < v
        ]


class ScaleUpResourceManager:
    def __init__(self, limiter: ResourceLimiter):
        self.limiter = limiter

    def resources_left(self, nodes: Sequence[Node]) -> ResourcesLeft:
        """max limits minus current cluster totals (manager.go:88)."""
        totals: Dict[str, float] = {CPU_RES: 0.0, MEM_RES: 0.0, GPU_RES: 0.0}
        for node in nodes:
            d = ResourceDelta.for_node(node)
            for k, v in d.resources.items():
                totals[k] = totals.get(k, 0.0) + v
        left: Dict[str, float] = {}
        for r, total in totals.items():
            if self.limiter.has_max(r):
                left[r] = max(0.0, self.limiter.get_max(r) - total)
        return ResourcesLeft(left)

    def apply_limits(
        self, new_count: int, left: ResourcesLeft, template: Node
    ) -> int:
        """Cap node count so the delta stays within remaining limits
        (manager.go:146)."""
        per_node = ResourceDelta.for_node(template)
        count = new_count
        for r, v in per_node.resources.items():
            if v <= 0:
                continue
            available = left.left.get(r, _INF)
            if available < _INF:
                count = min(count, int(available // v))
        return max(count, 0)
