"""StaticAutoscaler: one reconcile iteration (RunOnce) per scan interval.

Reference: cluster-autoscaler/core/static_autoscaler.go — RunOnce :288
(see SURVEY.md §3.2 for the full stack): leftover-taint cleanup :230,
node/pod listing :304, provider refresh :333, snapshot init :250, cluster
state update :376, unregistered-node cleanup / fixNodeGroupSize :413-455
:707-773, expendable filter + upcoming-node injection :471-519,
filter-out-schedulable :528, ScaleUp branch :560-580, ScaleDown branch
:582-691 with cooldown gates :628-640, soft taints :676.

The decision hot paths (predicate fit, binpacking, utilization, removal
refit, greedy packing) all run as batched device kernels; this loop is the
thin host shell around them.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from autoscaler_tpu.cloudprovider.interface import CloudProvider, InstanceState
from autoscaler_tpu.clusterstate.registry import ClusterStateRegistry
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.podlistprocessor import FilterOutSchedulablePodListProcessor
from autoscaler_tpu.core.scaledown.actuator import ActuationResult, ScaleDownActuator
from autoscaler_tpu.core.scaledown.planner import ScaleDownPlanner
from autoscaler_tpu.core.scaleup.orchestrator import ScaleUpOrchestrator, ScaleUpResult
from autoscaler_tpu.explain.reasons import (
    EVICTION_PREEMPTED_BY,
    REASON_EXPENDABLE_BELOW_CUTOFF,
    REASON_NAMES,
    REASON_NOT_CHOSEN,
    REASON_NO_VIABLE_GROUP,
    SkipReason,
)
from autoscaler_tpu.kube.api import ClusterAPI, EvictionError
from autoscaler_tpu.kube.objects import Node, Pod, Resources
from autoscaler_tpu.metrics import metrics as metrics_mod
from autoscaler_tpu.metrics.healthcheck import HealthCheck
from autoscaler_tpu.simulator.removal import UnremovableReason
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
from autoscaler_tpu import trace
from autoscaler_tpu.utils import klogx
from autoscaler_tpu.utils.errors import to_autoscaler_error


@dataclass
class RunOnceResult:
    scale_up: Optional[ScaleUpResult] = None
    scale_down: Optional[ActuationResult] = None
    scale_down_in_cooldown: bool = False
    cluster_healthy: bool = True
    pending_pods: int = 0
    filtered_schedulable: int = 0
    unneeded_nodes: int = 0
    removed_unregistered: int = 0
    # pending pods dropped below --expendable-pods-priority-cutoff this tick
    pending_expendable: int = 0
    # preemption engine (--preemption-enabled): pending pods the eviction-
    # packing pass admitted onto the existing cluster, and the victims it
    # actually evicted (sorted pod keys — ledger/driver consumers)
    preempt_admitted: int = 0
    preempted_pods: List[str] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)


class StaticAutoscaler:
    def __init__(
        self,
        provider: CloudProvider,
        api: ClusterAPI,
        options: Optional[AutoscalingOptions] = None,
        csr: Optional[ClusterStateRegistry] = None,
        scale_up_orchestrator: Optional[ScaleUpOrchestrator] = None,
        scale_down_planner: Optional[ScaleDownPlanner] = None,
        scale_down_actuator: Optional[ScaleDownActuator] = None,
        pod_list_processor: Optional[FilterOutSchedulablePodListProcessor] = None,
        metrics: Optional[metrics_mod.AutoscalerMetrics] = None,
        health_check: Optional[HealthCheck] = None,
        debugger=None,
        processors=None,
        tracer: Optional[trace.Tracer] = None,
        observatory=None,
    ):
        from autoscaler_tpu.perf import PerfObservatory
        from autoscaler_tpu.processors.pipeline import default_processors

        self.provider = provider
        self.api = api
        self.options = options or AutoscalingOptions()
        self.processors = processors or default_processors(self.options)
        self.csr = csr or ClusterStateRegistry(provider, self.options)
        self.metrics = metrics or metrics_mod.AutoscalerMetrics()
        # perf observatory (autoscaler_tpu/perf): per-route compile
        # telemetry, the XLA cost ledger, and device-residency accounting.
        # One per autoscaler — the loadgen driver's replays never share
        # mutable perf state with a prior run. Served by /perfz.
        self.observatory = observatory or PerfObservatory(
            metrics=self.metrics,
            cost_model=self.options.perf_cost_model,
            ring_capacity=self.options.perf_ring_size,
        )
        # decision explainer (autoscaler_tpu/explain): per-tick
        # DecisionRecords — constraint attribution, expander scoring table,
        # skip/backoff/breaker state, plan + scale-down reasons. One per
        # autoscaler, same lifecycle as the perf observatory; served by
        # /explainz, appended to the loadgen decision ledger.
        from autoscaler_tpu.explain import DecisionExplainer

        self.explainer = DecisionExplainer(
            ring_capacity=self.options.explain_ring_size
        )
        # SLO engine (autoscaler_tpu/slo): declarative targets over the
        # request-lifecycle SLIs — tick duration (the main span's timeline
        # extent), pending-pod latency (tracked from the explainer's
        # per-tick still-pending set), and the fleet serving objective.
        # One window record per tick, served by /sloz; the ring shares the
        # explain cadence/size since the pending-pod SLI reads its records.
        from autoscaler_tpu.slo import SloEngine, control_loop_slos

        self.slo = SloEngine(
            # the control-loop catalog only (tick duration, pending-pod
            # latency): this process runs no fleet coalescer, and an
            # objective that can never receive events would report a
            # permanently healthy fleet — the fleet_e2e spec lives with
            # the processes that serve fleet traffic
            specs=control_loop_slos(),
            metrics=self.metrics,
            ring_capacity=self.options.explain_ring_size,
        )
        # floor for perf tick ids: normally the trace id, but a re-entrant
        # tick (tracer degrades to a child span — no trace_id attr) must
        # still get a strictly increasing id or the ledger's monotonicity
        # gate trips on a pile of tick-0 records
        self._next_perf_tick = 0
        # content-addressed resident operand cache (snapshot/arena): the
        # estimator's dispatch arrays (requests/masks/allocs) are byte-
        # identical tick over tick in steady state — a hit re-dispatches
        # against the resident device handles instead of re-uploading
        self._operand_arena = None
        if self.options.arena_enabled:
            from autoscaler_tpu.snapshot.arena import OperandArena

            self._operand_arena = OperandArena()
        self.scale_up_orchestrator = scale_up_orchestrator or ScaleUpOrchestrator(
            provider,
            self.options,
            self.csr,
            observatory=self.observatory,
            operand_arena=self._operand_arena,
            balancing_processor=self.processors.node_group_set,
            template_provider=self.processors.template_node_info_provider,
            node_group_list_processor=self.processors.node_group_list,
            node_info_processor=self.processors.node_info,
            binpacking_limiter=self.processors.binpacking_limiter,
            metrics=self.metrics,
            # live priority-ConfigMap read (expander/priority/priority.go)
            priorities_fetch=(
                (lambda: api.read_configmap(
                    self.options.config_namespace, self.options.priority_config_map
                ))
                if self.options.priority_config_map
                else None
            ),
        )
        # preemption engine (--preemption-enabled): plans priority-aware
        # evictions against each tick's snapshot through the estimator's
        # kernel ladder (autoscaler_tpu/preempt). Built only when enabled
        # AND the orchestrator exposes its estimator — a custom
        # orchestrator without one silently gets no engine (decisions then
        # match preemption-off byte-for-byte).
        self.preempt_engine = None
        if self.options.preemption_enabled:
            est = getattr(self.scale_up_orchestrator, "estimator", None)
            if est is not None:
                from autoscaler_tpu.preempt import PreemptionEngine

                self.preempt_engine = PreemptionEngine(
                    est, metrics=self.metrics
                )
        self.scale_down_planner = scale_down_planner or ScaleDownPlanner(
            provider, self.options, set_processor=self.processors.scale_down_set
        )
        self.scale_down_actuator = scale_down_actuator or ScaleDownActuator(
            provider,
            self.options,
            api,
            self.scale_down_planner.deletion_tracker,
        )
        self.pod_list_processor = (
            pod_list_processor or self.processors.pod_list_processor
        )
        self.health_check = health_check or HealthCheck(
            self.options.max_inactivity_s, self.options.max_failing_time_s
        )
        self.debugger = debugger
        # one trace (span tree) per run_once, kept in a bounded flight
        # recorder served by /tracez; the loadgen driver passes its own
        # tracer (injected deterministic clock) so replays export
        # byte-identical traces
        self.tracer = tracer or trace.Tracer(
            metrics=self.metrics,
            recorder=trace.FlightRecorder(capacity=self.options.trace_ring_size),
            slow_tick_threshold_s=self.options.trace_slow_tick_threshold_s,
        )
        self.last_scale_up_ts: Optional[float] = None
        self.last_scale_down_delete_ts: Optional[float] = None
        self.last_scale_down_fail_ts: Optional[float] = None
        self._initialized = False
        # Packed tensors persist across loops: each loop's fresh snapshot
        # shares this packer, so tensors() costs O(listing delta), not
        # O(world) — the DeltaClusterSnapshot intent (delta.go:26-42).
        # With --arena-enabled the tensors additionally stay DEVICE-
        # resident: the packer emits delta programs (row scatters) against
        # a double-buffered donated arena instead of re-uploading dense
        # tensors, and a startup bucket-ladder prewarm plus the persistent
        # compile cache make the first real tick compile-free (ROADMAP
        # items 2 + 5).
        from autoscaler_tpu.snapshot.incremental import IncrementalPacker

        self._arena = None
        if self.options.arena_enabled:
            from autoscaler_tpu.kube.objects import NUM_RESOURCES
            from autoscaler_tpu.snapshot.arena import DeviceArena

            self._arena = DeviceArena(
                buckets=self.options.arena_buckets,
                observatory=self.observatory,
                metrics=self.metrics,
                # the tracer's timeline clock (synthetic under loadgen) so
                # prewarm walls — recorded before any tick trace exists —
                # replay byte-identically like every other perf figure
                clock=self.tracer.clock,
            )
            self._arena.prewarm(R=NUM_RESOURCES)
        self._packer = IncrementalPacker(arena=self._arena)
        # flight journal (autoscaler_tpu/journal): the black-box recorder.
        # Always on (bounded ring) — journal_enabled gates /journalz only,
        # journal_path additionally appends to disk. The packer sink
        # captures each tick's FIRST materialization (the decision-input
        # state: ClusterSnapshot caches tensors per version and revert()
        # restores the fork-time version, so that first materialization is
        # exactly what the estimator/expander/preemption pass read);
        # record_tick then pins it to the tick's decision record by hash.
        from autoscaler_tpu.journal import JournalRecorder

        self.journal = JournalRecorder(
            ring_capacity=self.options.journal_ring_size,
            keyframe_interval=self.options.journal_keyframe_interval,
            path=self.options.journal_path,
            options_doc=dataclasses.asdict(self.options),
            metrics=self.metrics,
        )
        self._packer.journal_sink = self.journal.observe_update

    # -- one reconcile iteration (reference :288) ----------------------------
    def run_once(self, now_ts: float) -> RunOnceResult:
        """Instrumented wrapper: the tick's span tree (whose span durations
        feed the per-phase duration metrics through one choke point),
        counters, liveness, and the on-demand debugging capture (reference
        metrics.go:399 + static_autoscaler.go:334,380,540,626,661)."""
        # advance the kernel ladder's breaker clock on loop time (simulated
        # time under loadgen — what makes breaker cooldowns replayable)
        ladder = self.kernel_ladder()
        if ladder is not None:
            ladder.tick(now_ts)
        with self.tracer.tick(metrics_mod.MAIN, now_ts=now_ts) as root:
            # open this tick's perf record: dispatches recorded between
            # begin_tick and end_tick are stamped with this tick id — the
            # trace id when the tracer issued one (/perfz and /tracez line
            # up by construction), else the monotonic floor (re-entrant
            # ticks have no trace_id and must not all collapse to 0)
            raw_id = root.attrs.get("trace_id")
            tick_id = max(
                int(raw_id) if raw_id is not None else 0,
                self._next_perf_tick,
            )
            self._next_perf_tick = tick_id + 1
            self.observatory.begin_tick(tick_id, now_ts)
            # the decision record shares the perf record's tick id, so
            # /explainz, /perfz and /tracez line up by construction
            self.explainer.begin_tick(tick_id, now_ts)
            # the journal line shares it too: /journalz drills down into
            # the same tick the other rings describe
            self.journal.begin_tick(tick_id)
            # the tick-duration SLI measures on the timeline seam: the
            # loadgen driver's synthetic clock makes the measured duration
            # (and every burn rate derived from it) replay byte-identically
            slo_t0 = trace.timeline_now()
            try:
                result = self._run_once_traced(now_ts, root)
            finally:
                # finalize even when the tick crashed (the crash-only loop
                # catches outside): the ledgers stay gap-free, and the
                # residency snapshot reflects whatever the tick left live
                with trace.span(metrics_mod.PERF_RECORD) as sp_perf:
                    from autoscaler_tpu.perf import POOL_ARENA, POOL_SNAPSHOT

                    self.observatory.residency.set(
                        POOL_SNAPSHOT, "packer", self._packer.device_bytes()
                    )
                    if self._arena is not None:
                        self.observatory.residency.set(
                            POOL_ARENA, "snapshot", self._arena.device_bytes()
                        )
                        if self._operand_arena is not None:
                            self.observatory.residency.set(
                                POOL_ARENA, "operands",
                                self._operand_arena.device_bytes(),
                            )
                        stats = self._arena.take_stats()
                        self.observatory.note_arena(stats)
                        sp_perf.set_attrs(
                            arena_delta_rows=stats.get("delta_rows", 0),
                            arena_full_uploads=stats.get("full_uploads", 0),
                            arena_promotions=stats.get("promotions", 0),
                            arena_rollbacks=stats.get("rollbacks", 0),
                        )
                    self.observatory.end_tick()
                # a crashed tick leaves a PARTIAL decision record — the
                # sections noted before the crash are exactly the
                # decisions that were made
                with trace.span(metrics_mod.EXPLAIN_RECORD):
                    explain_rec = self.explainer.end_tick()
                # journal the tick's state AFTER the decision record closes:
                # the journal line carries the explain line's hash, pinning
                # state history to decision history byte-for-byte
                with trace.span(metrics_mod.JOURNAL_RECORD):
                    self.journal.record_tick(explain_rec)
                    probe_every = self.options.journal_probe_interval
                    if probe_every > 0 and tick_id % probe_every == 0:
                        verdict = self.journal.probe()
                        if verdict.get("drift"):
                            # a silently wrong forensic answer becomes an
                            # alarm: counted, and stamped on the tick trace
                            self.metrics.journal_probe_drift_total.inc()
                            trace.add_event(
                                "journal.probe_drift",
                                tick=int(verdict.get("tick", -1)),
                                fields=",".join(verdict.get("fields", ())),
                                fit_drift=bool(verdict.get("fit_drift")),
                            )
                # SLO window: judge this tick's SLIs and compute burn
                # rates — crash paths included, so a crashing loop still
                # burns budget instead of going silent
                with trace.span(metrics_mod.SLO_WINDOW):
                    from autoscaler_tpu.slo import SLI_TICK_DURATION

                    self.slo.observe(
                        SLI_TICK_DURATION,
                        trace.timeline_now() - slo_t0,
                        now=now_ts,
                    )
                    self.slo.observe_explain(explain_rec)
                    self.slo.tick(now_ts, tick_id)
            root.set_attrs(
                pending=result.pending_pods,
                healthy=result.cluster_healthy,
                errors=len(result.errors),
            )
            return result

    def _run_once_traced(self, now_ts: float, root) -> RunOnceResult:
        m = self.metrics
        # optional device-timeline capture keyed by the host trace's tick id
        # (--jax-profiler-dir): the profiler session directory and the
        # flight-recorder trace share the id, so "why was tick 8124 slow"
        # has both the host span tree and the device profile
        profiling = False
        tick_id = int(root.attrs.get("trace_id", 0))
        if self.options.jax_profiler_dir:
            from autoscaler_tpu.trace.device import start_profiler_session

            profiling = start_profiler_session(
                self.options.jax_profiler_dir, tick_id
            )
        try:
            if profiling:
                # mark the tick as one profiler "step": profiler UIs group
                # the captured device activity per tick
                from autoscaler_tpu.trace.device import step_annotation

                with step_annotation("run_once", tick_id):
                    result = self._run_once_inner(now_ts)
            else:
                result = self._run_once_inner(now_ts)
        finally:
            if profiling:
                from autoscaler_tpu.trace.device import stop_profiler_session

                stop_profiler_session()
            # status ConfigMap write mirrors the reference's defer
            # (static_autoscaler.go:387-393 + clusterstate.go:701): it must
            # run on EVERY exit path — unhealthy-cluster and error returns
            # included — or operators would read a stale 'Healthy' status
            # exactly while the autoscaler is degraded.
            if self.options.write_status_configmap:
                try:
                    from autoscaler_tpu.clusterstate.status import build_status

                    self.api.write_configmap(
                        self.options.config_namespace,
                        self.options.status_config_map_name,
                        {
                            "status": build_status(
                                self.csr, now_ts, self.options.cluster_name,
                                degraded_rungs=self.degraded_rungs(),
                                # most recent COMPLETED record (this tick's
                                # is still open here — it closes in
                                # run_once's finally, after this write)
                                last_decision=self.explainer.last_decision_summary(),
                            ).render()
                        },
                    )
                    trace.add_event("status.configmap_write")
                except Exception as e:
                    # best-effort observability, never loop-fatal — but the
                    # failure is typed, counted, and on the tick's trace
                    err = to_autoscaler_error(e)
                    m.errors_total.inc(type=err.error_type.value)
                    trace.add_event(
                        "status.configmap_write_failed", error=str(err)
                    )
        # last_activity per activity label (metrics.go UpdateLastTime): the
        # main label every loop; scaleUp/scaleDown in their branches below
        m.last_activity.set(now_ts, activity=metrics_mod.MAIN)
        m.unschedulable_pods_count.set(result.pending_pods)
        m.unneeded_nodes_count.set(result.unneeded_nodes)
        m.node_groups_count.set(len(self.provider.node_groups()))
        m.cluster_safe_to_autoscale.set(1.0 if result.cluster_healthy else 0.0)

        # cluster-size gauges (metrics.go:112-200)
        t = self.csr.total_readiness()
        m.nodes_count.set(t.ready, state="ready")
        m.nodes_count.set(t.unready, state="unready")
        m.nodes_count.set(t.not_started, state="notStarted")
        m.nodes_count.set(t.long_unregistered, state="longUnregistered")
        m.nodes_count.set(t.unregistered, state="unregistered")
        m.max_nodes_count.set(self.options.max_nodes_total)
        # the registry holds the node list this iteration ran against — no
        # extra LIST against the control plane just for gauges
        nodes_now = self.csr.registered_nodes()
        m.cluster_cpu_current_cores.set(
            sum(n.allocatable.cpu_m for n in nodes_now) / 1000.0
        )
        m.cluster_memory_current_bytes.set(
            sum(n.allocatable.memory for n in nodes_now)
        )
        m.cpu_limits_cores.set(self.options.min_cores_total / 1000.0, direction="minimum")
        m.cpu_limits_cores.set(self.options.max_cores_total / 1000.0, direction="maximum")
        m.memory_limits_bytes.set(
            self.options.min_memory_total * 1024 * 1024, direction="minimum"
        )
        m.memory_limits_bytes.set(
            self.options.max_memory_total_mib * 1024 * 1024, direction="maximum"
        )
        if self.options.record_per_node_group_metrics:
            for g in self.provider.node_groups():
                m.node_group_min_count.set(g.min_size(), node_group=g.id())
                m.node_group_max_count.set(g.max_size(), node_group=g.id())
        m.nap_enabled.set(1.0 if self.options.node_autoprovisioning_enabled else 0.0)

        if result.scale_up is not None and result.scale_up.scaled_up:
            m.scaled_up_nodes_total.inc(result.scale_up.new_nodes)
            if self._group_has_accelerator(result.scale_up.chosen_group):
                m.scaled_up_gpu_nodes_total.inc(result.scale_up.new_nodes)
        if result.scale_up is not None and result.scale_up.error:
            m.failed_scale_ups_total.inc()
        if result.scale_down is not None:
            m.scaled_down_nodes_total.inc(
                len(result.scale_down.deleted_empty), reason="empty"
            )
            m.scaled_down_nodes_total.inc(
                len(result.scale_down.deleted_drain), reason="underutilized"
            )
            m.evicted_pods_total.inc(len(result.scale_down.evicted_pods))
        m.scale_down_in_cooldown.set(1.0 if result.scale_down_in_cooldown else 0.0)
        # reset every reason each loop so a reason that stops occurring
        # reports 0 instead of its last nonzero value
        by_reason: Dict[str, int] = {r.value: 0 for r in UnremovableReason}
        for u in self.scale_down_planner.last_unremovable():
            by_reason[u.reason.value] = by_reason.get(u.reason.value, 0) + 1
        for reason, count in by_reason.items():
            m.unremovable_nodes_count.set(count, reason=reason)
        # scale-up skip accounting mirrors the scale-down gauge above:
        # every closed SkipReason reset each loop so a reason that stops
        # occurring reports 0 (CA parity: skipped_scale_events_count)
        skip_counts: Dict[str, int] = {r.value: 0 for r in SkipReason}
        if result.scale_up is not None:
            for skip in result.scale_up.skipped_groups.values():
                skip_counts[skip.value] += 1
        for reason, count in skip_counts.items():
            m.scaleup_skipped_groups_total.set(count, reason=reason)
        if result.removed_unregistered:
            m.old_unregistered_nodes_removed_count.inc(result.removed_unregistered)
        tracker = self.scale_down_planner.deletion_tracker
        m.pending_node_deletions.set(
            tracker.deletions_count(drain=True) + tracker.deletions_count(drain=False)
        )
        for err in result.errors:
            m.errors_total.inc(type="internal")
        if result.errors:
            self.health_check.update_last_activity()
        else:
            self.health_check.update_last_success()
        self.processors.scale_down_status.process(result.scale_down)
        self.processors.autoscaling_status.process(result, now_ts)
        return result

    def _run_once_inner(self, now_ts: float) -> RunOnceResult:
        result = RunOnceResult()

        # startup: clean leftover taints from a crashed predecessor (:230)
        if not self._initialized:
            self.scale_down_actuator.clean_up_to_be_deleted_taints(self.api.list_nodes())
            self._initialized = True

        # 1. observe the world (:304) and refresh cloud caches (:333)
        with trace.span(metrics_mod.POLL) as sp:
            try:
                self.provider.refresh()
            except Exception as e:
                # typed routing; errors_total accounting rides the
                # result.errors loop at the end of _run_once_traced
                err = to_autoscaler_error(e)
                sp.set_attrs(error="refresh_failed")
                result.errors.append(f"provider refresh failed: {err}")
                return result
            all_nodes = self.api.list_nodes()
            all_pods = self.api.list_pods()
            pdbs = self.api.list_pdbs()
            sp.set_attrs(nodes=len(all_nodes), pods=len(all_pods))

        # actionable-cluster gate (reference processors/actionablecluster)
        if not self.processors.actionable_cluster.should_autoscale(all_nodes, now_ts):
            result.errors.append("cluster not actionable this iteration")
            # OnEmptyCluster → ResetUnneededNodes (actionable_cluster_
            # processor.go:68 via processors/callbacks): stale unneeded
            # clocks must not fire deletions the moment the cluster resumes
            self.scale_down_planner.unneeded.reset()
            return result

        # accelerator nodes still attaching devices count as unready
        # (processors/customresources, reference gpu_processor.go)
        _, accel_not_ready = self.processors.custom_resources.filter_out_nodes_with_unready_resources(
            all_nodes
        )
        if accel_not_ready:
            initializing = {n.name for n in accel_not_ready}
            all_nodes = [
                dataclasses.replace(n, ready=False) if n.name in initializing else n
                for n in all_nodes
            ]

        # 2. cluster state accounting (:376); nodes mid-deletion count in the
        # `deleted` readiness bucket, not as ready capacity
        with trace.span(metrics_mod.UPDATE_STATE):
            self.csr.register_deleted_nodes(
                self.scale_down_planner.deletion_tracker.in_flight_names()
            )
            self.csr.update_nodes(all_nodes, now_ts)
        result.cluster_healthy = self.csr.is_cluster_healthy()
        if not result.cluster_healthy:
            result.errors.append("cluster unhealthy: too many unready nodes")
            return result

        # 3. stuck-provision recovery (:413-455, :707-773)
        result.removed_unregistered = self._remove_old_unregistered(now_ts)
        self._delete_created_nodes_with_errors()

        # 4. build the snapshot (:250-354)
        with trace.span(metrics_mod.SNAPSHOT_BUILD) as sp_snap:
            snapshot = ClusterSnapshot(packer=self._packer)
            scheduled, pending = self._split_pods(all_pods)
            for node in all_nodes:
                snapshot.add_node(node)
            for pod in scheduled:
                if snapshot.get_node(pod.node_name) is not None:
                    snapshot.add_pod(pod, pod.node_name)
            for pod in pending:
                snapshot.add_pod(pod)

            # legacy TPU-request sanitizer (:459-466, utils/tpu/tpu.go:57)
            from autoscaler_tpu.utils.tpu import clear_tpu_requests

            pending = clear_tpu_requests(pending)

            # expendable filter (:471) + young-pod filter (:832). Dropped
            # pods are counted and ledgered (expendable_below_cutoff), not
            # silently vanished: a pod parked below the cutoff forever is a
            # config decision operators must be able to see on /explainz.
            cutoff = self.options.expendable_pods_priority_cutoff
            expendable = [p for p in pending if p.priority < cutoff]
            pending = [p for p in pending if p.priority >= cutoff]
            if expendable:
                self.metrics.pending_expendable_total.inc(len(expendable))
            if self.options.new_pod_scale_up_delay_s > 0:
                pending = [
                    p
                    for p in pending
                    if now_ts - p.creation_ts >= self.options.new_pod_scale_up_delay_s
                ]

            # pending-DaemonSet charge shared by upcoming-node injection and
            # the scale-up templates (--force-ds): lazily fetched at most
            # once per loop — idle iterations (nothing pending, nothing
            # upcoming) issue no LIST at all
            ds_memo: List = []

            def pending_ds():
                if not self.options.force_daemonsets:
                    return ()
                if not ds_memo:
                    ds_memo.append(self.api.list_daemonsets())
                return ds_memo[0]

            # upcoming (requested-not-yet-registered) nodes join the
            # simulation as virtual template nodes (:484-519)
            upcoming_names = self._inject_upcoming_nodes(
                snapshot, now_ts, pending_ds
            )
            sp_snap.set_attrs(
                scheduled=len(scheduled), pending=len(pending),
                upcoming=len(upcoming_names),
            )

        # 5. filter-out-schedulable (:528) — device-packed onto a fork
        with trace.span(metrics_mod.FILTER_OUT_SCHEDULABLE) as sp_filter:
            snapshot.fork()
            pending, filtered = self.pod_list_processor.process(snapshot, pending)
            snapshot.revert()
            sp_filter.set_attrs(absorbed=len(filtered), still_pending=len(pending))
        # quota-bounded per-pod verbosity (static_autoscaler.go:528 area +
        # utils/klogx defaults: 20 lines, 1000 at -v>=5)
        pod_quota = klogx.pods_logging_quota()
        for pod in pending:
            klogx.v(4).up_to(pod_quota).info("Pod %s is unschedulable", pod.key())
        klogx.v(4).over(pod_quota).info(
            "%d other unschedulable pods not logged", -pod_quota.left
        )
        result.filtered_schedulable = len(filtered)
        result.pending_pods = len(pending)

        # decision provenance: the tick's pending split and the breaker/
        # backoff state every later section is conditioned on
        result.pending_expendable = len(expendable)
        self.explainer.note(
            "pending",
            {
                "arrived": len(pending) + len(filtered),
                "filtered_schedulable": len(filtered),
                "pending": len(pending),
                "expendable": len(expendable),
            },
        )
        # dropped-pod provenance: the expendable verdicts are the tick's
        # baseline pods section; _note_scale_up_explain merges the
        # scale-up pass's reasons on top (no scale-up this tick — nothing
        # pending — still leaves these visible)
        expendable_doc = {
            p.key(): REASON_EXPENDABLE_BELOW_CUTOFF for p in expendable
        }
        if expendable_doc:
            self.explainer.note("pods", dict(expendable_doc))
        self.explainer.note("degraded_rungs", sorted(self.degraded_rungs()))
        self.explainer.note(
            "backoff",
            sorted(
                g.id()
                for g in self.provider.node_groups()
                if self.csr.backoff.is_backed_off(g.id(), now_ts)
            ),
        )

        # 5b. preemption planning (--preemption-enabled): which pending pods
        # the EXISTING cluster could admit by displacing strictly-lower-
        # priority residents (autoscaler_tpu/preempt via ops/preempt.py).
        # Planned before scale-up so the expander can penalize options that
        # leave evictions standing; actuated after it so pods whose
        # capacity is already coming evict nobody.
        preempt_plan = None
        preempt_doc = None
        if self.preempt_engine is not None and pending:
            preempt_plan = self.preempt_engine.plan(
                snapshot, eligible={p.key() for p in pending}
            )
            # journal the eligible set: `journal replay` re-runs this exact
            # pass on reconstructed state, and eligibility is a function of
            # Pod objects the state tensors do not carry
            self.journal.note(
                "preempt_eligible", sorted(p.key() for p in pending)
            )
            preempt_doc = {
                "route": preempt_plan.route,
                "admitted": preempt_plan.admitted,
                "evictions": [
                    {
                        "pod": victim,
                        "reason": EVICTION_PREEMPTED_BY,
                        "by": preempt_plan.victims[victim],
                        "node": preempt_plan.victim_pods[victim].node_name,
                    }
                    for victim in sorted(preempt_plan.victims)
                ],
            }
            self.explainer.note("preemption", dict(preempt_doc))
            result.preempt_admitted = len(preempt_plan.admitted)

        # 6. scale-up (:560-580)
        if pending:
            with trace.span(metrics_mod.SCALE_UP) as sp_up:
                up = self.scale_up_orchestrator.scale_up(
                    pending, all_nodes, now_ts,
                    # new nodes boot the group's daemonsets: their observed
                    # overhead on the template's source node is charged
                    # against template capacity (simulator/nodes.go:38)
                    pods_of_node=snapshot.pods_on_node,
                    # --force-ds additionally charges suitable-but-not-yet-
                    # running DaemonSets (simulator/nodes.go:56)
                    pending_daemonsets=pending_ds(),
                    # eviction-churn score column (expander/core.py): how
                    # many planned evictions an option leaves standing
                    preemption_churn=(
                        preempt_plan.churn if preempt_plan is not None
                        else None
                    ),
                )
                self._note_scale_up_explain(up, base_pods=expendable_doc)
                sp_up.set_attrs(
                    scaled_up=up.scaled_up,
                    group=up.chosen_group or "",
                    new_nodes=up.new_nodes,
                    skipped_groups=len(up.skipped_groups),
                    remain_unschedulable=len(up.pods_remain_unschedulable),
                )
            self.metrics.last_activity.set(now_ts, activity=metrics_mod.SCALE_UP)
            result.scale_up = up
            self.processors.scale_up_status.process(up)
            if up.scaled_up:
                self.last_scale_up_ts = now_ts
        min_size_ups = self.scale_up_orchestrator.scale_up_to_node_group_min_size(now_ts)
        if min_size_ups:
            self.last_scale_up_ts = now_ts

        # 6b. actuate planned evictions — only for admitted pods whose
        # capacity is NOT already coming from this tick's scale-up
        # (pods_triggered): preemption bridges the gap for the rest.
        # Victims evicted in sorted order (replay determinism); a typed
        # eviction failure is recorded and the loop continues — the victim
        # stays resident and next tick replans.
        if preempt_plan is not None and preempt_plan.victims:
            covered = set()
            if result.scale_up is not None:
                covered = {
                    p.key() for p in result.scale_up.pods_triggered
                }
            evicted: List[str] = []
            evict_failed: List[str] = []
            for victim in sorted(preempt_plan.victims):
                if preempt_plan.victims[victim] in covered:
                    continue
                try:
                    self.api.evict_pod(preempt_plan.victim_pods[victim])
                except EvictionError as e:
                    result.errors.append(
                        f"preemption eviction of {victim} failed: {e}"
                    )
                    evict_failed.append(victim)
                else:
                    evicted.append(victim)
            if evicted:
                self.metrics.preempted_pods_total.inc(len(evicted))
                self.metrics.evicted_pods_total.inc(len(evicted))
                self.metrics.last_activity.set(
                    now_ts, activity=metrics_mod.PREEMPT_PLAN
                )
            result.preempted_pods = evicted
            preempt_doc = dict(preempt_doc)
            preempt_doc["evicted"] = evicted
            self.explainer.note("preemption", preempt_doc)
            # journal the actuation context: the evicted list is victims
            # minus scale-up-covered evictors minus API failures — the
            # coverage set and the failures are environment/decision state
            # `journal replay` cannot re-derive from tensors alone
            self.journal.note("preempt_covered", sorted(covered))
            self.journal.note("preempt_evict_failed", evict_failed)

        # 7. scale-down branch (:582-691)
        if self.options.node_autoprovisioning_enabled:
            # NAP cleanup: drop empty autoprovisioned groups (:650)
            self.processors.node_group_manager.remove_unneeded_node_groups(
                self.provider, self.metrics
            )
        if self.options.scale_down_enabled:
            with trace.span(metrics_mod.SCALE_DOWN) as sp_down:
                with trace.span(metrics_mod.FIND_UNNEEDED):
                    candidates = self.processors.scale_down_candidates_sorting.sort(
                        self.processors.scale_down_node.get_scale_down_candidates(
                            self._scale_down_candidates(all_nodes, upcoming_names),
                            all_nodes,
                        )
                    )
                    self.scale_down_planner.update_cluster_state(
                        snapshot, candidates, pdbs, now_ts
                    )
                self.metrics.last_activity.set(
                    now_ts, activity=metrics_mod.SCALE_DOWN
                )
                result.unneeded_nodes = len(self.scale_down_planner.unneeded_names())
                self.processors.notify_scale_down_candidates(
                    self.scale_down_planner.unneeded_names()
                )
                in_cooldown = self._scale_down_in_cooldown(now_ts)
                result.scale_down_in_cooldown = in_cooldown
                sp_down.set_attrs(
                    unneeded=result.unneeded_nodes, in_cooldown=in_cooldown
                )
                if not in_cooldown:
                    plan = self.scale_down_planner.nodes_to_delete(snapshot, now_ts)
                    if plan.empty or plan.drain:
                        down = self.scale_down_actuator.start_deletion(plan, now_ts)
                        result.scale_down = down
                        sp_down.set_attrs(
                            deleted_empty=len(down.deleted_empty),
                            deleted_drain=len(down.deleted_drain),
                        )
                        if down.deleted_empty or down.deleted_drain:
                            self.last_scale_down_delete_ts = now_ts
                            # per-node registration widens the group's
                            # acceptable range while the cloud deletion is in
                            # flight (clusterstate.go RegisterScaleDown)
                            deleted = set(down.deleted_empty + down.deleted_drain)
                            registered_any = False
                            for r in plan.empty + plan.drain:
                                if r.node.name in deleted:
                                    g = self.provider.node_group_for_node(r.node)
                                    self.csr.register_scale_down(
                                        now_ts, g.id() if g else "", r.node.name
                                    )
                                    registered_any = True
                            if not registered_any:
                                self.csr.register_scale_down(now_ts)
                            # destinations of the deleted nodes' simulated
                            # pods restart their unneeded clocks
                            # (simulator/tracker.go)
                            for name in down.deleted_empty + down.deleted_drain:
                                self.scale_down_planner.node_deleted(name, now_ts)
                            gpu_deleted = sum(
                                1
                                for r in plan.empty + plan.drain
                                if r.node.name in deleted
                                and (
                                    r.node.allocatable.gpu > 0
                                    or r.node.allocatable.tpu > 0
                                )
                            )
                            if gpu_deleted:
                                self.metrics.scaled_down_gpu_nodes_total.inc(
                                    gpu_deleted
                                )
                        if down.failed:
                            self.last_scale_down_fail_ts = now_ts
                # keep soft taints in sync either way (:676)
                self.scale_down_actuator.update_soft_deletion_taints(
                    self.api.list_nodes(), self.scale_down_planner.unneeded_names()
                )
                # decision provenance: what scale-down spared and why
                unremovable: Dict[str, int] = {}
                for u in self.scale_down_planner.last_unremovable():
                    unremovable[u.reason.value] = (
                        unremovable.get(u.reason.value, 0) + 1
                    )
                down = result.scale_down
                self.explainer.note(
                    "scale_down",
                    {
                        "unneeded": sorted(
                            self.scale_down_planner.unneeded_names()
                        ),
                        "unremovable": {
                            k: unremovable[k] for k in sorted(unremovable)
                        },
                        "in_cooldown": in_cooldown,
                        "deleted": sorted(
                            (down.deleted_empty + down.deleted_drain)
                            if down is not None else []
                        ),
                    },
                )
        if self.debugger is not None and self.debugger.is_data_collection_allowed():
            self.debugger.capture(
                self, snapshot, pending, result, filtered_pods=filtered,
                now=now_ts,
            )
        return result

    # -- helpers -------------------------------------------------------------
    def _note_scale_up_explain(
        self, up: ScaleUpResult, base_pods: Optional[Dict[str, str]] = None
    ) -> None:
        """Assemble the scale-up sections of this tick's DecisionRecord
        from the orchestrator result: the estimator's constraint
        attribution, the expander's full scoring table, the closed skip
        reasons, the executed plan, and one reason per pod that stayed
        pending (a pod the estimator could place SOMEWHERE but the chosen
        option did not cover reads 'not_chosen'; a pod that never reached
        estimation reads 'no_viable_group'). ``base_pods`` carries verdicts
        settled before scale-up (expendable_below_cutoff) that the pods
        section must keep."""
        ex = self.explainer
        explain = up.estimator_explain or {}
        ex.note("estimator", {"groups": explain.get("groups", {})})
        ex.note(
            "expander",
            {
                "options": list(up.expander_table),
                "chosen": up.chosen_group or "",
                "score": up.chosen_score,
            },
        )
        ex.note(
            "skipped_groups",
            {g: r.value for g, r in sorted(up.skipped_groups.items())},
        )
        # the orchestrator's actual executed list, not a reconstruction
        # from chosen_group (balancing can hand the chosen group zero
        # nodes while a similar group scales)
        executed = sorted([g, int(d)] for g, d in up.executed if d > 0)
        ex.note(
            "scale_up",
            {
                "executed": executed,
                "error": up.error,
                "remain_unschedulable": len(up.pods_remain_unschedulable),
                "pods_triggered": sorted(p.key() for p in up.pods_triggered),
            },
        )
        pod_reasons = explain.get("pod_reasons", {})
        pods_doc = dict(base_pods or {})
        for p in up.pods_remain_unschedulable:
            reason = pod_reasons.get(p.key())
            if reason is None:
                reason = REASON_NO_VIABLE_GROUP
            elif reason == REASON_NAMES[0]:
                # schedulable somewhere, but the winning option (or a
                # failed/capped execution) did not cover this pod
                reason = REASON_NOT_CHOSEN
            pods_doc[p.key()] = reason
        ex.note("pods", pods_doc)

    def kernel_ladder(self):
        """The estimator's circuit-broken kernel ladder, when wired (the
        default orchestrator always wires one; a custom estimator may not)."""
        est = getattr(self.scale_up_orchestrator, "estimator", None)
        return getattr(est, "ladder", None)

    def degraded_rungs(self) -> List[str]:
        """Kernel rungs whose breaker is not closed. Nonempty = degraded
        mode: decisions still flow, on a lower (slower) rung — surfaced on
        /health-check, /status, and the status ConfigMap."""
        ladder = self.kernel_ladder()
        return ladder.degraded() if ladder is not None else []

    def _group_has_accelerator(self, group_id: Optional[str]) -> bool:
        if not group_id:
            return False
        for g in self.provider.node_groups():
            if g.id() == group_id:
                try:
                    tmpl = g.template_node_info()
                except Exception as e:
                    err = to_autoscaler_error(e)
                    self.metrics.errors_total.inc(type=err.error_type.value)
                    return False
                return tmpl.allocatable.gpu > 0 or tmpl.allocatable.tpu > 0
        return False

    def _split_pods(self, pods: Sequence[Pod]) -> Tuple[List[Pod], List[Pod]]:
        scheduled, pending = [], []
        for pod in pods:
            (scheduled if pod.node_name else pending).append(pod)
        return scheduled, pending

    def _inject_upcoming_nodes(
        self, snapshot: ClusterSnapshot, now_ts: float, pending_ds=lambda: ()
    ) -> List[str]:
        """Virtual nodes for capacity that was requested but hasn't
        registered (:484-519) so we don't double scale-up.

        Routed through the template provider so the virtual node carries the
        group's daemon overhead: an upcoming node boots its daemonsets, and
        crediting it with full allocatable would let filter-out-schedulable
        over-absorb pending pods, under-provisioning by one boot cycle per
        loop. The virtual allocatable IS the packing capacity; resource
        limits are unaffected (they count real provider nodes)."""
        injected: List[str] = []
        upcoming = self.csr.get_upcoming_nodes()
        groups = {g.id(): g for g in self.provider.node_groups()}
        tmpl_provider = self.processors.template_node_info_provider
        nodes_by_group: Dict[str, List[Node]] = {}
        if tmpl_provider is not None and upcoming:
            for node in snapshot.nodes():
                g = self.provider.node_group_for_node(node)
                if g is not None:
                    nodes_by_group.setdefault(g.id(), []).append(node)
        for gid, count in upcoming.items():
            group = groups.get(gid)
            if group is None:
                continue
            template = None
            if tmpl_provider is not None:
                template = tmpl_provider.template_for(
                    group, nodes_by_group.get(gid, []), now_ts,
                    pods_of_node=snapshot.pods_on_node,
                    pending_daemonsets=pending_ds(),
                )
            if template is None:
                try:
                    template = group.template_node_info()
                except Exception as e:
                    err = to_autoscaler_error(e)
                    self.metrics.errors_total.inc(type=err.error_type.value)
                    continue
            if template is None:
                continue
            cap = template.packing_capacity()
            for i in range(count):
                virtual = dataclasses.replace(
                    template,
                    name=f"upcoming-{gid}-{i}",
                    allocatable=cap,
                    daemon_overhead=Resources(),
                    taints=list(template.taints),
                    labels=dict(template.labels),
                )
                snapshot.add_node(virtual)
                injected.append(virtual.name)
        return injected

    def _scale_down_candidates(
        self, all_nodes: Sequence[Node], upcoming_names: Sequence[str]
    ) -> List[Node]:
        upcoming = set(upcoming_names)
        out = []
        for node in all_nodes:
            if node.name in upcoming:
                continue
            if self.scale_down_planner.deletion_tracker.is_being_deleted(node.name):
                continue
            out.append(node)
        return out

    def _scale_down_in_cooldown(self, now_ts: float) -> bool:
        """reference :628-640."""
        o = self.options
        if (
            self.last_scale_up_ts is not None
            and now_ts - self.last_scale_up_ts < o.scale_down_delay_after_add_s
        ):
            return True
        delay_after_delete = o.scale_down_delay_after_delete_s or o.scan_interval_s
        if (
            self.last_scale_down_delete_ts is not None
            and now_ts - self.last_scale_down_delete_ts < delay_after_delete
        ):
            return True
        if (
            self.last_scale_down_fail_ts is not None
            and now_ts - self.last_scale_down_fail_ts < o.scale_down_delay_after_failure_s
        ):
            return True
        return False

    def _remove_old_unregistered(self, now_ts: float) -> int:
        """Instances stuck creating past the provision timeout are deleted
        (:732). The registry tracks per-instance first-seen timestamps, so a
        freshly booting instance survives an autoscaler restart — only
        long-unregistered ones (past max_node_provision_time) are removed."""
        removed = 0
        groups = {g.id(): g for g in self.provider.node_groups()}
        for gid, instances in self.csr.long_unregistered_instances().items():
            group = groups.get(gid)
            if group is None:
                continue
            stuck = [Node(name=i.id, provider_id=i.id) for i in instances]
            try:
                group.delete_nodes(stuck)
                removed += len(stuck)
            except Exception as e:
                err = to_autoscaler_error(e)
                self.metrics.errors_total.inc(type=err.error_type.value)
        return removed

    def _delete_created_nodes_with_errors(self) -> None:
        """Instances that failed creation are deleted so the target shrinks
        and a different group can be tried (:773)."""
        errored = self.csr.instances_with_errors()
        groups = {g.id(): g for g in self.provider.node_groups()}
        for gid, instances in errored.items():
            group = groups.get(gid)
            if group is None:
                continue
            try:
                group.delete_nodes(
                    [Node(name=i.id, provider_id=i.id) for i in instances]
                )
            except Exception as e:
                err = to_autoscaler_error(e)
                self.metrics.errors_total.inc(type=err.error_type.value)
                try:
                    group.decrease_target_size(len(instances))
                except Exception as e2:
                    err2 = to_autoscaler_error(e2)
                    self.metrics.errors_total.inc(
                        type=err2.error_type.value
                    )
