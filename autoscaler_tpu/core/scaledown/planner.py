"""Scale-down planner: decide which nodes are unneeded and which to delete.

Reference: cluster-autoscaler/core/scaledown/planner/planner.go — Planner :62,
UpdateClusterState :103 (fork → inject recently-evicted pods → categorize),
categorizeNodes :252 (eligibility filter then per-node SimulateNodeRemoval
under ScaleDownSimulationTimeout), NodesToDelete :134 (limits + unneeded-time
gates + parallelism caps), and the candidate-pool bounds of the legacy path
(legacy.go:152-180: 30 non-empty candidates, pool ratio 0.1, pool min 50).
The per-node removal simulation is batched into one device dispatch
(simulator/removal.py), so the simulation-timeout knob bounds one call, not a
loop.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from autoscaler_tpu.cloudprovider.interface import CloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.scaledown.eligibility import EligibilityChecker
from autoscaler_tpu.core.scaledown.limits import LimitsFinder, build_resource_limiter
from autoscaler_tpu.core.scaleup.resource_manager import ResourceDelta
from autoscaler_tpu.core.scaledown.tracking import (
    NodeDeletionTracker,
    RemainingPdbTracker,
    UnneededNodes,
    UnremovableNodesCache,
)
from autoscaler_tpu.kube.objects import Node, PodDisruptionBudget
from autoscaler_tpu.simulator.drain import daemonset_pods_of
from autoscaler_tpu.simulator.removal import (
    NodeToRemove,
    RemovalSimulator,
    UnremovableNode,
    UnremovableReason,
)
from autoscaler_tpu.simulator.tracker import UsageTracker
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
from autoscaler_tpu import trace


@dataclass
class ScaleDownPlan:
    empty: List[NodeToRemove] = field(default_factory=list)
    drain: List[NodeToRemove] = field(default_factory=list)
    unremovable: List[UnremovableNode] = field(default_factory=list)


class ScaleDownPlanner:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        deletion_tracker: Optional[NodeDeletionTracker] = None,
        removal_simulator: Optional[RemovalSimulator] = None,
        set_processor=None,
    ):
        self.provider = provider
        self.options = options
        self.eligibility = EligibilityChecker(options, provider)
        self.unneeded = UnneededNodes()
        self.unremovable_cache = UnremovableNodesCache(
            options.unremovable_node_recheck_timeout_s
        )
        self.deletion_tracker = deletion_tracker or NodeDeletionTracker()
        if removal_simulator is None:
            from autoscaler_tpu.simulator.drain import DrainabilityRules

            # drain policy knobs flow from options (they were silently
            # defaulted before — --skip-nodes-with-* and --min-replica-count
            # had no effect on the default path)
            removal_simulator = RemovalSimulator(
                rules=DrainabilityRules(
                    skip_nodes_with_system_pods=options.skip_nodes_with_system_pods,
                    skip_nodes_with_local_storage=options.skip_nodes_with_local_storage,
                    skip_nodes_with_custom_controller_pods=(
                        options.skip_nodes_with_custom_controller_pods
                    ),
                    min_replica_count=options.min_replica_count,
                )
            )
        self.simulator = removal_simulator
        self._adaptive_candidate_limit: Optional[int] = None
        self.limits_finder = LimitsFinder(build_resource_limiter(options, provider))
        self.set_processor = set_processor
        self.usage_tracker = UsageTracker()
        self._last_unremovable: List[UnremovableNode] = []
        self._utilization: Dict[str, float] = {}

    # -- per-loop update (reference planner.go:103) --------------------------
    def update_cluster_state(
        self,
        snapshot: ClusterSnapshot,
        scale_down_candidates: Sequence[Node],
        pdbs: Sequence[PodDisruptionBudget],
        now_ts: float,
    ) -> None:
        eligible, utilization, unremovable = self.eligibility.filter_out_unremovable(
            snapshot, scale_down_candidates, now_ts, self.unremovable_cache
        )
        self._utilization = utilization

        # Empty nodes are detected over ALL eligible nodes — they need no
        # drain simulation, and the reference finds them before the pool
        # heuristics kick in (legacy.go:101 phase order: utilization filter →
        # empty nodes → candidate pools). The pool bounds (legacy.go:152-180)
        # only cap the expensive non-empty (drain-simulation) candidates.
        empty_names = set(self.simulator.find_empty_nodes(snapshot, eligible))
        pool = self._bound_candidates([n for n in eligible if n not in empty_names])
        non_empty = pool
        limit = self.options.scale_down_non_empty_candidates_count
        if limit > 0:
            non_empty = non_empty[:limit]
        # ScaleDownSimulationTimeout (planner.go:262-272) adapted to the
        # batched dispatch: one device call can't stop mid-way, so the bound
        # is enforced across loops — a dispatch that blows the budget halves
        # the next loop's candidate width (AIMD), growing back while under
        # half-budget. 0 disables.
        if self._adaptive_candidate_limit is not None:
            non_empty = non_empty[: self._adaptive_candidate_limit]

        # timeline clock (graftlint GL001): the AIMD clamp below FEEDS BACK
        # into next tick's candidate width, so a wall-clock measurement here
        # would make replayed decision logs diverge on a slow host
        sim_start = trace.timeline_now()
        to_remove, not_removable = self.simulator.find_nodes_to_remove(
            snapshot, non_empty, pdbs
        )
        sim_s = trace.timeline_now() - sim_start
        budget = self.options.scale_down_simulation_timeout_s
        if budget > 0:
            if non_empty and sim_s > budget and len(non_empty) > 1:
                self._adaptive_candidate_limit = max(1, len(non_empty) // 2)
            elif self._adaptive_candidate_limit is not None and (
                not non_empty or sim_s < budget / 2
            ):
                # decay the clamp on fast dispatches AND on loops with no
                # non-empty candidates — a clamp from one past slow dispatch
                # must not throttle scale-down indefinitely
                widened = self._adaptive_candidate_limit * 2
                self._adaptive_candidate_limit = (
                    None if widened >= max(len(pool), 1) else widened
                )
        # remember the simulated moves so an actual deletion later can reset
        # the unneeded clocks of its destination nodes (simulator/tracker.go)
        for r in to_remove:
            for dest in set(r.destinations.values()):
                self.usage_tracker.register_usage(r.node.name, dest, now_ts)
        self.usage_tracker.cleanup(
            now_ts - max(2 * self.options.node_group_defaults.scale_down_unneeded_time_s, 600.0)
        )
        for u in not_removable:
            if u.node is not None:
                self.unremovable_cache.add(u.node.name, now_ts)
        unremovable.extend(not_removable)
        self._last_unremovable = unremovable

        # sorted(): empty_names is a SET, and this list's order becomes the
        # UnneededNodes insertion order, which is the order nodes_to_delete
        # walks when it crops to max_empty_bulk_delete — iterating the set
        # raw let PYTHONHASHSEED pick WHICH empty nodes die (caught by the
        # gym tuning ledger's cross-process byte-diff; the runtime
        # sanitizer can't see it because no ambient source fires)
        unneeded_nodes = [snapshot.get_node(n) for n in sorted(empty_names)]
        unneeded_nodes += [r.node for r in to_remove]
        self.unneeded.update([n for n in unneeded_nodes if n is not None], now_ts)
        self._empty_names = empty_names
        self._drainable = {r.node.name: r for r in to_remove}

    def _bound_candidates(self, eligible: List[str]) -> List[str]:
        ratio = self.options.scale_down_candidates_pool_ratio
        min_count = self.options.scale_down_candidates_pool_min_count
        if ratio >= 1.0:
            return eligible
        pool_size = max(int(len(eligible) * ratio), min_count)
        return eligible[:pool_size]

    # -- decision (reference planner.go:134) ---------------------------------
    def nodes_to_delete(self, snapshot: ClusterSnapshot, now_ts: float) -> ScaleDownPlan:
        plan = ScaleDownPlan(unremovable=list(self._last_unremovable))
        deletions_per_group: Dict[str, int] = {}
        # Cluster-wide floors (planner.go:145 LimitsFinder.LimitsLeft): how
        # much cores/memory/gpu scale-down may still remove before breaching
        # min_*_total. Nodes already mid-deletion don't count toward totals.
        limits_left = self.limits_finder.limits_left(
            snapshot.nodes(), self.deletion_tracker.is_being_deleted
        )

        def group_of(node: Node):
            g = self.provider.node_group_for_node(node)
            return g.id() if g else None

        for name in self.unneeded.names():
            node = snapshot.get_node(name)
            if node is None or self.deletion_tracker.is_being_deleted(name):
                continue
            gid = group_of(node)
            if gid is None:
                continue  # node outside any group is never deleted by us
            in_group = self.deletion_tracker.deletions_in_group(
                gid
            ) + deletions_per_group.get(gid, 0)
            if not self.unneeded.removable_at(
                node, now_ts, self.options, self.provider, in_group
            ):
                continue
            if name in self._empty_names:
                if len(plan.empty) < self.options.max_empty_bulk_delete:
                    if limits_left.try_decrement(ResourceDelta.for_node(node)):
                        plan.unremovable.append(
                            UnremovableNode(
                                node, UnremovableReason.MINIMAL_RESOURCE_LIMIT_EXCEEDED
                            )
                        )
                        continue
                    ds = daemonset_pods_of(snapshot.pods_on_node(name))
                    plan.empty.append(NodeToRemove(node, daemonset_pods=ds))
                    deletions_per_group[gid] = deletions_per_group.get(gid, 0) + 1
            elif name in self._drainable:
                if len(plan.drain) < self.options.max_drain_parallelism:
                    if limits_left.try_decrement(ResourceDelta.for_node(node)):
                        plan.unremovable.append(
                            UnremovableNode(
                                node, UnremovableReason.MINIMAL_RESOURCE_LIMIT_EXCEEDED
                            )
                        )
                        continue
                    plan.drain.append(self._drainable[name])
                    deletions_per_group[gid] = deletions_per_group.get(gid, 0) + 1
        # Final-selection seam (reference planner.go:151
        # ScaleDownSetProcessor.GetNodesToRemove); the default processor
        # crops to max_scale_down_parallelism, empty nodes first.
        cap = self.options.max_scale_down_parallelism
        if self.set_processor is not None:
            picked = self.set_processor.get_nodes_to_remove(
                plan.empty + plan.drain, cap
            )
            picked_set = {id(r) for r in picked}
            plan.empty = [r for r in plan.empty if id(r) in picked_set]
            plan.drain = [r for r in plan.drain if id(r) in picked_set]
        else:
            total = len(plan.empty) + len(plan.drain)
            if cap > 0 and total > cap:
                keep_empty = min(len(plan.empty), cap)
                plan.empty = plan.empty[:keep_empty]
                plan.drain = plan.drain[: max(0, cap - keep_empty)]
        # Joint re-validation: the per-candidate simulation above evaluated
        # each drain against the same base state; the picked set must also
        # hold *together* (no double-booked capacity, no destinations on
        # nodes that are themselves leaving). Mirrors the reference's
        # fresh-snapshot re-check during actuation (actuator.go:371).
        if plan.drain:
            empty_names = [r.node.name for r in plan.empty]
            valid, rejected = self.simulator.validate_removal_set(
                snapshot, plan.drain, also_removed=empty_names
            )
            plan.drain = valid
            plan.unremovable.extend(rejected)
        return plan

    def node_deleted(self, node_name: str, now_ts: float) -> List[str]:
        """A node was actually removed: reset the unneeded clocks of the
        nodes its drain simulation used as destinations (their utilization is
        about to rise when the real evictions land). Returns the reset names."""
        destinations = self.usage_tracker.remove_node(node_name)
        for dest in destinations:
            self.unneeded.reset_since(dest, now_ts)
        return destinations

    def utilization_of(self, node_name: str) -> Optional[float]:
        return self._utilization.get(node_name)

    def unneeded_names(self) -> List[str]:
        return self.unneeded.names()

    def last_unremovable(self) -> List[UnremovableNode]:
        """The previous update's rejection list (metrics + status surface)."""
        return list(self._last_unremovable)
