"""Cluster-wide resource floors for scale-down.

Reference: cluster-autoscaler/core/scaledown/resource/limits.go —
LimitsFinder.LimitsLeft :64 (cluster totals minus configured minimums,
nodes mid-deletion excluded from the totals), CheckDeltaWithinLimits :208
and TryDecrementBy :224 (all-or-nothing decrement per node). The reference
refuses to delete a node that would push total cores/memory/custom
resources under the operator's floor; the floors come from the cloud
provider's ResourceLimiter, which itself defaults to the
min/max_*_total AutoscalingOptions (context/autoscaling_context.go:79).

Units follow core/scaleup/resource_manager.py: cpu in millicores, memory
in MiB, gpu in device count.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from autoscaler_tpu.cloudprovider.interface import CloudProvider, ResourceLimiter
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.scaleup.resource_manager import (
    CPU_RES,
    MEM_RES,
    ResourceDelta,
)
from autoscaler_tpu.kube.objects import Node


def build_resource_limiter(
    options: AutoscalingOptions, provider: Optional[CloudProvider] = None
) -> ResourceLimiter:
    """Effective limiter: the options-derived floors/caps, overridden by any
    entries the cloud provider declares (the reference consults the
    provider's ResourceLimiter, which wraps the options defaults)."""
    min_limits: Dict[str, float] = {}
    max_limits: Dict[str, float] = {}
    if options.min_cores_total > 0:
        min_limits[CPU_RES] = options.min_cores_total
    max_limits[CPU_RES] = options.max_cores_total
    if options.min_memory_total > 0:
        min_limits[MEM_RES] = options.min_memory_total
    max_limits[MEM_RES] = options.max_memory_total_mib
    for name, (lo, hi) in options.gpu_total.items():
        if lo > 0:
            min_limits[name] = float(lo)
        max_limits[name] = float(hi)
    if provider is not None:
        plim = provider.get_resource_limiter()
        min_limits.update(plim.min_limits)
        max_limits.update(plim.max_limits)
    return ResourceLimiter(min_limits=min_limits, max_limits=max_limits)


class ScaleDownLimits:
    """Remaining deletable amount per limited resource. No entry = no floor
    (limits.go:77 'only actual limits into final map')."""

    def __init__(self, left: Dict[str, float]):
        self.left = left

    def check_delta(self, delta: ResourceDelta) -> List[str]:
        """Resources whose floor the delta would breach (limits.go:208)."""
        return [
            r
            for r, v in delta.resources.items()
            if v > 0 and r in self.left and v > self.left[r]
        ]

    def try_decrement(self, delta: ResourceDelta) -> List[str]:
        """All-or-nothing decrement (limits.go:224): on success ([] returned)
        the remaining headroom shrinks by the node's footprint; an exceeded
        delta leaves the limits untouched."""
        exceeded = self.check_delta(delta)
        if exceeded:
            return exceeded
        for r, v in delta.resources.items():
            if r in self.left:
                self.left[r] -= v
        return []


class LimitsFinder:
    """limits.go:53 — computes how much of each limited resource scale-down
    may still delete."""

    def __init__(self, limiter: ResourceLimiter):
        self.limiter = limiter

    def limits_left(
        self,
        nodes: Sequence[Node],
        is_being_deleted: Callable[[str], bool] = lambda name: False,
    ) -> ScaleDownLimits:
        """Cluster totals (excluding nodes mid-deletion, limits.go:113) minus
        each configured minimum, floored at zero (limits.go:100)."""
        totals: Dict[str, float] = {}
        for node in nodes:
            if is_being_deleted(node.name):
                continue
            for r, v in ResourceDelta.for_node(node).resources.items():
                totals[r] = totals.get(r, 0.0) + v
        left: Dict[str, float] = {}
        for r, floor in self.limiter.min_limits.items():
            if floor > 0:
                left[r] = max(0.0, totals.get(r, 0.0) - floor)
        return ScaleDownLimits(left)
