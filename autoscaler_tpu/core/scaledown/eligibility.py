"""Scale-down eligibility: which nodes are even candidates.

Reference: cluster-autoscaler/core/scaledown/eligibility/eligibility.go:66
(FilterOutUnremovable: scale-down-disabled annotation, unready policy,
per-nodegroup utilization threshold :164, GPU-aware threshold) — with the
utilization pass vectorized into one device reduction (ops/utilization.py)
instead of a per-node loop.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from autoscaler_tpu.cloudprovider.interface import CloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.kube.objects import (
    GPU,
    SCALE_DOWN_DISABLED_ANNOTATION,
    Node,
)
from autoscaler_tpu.ops.utilization import node_utilization
from autoscaler_tpu.simulator.removal import UnremovableNode, UnremovableReason
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
from autoscaler_tpu.utils import klogx


@dataclass
class EligibilityChecker:
    options: AutoscalingOptions
    provider: Optional[CloudProvider] = None

    def filter_out_unremovable(
        self,
        snapshot: ClusterSnapshot,
        candidates: Sequence[Node],
        now_ts: float,
        unremovable_cache=None,
    ) -> Tuple[List[str], Dict[str, float], List[UnremovableNode]]:
        """→ (eligible node names, utilization by name, unremovable). One
        utilization kernel call covers all nodes."""
        tensors, meta = snapshot.tensors()
        exclude = self._excluded_usage(tensors, meta)
        util = np.asarray(node_utilization(tensors, exclude_used=exclude))
        alloc_gpu = np.asarray(tensors.node_alloc[:, GPU])

        eligible: List[str] = []
        utilization: Dict[str, float] = {}
        unremovable: List[UnremovableNode] = []
        # per-loop quota for per-node lines (eligibility.go:71)
        util_quota = klogx.new_logging_quota(20)
        for node in candidates:
            if unremovable_cache is not None and unremovable_cache.is_recently_unremovable(
                node.name, now_ts
            ):
                unremovable.append(
                    UnremovableNode(node, UnremovableReason.RECENTLY_UNREMOVABLE)
                )
                continue
            if node.annotations.get(SCALE_DOWN_DISABLED_ANNOTATION, "").lower() == "true":
                unremovable.append(
                    UnremovableNode(node, UnremovableReason.SCALE_DOWN_DISABLED_ANNOTATION)
                )
                continue
            j = meta.node_index.get(node.name)
            if j is None:
                continue
            u = float(util[j])
            utilization[node.name] = u
            klogx.v(4).up_to(util_quota).info(
                "Node %s utilization %.3f", node.name, u
            )
            group_opts = self._group_options(node)
            threshold = (
                group_opts.scale_down_gpu_utilization_threshold
                if alloc_gpu[j] > 0
                else group_opts.scale_down_utilization_threshold
            )
            if not node.ready:
                # unready nodes are scale-down candidates regardless of
                # utilization (reference eligibility.go: unready path) —
                # unless the operator disabled it (ScaleDownUnreadyEnabled)
                if self.options.scale_down_unready_enabled:
                    eligible.append(node.name)
                else:
                    unremovable.append(
                        UnremovableNode(node, UnremovableReason.UNREADY_NOT_ALLOWED)
                    )
            elif u >= threshold:
                unremovable.append(
                    UnremovableNode(node, UnremovableReason.NOT_UTILIZED_ENOUGH)
                )
            else:
                eligible.append(node.name)
        klogx.v(4).over(util_quota).info(
            "Skipped logging utilization for %d other nodes", -util_quota.left
        )
        return eligible, utilization, unremovable

    def _excluded_usage(self, tensors, meta):
        """[N, R] usage to subtract from the utilization numerator when
        DaemonSet/mirror pods are configured as free (info.go:49
        CalculateUtilization's skipDaemonSetPods/skipMirrorPods)."""
        skip_ds = self.options.ignore_daemonsets_utilization
        skip_mirror = self.options.ignore_mirror_pods_utilization
        if not (skip_ds or skip_mirror):
            return None
        from autoscaler_tpu.snapshot.packer import resources_row

        exclude = np.zeros(tensors.node_alloc.shape, np.float32)
        ext = meta.extended_resources  # rows must match the widened axis
        for pod in meta.pods:
            if not pod.node_name:
                continue
            if (skip_ds and pod.daemonset) or (skip_mirror and pod.mirror):
                j = meta.node_index.get(pod.node_name)
                if j is not None:
                    exclude[j] += resources_row(pod.requests, 1.0, ext)
        return exclude

    def _group_options(self, node: Node):
        if self.provider is not None:
            group = self.provider.node_group_for_node(node)
            if group is not None:
                return self.options.group_options(group.id())
        return self.options.node_group_defaults
