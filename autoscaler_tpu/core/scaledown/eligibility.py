"""Scale-down eligibility: which nodes are even candidates.

Reference: cluster-autoscaler/core/scaledown/eligibility/eligibility.go:66
(FilterOutUnremovable: scale-down-disabled annotation, unready policy,
per-nodegroup utilization threshold :164, GPU-aware threshold) — with the
utilization pass vectorized into one device reduction (ops/utilization.py)
instead of a per-node loop.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from autoscaler_tpu.cloudprovider.interface import CloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.kube.objects import (
    GPU,
    SCALE_DOWN_DISABLED_ANNOTATION,
    Node,
)
from autoscaler_tpu.ops.utilization import node_utilization
from autoscaler_tpu.simulator.removal import UnremovableNode, UnremovableReason
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot


@dataclass
class EligibilityChecker:
    options: AutoscalingOptions
    provider: Optional[CloudProvider] = None

    def filter_out_unremovable(
        self,
        snapshot: ClusterSnapshot,
        candidates: Sequence[Node],
        now_ts: float,
        unremovable_cache=None,
    ) -> Tuple[List[str], Dict[str, float], List[UnremovableNode]]:
        """→ (eligible node names, utilization by name, unremovable). One
        utilization kernel call covers all nodes."""
        tensors, meta = snapshot.tensors()
        util = np.asarray(node_utilization(tensors))
        alloc_gpu = np.asarray(tensors.node_alloc[:, GPU])

        eligible: List[str] = []
        utilization: Dict[str, float] = {}
        unremovable: List[UnremovableNode] = []
        for node in candidates:
            if unremovable_cache is not None and unremovable_cache.is_recently_unremovable(
                node.name, now_ts
            ):
                unremovable.append(
                    UnremovableNode(node, UnremovableReason.RECENTLY_UNREMOVABLE)
                )
                continue
            if node.annotations.get(SCALE_DOWN_DISABLED_ANNOTATION, "").lower() == "true":
                unremovable.append(
                    UnremovableNode(node, UnremovableReason.SCALE_DOWN_DISABLED_ANNOTATION)
                )
                continue
            j = meta.node_index.get(node.name)
            if j is None:
                continue
            u = float(util[j])
            utilization[node.name] = u
            group_opts = self._group_options(node)
            threshold = (
                group_opts.scale_down_gpu_utilization_threshold
                if alloc_gpu[j] > 0
                else group_opts.scale_down_utilization_threshold
            )
            if not node.ready:
                # unready nodes are scale-down candidates regardless of
                # utilization (reference eligibility.go: unready path)
                eligible.append(node.name)
            elif u >= threshold:
                unremovable.append(
                    UnremovableNode(node, UnremovableReason.NOT_UTILIZED_ENOUGH)
                )
            else:
                eligible.append(node.name)
        return eligible, utilization, unremovable

    def _group_options(self, node: Node):
        if self.provider is not None:
            group = self.provider.node_group_for_node(node)
            if group is not None:
                return self.options.group_options(group.id())
        return self.options.node_group_defaults
