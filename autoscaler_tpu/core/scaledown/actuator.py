"""Scale-down actuation: taint → evict → delete, with budgets and batching.

Reference: cluster-autoscaler/core/scaledown/actuation/ —
Actuator.StartDeletion actuator.go:80 (budget crop :126 → sync taint :187 →
empty :156 / drain :206 → per-node scheduleDeletion :356 → batcher),
Evictor drain.go:83,90 (retry loop, eviction headroom, DaemonSet best-effort
eviction :178), NodeDeletionBatcher delete_in_batch.go:71,115 (per-group
batched DeleteNodes), soft taints softtaint.go:31,77 (bulk PreferNoSchedule
budget). The reference runs deletions on goroutines; this host runs them
synchronously per loop iteration (the cloud call is the bottleneck either
way) while preserving ordering, budgets, and failure bookkeeping.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from autoscaler_tpu.cloudprovider.interface import CloudProvider, NodeGroup
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.scaledown.planner import ScaleDownPlan
from autoscaler_tpu.core.scaledown.tracking import NodeDeletionTracker
from autoscaler_tpu.kube.api import (
    ClusterAPI,
    EvictionError,
    deletion_candidate_taint,
    to_be_deleted_taint,
)
from autoscaler_tpu.kube.objects import (
    DELETION_CANDIDATE_TAINT,
    TO_BE_DELETED_TAINT,
    Node,
    Pod,
)
from autoscaler_tpu.simulator.removal import NodeToRemove


@dataclass
class ActuationResult:
    deleted_empty: List[str] = field(default_factory=list)
    deleted_drain: List[str] = field(default_factory=list)
    failed: Dict[str, str] = field(default_factory=dict)
    evicted_pods: List[str] = field(default_factory=list)


class Evictor:
    """reference actuation/drain.go:83 DrainNodeWithPods."""

    def __init__(self, api: ClusterAPI, max_retries: int = 3):
        self.api = api
        self.max_retries = max_retries

    def drain_node(
        self, node: Node, pods: Sequence[Pod], tracker: NodeDeletionTracker, now_ts: float
    ) -> Tuple[bool, List[str]]:
        evicted: List[str] = []
        for pod in pods:
            ok = False
            last_err = ""
            for _ in range(self.max_retries):
                try:
                    self.api.evict_pod(pod)
                    tracker.register_eviction(pod.key(), now_ts)
                    evicted.append(pod.key())
                    ok = True
                    break
                except EvictionError as e:
                    last_err = str(e)
            if not ok:
                return False, evicted
        return True, evicted

    def evict_daemonset_pods(self, pods: Sequence[Pod]) -> List[str]:
        """Best-effort DaemonSet eviction (reference actuation/drain.go:177):
        failures never block the node deletion, and PDBs are not simulated —
        the eviction API enforces them server-side (the reference has the
        same behavior; see ROADMAP #3 note)."""
        evicted: List[str] = []
        for pod in pods:
            try:
                self.api.evict_pod(pod)
                evicted.append(pod.key())
            except EvictionError:
                pass
        return evicted


class NodeDeletionBatcher:
    """reference actuation/delete_in_batch.go:71 — collect nodes per group,
    flush as one DeleteNodes cloud call."""

    def __init__(self, provider: CloudProvider):
        self.provider = provider
        self._pending: Dict[str, List[Node]] = {}

    def add_node(self, group: NodeGroup, node: Node) -> None:
        self._pending.setdefault(group.id(), []).append(node)

    def flush(self) -> Dict[str, Optional[str]]:
        """→ group id → error (None on success)."""
        results: Dict[str, Optional[str]] = {}
        groups = {g.id(): g for g in self.provider.node_groups()}
        for gid, nodes in self._pending.items():
            group = groups.get(gid)
            if group is None:
                results[gid] = f"group {gid} no longer exists"
                continue
            try:
                group.delete_nodes(nodes)
                results[gid] = None
            except Exception as e:
                results[gid] = str(e)
        self._pending.clear()
        return results


class ScaleDownActuator:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        api: ClusterAPI,
        tracker: Optional[NodeDeletionTracker] = None,
    ):
        self.provider = provider
        self.options = options
        self.api = api
        self.tracker = tracker or NodeDeletionTracker()
        self.evictor = Evictor(api)

    # -- reference actuator.go:80 -------------------------------------------
    def start_deletion(self, plan: ScaleDownPlan, now_ts: float) -> ActuationResult:
        result = ActuationResult()
        empty = plan.empty[: self.options.max_empty_bulk_delete]
        drain = plan.drain[: self.options.max_drain_parallelism]

        # 1. taint everything up front, atomically-ish (actuator.go:95,111);
        # roll back taints on nodes we end up not deleting.
        tainted: List[str] = []
        for r in empty + drain:
            try:
                self.api.add_taint(r.node.name, to_be_deleted_taint())
                tainted.append(r.node.name)
            except Exception as e:
                result.failed[r.node.name] = f"taint failed: {e}"
        empty = [r for r in empty if r.node.name not in result.failed]
        drain = [r for r in drain if r.node.name not in result.failed]

        batcher = NodeDeletionBatcher(self.provider)
        staged: List[Tuple[NodeToRemove, bool]] = []  # (node, was_drain)

        for r in empty:
            group = self.provider.node_group_for_node(r.node)
            if group is None:
                result.failed[r.node.name] = "no node group"
                continue
            self.tracker.start_deletion(group.id(), r.node.name, drain=False)
            if self.options.daemonset_eviction_for_empty_nodes:
                result.evicted_pods.extend(
                    self.evictor.evict_daemonset_pods(r.daemonset_pods)
                )
            batcher.add_node(group, r.node)
            staged.append((r, False))

        for r in drain:
            group = self.provider.node_group_for_node(r.node)
            if group is None:
                result.failed[r.node.name] = "no node group"
                continue
            self.tracker.start_deletion(group.id(), r.node.name, drain=True)
            ok, evicted = self.evictor.drain_node(r.node, r.pods_to_reschedule, self.tracker, now_ts)
            result.evicted_pods.extend(evicted)
            if ok and self.options.daemonset_eviction_for_occupied_nodes:
                result.evicted_pods.extend(
                    self.evictor.evict_daemonset_pods(r.daemonset_pods)
                )
            if not ok:
                self.tracker.end_deletion(group.id(), r.node.name, ok=False, error="eviction failed", ts=now_ts)
                result.failed[r.node.name] = "eviction failed"
                self.api.remove_taint(r.node.name, TO_BE_DELETED_TAINT)
                continue
            batcher.add_node(group, r.node)
            staged.append((r, True))

        # 2. one batched cloud delete per group (delete_in_batch.go:115).
        errors = batcher.flush()
        for r, was_drain in staged:
            group = self.provider.node_group_for_node(r.node)
            gid = group.id() if group else ""
            err = errors.get(gid)
            if err:
                self.tracker.end_deletion(gid, r.node.name, ok=False, error=err, ts=now_ts)
                result.failed[r.node.name] = err
                self.api.remove_taint(r.node.name, TO_BE_DELETED_TAINT)
                continue
            self.api.delete_node_object(r.node.name)
            self.tracker.end_deletion(gid, r.node.name, ok=True, ts=now_ts)
            (result.deleted_drain if was_drain else result.deleted_empty).append(
                r.node.name
            )
            self.api.record_event(
                "Node", r.node.name, "ScaleDown", "node removed by autoscaler"
            )
        return result

    # -- soft taints (reference softtaint.go:31,77) --------------------------
    def update_soft_deletion_taints(
        self, all_nodes: Sequence[Node], unneeded_names: Sequence[str]
    ) -> int:
        """Keep DeletionCandidate (PreferNoSchedule) taints in sync with the
        current unneeded set, bounded by the bulk budget."""
        budget = self.options.max_bulk_soft_taint_count
        changed = 0
        unneeded = set(unneeded_names)
        for node in all_nodes:
            if changed >= budget:
                break
            has = any(t.key == DELETION_CANDIDATE_TAINT for t in node.taints)
            if node.name in unneeded and not has:
                self.api.add_taint(node.name, deletion_candidate_taint())
                changed += 1
            elif node.name not in unneeded and has:
                self.api.remove_taint(node.name, DELETION_CANDIDATE_TAINT)
                changed += 1
        return changed

    def clean_up_to_be_deleted_taints(self, nodes: Sequence[Node]) -> int:
        """Startup cleanup of leftover ToBeDeleted taints from a crashed
        predecessor (reference static_autoscaler.go:230-248)."""
        removed = 0
        for node in nodes:
            if any(t.key == TO_BE_DELETED_TAINT for t in node.taints):
                if not self.tracker.is_being_deleted(node.name):
                    self.api.remove_taint(node.name, TO_BE_DELETED_TAINT)
                    removed += 1
        return removed
