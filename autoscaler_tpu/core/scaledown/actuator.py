"""Scale-down actuation: taint → evict → delete, concurrent with budgets,
pacing, and batching.

Reference: cluster-autoscaler/core/scaledown/actuation/ —
Actuator.StartDeletion actuator.go:80 (budget crop :126 → sync taint :187 →
async empty :156 / drain :206 → per-node scheduleDeletion goroutine :356 →
batcher), Evictor drain.go:83,90 (time-budgeted retry loop: EvictionRetryTime
between attempts, MaxPodEvictionTime per pod, then a wait for actual pod
termination bounded by grace + PodEvictionHeadroom; DaemonSet best-effort
eviction :178), NodeDeletionBatcher delete_in_batch.go:71,115 (per-group
batched DeleteNodes on a timer), soft taints softtaint.go:31,77 (bulk
PreferNoSchedule budget).

Like the reference's goroutines, node drains here run on a thread pool
bounded by max_scale_down_parallelism (the cloud/API calls are IO-bound, so
threads are the right host-side concurrency primitive). start_deletion joins
the wave by default so the control loop keeps its synchronous contract; the
NodeDeletionTracker stays the cross-loop source of truth either way.
"""
from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from autoscaler_tpu.cloudprovider.interface import CloudProvider, NodeGroup
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.scaledown.planner import ScaleDownPlan
from autoscaler_tpu.core.scaledown.tracking import NodeDeletionTracker
from autoscaler_tpu.kube.api import (
    ClusterAPI,
    EvictionError,
    deletion_candidate_taint,
    to_be_deleted_taint,
)
from autoscaler_tpu.kube.objects import (
    DELETION_CANDIDATE_TAINT,
    TO_BE_DELETED_TAINT,
    Node,
    Pod,
)
from autoscaler_tpu.simulator.removal import NodeToRemove
from autoscaler_tpu.utils.errors import to_autoscaler_error


@dataclass
class ActuationResult:
    deleted_empty: List[str] = field(default_factory=list)
    deleted_drain: List[str] = field(default_factory=list)
    failed: Dict[str, str] = field(default_factory=dict)
    evicted_pods: List[str] = field(default_factory=list)


class Evictor:
    """reference actuation/drain.go:83 DrainNodeWithPods — per-pod eviction
    with a time-budgeted retry loop, then a bounded wait for the evicted
    pods to actually disappear. clock/sleep are injectable for tests."""

    def __init__(
        self,
        api: ClusterAPI,
        options: AutoscalingOptions,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.api = api
        self.options = options
        self.clock = clock
        self.sleep = sleep

    def drain_node(
        self, node: Node, pods: Sequence[Pod], tracker: NodeDeletionTracker, now_ts: float
    ) -> Tuple[bool, List[str]]:
        evicted: List[str] = []
        for pod in pods:
            if not self._evict_with_retry(pod):
                return False, evicted
            tracker.register_eviction(pod.key(), now_ts)
            evicted.append(pod.key())
        self._wait_pods_gone(pods)
        return True, evicted

    def _evict_with_retry(self, pod: Pod) -> bool:
        """Retry until MaxPodEvictionTime elapses, pausing EvictionRetryTime
        between attempts (drain.go:90). Always makes at least one attempt."""
        deadline = self.clock() + self.options.max_pod_eviction_time_s
        while True:
            try:
                self.api.evict_pod(pod)
                return True
            except EvictionError:
                if self.clock() >= deadline:
                    return False
                self.sleep(self.options.eviction_retry_time_s)

    def _wait_pods_gone(self, pods: Sequence[Pod]) -> None:
        """Bounded confirmation that evicted pods terminated: grace period
        plus PodEvictionHeadroom (drain.go:123-140)."""
        budget = (
            self.options.max_graceful_termination_s
            + self.options.pod_eviction_headroom_s
        )
        deadline = self.clock() + budget
        remaining = [p.key() for p in pods]
        while remaining and self.clock() < deadline:
            remaining = [k for k in remaining if self.api.pod_exists(k)]
            if remaining:
                self.sleep(0.5)

    def evict_daemonset_pods(self, pods: Sequence[Pod]) -> List[str]:
        """Best-effort DaemonSet eviction (reference actuation/drain.go:177):
        failures never block the node deletion, and PDBs are not simulated —
        the eviction API enforces them server-side (the reference has the
        same behavior; see ROADMAP #3 note)."""
        evicted: List[str] = []
        for pod in pods:
            try:
                self.api.evict_pod(pod)
                evicted.append(pod.key())
            except EvictionError:
                pass
        return evicted


class NodeDeletionBatcher:
    """reference actuation/delete_in_batch.go:71 — collect nodes per group;
    with a positive interval the FIRST add for a group arms a timer that
    flushes that group's batch as one DeleteNodes call (:115); interval 0
    means flush-per-add. Thread-safe: drain workers add concurrently.

    on_result(node, group_id, error_or_None) fires once per node when its
    batch flushes."""

    def __init__(
        self,
        provider: CloudProvider,
        interval_s: float = 0.0,
        on_result: Optional[Callable[[Node, str, Optional[str]], None]] = None,
    ):
        self.provider = provider
        self.interval_s = interval_s
        self.on_result = on_result
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight = 0
        self._pending: Dict[str, List[Node]] = {}
        self._timers: Dict[str, threading.Timer] = {}

    def add_node(self, group: NodeGroup, node: Node) -> None:
        gid = group.id()
        with self._lock:
            self._pending.setdefault(gid, []).append(node)
            if self.interval_s <= 0:
                pass  # flushed below, outside the lock
            elif gid not in self._timers:
                t = threading.Timer(self.interval_s, self._flush_group, args=(gid,))
                t.daemon = True
                self._timers[gid] = t
                t.start()
        if self.interval_s <= 0:
            self._flush_group(gid)

    def _take_group(self, gid: str) -> List[Node]:
        """Pop a group's batch; a non-empty take marks a flush in flight so
        flush() can join timer flushes that already popped their nodes."""
        with self._lock:
            timer = self._timers.pop(gid, None)
            if timer is not None:
                timer.cancel()
            nodes = self._pending.pop(gid, [])
            if nodes:
                self._inflight += 1
            return nodes

    def _flush_group(
        self, gid: str, groups: Optional[Dict[str, NodeGroup]] = None
    ) -> Dict[str, Optional[str]]:
        nodes = self._take_group(gid)
        if not nodes:
            return {}
        try:
            if groups is None:
                groups = {g.id(): g for g in self.provider.node_groups()}
            group = groups.get(gid)
            if group is None:
                err: Optional[str] = f"group {gid} no longer exists"
            else:
                try:
                    group.delete_nodes(nodes)
                    err = None
                except Exception as e:
                    # typed wrapping: str() is preserved for non-empty
                    # messages, and an empty one gains the exception class
                    err = str(to_autoscaler_error(e))
            if self.on_result is not None:
                for node in nodes:
                    self.on_result(node, gid, err)
            return {gid: err}
        finally:
            with self._idle:
                self._inflight -= 1
                self._idle.notify_all()

    def flush(self) -> Dict[str, Optional[str]]:
        """Force-flush everything now (cancels pending timers) and JOIN any
        timer flush already mid-delete, so callers get the full wave's
        results before returning. The control loop uses this to close a
        deletion wave synchronously."""
        with self._lock:
            gids = list(self._pending.keys())
        results: Dict[str, Optional[str]] = {}
        groups = {g.id(): g for g in self.provider.node_groups()} if gids else {}
        for gid in gids:
            results.update(self._flush_group(gid, groups))
        with self._idle:
            while self._inflight > 0:
                self._idle.wait()
        return results


class ScaleDownActuator:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        api: ClusterAPI,
        tracker: Optional[NodeDeletionTracker] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.provider = provider
        self.options = options
        self.api = api
        self.tracker = tracker or NodeDeletionTracker()
        self.sleep = sleep
        self.evictor = Evictor(api, options, clock=clock, sleep=sleep)

    # -- reference actuator.go:80 -------------------------------------------
    def start_deletion(self, plan: ScaleDownPlan, now_ts: float) -> ActuationResult:
        result = ActuationResult()
        result_lock = threading.Lock()
        empty = plan.empty[: self.options.max_empty_bulk_delete]
        drain = plan.drain[: self.options.max_drain_parallelism]

        def rollback_node(name: str) -> None:
            """A node that survives a failed/aborted deletion must return
            to service: taint off, and cordon off if we cordoned it — else
            it stays unschedulable forever (reference CleanToBeDeleted
            uncordons when the flag is set). Independent attempts: a failed
            taint removal must not skip the uncordon, and a cordon that
            landed server-side before its call raised must still be undone."""
            try:
                self.api.remove_taint(name, TO_BE_DELETED_TAINT)
            except Exception as e:
                # best-effort by design, but the swallow must not be
                # silent: a node left tainted is invisible to schedulers
                # until the next loop re-reconciles it
                logging.getLogger("scaledown").debug(
                    "rollback: taint removal on %s failed: %s",
                    name,
                    to_autoscaler_error(e),
                )
            if self.options.cordon_node_before_terminating:
                try:
                    self.api.uncordon_node(name)
                except Exception as e:
                    logging.getLogger("scaledown").debug(
                        "rollback: uncordon of %s failed: %s",
                        name,
                        to_autoscaler_error(e),
                    )

        # 1. taint everything up front, atomically-ish (actuator.go:95,111);
        # roll back taints on nodes we end up not deleting.
        for r in empty + drain:
            try:
                self.api.add_taint(r.node.name, to_be_deleted_taint())
                if self.options.cordon_node_before_terminating:
                    self.api.cordon_node(r.node.name)
            except Exception as e:
                # typed wrapping keeps str() identical for non-empty
                # messages, so the result map reads the same downstream
                result.failed[r.node.name] = (
                    f"taint failed: {to_autoscaler_error(e)}"
                )
                rollback_node(r.node.name)
        empty = [r for r in empty if r.node.name not in result.failed]
        drain = [r for r in drain if r.node.name not in result.failed]

        was_drain: Dict[str, bool] = {}

        def on_batch_result(node: Node, gid: str, err: Optional[str]) -> None:
            if err:
                self.tracker.end_deletion(gid, node.name, ok=False, error=err, ts=now_ts)
                with result_lock:
                    result.failed[node.name] = err
                rollback_node(node.name)
                return
            self.api.delete_node_object(node.name)
            self.tracker.end_deletion(gid, node.name, ok=True, ts=now_ts)
            with result_lock:
                (
                    result.deleted_drain if was_drain[node.name] else result.deleted_empty
                ).append(node.name)
            self.api.record_event(
                "Node", node.name, "ScaleDown", "node removed by autoscaler"
            )

        batcher = NodeDeletionBatcher(
            self.provider,
            interval_s=self.options.node_deletion_batcher_interval_s,
            on_result=on_batch_result,
        )

        def delete_empty(r: NodeToRemove, group: NodeGroup) -> None:
            """actuator.go:156 deleteAsyncEmpty — no drain simulation, just
            optional best-effort DS eviction then the batched cloud delete."""
            if self.options.node_delete_delay_after_taint_s > 0:
                # scheduler gets time to observe the ToBeDeleted taint
                # (actuator.go NodeDeleteDelayAfterTaint); paid inside the
                # worker so parallel waves overlap the pause
                self.sleep(self.options.node_delete_delay_after_taint_s)
            if self.options.daemonset_eviction_for_empty_nodes:
                evicted = self.evictor.evict_daemonset_pods(r.daemonset_pods)
                with result_lock:
                    result.evicted_pods.extend(evicted)
            batcher.add_node(group, r.node)

        def delete_drain(r: NodeToRemove, group: NodeGroup) -> None:
            """actuator.go:206,356 scheduleDeletion — evict (paced), then
            hand the node to the batcher; eviction failure rolls the taint
            back and never reaches the cloud call."""
            if self.options.node_delete_delay_after_taint_s > 0:
                self.sleep(self.options.node_delete_delay_after_taint_s)
            ok, evicted = self.evictor.drain_node(
                r.node, r.pods_to_reschedule, self.tracker, now_ts
            )
            with result_lock:
                result.evicted_pods.extend(evicted)
            if ok and self.options.daemonset_eviction_for_occupied_nodes:
                ds_evicted = self.evictor.evict_daemonset_pods(r.daemonset_pods)
                with result_lock:
                    result.evicted_pods.extend(ds_evicted)
            if not ok:
                self.tracker.end_deletion(
                    group.id(), r.node.name, ok=False, error="eviction failed", ts=now_ts
                )
                with result_lock:
                    result.failed[r.node.name] = "eviction failed"
                rollback_node(r.node.name)
                return
            batcher.add_node(group, r.node)

        def run_guarded(fn, r: NodeToRemove, group: NodeGroup) -> None:
            """An unexpected error in a worker must still close out the
            node's deletion (end_deletion + taint rollback) — an unretrieved
            future exception would otherwise leak the node in the tracker as
            being-deleted forever."""
            try:
                fn(r, group)
            except Exception as e:
                # one typed rendering feeds both the tracker and the
                # result map so they can never disagree about the cause
                msg = str(to_autoscaler_error(e))
                self.tracker.end_deletion(
                    group.id(), r.node.name, ok=False, error=msg, ts=now_ts
                )
                with result_lock:
                    result.failed[r.node.name] = msg
                rollback_node(r.node.name)

        # 2. fan the wave out on a bounded worker pool (the goroutine analog).
        workers = max(1, self.options.max_scale_down_parallelism)
        with ThreadPoolExecutor(max_workers=workers) as pool:
            for r, is_drain, fn in [(r, False, delete_empty) for r in empty] + [
                (r, True, delete_drain) for r in drain
            ]:
                group = self.provider.node_group_for_node(r.node)
                if group is None:
                    result.failed[r.node.name] = "no node group"
                    # the up-front taint/cordon must not outlive the abort
                    rollback_node(r.node.name)
                    continue
                was_drain[r.node.name] = is_drain
                self.tracker.start_deletion(group.id(), r.node.name, drain=is_drain)
                pool.submit(run_guarded, fn, r, group)
        # 3. close the wave: one batched cloud delete per group
        # (delete_in_batch.go:115), even if the batch timer hasn't fired.
        batcher.flush()
        return result

    # -- soft taints (reference softtaint.go:31,77) --------------------------
    def update_soft_deletion_taints(
        self, all_nodes: Sequence[Node], unneeded_names: Sequence[str]
    ) -> int:
        """Keep DeletionCandidate (PreferNoSchedule) taints in sync with the
        current unneeded set, bounded by the bulk count budget AND the time
        budget (reference softtaint.go:77 — each taint is one API round
        trip, and a slow control plane must not let this housekeeping eat
        the whole tick). The clock is the tracer's timeline seam, so the
        budget check replays deterministically under loadgen."""
        from autoscaler_tpu import trace

        budget = self.options.max_bulk_soft_taint_count
        time_budget = self.options.max_bulk_soft_taint_time_s
        t0 = trace.timeline_now()
        changed = 0
        unneeded = set(unneeded_names)
        for node in all_nodes:
            if changed >= budget:
                break
            if time_budget > 0 and trace.timeline_now() - t0 > time_budget:
                break
            has = any(t.key == DELETION_CANDIDATE_TAINT for t in node.taints)
            if node.name in unneeded and not has:
                self.api.add_taint(node.name, deletion_candidate_taint())
                changed += 1
            elif node.name not in unneeded and has:
                self.api.remove_taint(node.name, DELETION_CANDIDATE_TAINT)
                changed += 1
        return changed

    def clean_up_to_be_deleted_taints(self, nodes: Sequence[Node]) -> int:
        """Startup cleanup of leftover ToBeDeleted taints from a crashed
        predecessor (reference static_autoscaler.go:230-248)."""
        removed = 0
        for node in nodes:
            if any(t.key == TO_BE_DELETED_TAINT for t in node.taints):
                if not self.tracker.is_being_deleted(node.name):
                    self.api.remove_taint(node.name, TO_BE_DELETED_TAINT)
                    removed += 1
        return removed
