"""Scale-down bookkeeping: unneeded-time tracking, unremovable TTL cache,
node deletion tracker, PDB tracker.

Reference:
- unneeded nodes: core/scaledown/unneeded/nodes.go:38 (Update, RemovableAt
  :120 — node must be continuously unneeded for scale_down_unneeded_time /
  unready for scale_down_unready_time, group must stay >= min size, cluster
  resource minimums must hold)
- unremovable cache: core/scaledown/unremovable/nodes.go:30 (TTL re-check)
- deletion tracker: core/scaledown/deletiontracker/nodedeletiontracker.go:32
- PDB tracker: core/scaledown/pdb/pdb.go:26 + basic.go:66,86
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from autoscaler_tpu.cloudprovider.interface import CloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.kube.objects import Node, Pod, PodDisruptionBudget


@dataclass
class _UnneededEntry:
    since_ts: float
    node: Node


class UnneededNodes:
    """Tracks how long each node has been continuously unneeded."""

    def __init__(self) -> None:
        self._entries: Dict[str, _UnneededEntry] = {}

    def reset(self) -> None:
        """Drop all unneeded clocks (the reference's ResetUnneededNodes
        callback, fired when the cluster becomes non-actionable so stale
        timers can't trigger deletions when it resumes)."""
        self._entries.clear()

    def update(self, unneeded: Sequence[Node], now_ts: float) -> None:
        names = {n.name for n in unneeded}
        for name in list(self._entries):
            if name not in names:
                del self._entries[name]
        for node in unneeded:
            if node.name not in self._entries:
                self._entries[node.name] = _UnneededEntry(now_ts, node)
            else:
                self._entries[node.name].node = node

    def names(self) -> List[str]:
        return list(self._entries)

    def since(self, name: str) -> Optional[float]:
        e = self._entries.get(name)
        return e.since_ts if e else None

    def reset_since(self, name: str, now_ts: float) -> None:
        """Restart a node's continuously-unneeded clock — used when pods from
        a just-deleted node were simulated onto it (UsageTracker), since its
        utilization is about to rise."""
        e = self._entries.get(name)
        if e is not None:
            e.since_ts = now_ts

    def removable_at(
        self,
        node: Node,
        now_ts: float,
        options: AutoscalingOptions,
        provider: Optional[CloudProvider] = None,
        nodes_being_deleted_in_group: int = 0,
    ) -> bool:
        """reference unneeded/nodes.go:120 RemovableAt."""
        e = self._entries.get(node.name)
        if e is None:
            return False
        group_opts = options.node_group_defaults
        group = provider.node_group_for_node(node) if provider else None
        if group is not None:
            group_opts = options.group_options(group.id())
        required = (
            group_opts.scale_down_unneeded_time_s
            if node.ready
            else group_opts.scale_down_unready_time_s
        )
        if now_ts - e.since_ts < required:
            return False
        if group is not None:
            remaining = group.target_size() - nodes_being_deleted_in_group - 1
            if remaining < group.min_size():
                return False
        return True


class UnremovableNodesCache:
    """TTL cache so unremovable nodes are not re-simulated every loop
    (reference unremovable/nodes.go:30)."""

    def __init__(self, ttl_s: float = 300.0):
        self.ttl_s = ttl_s
        self._until: Dict[str, float] = {}

    def add(self, node_name: str, now_ts: float) -> None:
        self._until[node_name] = now_ts + self.ttl_s

    def is_recently_unremovable(self, node_name: str, now_ts: float) -> bool:
        return self._until.get(node_name, 0.0) > now_ts

    def clear(self) -> None:
        self._until.clear()


@dataclass
class DeletionResult:
    node_name: str
    group_id: str
    ok: bool
    error: str = ""
    ts: float = 0.0


class NodeDeletionTracker:
    """In-flight deletion accounting (reference
    deletiontracker/nodedeletiontracker.go:32,70-173)."""

    def __init__(self) -> None:
        # deletions run on worker threads (actuator.py) — guard all mutation
        self._lock = threading.Lock()
        self._empty: Dict[str, str] = {}   # node → group
        self._drained: Dict[str, str] = {}
        self._results: List[DeletionResult] = []
        self._evictions: Dict[str, float] = {}  # pod key → ts

    def start_deletion(self, group_id: str, node_name: str, drain: bool) -> None:
        with self._lock:
            (self._drained if drain else self._empty)[node_name] = group_id

    def end_deletion(self, group_id: str, node_name: str, ok: bool, error: str = "", ts: float = 0.0) -> None:
        with self._lock:
            self._empty.pop(node_name, None)
            self._drained.pop(node_name, None)
            self._results.append(DeletionResult(node_name, group_id, ok, error, ts))

    def is_being_deleted(self, node_name: str) -> bool:
        with self._lock:
            return node_name in self._empty or node_name in self._drained

    def deletions_in_group(self, group_id: str) -> int:
        with self._lock:
            return sum(1 for g in self._empty.values() if g == group_id) + sum(
                1 for g in self._drained.values() if g == group_id
            )

    def deletions_count(self, drain: bool) -> int:
        with self._lock:
            return len(self._drained) if drain else len(self._empty)

    def in_flight_names(self) -> List[str]:
        with self._lock:
            return list(self._empty) + list(self._drained)

    def register_eviction(self, pod_key: str, ts: float) -> None:
        with self._lock:
            self._evictions[pod_key] = ts

    def recent_evictions(self, since_ts: float) -> List[str]:
        with self._lock:
            return [k for k, t in self._evictions.items() if t >= since_ts]

    def drain_results(self) -> List[DeletionResult]:
        with self._lock:
            return list(self._results)

    def clear_results(self) -> None:
        with self._lock:
            self._results.clear()


class RemainingPdbTracker:
    """reference pdb/basic.go — per-loop PDB budget accounting."""

    def __init__(self, pdbs: Sequence[PodDisruptionBudget] = ()):
        self._pdbs = list(pdbs)
        self._remaining: Dict[int, int] = {id(p): p.disruptions_allowed for p in self._pdbs}

    def set_pdbs(self, pdbs: Sequence[PodDisruptionBudget]) -> None:
        self._pdbs = list(pdbs)
        self._remaining = {id(p): p.disruptions_allowed for p in self._pdbs}

    def matching(self, pod: Pod) -> List[PodDisruptionBudget]:
        return [
            p
            for p in self._pdbs
            if p.namespace == pod.namespace and p.selector.matches(pod.labels)
        ]

    def can_remove_pods(self, pods: Sequence[Pod]) -> bool:
        """reference basic.go:66 CanRemovePods."""
        need: Dict[int, int] = {}
        for pod in pods:
            for pdb in self.matching(pod):
                need[id(pdb)] = need.get(id(pdb), 0) + 1
        return all(self._remaining.get(k, 0) >= v for k, v in need.items())

    def remove_pods(self, pods: Sequence[Pod]) -> None:
        """reference basic.go:86 RemovePods — commit the budget use."""
        for pod in pods:
            for pdb in self.matching(pod):
                self._remaining[id(pdb)] -= 1

    def pdbs(self) -> List[PodDisruptionBudget]:
        return list(self._pdbs)
