"""Device-mesh parallelism for multi-scenario what-if evaluation.

The reference evaluates one snapshot at a time (Fork/Revert on a single
in-memory snapshot, cluster-autoscaler/simulator/clustersnapshot/delta.go:
448-469) and loops serially over node groups. Here the two embarrassingly
parallel axes of the decision problem become mesh axes:

- ``scenario`` — independent what-if worlds (spot-pricing scenarios, candidate
  futures; BASELINE config #5's 8-scenario pmap) — the data-parallel axis.
- ``group`` — node groups whose expansion options are independent until the
  final expander reduction — the model-parallel axis; the cross-group argmin
  (the expander's BestOption, reference expander/expander.go:52) is the one
  collective, an all_gather over ICI.

shard_map + jax.sharding.Mesh so the same code runs on 1 chip, a v5e-8 ICI
mesh, or multi-host DCN meshes — XLA inserts the collectives.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from autoscaler_tpu.kube.objects import PODS
from autoscaler_tpu.ops.binpack import ffd_binpack_groups

UNSCHEDULED_PENALTY = 1.0e6  # cost per pod left pending, dominates node price


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """Version-portable shard_map: jax >= 0.6 exposes jax.shard_map (with
    check_vma); earlier releases carry it in jax.experimental.shard_map
    (with check_rep). Replication checking stays off either way — the
    expander argmin deliberately returns replicated values from gathered
    shards."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def factor_mesh(n: int) -> tuple[int, int]:
    """Split n devices into (scenario, group) dims, group dim = largest
    divisor <= sqrt(n) so both axes get parallelism when possible."""
    g = 1
    for d in range(int(n**0.5), 0, -1):
        if n % d == 0:
            g = d
            break
    return n // g, g


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    s, g = factor_mesh(len(devices))
    return Mesh(np.asarray(devices).reshape(s, g), ("scenario", "group"))


def arrange_devices_for_hosts(devices: Sequence) -> np.ndarray:
    """[scenario, group] device grid for a (possibly multi-host) fleet.

    Collective-placement rationale (the scaling-book recipe: put the axis
    that carries collectives on the fastest interconnect):
    - the ``group`` axis carries the ONLY collective in the decision step
      (the expander's cross-group argmin all_gather) → it must stay INSIDE
      a host so the gather rides ICI;
    - the ``scenario`` axis is embarrassingly parallel (independent what-if
      worlds, zero collectives) → it is free to span hosts over DCN.

    So: group axis = devices of one process (ICI), scenario axis = host
    index × per-host scenario splits (DCN × ICI). Falls back to the flat
    single-host factorization when every device shares a process.

    Duck-typed on ``.process_index`` so the layout logic is testable
    without a real multi-host fleet; requires a homogeneous fleet (same
    device count per host).
    """
    by_host: dict = {}
    for d in devices:
        by_host.setdefault(d.process_index, []).append(d)
    hosts = [by_host[k] for k in sorted(by_host)]
    n_hosts = len(hosts)
    per_host = len(hosts[0])
    if any(len(h) != per_host for h in hosts):
        raise ValueError(
            f"heterogeneous fleet: {[len(h) for h in hosts]} devices per host"
        )
    if n_hosts == 1:
        s, g = factor_mesh(per_host)
        return np.asarray(hosts[0]).reshape(s, g)
    # groups get the WHOLE ICI domain: with n_hosts > 1 the scenario axis
    # already has host-level parallelism, so nothing justifies splitting a
    # host's ICI between the axes (and a split would shrink the all_gather's
    # interconnect share)
    s_local, g = 1, per_host
    grid = np.empty((n_hosts * s_local, g), dtype=object)
    for hi, host_devs in enumerate(hosts):
        grid[hi * s_local : (hi + 1) * s_local, :] = np.asarray(
            host_devs
        ).reshape(s_local, g)
    return grid


def make_multihost_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Mesh for a multi-host fleet: scenario axis spans hosts (DCN),
    group axis stays within each host (ICI). On one host this equals
    make_mesh. Call jax.distributed.initialize() first on real fleets."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(arrange_devices_for_hosts(devices), ("scenario", "group"))


class WhatIfResult(NamedTuple):
    node_counts: jax.Array   # [S, G] i32 — nodes needed per scenario × group
    total_costs: jax.Array   # [S, G] f32 — price·count + penalty·unscheduled
    best_group: jax.Array    # [S] i32 — expander argmin per scenario
    best_cost: jax.Array     # [S] f32


def _whatif_local(pod_req, pod_masks, allocs, prices, caps, *, max_nodes,
                  group_axis, binpack_fn=None, scenario_loop=False):
    """Per-shard body: batched FFD over the local (scenario, group) block,
    then the expander reduction with an all_gather across the group axis.

    ``binpack_fn`` swaps the kernel (default ffd_binpack_groups; e.g. the
    Pallas twin). ``scenario_loop`` unrolls the scenario batch as a Python
    loop instead of vmap — required for kernels whose pallas_call does not
    vmap (the per-shard scenario count is small and static)."""
    S_loc = allocs.shape[0]
    kern = binpack_fn if binpack_fn is not None else ffd_binpack_groups

    def per_scenario(alloc_s, price_s):
        res = kern(pod_req, pod_masks, alloc_s, max_nodes=max_nodes, node_caps=caps)
        valid = pod_req[:, PODS] > 0  # real pods carry a pods-count of 1
        pending = jnp.sum(valid) - jnp.sum(res.scheduled & valid[None, :], axis=1)
        cost = price_s * res.node_count.astype(jnp.float32) + UNSCHEDULED_PENALTY * pending.astype(
            jnp.float32
        )
        return res.node_count, cost

    if scenario_loop:
        outs = [per_scenario(allocs[s], prices[s]) for s in range(S_loc)]
        counts = jnp.stack([o[0] for o in outs])
        costs = jnp.stack([o[1] for o in outs])
    else:
        counts, costs = jax.vmap(per_scenario)(allocs, prices)  # [S_loc, G_loc]

    if group_axis is None:
        all_costs = costs
        base = 0
    else:
        gathered = jax.lax.all_gather(costs, group_axis)      # [g_dim, S_loc, G_loc]
        all_costs = jnp.transpose(gathered, (1, 0, 2)).reshape(S_loc, -1)
        base = 0  # indices in all_costs are already global (block-ordered)
    best = jnp.argmin(all_costs, axis=1).astype(jnp.int32) + base
    best_cost = jnp.min(all_costs, axis=1)
    return counts, costs, best, best_cost


def whatif_best_options(
    mesh: Mesh,
    pod_req: jax.Array,      # [P, R] shared pending pods
    pod_masks: jax.Array,    # [G, P] per-group predicate masks (shared across scenarios)
    allocs: jax.Array,       # [S, G, R] per-scenario template capacities
    prices: jax.Array,       # [S, G] per-scenario per-group node price
    caps: jax.Array,         # [G] i32 per-group node caps
    max_nodes: int,
    binpack_fn=None,
    scenario_loop: bool = False,
) -> WhatIfResult:
    """Full multi-scenario scale-up evaluation, sharded over the mesh.

    S must divide by mesh['scenario'], G by mesh['group'] (pad upstream).
    ``binpack_fn``/``scenario_loop``: see _whatif_local — the Pallas twin
    runs under shard_map with binpack_fn=ffd_binpack_groups_pallas,
    scenario_loop=True.
    """
    s_dim = mesh.shape["scenario"]
    g_dim = mesh.shape["group"]
    S, G = allocs.shape[0], allocs.shape[1]
    assert S % s_dim == 0, f"S={S} not divisible by scenario dim {s_dim}"
    assert G % g_dim == 0, f"G={G} not divisible by group dim {g_dim}"

    fn = functools.partial(
        _whatif_local, max_nodes=max_nodes,
        group_axis="group" if g_dim > 1 else None,
        binpack_fn=binpack_fn, scenario_loop=scenario_loop,
    )
    mapped = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(
            P(None, None),            # pod_req replicated
            P("group", None),         # masks split over groups
            P("scenario", "group", None),
            P("scenario", "group"),
            P("group",),
        ),
        out_specs=(
            P("scenario", "group"),   # counts
            P("scenario", "group"),   # costs
            P("scenario"),            # best group (global index)
            P("scenario"),            # best cost
        ),
    )
    counts, costs, best, best_cost = mapped(pod_req, pod_masks, allocs, prices, caps)
    return WhatIfResult(counts, costs, best, best_cost)


def sharded_affinity_estimate(
    mesh: Mesh,
    pod_req: jax.Array,      # [P, R]
    pod_masks: jax.Array,    # [G, P]
    allocs: jax.Array,       # [G, R]
    caps: jax.Array,         # [G] i32
    max_nodes: int,
    match: jax.Array,        # [T, P]
    aff_of: jax.Array,       # [T, P]
    anti_of: jax.Array,      # [T, P]
    node_level: jax.Array,   # [T]
    has_label: jax.Array,    # [G, T]
    spread: tuple | None = None,  # SpreadTermTensors 11-tuple (G-axis at 5..10)
    use_pallas: bool = False,     # route the bitset-carry Pallas twin
):
    """Dynamic inter-pod-affinity (+hard-spread) FFD estimation sharded over
    a 1-D ``group`` mesh: each device runs the full scan carry for its group
    block (per-group affinity/spread state is independent across groups, so
    the group axis shards with zero collectives — the multi-chip layout for
    the reference's worst-case workload, FAQ.md:151-153). Term tensors and
    the shared pod matrix replicate; [G, ·] tensors (masks, allocs, caps,
    has_label, and the spread tuple's per-group static context, slots 5-10)
    shard. ``use_pallas`` dispatches each shard's scan through the
    Pallas twin (ops/pallas_binpack_affinity: bitset affinity carry +
    count-plane spread)."""
    from autoscaler_tpu.ops.binpack import ffd_binpack_groups_affinity

    # Inert spread tuples gate as S=0, like the estimator route's
    # sp_of.any() check (advisor r5: bucket_terms pads S to a minimum, so a
    # padded-but-undeclared tuple must not trip the S>32 / VMEM gate — the
    # terms can't affect placement). Dropped before dispatch so both kernel
    # routes skip the dead spread carry entirely.
    if spread is not None and not np.asarray(spread[0]).any():
        spread = None

    if use_pallas:
        from autoscaler_tpu.ops.pallas_binpack import VMEM_BUDGET
        from autoscaler_tpu.ops.pallas_binpack_affinity import (
            affinity_vmem_estimate,
            ffd_binpack_groups_affinity_pallas,
        )

        # Same VMEM byte-model gate as the estimator route (advisor r4:
        # this is a public entry point, and a shape past the budget would
        # die in Mosaic compilation with no recovery mid-shard_map — fail
        # loud and early instead, naming the knob that routes around it).
        TP = max((int(match.shape[0]) + 31) // 32, 1)
        # the 11-tuple's slot 2 is the [S] per-term level vector (same
        # S-derivation the kernels use: binpack.py "spread[2].shape[0]")
        S = int(spread[2].shape[0]) if spread is not None else 0
        est = affinity_vmem_estimate(
            int(pod_req.shape[1]), TP, max_nodes, chunk=256, S=S
        )
        if est > VMEM_BUDGET or S > 32:
            raise ValueError(
                f"shape exceeds the Pallas VMEM gate (est={est}B "
                f"budget={VMEM_BUDGET}B, S={S}); pass use_pallas=False to "
                "ride the XLA scan like the estimator's fallback route"
            )

    g_dim = mesh.shape["group"]
    G = pod_masks.shape[0]
    assert G % g_dim == 0, f"G={G} not divisible by group dim {g_dim}"

    def body(pod_req, pod_masks, allocs, caps, match, aff_of, anti_of,
             node_level, has_label, spread_arg):
        if use_pallas:
            # graftlint: disable=GL003 — shard_map body: per-shard dispatch inside an SPMD program; the caller-side ladder can't wrap individual shards
            return ffd_binpack_groups_affinity_pallas(
                pod_req, pod_masks, allocs, max_nodes=max_nodes,
                match=match, aff_of=aff_of, anti_of=anti_of,
                node_level=node_level, has_label=has_label,
                node_caps=caps, spread=spread_arg,
            )
        # graftlint: disable=GL003 — shard_map body: per-shard dispatch inside an SPMD program; the caller-side ladder can't wrap individual shards
        return ffd_binpack_groups_affinity(
            pod_req, pod_masks, allocs, max_nodes=max_nodes,
            match=match, aff_of=aff_of, anti_of=anti_of,
            node_level=node_level, has_label=has_label,
            node_caps=caps, spread=spread_arg,
        )

    rep = P()
    gshard = P("group")
    spread_specs = None
    if spread is not None:
        spread_specs = tuple([rep] * 5 + [gshard] * 6)
    mapped = _shard_map(
        body,
        mesh=mesh,
        in_specs=(rep, gshard, gshard, gshard, rep, rep, rep, rep, gshard,
                  spread_specs),
        out_specs=gshard,  # prefix: every BinpackResult leaf is [G, ...]
    )
    return mapped(pod_req, pod_masks, allocs, caps, match, aff_of, anti_of,
                  node_level, has_label, spread)


def fleet_batch_estimate(
    mesh: Optional[Mesh],
    scen_req,     # [S, P, R] f32 — per-tenant pod matrices, bucket-padded
    scen_masks,   # [S, G, P] bool
    scen_allocs,  # [S, G, R] f32
    scen_caps,    # [S, G] i32 — per-tenant caps (already min'd w/ tenant max)
    max_nodes: int,
):
    """One coalesced multi-tenant batch → (counts [S, G] i32, scheduled
    [S, G, P] bool), as numpy. THE fleet serving dispatch (ROADMAP item 1 /
    BASELINE config 5): the scenario axis carries independent tenants, the
    group axis each tenant's node groups, and both shard over the existing
    ``P("scenario", "group")`` mesh layout with ZERO collectives — per-
    tenant verdicts cannot observe co-batched tenants, which is what the
    loadgen fairness certificate checks byte-for-byte.

    ``mesh=None`` (or a 1-device mesh) dispatches the batched kernel
    directly — the single-chip serving shape. On a mesh, S must divide the
    scenario dim and G the group dim; the fleet bucketer pads to guarantee
    it. Dispatch rides the fleet coalescer's circuit-broken ladder
    (fleet/coalescer.py), never called raw from the serving path."""
    from autoscaler_tpu.ops.binpack import ffd_binpack_scenarios

    scen_req = jnp.asarray(scen_req, jnp.float32)
    scen_masks = jnp.asarray(scen_masks, bool)
    scen_allocs = jnp.asarray(scen_allocs, jnp.float32)
    scen_caps = jnp.asarray(scen_caps, jnp.int32)
    if mesh is None or mesh.size == 1:
        # graftlint: disable=GL003 — fleet batched dispatch entry: the fleet ladder (fleet/coalescer._dispatch_batch) wraps THIS call; a kernel fault surfaces there and degrades to the serial oracle rung
        res = ffd_binpack_scenarios(
            scen_req, scen_masks, scen_allocs, max_nodes=max_nodes,
            scen_caps=scen_caps,
        )
        return np.asarray(res.node_count), np.asarray(res.scheduled)

    s_dim = mesh.shape["scenario"]
    g_dim = mesh.shape["group"]
    S, G = scen_masks.shape[0], scen_masks.shape[1]
    if S % s_dim != 0 or G % g_dim != 0:
        # an ad-hoc bucket (over-sized request) or an undersized batch may
        # not tile the mesh; serve it single-device rather than refuse —
        # correctness is the contract, sharding is the optimization
        # graftlint: disable=GL003 — same fleet dispatch entry as the mesh==None branch above; the fleet ladder wraps the call
        res = ffd_binpack_scenarios(
            scen_req, scen_masks, scen_allocs, max_nodes=max_nodes,
            scen_caps=scen_caps,
        )
        return np.asarray(res.node_count), np.asarray(res.scheduled)

    def body(req, masks, allocs, caps):
        # graftlint: disable=GL003 — shard_map body: per-shard dispatch inside an SPMD program; the fleet ladder wraps the whole mapped call
        res = ffd_binpack_scenarios(
            req, masks, allocs, max_nodes=max_nodes, scen_caps=caps
        )
        return res.node_count, res.scheduled

    mapped = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P("scenario", None, None),
            P("scenario", "group", None),
            P("scenario", "group", None),
            P("scenario", "group"),
        ),
        out_specs=(
            P("scenario", "group"),
            P("scenario", "group", None),
        ),
    )
    counts, scheduled = mapped(scen_req, scen_masks, scen_allocs, scen_caps)
    return np.asarray(counts), np.asarray(scheduled)


def fleet_solo_estimate(
    pod_req,          # [P, R] f32 — one tenant's exact (unpadded) operands
    pod_masks,        # [G, P] bool
    template_allocs,  # [G, R] f32
    node_caps,        # [G] i32
    max_nodes: int,
):
    """One tenant's request dispatched ALONE on the device kernel — the
    baseline side of the fleet fairness certificate (and of ``bench.py
    --fleet``'s sequential lane): what the tenant would get paying its own
    dispatch today. → (counts [G] i32, scheduled [G, P] bool) numpy."""
    # graftlint: disable=GL003 — the solo certification/bench baseline: deliberately ladder-free so the comparison isolates batching, not resilience
    res = ffd_binpack_groups(
        jnp.asarray(pod_req, jnp.float32),
        jnp.asarray(pod_masks, bool),
        jnp.asarray(template_allocs, jnp.float32),
        max_nodes=max_nodes,
        node_caps=jnp.asarray(node_caps, jnp.int32),
    )
    return np.asarray(res.node_count), np.asarray(res.scheduled)


def sharded_scaledown_step(
    mesh: Mesh,
    snap,                    # SnapshotTensors (replicated pytree)
    candidate_nodes: jax.Array,  # [C] i32 — C divisible by the mesh size
    pod_slots: jax.Array,        # [C, S]
    blocked: jax.Array,          # [C] bool
    excluded: jax.Array,         # [N] bool — nodes leaving in the joint plan
    spread: tuple | None = None,        # 8-array schedule context
    static_counts: jax.Array | None = None,  # [S, D]
    cand_sub: jax.Array | None = None,       # [C, S]
):
    """The full scale-down decision step on a 1-D ``candidate`` mesh, the
    deployment shape for multi-chip scale-down:

    1. per-candidate categorization shards over candidates (each lane refits
       one drained node's movable pods — reference planner.go:252
       categorizeNodes, embarrassingly parallel);
    2. an all_gather pulls every candidate's slots back to all devices;
    3. the sequential joint set re-validation (reference actuator.go:371
       re-simulation) runs replicated on the gathered full set — it shares
       one capacity carry across candidates, so it is inherently one lane.

    Returns (per_candidate: RemovalFeasibility over [C], joint:
    RemovalFeasibility over [C]) with identical values on every device.
    """
    from autoscaler_tpu.ops.scaledown import (
        joint_removal_feasibility,
        joint_removal_feasibility_spread,
        removal_feasibility,
        removal_feasibility_spread,
    )

    n_dev = mesh.shape["candidate"]
    C = candidate_nodes.shape[0]
    assert C % n_dev == 0, f"C={C} not divisible by mesh size {n_dev}"
    # The spread trio travels together: the body branches on `spread` alone
    # and removal_feasibility_spread requires all three.
    opts = (spread is None, static_counts is None, cand_sub is None)
    assert all(opts) or not any(opts), (
        "spread, static_counts and cand_sub must be passed all-or-none"
    )

    def body(snap, cands, slots, blocked, excluded, spread_arg, counts, sub):
        if spread_arg is not None:
            per = removal_feasibility_spread(
                snap, cands, slots, blocked, spread_arg, counts, sub
            )
        else:
            per = removal_feasibility(snap, cands, slots, blocked)
        gather = lambda x: jax.lax.all_gather(x, "candidate").reshape(
            (-1,) + x.shape[1:]
        )
        cands_all = gather(cands)
        slots_all = gather(slots)
        if spread_arg is not None:
            joint = joint_removal_feasibility_spread(
                snap, cands_all, slots_all, excluded, spread_arg, counts,
                gather(sub),
            )
        else:
            joint = joint_removal_feasibility(snap, cands_all, slots_all, excluded)
        return per, joint

    rep = P()
    cshard = P("candidate")
    mapped = _shard_map(
        body,
        mesh=mesh,
        in_specs=(rep, cshard, cshard, cshard, rep,
                  rep if spread is not None else None,
                  rep if static_counts is not None else None,
                  cshard if cand_sub is not None else None),
        out_specs=(cshard, rep),  # prefixes: per-candidate leaves shard
                                  # over [C, ...]; the joint result replicates
    )
    return mapped(snap, candidate_nodes, pod_slots, blocked, excluded,
                  spread, static_counts, cand_sub)
