"""Quota-limited verbose logging.

Reference: cluster-autoscaler/utils/klogx/klogx.go — per-loop log quotas so
verbose per-pod / per-node lines cannot flood the log at scale (a 100k-pod
burst would otherwise emit 100k "pod is unschedulable" lines every loop),
plus defaults.go's pods quota (20 lines normally, 1000 at verbosity >= 5).

Backed by stdlib logging on the "autoscaler_tpu" logger; verbosity mirrors
klog's -v levels (set_verbosity). Usage, mirroring the reference:

    quota = pods_logging_quota()
    for pod in pods:
        v(4).up_to(quota).info("Pod %s is unschedulable", pod.key())
    v(4).over(quota).info("%d other pods skipped", -quota.left)
"""
from __future__ import annotations

import logging
from dataclasses import dataclass

logger = logging.getLogger("autoscaler_tpu")

MAX_PODS_LOGGED = 20       # defaults.go MaxPodsLogged
MAX_PODS_LOGGED_V5 = 1000  # defaults.go MaxPodsLoggedV5

_verbosity = 0


def set_verbosity(n: int) -> None:
    """klog's -v flag analog (wired from main.py --v)."""
    global _verbosity
    _verbosity = int(n)


def verbosity() -> int:
    return _verbosity


@dataclass
class Quota:
    """Log lines that may still print before suppression (klogx.go Quota).
    `left` goes negative past the limit so the Over() summary can report
    exactly how many lines were swallowed."""

    limit: int
    left: int

    def reset(self) -> None:
        self.left = self.limit


def new_logging_quota(n: int) -> Quota:
    return Quota(n, n)


def pods_logging_quota() -> Quota:
    """Default per-loop quota for per-pod lines (defaults.go)."""
    return new_logging_quota(
        MAX_PODS_LOGGED_V5 if _verbosity >= 5 else MAX_PODS_LOGGED
    )


class Verbose:
    """klogx.Verbose: a maybe-enabled logging handle."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def up_to(self, quota: Quota) -> "Verbose":
        """Consume one line of quota; disabled once the quota is spent."""
        if not self.enabled:
            return self
        quota.left -= 1
        return Verbose(quota.left >= 0)

    def over(self, quota: Quota) -> "Verbose":
        """Enabled only if the quota WAS exceeded — for the summary line."""
        if not self.enabled:
            return self
        return Verbose(quota.left < 0)

    def info(self, msg: str, *args) -> None:
        if self.enabled:
            logger.info(msg, *args)


def v(level: int) -> Verbose:
    """klogx.V: enabled iff the configured verbosity reaches `level`."""
    return Verbose(level <= _verbosity)
