"""Test fixture builders, modeled on the reference's
cluster-autoscaler/utils/test/test_utils.go:36,179 (BuildTestNode,
BuildTestPod, SetNodeReadyState, AddGpusToNode). Used by unit tests, the
benchmark grid, and bench.py workload generators alike.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from autoscaler_tpu.kube.objects import (
    Affinity,
    LabelSelector,
    Node,
    OwnerRef,
    Pod,
    PodAffinityTerm,
    Resources,
    Taint,
    Toleration,
)

MB = 1024 * 1024
GB = 1024 * MB


def build_test_node(
    name: str,
    cpu_m: float = 1000,
    mem: float = 2 * GB,
    pods: float = 110,
    gpu: float = 0,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    ready: bool = True,
) -> Node:
    return Node(
        name=name,
        allocatable=Resources(cpu_m=cpu_m, memory=mem, gpu=gpu, pods=pods),
        labels={"kubernetes.io/hostname": name, **(labels or {})},
        taints=list(taints or []),
        ready=ready,
        provider_id=f"test:///{name}",
    )


def build_test_pod(
    name: str,
    cpu_m: float = 100,
    mem: float = 200 * MB,
    namespace: str = "default",
    node_name: str = "",
    labels: Optional[Dict[str, str]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    tolerations: Optional[List[Toleration]] = None,
    affinity: Optional[Affinity] = None,
    owner_kind: str = "ReplicaSet",
    priority: int = 0,
) -> Pod:
    return Pod(
        name=name,
        namespace=namespace,
        requests=Resources(cpu_m=cpu_m, memory=mem),
        labels=dict(labels or {}),
        node_selector=dict(node_selector or {}),
        tolerations=list(tolerations or []),
        affinity=affinity,
        owner_ref=OwnerRef(kind=owner_kind, name=f"{name}-owner") if owner_kind else None,
        priority=priority,
        node_name=node_name,
    )


def anti_affinity(match_labels: Dict[str, str], topology_key: str = "kubernetes.io/hostname") -> Affinity:
    return Affinity(
        pod_anti_affinity=(
            PodAffinityTerm(
                selector=LabelSelector.from_dict(match_labels),
                topology_key=topology_key,
            ),
        )
    )


def pod_affinity(match_labels: Dict[str, str], topology_key: str = "kubernetes.io/hostname") -> Affinity:
    return Affinity(
        pod_affinity=(
            PodAffinityTerm(
                selector=LabelSelector.from_dict(match_labels),
                topology_key=topology_key,
            ),
        )
    )
