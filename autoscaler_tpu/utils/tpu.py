"""TPU-request sanitizer.

Reference: cluster-autoscaler/utils/tpu/tpu.go:57 (ClearTPURequests): the
reference strips `cloud-tpus.google.com/*` resource requests from pods
before simulation, because TPU devices are attached after scheduling and
would otherwise make every pod unschedulable in the simulated world. In this
framework TPU capacity is a first-class resource axis, so the sanitizer is
*configurable*: strip the legacy cloud-tpus requests (parity behavior), keep
native tpu-axis requests.
"""
from __future__ import annotations

import dataclasses
import os
from typing import List, Sequence

from autoscaler_tpu.kube.objects import Pod

LEGACY_TPU_PREFIX = "cloud-tpus.google.com/"


def pin_cpu_if_requested() -> None:
    """Honor a JAX_PLATFORMS=cpu request BEFORE any device use.

    A site hook (the axon TPU plugin) can re-pin the platform at import,
    overriding the env var alone — only jax.config.update sticks. Backends
    initialize lazily, so calling this at entry-point start is early
    enough even after jax.numpy has been imported. Shared by the process
    entry points (vpa/main, main; benches/graft entry/conftest mirror the
    same rule); accepts the comma-list form ('cpu,tpu' pins the leading
    request) the exact-match copies missed."""
    req = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if req == "cpu" or req.startswith("cpu,"):
        import jax

        jax.config.update("jax_platforms", "cpu")


def clear_tpu_requests(pods: Sequence[Pod], strip_native: bool = False) -> List[Pod]:
    """→ pods with (legacy) TPU requests removed; untouched pods pass through
    by identity so callers can cheaply detect changes."""
    out: List[Pod] = []
    for pod in pods:
        legacy = any(k.startswith(LEGACY_TPU_PREFIX) for k in pod.annotations)
        if (pod.requests.tpu and strip_native) or legacy:
            requests = dataclasses.replace(
                pod.requests, tpu=0.0 if (strip_native or legacy) else pod.requests.tpu
            )
            annotations = {
                k: v
                for k, v in pod.annotations.items()
                if not k.startswith(LEGACY_TPU_PREFIX)
            }
            out.append(
                dataclasses.replace(pod, requests=requests, annotations=annotations)
            )
        else:
            out.append(pod)
    return out
