"""Shared randomized worlds for the sharded-kernel-fleet certification.

One definition of each scenario, consumed by BOTH the driver-visible
multi-chip dryrun (__graft_entry__._dryrun_kernel_fleet) and the pytest
suite (tests/test_parallel.py TestShardedKernelFleet) — so the dryrun and
the suite can never silently certify different workloads.
"""
from __future__ import annotations

import numpy as np

from autoscaler_tpu.kube.objects import CPU, MEMORY, PODS


def affinity_world(G: int, P: int, T: int, M: int, seed: int = 9):
    """Randomized dynamic-affinity estimation inputs: heterogeneous pods,
    per-group masks/templates, and a term structure mixing affinity and
    anti-affinity at hostname and group scope. Returns a dict matching
    ffd_binpack_groups_affinity's keyword surface (numpy arrays)."""
    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(100, 1500, P)
    pod_req[:, MEMORY] = rng.integers(128, 2048, P)
    pod_req[:, PODS] = 1
    masks = rng.random((G, P)) > 0.1
    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.integers(3000, 8000, G)
    allocs[:, MEMORY] = rng.integers(8192, 16384, G)
    allocs[:, PODS] = 110
    match = rng.random((T, P)) < 0.2
    aff_of = (rng.random((T, P)) < 0.08) & match
    anti_of = (rng.random((T, P)) < 0.08) & match & ~aff_of
    return dict(
        pod_req=pod_req,
        pod_masks=masks,
        template_allocs=allocs,
        match=match,
        aff_of=aff_of,
        anti_of=anti_of,
        node_level=rng.random(T) < 0.5,
        has_label=rng.random((G, T)) < 0.9,
        node_caps=np.full(G, M, np.int32),
    )


def spread_world(G: int, P: int, M: int):
    """A hard-topology-spread world where the skew gate actually bites:
    every other pod carries a DoNotSchedule zone constraint and the cluster
    context holds an EMPTY zone-other domain, so each group's wave budget is
    maxSkew + min_other(0) = 1 (a template-only single-domain world never
    blocks — see tests/test_spread_binpack.py). Returns (kernel_kwargs,
    spread_tuple) with zero-width affinity terms."""
    from autoscaler_tpu.estimator.binpacking import _spread_tuple
    from autoscaler_tpu.kube.objects import (
        LabelSelector,
        TopologySpreadConstraint,
    )
    from autoscaler_tpu.snapshot.affinity import build_spread_terms
    from autoscaler_tpu.utils.test_utils import build_test_node, build_test_pod

    ZONE = "topology.kubernetes.io/zone"
    constraint = TopologySpreadConstraint(
        max_skew=1, topology_key=ZONE,
        selector=LabelSelector.from_dict({"app": "web"}),
        when_unsatisfiable="DoNotSchedule",
    )
    pods = []
    for i in range(P):
        p = build_test_pod(f"p{i}", cpu_m=100, labels={"app": "web"})
        if i % 2 == 0:
            p.topology_spread = (constraint,)
        pods.append(p)
    templates = []
    for g in range(G):
        t = build_test_node(f"tmpl-{g}", cpu_m=4000)
        t.labels[ZONE] = f"zone-{g % 3}"
        templates.append(t)
    other = build_test_node("existing-other", cpu_m=4000)
    other.labels[ZONE] = "zone-other"
    spread = _spread_tuple(
        build_spread_terms(pods, templates, cluster=([other], [], []))
    )

    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = 100
    pod_req[:, PODS] = 1
    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = 4000
    allocs[:, PODS] = 110
    z = np.zeros((1, P), bool)
    kwargs = dict(
        pod_req=pod_req,
        pod_masks=np.ones((G, P), bool),
        template_allocs=allocs,
        match=z,
        aff_of=z,
        anti_of=z,
        node_level=np.zeros(1, bool),
        has_label=np.ones((G, 1), bool),
        node_caps=np.full(G, M, np.int32),
    )
    return kwargs, spread


def scaledown_spread_world(n_zones: int = 2, per_zone: int = 8,
                           cands_per_zone: int = 4):
    """An object-level drain world where hard topology-spread gates the
    refit: every node hosts one movable "web" pod carrying a DoNotSchedule
    zone constraint (maxSkew=1), so draining a node must re-place its pod
    without re-skewing the zones. Returns (tensors, cand, pod_slots,
    blocked, excluded, spread8, static_counts, cand_sub) — the exact
    argument set of removal_feasibility_spread, built by the same private
    helpers the RemovalSimulator uses."""
    from autoscaler_tpu.kube.objects import (
        LabelSelector,
        TopologySpreadConstraint,
    )
    from autoscaler_tpu.simulator.removal import (
        _cand_sub_matrix,
        _spread_refit_context,
    )
    from autoscaler_tpu.snapshot.packer import pack
    from autoscaler_tpu.utils.test_utils import build_test_node, build_test_pod

    ZONE = "topology.kubernetes.io/zone"
    constraint = TopologySpreadConstraint(
        max_skew=1, topology_key=ZONE,
        selector=LabelSelector.from_dict({"app": "web"}),
        when_unsatisfiable="DoNotSchedule",
    )
    nodes, pods = [], []
    pods_on: dict = {}
    for z in range(n_zones):
        for i in range(per_zone):
            name = f"n-{z}-{i}"
            n = build_test_node(name, cpu_m=4000)
            n.labels[ZONE] = f"zone-{z}"
            p = build_test_pod(
                f"w-{z}-{i}", cpu_m=300, labels={"app": "web"},
                node_name=name,
            )
            p.topology_spread = (constraint,)
            nodes.append(n)
            pods.append(p)
            pods_on[name] = [p]
    tensors, meta = pack(nodes, pods)
    cand_names = [
        f"n-{z}-{i}" for z in range(n_zones) for i in range(cands_per_zone)
    ]
    movers = [pods_on[c] for c in cand_names]
    spread8, static_counts, sp_match_np = _spread_refit_context(
        meta, tensors, [m for ms in movers for m in ms]
    )
    C = len(cand_names)
    cand = np.asarray([meta.node_index[c] for c in cand_names], np.int32)
    pod_slots = np.full((C, 2), -1, np.int32)
    for ci, ms in enumerate(movers):
        for si, p in enumerate(ms):
            pod_slots[ci, si] = meta.pod_index[p.key()]
    blocked = np.zeros(C, bool)
    excluded = np.zeros(int(tensors.node_valid.shape[0]), bool)
    excluded[cand] = True
    cand_sub = _cand_sub_matrix(sp_match_np, meta, movers)
    return (tensors, cand, pod_slots, blocked, excluded,
            spread8, static_counts, cand_sub)


def scaledown_world(N: int, P: int, C: int, slots: int, seed: int = 7):
    """A packed cluster with C drain candidates: random pod→node placement,
    a mostly-permissive dense sched_mask, per-candidate movable-pod slots,
    and the joint-plan exclusion set. Returns (snap, cand, pod_slots,
    blocked, excluded) ready for removal_feasibility /
    joint_removal_feasibility."""
    import jax.numpy as jnp

    from autoscaler_tpu.snapshot.tensors import SnapshotTensors

    rng = np.random.default_rng(seed)
    node_alloc = np.zeros((N, 6), np.float32)
    node_alloc[:, CPU] = 4000
    node_alloc[:, PODS] = 110
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(200, 900, P)
    pod_req[:, PODS] = 1
    pod_node = rng.integers(0, N, P).astype(np.int32)
    node_used = np.zeros((N, 6), np.float32)
    for i in range(P):
        node_used[pod_node[i]] += pod_req[i]
    snap = SnapshotTensors(
        node_alloc=jnp.asarray(node_alloc),
        node_used=jnp.asarray(node_used),
        node_valid=jnp.ones(N, bool),
        node_group=jnp.zeros(N, np.int32),
        pod_req=jnp.asarray(pod_req),
        pod_valid=jnp.ones(P, bool),
        pod_node=jnp.asarray(pod_node),
        sched_mask=jnp.asarray(rng.random((P, N)) > 0.05),
    )
    cand = rng.choice(N, C, replace=False).astype(np.int32)
    pod_slots = np.full((C, slots), -1, np.int32)
    for ci, j in enumerate(cand):
        on = np.where(pod_node == j)[0][:slots]
        pod_slots[ci, : len(on)] = on
    blocked = np.zeros(C, bool)
    excluded = np.zeros(N, bool)
    excluded[cand] = True
    return snap, cand, pod_slots, blocked, excluded
