"""In-process profiling endpoints — the `/debug/pprof` analog.

The reference exposes Go's net/http/pprof behind `--profiling`
(main.go:518-520).  Python has no built-in pprof server, so this module
provides the same three capabilities with stdlib-only machinery:

- ``SamplingProfiler``: a wall-clock sampling profiler over ALL threads
  (polls ``sys._current_frames()``), emitting collapsed-stack lines
  (``a;b;c count``) directly consumable by flamegraph tooling — the
  analog of ``/debug/pprof/profile?seconds=N``.
- ``heap_profile``: tracemalloc-backed allocation snapshot grouped by
  source line — the analog of ``/debug/pprof/heap``.
- ``thread_dump``: current stacks of every live thread — the analog of
  ``/debug/pprof/goroutine?debug=2``.

Sampling keeps overhead bounded (default 100 Hz; each sample is a dict
copy of frame pointers, no tracing hooks), so it is safe to run against
a live control loop the same way Go's pprof is.
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


# Serializes /debug/pprof/profile requests, like Go's net/http/pprof CPU
# profile (a second concurrent request is rejected): N parallel 100 Hz
# samplers would multiply overhead on the live control loop.
PROFILE_LOCK = threading.Lock()

# thread ids currently running a SamplingProfiler: concurrent profile
# requests must not sample each other's profiling loops
_ACTIVE_PROFILER_THREADS: set = set()


class SamplingProfiler:
    """Collapsed-stack sampling profiler across all threads."""

    def __init__(self, hz: float = 100.0):
        self.hz = hz
        self._samples: Counter = Counter()
        self._count = 0

    def _take_sample(self, skip: set) -> None:
        for tid, frame in sys._current_frames().items():
            if tid in skip:
                continue
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
                f = f.f_back
            self._samples[";".join(reversed(parts))] += 1
        self._count += 1

    def run(self, seconds: float) -> str:
        """Sample for ``seconds``, then render collapsed stacks."""
        interval = 1.0 / self.hz
        deadline = time.monotonic() + seconds
        me = threading.get_ident()
        _ACTIVE_PROFILER_THREADS.add(me)
        try:
            while time.monotonic() < deadline:
                self._take_sample(skip=_ACTIVE_PROFILER_THREADS)
                time.sleep(interval)
        finally:
            _ACTIVE_PROFILER_THREADS.discard(me)
        return self.render()

    def render(self) -> str:
        lines = [
            f"# wall-clock samples: {self._count} @ {self.hz:g} Hz",
        ]
        for stack, n in self._samples.most_common():
            lines.append(f"{stack} {n}")
        return "\n".join(lines) + "\n"


def heap_profile(limit: int = 50) -> str:
    """tracemalloc snapshot grouped by line (``/debug/pprof/heap`` analog).

    Requires tracemalloc to have been started (done by the observability
    server when profiling is enabled); reports an explanatory line if not.
    """
    import tracemalloc

    if not tracemalloc.is_tracing():
        return "# tracemalloc not tracing; start with --profiling\n"
    try:
        snap = tracemalloc.take_snapshot()
    except RuntimeError:
        # races server stop(): tracing ended between the check and snapshot
        return "# tracemalloc not tracing; start with --profiling\n"
    stats = snap.statistics("lineno")
    total = sum(s.size for s in stats)
    lines = [f"# heap: {total / 1024:.1f} KiB tracked in {len(stats)} sites"]
    for s in stats[:limit]:
        frame = s.traceback[0]
        lines.append(
            f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno} "
            f"size={s.size} count={s.count}"
        )
    return "\n".join(lines) + "\n"


def thread_dump() -> str:
    """All live thread stacks (``/debug/pprof/goroutine?debug=2`` analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(out) + "\n"


class LoopWatchdog:
    """Soft-deadline watchdog for the control loop's ticks.

    ``arm()`` before each ``run_once``, ``disarm()`` after. If a tick is
    still running when the soft deadline lapses, the watchdog thread dumps
    every live thread's stack (``thread_dump``) exactly once for that tick
    — so a wedged iteration (device hang, stuck HTTP read, deadlock)
    leaves evidence of WHERE it was stuck before the liveness probe's
    max-inactivity deadline has the process killed and restarted.

    The watchdog never unwedges anything itself (crash-only discipline:
    recovery is the supervisor's restart); it only observes.
    """

    def __init__(self, soft_deadline_s: float, emit=None):
        import sys as _sys

        self.soft_deadline_s = soft_deadline_s
        self._emit = emit or (lambda text: print(text, file=_sys.stderr))
        self._cond = threading.Condition()
        self._deadline: float = 0.0   # 0 = disarmed
        self._fired = False
        self.fired_count = 0          # observability for tests/metrics
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, name="loop-watchdog", daemon=True
        )
        self._thread.start()

    def arm(self) -> None:
        with self._cond:
            self._deadline = time.monotonic() + self.soft_deadline_s
            self._fired = False
            self._cond.notify()

    def disarm(self) -> None:
        with self._cond:
            self._deadline = 0.0
            self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                if self._stopped:
                    return
                if self._deadline == 0.0 or self._fired:
                    self._cond.wait()
                    continue
                wait = self._deadline - time.monotonic()
                if wait > 0:
                    self._cond.wait(timeout=wait)
                    continue
                self._fired = True
                self.fired_count += 1
                deadline_s = self.soft_deadline_s
            # dump OUTSIDE the lock: thread_dump walks every frame and must
            # not block arm/disarm from the control loop
            self._emit(
                f"watchdog: run_once exceeded its {deadline_s:.0f}s soft "
                f"deadline; all-thread stack dump:\n{thread_dump()}"
            )


PPROF_INDEX = """\
/debug/pprof/ — profiling index (Go net/http/pprof analog)
  /debug/pprof/profile?seconds=N   collapsed-stack wall profile (default 5s)
  /debug/pprof/heap                tracemalloc allocation snapshot
  /debug/pprof/threadz             live thread stack dump
"""
