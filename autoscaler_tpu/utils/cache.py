"""Expiring cache + quota-limited logging.

Reference: cluster-autoscaler/utils/expiring/ (time-bounded cache used for
template NodeInfos etc.) and utils/klogx/ (quota-limited verbose logging: at
most N log lines per loop for high-cardinality messages like per-pod
scheduling verdicts).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Generic, Optional, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class ExpiringCache(Generic[K, V]):
    def __init__(self, ttl_s: float, clock: Callable[[], float] = time.monotonic):
        self.ttl_s = ttl_s
        self._clock = clock
        self._store: Dict[K, Tuple[V, float]] = {}

    def get(self, key: K) -> Optional[V]:
        entry = self._store.get(key)
        if entry is None:
            return None
        value, ts = entry
        if self._clock() - ts > self.ttl_s:
            del self._store[key]
            return None
        return value

    def put(self, key: K, value: V) -> None:
        self._store[key] = (value, self._clock())

    def invalidate(self, key: Optional[K] = None) -> None:
        if key is None:
            self._store.clear()
        else:
            self._store.pop(key, None)

    def __len__(self) -> int:
        now = self._clock()
        self._store = {
            k: (v, ts) for k, (v, ts) in self._store.items() if now - ts <= self.ttl_s
        }
        return len(self._store)


class QuotaLogger:
    """At most `quota` messages per loop iteration; the rest are summarized
    (utils/klogx/ NewLoggingQuota pattern)."""

    def __init__(self, quota: int = 50, logger: Optional[logging.Logger] = None):
        self.quota = quota
        self.logger = logger or logging.getLogger("autoscaler_tpu")
        self._used = 0
        self._dropped = 0

    def reset(self) -> None:
        if self._dropped:
            self.logger.info("... and %d more messages (quota %d)", self._dropped, self.quota)
        self._used = 0
        self._dropped = 0

    def log(self, msg: str, *args: Any) -> None:
        if self._used < self.quota:
            self._used += 1
            self.logger.info(msg, *args)
        else:
            self._dropped += 1

    @property
    def dropped(self) -> int:
        return self._dropped
