"""Shared daemon poll loop for the auxiliary binaries (VPA, nanny).

The reference's RunOnce loops log transient errors and keep ticking
(recommender routines/recommender.go, nanny nanny_lib.go:103); this is that
shape once, instead of re-inlined per binary. Sleep is drift-compensated:
the tick cadence is interval_s regardless of how long fn took.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, Optional


def poll_loop(
    fn: Callable[[], object],
    interval_s: float,
    max_iterations: int = 0,
    logger: Optional[logging.Logger] = None,
) -> int:
    """Run ``fn`` every ``interval_s`` seconds until KeyboardInterrupt or
    ``max_iterations`` (0 = forever). Exceptions from ``fn`` are logged and
    the loop continues — a transient API error must not kill the daemon or
    its accumulated in-memory state. Returns 0 (the process exit code)."""
    log = logger or logging.getLogger("poll")
    iterations = 0
    try:
        while True:
            start = time.monotonic()
            try:
                fn()
            except Exception:  # noqa: BLE001 — log-and-continue by design
                log.exception("pass failed; continuing next tick")
            iterations += 1
            if max_iterations and iterations >= max_iterations:
                return 0
            time.sleep(max(interval_s - (time.monotonic() - start), 0.0))
    except KeyboardInterrupt:
        return 0
