"""Shared stdlib JSON-over-HTTP request helper for the REST transports
(kube/client.py and cloudprovider/gce_rest.py) so the request/auth/error
pattern cannot drift between them.

Error mapping is the caller's via `on_error(status, detail) -> Exception`:
HTTP errors pass their status code; transport-level failures (DNS, refused,
timeout, non-JSON 2xx body) pass status 0.
"""
from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional


def json_request(
    url: str,
    method: str = "GET",
    body: Optional[dict] = None,
    headers: Optional[Dict[str, str]] = None,
    timeout_s: float = 30.0,
    context: Optional[ssl.SSLContext] = None,
    on_error: Callable[[int, str], Exception] = lambda s, d: RuntimeError(
        f"HTTP {s}: {d}"
    ),
    stream: bool = False,
):
    """One JSON request. Returns the decoded dict ({} on empty body), or the
    raw response object when stream=True (caller closes it)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Accept", "application/json")
    if data is not None and not any(
        k.lower() == "content-type" for k in (headers or {})
    ):
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout_s, context=context)
    except urllib.error.HTTPError as e:
        raise on_error(e.code, e.read().decode(errors="replace")[:512]) from None
    except urllib.error.URLError as e:
        raise on_error(0, str(e.reason)) from None
    except OSError as e:  # bare socket timeouts etc.
        raise on_error(0, str(e)) from None
    if stream:
        return resp
    payload = resp.read()
    resp.close()
    if not payload:
        return {}
    try:
        return json.loads(payload)
    except json.JSONDecodeError as e:
        # a proxy/LB returning HTML-with-200 must surface through the same
        # error contract as any other transport failure
        raise on_error(0, f"non-JSON response ({e})") from None
