"""Shared stdlib JSON-over-HTTP request helper for the REST transports
(kube/client.py and cloudprovider/gce_rest.py) so the request/auth/error
pattern cannot drift between them.

Error mapping is the caller's via `on_error(status, detail) -> Exception`:
HTTP errors pass their status code; transport-level failures (DNS, refused,
timeout, non-JSON 2xx body) pass status 0.
"""
from __future__ import annotations

import json
import random
import ssl
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class RetryPolicy:
    """Bounded, jittered exponential retry for IDEMPOTENT requests.

    Only transient outcomes retry: transport-level failures (DNS, refused,
    timeout) and HTTP 429/5xx. A 429/503 ``Retry-After`` header (seconds
    form) is honored, capped at ``max_sleep_s``. Jitter (0.5-1.0x) keeps a
    fleet of restarted control loops from synchronizing their retries
    against a recovering API server. Non-idempotent writes must NOT pass a
    policy — the caller cannot know whether the server applied the mutation.
    """

    attempts: int = 3                 # total tries, including the first
    base_sleep_s: float = 0.25
    max_sleep_s: float = 5.0
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)
    rng: Callable[[], float] = field(default=random.random, repr=False)

    def backoff_s(self, attempt: int, retry_after_s: Optional[float]) -> float:
        """Delay before retry number ``attempt`` (1-based)."""
        if retry_after_s is not None:
            return min(max(retry_after_s, 0.0), self.max_sleep_s)
        exp = min(self.base_sleep_s * (2 ** (attempt - 1)), self.max_sleep_s)
        return exp * (0.5 + 0.5 * self.rng())


def _trace_retry(attempt: int, **attrs) -> None:
    """Stamp a retry on the enclosing request's trace span (no-op outside a
    tick trace): a backoff storm must be attributable to the phase that
    issued the request, not just a counter somewhere."""
    from autoscaler_tpu import trace

    trace.add_event("http.retry", attempt=attempt, **attrs)


def _retry_after_seconds(headers) -> Optional[float]:
    try:
        value = headers.get("Retry-After") if headers is not None else None
    except AttributeError:
        return None
    if not value:
        return None
    try:
        return float(value)
    except ValueError:
        return None  # HTTP-date form: fall back to exponential pacing


def json_request(
    url: str,
    method: str = "GET",
    body: Optional[dict] = None,
    headers: Optional[Dict[str, str]] = None,
    timeout_s: float = 30.0,
    context: Optional[ssl.SSLContext] = None,
    on_error: Callable[[int, str], Exception] = lambda s, d: RuntimeError(
        f"HTTP {s}: {d}"
    ),
    stream: bool = False,
    retry: Optional[RetryPolicy] = None,
):
    """One JSON request. Returns the decoded dict ({} on empty body), or the
    raw response object when stream=True (caller closes it). ``retry``
    (idempotent callers only) retries transient failures — 429/5xx honoring
    Retry-After, plus transport errors — with jittered bounded backoff."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Accept", "application/json")
    if data is not None and not any(
        k.lower() == "content-type" for k in (headers or {})
    ):
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    attempts = retry.attempts if retry is not None else 1
    attempt = 0
    while True:
        attempt += 1
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout_s, context=context
            )
            break
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:512]
            transient = e.code == 429 or e.code >= 500
            if retry is not None and transient and attempt < attempts:
                _trace_retry(attempt, status=e.code)
                retry.sleep(
                    retry.backoff_s(attempt, _retry_after_seconds(e.headers))
                )
                continue
            raise on_error(e.code, detail) from None
        except urllib.error.URLError as e:
            # full socket timeouts are NOT retried: each one already
            # consumed timeout_s, so re-sending would stall a control-loop
            # tick for attempts x timeout_s — past the watchdog's soft
            # deadline — for a server that is wedged, not flaking. Only
            # fast transport errors (refused, DNS, reset) retry.
            timed_out = isinstance(e.reason, TimeoutError)
            if retry is not None and attempt < attempts and not timed_out:
                _trace_retry(attempt, error=type(e.reason).__name__)
                retry.sleep(retry.backoff_s(attempt, None))
                continue
            raise on_error(0, str(e.reason)) from None
        except OSError as e:  # bare socket errors
            if (
                retry is not None
                and attempt < attempts
                and not isinstance(e, TimeoutError)
            ):
                _trace_retry(attempt, error=type(e).__name__)
                retry.sleep(retry.backoff_s(attempt, None))
                continue
            raise on_error(0, str(e)) from None
    if stream:
        return resp
    payload = resp.read()
    resp.close()
    if not payload:
        return {}
    try:
        return json.loads(payload)
    except json.JSONDecodeError as e:
        # a proxy/LB returning HTML-with-200 must surface through the same
        # error contract as any other transport failure
        raise on_error(0, f"non-JSON response ({e})") from None
