"""Typed autoscaler errors.

Reference: cluster-autoscaler/utils/errors/ (AutoscalerError with error
types: ApiCallError, InternalError, TransientError, ConfigurationError,
NodeGroupDoesNotExistError) — the type drives retry/backoff decisions and
metrics labels.
"""
from __future__ import annotations

import enum
from typing import Optional


class ErrorType(enum.Enum):
    API_CALL = "apiCallError"
    INTERNAL = "internalError"
    TRANSIENT = "transientError"
    CONFIGURATION = "configurationError"
    NODE_GROUP_DOES_NOT_EXIST = "nodeGroupDoesNotExistError"


class AutoscalerError(Exception):
    def __init__(self, error_type: ErrorType, message: str):
        super().__init__(message)
        self.error_type = error_type

    @property
    def retriable(self) -> bool:
        return self.error_type in (ErrorType.TRANSIENT, ErrorType.API_CALL)

    def prefixed(self, prefix: str) -> "AutoscalerError":
        # chain the original so logging the wrapper (exc_info) still shows
        # the real traceback — the crash-only loop relies on this
        new = AutoscalerError(self.error_type, f"{prefix}{self}")
        new.__cause__ = self
        return new


def to_autoscaler_error(err: Exception) -> AutoscalerError:
    """Wrap any exception as a typed AutoscalerError, preserving the
    original as ``__cause__`` so the crash-only control loop's logs keep
    the real traceback instead of a stringified tail."""
    if isinstance(err, AutoscalerError):
        return err
    wrapped = AutoscalerError(ErrorType.INTERNAL, str(err) or type(err).__name__)
    wrapped.__cause__ = err
    return wrapped
