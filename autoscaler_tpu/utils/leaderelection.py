"""Single-leader election (active/passive HA).

Reference: cluster-autoscaler/main.go:525-573 (leaderelection.RunOrDie over a
Kubernetes Lease: 15s lease, 10s renew deadline, 2s retry). The framework is
control-plane-agnostic, so the lease backend is pluggable: the built-in
FileLease works on any shared filesystem; a Kubernetes-Lease or cloud-lock
backend implements the same two methods. The autoscaler is stateless
(snapshot rebuilt every loop, static_autoscaler.go:250) so failover needs no
state handover — the new leader just starts reconciling.
"""
from __future__ import annotations

import json
import os
import socket
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional, Protocol


class Lease(Protocol):
    def try_acquire(self, holder: str, now_ts: float) -> bool: ...

    def release(self, holder: str) -> None: ...


@dataclass
class FileLease:
    """Advisory lease in a file: atomic create-or-steal with TTL expiry."""

    path: str
    ttl_s: float = 15.0

    def try_acquire(self, holder: str, now_ts: float) -> bool:
        record = {"holder": holder, "renewed": now_ts}
        try:
            current = self._read()
            if (
                current is not None
                and current["holder"] != holder
                and now_ts - current["renewed"] < self.ttl_s
            ):
                return False
            tmp = f"{self.path}.{uuid.uuid4().hex}.tmp"
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self.path)  # atomic on POSIX
            return True
        except OSError:
            return False

    def release(self, holder: str) -> None:
        current = self._read()
        if current is not None and current["holder"] == holder:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class LeaderElector:
    """run() blocks until leadership, then invokes the loop callback while
    renewing; on lost leadership it returns (the process should exit and let
    the orchestrator restart it — main.go:568's OnStoppedLeading fatal)."""

    def __init__(
        self,
        lease: Lease,
        identity: Optional[str] = None,
        renew_period_s: float = 2.0,
        renew_deadline_s: float = 10.0,
        clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.lease = lease
        self.identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self.renew_period_s = renew_period_s
        self.renew_deadline_s = renew_deadline_s
        self.clock = clock
        self.sleep = sleep

    def run(self, on_started_leading: Callable[[Callable[[], bool]], None]) -> None:
        """on_started_leading receives a `still_leader()` callback it must
        consult between loop iterations.

        While leading, the lease is renewed on a BACKGROUND thread every
        renew_period_s (the reference's 2s renew goroutine) — a long loop
        iteration can't let the lease expire mid-iteration and split-brain
        a second replica in. Renewal failures are tolerated for
        renew_deadline_s (reference: 10s) before leadership is considered
        lost, so one transient apiserver error doesn't dethrone a healthy
        leader."""
        while not self.lease.try_acquire(self.identity, self.clock()):
            self.sleep(self.renew_period_s)

        import threading

        stop = threading.Event()
        state = {"leading": True, "last_renew": self.clock()}

        def renewer() -> None:
            while not stop.wait(self.renew_period_s):
                ok = False
                try:
                    ok = self.lease.try_acquire(self.identity, self.clock())
                except Exception:  # noqa: BLE001 — network lease errors count
                    ok = False     # toward the renew deadline, not a crash
                now = self.clock()
                if ok:
                    state["last_renew"] = now
                elif now - state["last_renew"] > self.renew_deadline_s:
                    state["leading"] = False
                    return

        renew_thread = threading.Thread(target=renewer, daemon=True)
        renew_thread.start()

        def still_leader() -> bool:
            # freshness matters as much as the flag: a renewal hung in a
            # blackholed request must not keep an expired leader active
            return (
                state["leading"]
                and self.clock() - state["last_renew"] <= self.renew_deadline_s
            )

        try:
            on_started_leading(still_leader)
        finally:
            stop.set()
            renew_thread.join(timeout=self.renew_period_s * 2)
            if renew_thread.is_alive():
                # a renewal is still in flight; releasing now could race its
                # completing PUT and re-create the lease under our dead
                # identity — let the TTL expire it instead
                pass
            else:
                self.lease.release(self.identity)
