"""Generic circuit breaker: closed / open / half-open.

The reference has no breaker — its per-dispatch try/fallback re-attempts a
deterministically failing path on every loop, re-paying compile/dispatch
latency for the same failure each tick. This breaker converts that into a
degradation contract: after ``failure_threshold`` consecutive failures the
protected resource is OPEN (callers skip it outright), after ``cooldown_s``
a single half-open probe is admitted, and the probe's outcome decides
between CLOSED (recovered) and another full OPEN window.

Time is explicit (callers pass ``now``) rather than read from the wall
clock, so the breaker runs identically under the loadgen driver's simulated
clock — a prerequisite for byte-identical decision-log replay of fault
scenarios — and under long fake-clock horizons in tests.

Thread safety: all state moves under one lock. In HALF_OPEN exactly one
caller wins the probe slot; concurrent ``allow`` calls during the probe are
refused (they fall down their own ladder) so a recovering resource is never
stampeded — exercised by tests/test_resilience.py's concurrency stress.
"""
from __future__ import annotations

import enum
import threading
from typing import Callable, Optional


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown_s: float = 120.0,
        name: str = "",
        on_transition: Optional[
            Callable[[BreakerState, BreakerState], None]
        ] = None,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got {failure_threshold}")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.name = name
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._consecutive_failures = 0
        self._opened_ts = 0.0
        self._probe_in_flight = False

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive_failures

    def _transition_locked(self, new: BreakerState) -> None:
        # *_locked suffix = caller holds self._lock (the graftlint GL004
        # convention); the callback runs under it too — callbacks are
        # metric/log writes and must not call back into the breaker
        old = self._state
        if old is new:
            return
        self._state = new
        if self._on_transition is not None:
            self._on_transition(old, new)

    def allow(self, now: float) -> bool:
        """May a caller engage the protected resource right now? In
        HALF_OPEN at most one caller gets True (the probe); the probe slot
        is held until that caller records success or failure."""
        with self._lock:
            if self._state is BreakerState.CLOSED:
                return True
            if self._state is BreakerState.OPEN:
                if now - self._opened_ts < self.cooldown_s:
                    return False
                self._transition_locked(BreakerState.HALF_OPEN)
                self._probe_in_flight = True
                return True
            # HALF_OPEN
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def record_success(self, now: float) -> None:
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                self._probe_in_flight = False
                self._consecutive_failures = 0
                self._transition_locked(BreakerState.CLOSED)
            elif self._state is BreakerState.CLOSED:
                self._consecutive_failures = 0
            # success reported while OPEN is a stale caller (admitted before
            # the trip): the open window stands

    def record_neutral(self, now: float) -> None:
        """The admitted caller could not exercise the resource at all (e.g.
        environmentally unavailable). Resolves a HALF_OPEN probe as success —
        an unexercisable resource is not faulting, and the breaker must not
        wedge open against it — but in CLOSED state changes NOTHING: in
        particular it does not reset the consecutive-failure streak, so
        interleaved unavailability can't keep a persistently faulting
        resource from ever tripping."""
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                self._probe_in_flight = False
                self._consecutive_failures = 0
                self._transition_locked(BreakerState.CLOSED)

    def release_probe(self, now: float) -> None:
        """The admitted half-open prober could not engage the resource for
        THIS call (routed around it): return the probe slot so a later
        caller can probe, leaving the breaker HALF_OPEN — the resource was
        not exercised, so neither success nor failure can be concluded."""
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                self._probe_in_flight = False

    def record_failure(self, now: float) -> None:
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                self._probe_in_flight = False
                self._opened_ts = now
                self._transition_locked(BreakerState.OPEN)
            elif self._state is BreakerState.CLOSED:
                self._consecutive_failures += 1
                if self._consecutive_failures >= self.failure_threshold:
                    self._opened_ts = now
                    self._transition_locked(BreakerState.OPEN)
            # failures reported while OPEN are stale: re-extending the
            # window on them would starve the half-open probe
