"""VPA object model: the VerticalPodAutoscaler CRD analog.

Reference: vertical-pod-autoscaler/pkg/apis/autoscaling.k8s.io/v1/types.go —
VerticalPodAutoscaler (targetRef + updatePolicy + resourcePolicy),
UpdateMode (Off/Initial/Recreate/Auto), ContainerResourcePolicy
(minAllowed/maxAllowed/controlledResources/mode).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from autoscaler_tpu.kube.objects import LabelSelector
from autoscaler_tpu.vpa.recommender import Recommendation


class UpdateMode(enum.Enum):
    OFF = "Off"            # recommend only, never apply
    INITIAL = "Initial"    # apply at pod creation (admission) only
    RECREATE = "Recreate"  # evict + re-admit
    AUTO = "Auto"          # currently equivalent to Recreate


class ContainerScalingMode(enum.Enum):
    AUTO = "Auto"
    OFF = "Off"


@dataclass
class ContainerResourcePolicy:
    """Per-container bounds the recommendation is clamped into
    (types.go ContainerResourcePolicy)."""

    container_name: str = "*"
    mode: ContainerScalingMode = ContainerScalingMode.AUTO
    min_cpu: float = 0.0           # cores
    max_cpu: float = float("inf")
    min_memory: float = 0.0        # bytes
    max_memory: float = float("inf")


@dataclass
class Vpa:
    """One VerticalPodAutoscaler object."""

    name: str
    namespace: str = "default"
    target_selector: LabelSelector = field(default_factory=LabelSelector)
    update_mode: UpdateMode = UpdateMode.AUTO
    resource_policies: List[ContainerResourcePolicy] = field(default_factory=list)

    def policy_for(self, container: str) -> ContainerResourcePolicy:
        wildcard = ContainerResourcePolicy()
        for p in self.resource_policies:
            if p.container_name == container:
                return p
            if p.container_name == "*":
                wildcard = p
        return wildcard

    def clamp(self, container: str, rec: Recommendation) -> Optional[Recommendation]:
        """Recommendation → policy-clamped recommendation; None if scaling is
        off for this container."""
        p = self.policy_for(container)
        if p.mode == ContainerScalingMode.OFF:
            return None

        def _c(v, lo, hi):
            return min(max(v, lo), hi)

        return Recommendation(
            target_cpu=_c(rec.target_cpu, p.min_cpu, p.max_cpu),
            target_memory=_c(rec.target_memory, p.min_memory, p.max_memory),
            lower_cpu=_c(rec.lower_cpu, p.min_cpu, p.max_cpu),
            lower_memory=_c(rec.lower_memory, p.min_memory, p.max_memory),
            upper_cpu=_c(rec.upper_cpu, p.min_cpu, p.max_cpu),
            upper_memory=_c(rec.upper_memory, p.min_memory, p.max_memory),
        )


def match_vpa(vpas: List[Vpa], namespace: str, labels: Dict[str, str]) -> Optional[Vpa]:
    """First VPA whose selector matches the pod's labels in-namespace
    (the admission controller's VPA lookup, resource/pod/handler.go)."""
    for vpa in vpas:
        if vpa.namespace == namespace and vpa.target_selector.matches(labels):
            return vpa
    return None
