"""VPA admission controller: mutating webhook that patches pod requests at
create time.

Reference: vertical-pod-autoscaler/pkg/admission-controller/logic/server.go
:37,59 — the webhook server receives an AdmissionReview for pod CREATE,
matches a VPA by target selector, and returns a base64 JSONPatch setting each
container's resource requests to the (policy-clamped) recommendation; pods
are never rejected, only patched (failurePolicy Ignore). Certificate
provisioning (certs.go / gencerts.sh) lives in vpa/certs.py — pass a
CertBundle to serve HTTPS in-process, or omit it to terminate TLS in front.

The patch computation is a pure function (`review_pod`) so it is testable
without sockets; `AdmissionServer` wraps it in a stdlib HTTP server.
"""
from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from autoscaler_tpu.kube.convert import (
    format_cpu_quantity,
    format_memory_quantity,
)
from autoscaler_tpu.vpa.api import UpdateMode, Vpa, match_vpa
from autoscaler_tpu.vpa.recommender import ContainerKey, Recommendation


def _cpu_str(cores: float) -> str:
    return format_cpu_quantity(cores, minimum_m=0)


def _mem_str(b: float) -> str:
    return format_memory_quantity(b, minimum=0)


_SUFFIX = {
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
}


def _parse_qty(s) -> Optional[float]:
    """Kubernetes quantity string → float (cores for cpu incl. 'm' suffix,
    bytes for memory incl. binary/decimal suffixes). None if unparseable."""
    if s is None:
        return None
    s = str(s).strip()
    try:
        if s.endswith("m"):
            return float(s[:-1]) / 1000.0
        for suf, mult in _SUFFIX.items():
            if s.endswith(suf):
                return float(s[: -len(suf)]) * mult
        return float(s)
    except ValueError:
        return None


def _proportional_limit(
    limits: Dict, requests: Dict, resource: str, new_request: float
) -> Optional[float]:
    """Scale the container's declared limit by the request change, keeping the
    original request:limit ratio — the reference's GetProportionalLimit
    (admission-controller/resource/pod/patch/resource_updates.go). Without
    this, raising a request above a declared limit yields a pod the apiserver
    rejects at validation (requests must be <= limits). When no original
    request was declared, Kubernetes defaults it to the limit, so the ratio is
    1 and the new limit equals the new request."""
    lim = _parse_qty(limits.get(resource))
    if lim is None or lim <= 0:
        return None
    orig = _parse_qty(requests.get(resource))
    if orig is None or orig <= 0:
        orig = lim
    return new_request * lim / orig


def review_pod(
    review: Dict,
    vpas: List[Vpa],
    recommendations: Dict[ContainerKey, Recommendation],
) -> Dict:
    """AdmissionReview request dict → AdmissionReview response dict with a
    JSONPatch over /spec/containers/N/resources/requests. Always allowed;
    patch only when a matching VPA (mode != Off) has a recommendation."""
    request = review.get("request", {})
    uid = request.get("uid", "")
    pod = request.get("object", {}) or {}
    meta = pod.get("metadata", {}) or {}
    namespace = request.get("namespace") or meta.get("namespace", "default")
    labels = meta.get("labels", {}) or {}

    response: Dict = {"uid": uid, "allowed": True}
    out = {
        "apiVersion": review.get("apiVersion", "admission.k8s.io/v1"),
        "kind": "AdmissionReview",
        "response": response,
    }

    vpa = match_vpa(vpas, namespace, labels)
    if vpa is None or vpa.update_mode == UpdateMode.OFF:
        return out

    patches: List[Dict] = []
    containers = (pod.get("spec", {}) or {}).get("containers", []) or []
    for i, container in enumerate(containers):
        name = container.get("name", "")
        rec = recommendations.get(ContainerKey(vpa.name, name, vpa.namespace))
        if rec is None:
            continue
        clamped = vpa.clamp(name, rec)
        if clamped is None:  # container scaling Off
            continue
        resources = container.get("resources") or {}
        if "resources" not in container:
            patches.append({"op": "add", "path": f"/spec/containers/{i}/resources", "value": {}})
        if "requests" not in resources:
            patches.append(
                {"op": "add", "path": f"/spec/containers/{i}/resources/requests", "value": {}}
            )
        patches.append(
            {
                "op": "add",
                "path": f"/spec/containers/{i}/resources/requests/cpu",
                "value": _cpu_str(clamped.target_cpu),
            }
        )
        patches.append(
            {
                "op": "add",
                "path": f"/spec/containers/{i}/resources/requests/memory",
                "value": _mem_str(clamped.target_memory),
            }
        )
        limits = resources.get("limits") or {}
        requests = resources.get("requests") or {}
        cpu_lim = _proportional_limit(limits, requests, "cpu", clamped.target_cpu)
        if cpu_lim is not None:
            patches.append(
                {
                    "op": "add",
                    "path": f"/spec/containers/{i}/resources/limits/cpu",
                    "value": _cpu_str(cpu_lim),
                }
            )
        mem_lim = _proportional_limit(limits, requests, "memory", clamped.target_memory)
        if mem_lim is not None:
            patches.append(
                {
                    "op": "add",
                    "path": f"/spec/containers/{i}/resources/limits/memory",
                    "value": _mem_str(mem_lim),
                }
            )
    if patches:
        # one breadcrumb per pod (reference vpaUpdates annotation); adding the
        # single key preserves existing annotations — an "add" of the whole
        # map would wipe them (RFC 6902: add on an existing member replaces)
        if meta.get("annotations") is None:
            patches.append({"op": "add", "path": "/metadata/annotations", "value": {}})
        patches.append(
            {
                "op": "add",
                "path": "/metadata/annotations/vpaUpdates",
                "value": f"Pod resources updated by {vpa.name}",
            }
        )
    if patches:
        response["patchType"] = "JSONPatch"
        response["patch"] = base64.b64encode(
            json.dumps(patches).encode()
        ).decode()
    return out


class AdmissionServer:
    """Stdlib HTTP wrapper: POST /mutate with an AdmissionReview body."""

    def __init__(
        self,
        vpas: List[Vpa],
        recommendations: Dict[ContainerKey, Recommendation],
        host: str = "127.0.0.1",
        port: int = 0,
        tls: Optional["CertBundle"] = None,
    ):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (stdlib API)
                if self.path != "/mutate":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    review = json.loads(self.rfile.read(length) or b"{}")
                    body = json.dumps(
                        review_pod(review, outer.vpas, outer.recommendations)
                    ).encode()
                except (ValueError, KeyError) as e:
                    self.send_error(400, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/health-check":
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                else:
                    self.send_error(404)

            def log_message(self, *args):
                pass

        self.vpas = vpas
        self.recommendations = recommendations
        self.tls = tls
        if tls is None:
            self._server = ThreadingHTTPServer((host, port), Handler)
        else:
            # Handshake must NOT run in the accept loop: wrapping the
            # listening socket makes accept() perform the full handshake in
            # the serve_forever thread, so one stalled client (half-open
            # connection, port scan) would block every subsequent webhook
            # request — and with failurePolicy Ignore, pods would silently
            # admit unpatched. Wrap per-connection with a lazy handshake (it
            # then happens in the per-request handler thread) plus a socket
            # timeout so dead clients release their thread.
            ssl_ctx = tls.server_ssl_context()

            class TlsServer(ThreadingHTTPServer):
                def get_request(self):
                    sock, addr = self.socket.accept()
                    sock.settimeout(30.0)
                    return (
                        ssl_ctx.wrap_socket(
                            sock, server_side=True, do_handshake_on_connect=False
                        ),
                        addr,
                    )

                def handle_error(self, request, client_address):
                    # failed handshakes/dead clients are the client's
                    # problem; anything else (a handler bug) must keep the
                    # stdlib traceback — with failurePolicy Ignore a silent
                    # failure means pods admit unpatched with no trail
                    import socket
                    import ssl as _ssl
                    import sys

                    exc = sys.exc_info()[1]  # sys.exception() needs 3.11+
                    if isinstance(
                        exc, (_ssl.SSLError, socket.timeout, ConnectionError)
                    ):
                        return
                    super().handle_error(request, client_address)

            self._server = TlsServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address[:2]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
