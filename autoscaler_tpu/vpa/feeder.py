"""VPA input pipeline: live metrics feeding + history replay.

Reference: vertical-pod-autoscaler/pkg/recommender/input/ —
ClusterStateFeeder (cluster_feeder.go:67) pulls container usage from the
metrics API every pass and streams samples into the model;
HistoryProvider (input/history/history_provider.go) replays Prometheus
range-query timeseries once at startup so a fresh recommender does not begin
cold; the OOM observer (input/oom/observer.go) turns container OOMKill events
into padded memory samples.

The transport is a protocol (`MetricsSource` / `HistorySource`), so tests and
zero-egress environments use the in-memory fakes; a deploy site plugs a
metrics-server or Prometheus client with the same surface. Samples are
batched into the model's vectorized add_* entry points — one numpy dispatch
per pass, not one per container.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from autoscaler_tpu.vpa.api import Vpa, match_vpa
from autoscaler_tpu.vpa.recommender import ClusterStateModel, ContainerKey, instance_key


@dataclass
class ContainerUsage:
    """One scrape: instantaneous cpu (cores) + memory working set (bytes)."""

    namespace: str
    pod_name: str
    container: str
    pod_labels: Dict[str, str] = field(default_factory=dict)
    cpu_cores: float = 0.0
    memory_bytes: float = 0.0


class MetricsSource(abc.ABC):
    """The metrics-API surface the feeder needs (cluster_feeder.go uses
    MetricsClient; same shape)."""

    @abc.abstractmethod
    def container_usage(self, now_ts: float) -> List[ContainerUsage]: ...


class HistorySource(abc.ABC):
    """Range-query surface: per-container (ts, value) series
    (history_provider.go GetClusterHistory)."""

    @abc.abstractmethod
    def cpu_series(self) -> Dict[Tuple[str, str, str], List[Tuple[float, float]]]:
        """(namespace, pod, container) → [(ts, cores)]."""

    @abc.abstractmethod
    def memory_series(self) -> Dict[Tuple[str, str, str], List[Tuple[float, float]]]:
        """(namespace, pod, container) → [(ts, bytes)]."""

    @abc.abstractmethod
    def pod_labels(self) -> Dict[Tuple[str, str], Dict[str, str]]:
        """(namespace, pod) → labels (for VPA matching)."""


class InMemoryMetrics(MetricsSource, HistorySource):
    """Test/hermetic implementation of both surfaces."""

    def __init__(self) -> None:
        self._usage: List[ContainerUsage] = []
        self._cpu: Dict[Tuple[str, str, str], List[Tuple[float, float]]] = {}
        self._mem: Dict[Tuple[str, str, str], List[Tuple[float, float]]] = {}
        self._labels: Dict[Tuple[str, str], Dict[str, str]] = {}

    def set_usage(self, usage: Sequence[ContainerUsage]) -> None:
        self._usage = list(usage)

    def add_history(
        self,
        namespace: str,
        pod: str,
        container: str,
        labels: Dict[str, str],
        cpu: Sequence[Tuple[float, float]] = (),
        memory: Sequence[Tuple[float, float]] = (),
    ) -> None:
        key = (namespace, pod, container)
        self._cpu.setdefault(key, []).extend(cpu)
        self._mem.setdefault(key, []).extend(memory)
        self._labels[(namespace, pod)] = dict(labels)

    def container_usage(self, now_ts: float) -> List[ContainerUsage]:
        return list(self._usage)

    def cpu_series(self):
        return self._cpu

    def memory_series(self):
        return self._mem

    def pod_labels(self):
        return self._labels


class ClusterStateFeeder:
    """Streams metrics into the histogram model, one batched call per pass."""

    def __init__(self, model: ClusterStateModel, vpas: List[Vpa]):
        self.model = model
        self.vpas = vpas

    def _key_for(self, namespace: str, labels: Dict[str, str], container: str) -> Optional[ContainerKey]:
        vpa = match_vpa(self.vpas, namespace, labels)
        if vpa is None:
            return None
        return ContainerKey(vpa.name, container, vpa.namespace)

    def feed_once(self, source: MetricsSource, now_ts: float) -> int:
        """One live scrape → model. Returns samples ingested."""
        keys: List[ContainerKey] = []
        cpu: List[float] = []
        mem: List[float] = []
        pods: List[str] = []
        for u in source.container_usage(now_ts):
            key = self._key_for(u.namespace, u.pod_labels, u.container)
            if key is None:
                continue
            keys.append(key)
            cpu.append(u.cpu_cores)
            mem.append(u.memory_bytes)
            pods.append(instance_key(u.namespace, u.pod_name))
        if not keys:
            return 0
        ts = [now_ts] * len(keys)
        self.model.add_cpu_samples(keys, cpu, ts)
        self.model.add_memory_peaks(keys, mem, ts, pods)
        return len(keys)

    def replay_history(self, source: HistorySource) -> int:
        """Startup backfill (history_provider.go): every stored point becomes
        a sample at its original timestamp, so the decaying histograms weight
        it correctly. Returns samples ingested."""
        labels_of = source.pod_labels()
        count = 0
        keys: List[ContainerKey] = []
        values: List[float] = []
        ts: List[float] = []
        for (ns, pod, container), series in source.cpu_series().items():
            key = self._key_for(ns, labels_of.get((ns, pod), {}), container)
            if key is None:
                continue
            for t, v in series:
                keys.append(key)
                values.append(v)
                ts.append(t)
        if keys:
            self.model.add_cpu_samples(keys, values, ts)
            count += len(keys)
        keys, values, ts, pods = [], [], [], []
        for (ns, pod, container), series in source.memory_series().items():
            key = self._key_for(ns, labels_of.get((ns, pod), {}), container)
            if key is None:
                continue
            for t, v in series:
                keys.append(key)
                values.append(v)
                ts.append(t)
                pods.append(instance_key(ns, pod))
        if keys:
            self.model.add_memory_peaks(keys, values, ts, pods)
            count += len(keys)
        return count
