"""Decaying-exponential histograms, vectorized across all containers.

Reference: vertical-pod-autoscaler/pkg/recommender/util/histogram.go:34,159
(exponential buckets: first bucket 0.01 cores / 10MB, ratio 1.05; weighted
percentile) and decaying_histogram.go:53,108 (half-life decay 24h: new
samples are scaled by 2^((t-ref)/half_life) and the bank is periodically
re-referenced to keep weights in float range), plus the checkpoint
(de)serialization at util/histogram.go:224,249.

The reference keeps one Go histogram object per (VPA, container, resource);
here a HistogramBank holds ALL of them as one [C, B] weight matrix, so a
whole cluster's sample ingestion is one scatter-add and every percentile is
one cumsum — the embarrassingly-vectorizable path SURVEY.md §7 stage 8
calls out.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# reference constants (histogram_options for cpu / memory)
CPU_FIRST_BUCKET = 0.01      # cores
MEMORY_FIRST_BUCKET = 1e7    # bytes (10MB)
BUCKET_RATIO = 1.05
NUM_BUCKETS = 176            # covers ~0.01..~50 cores / 10MB..~50TB
DEFAULT_HALF_LIFE_S = 24 * 3600.0
EPSILON = 1e-15


@dataclass(frozen=True)
class HistogramSpec:
    first_bucket: float
    ratio: float = BUCKET_RATIO
    num_buckets: int = NUM_BUCKETS

    def bucket_of(self, values: np.ndarray) -> np.ndarray:
        v = np.maximum(np.asarray(values, np.float64), EPSILON)
        idx = np.floor(np.log(v / self.first_bucket) / np.log(self.ratio)) + 1.0
        # values below first_bucket land in bucket 0
        return np.clip(idx, 0, self.num_buckets - 1).astype(np.int32)

    def bucket_start(self, idx) -> np.ndarray:
        i = np.asarray(idx, np.float64)
        return np.where(i <= 0, 0.0, self.first_bucket * self.ratio ** (i - 1.0))


CPU_SPEC = HistogramSpec(CPU_FIRST_BUCKET)
MEMORY_SPEC = HistogramSpec(MEMORY_FIRST_BUCKET)


class HistogramBank:
    """[C, B] decaying histogram bank for C containers."""

    def __init__(
        self,
        num_series: int,
        spec: HistogramSpec,
        half_life_s: float = DEFAULT_HALF_LIFE_S,
    ):
        self.spec = spec
        self.half_life_s = half_life_s
        self.ref_ts = 0.0
        self.weights = jnp.zeros((num_series, spec.num_buckets), jnp.float32)
        self.total = jnp.zeros((num_series,), jnp.float32)

    @property
    def num_series(self) -> int:
        return self.weights.shape[0]

    def grow_to(self, num_series: int) -> None:
        if num_series <= self.num_series:
            return
        pad = num_series - self.num_series
        self.weights = jnp.pad(self.weights, ((0, pad), (0, 0)))
        self.total = jnp.pad(self.total, (0, pad))

    def _decay_factor(self, ts: np.ndarray) -> np.ndarray:
        return np.power(2.0, (np.asarray(ts) - self.ref_ts) / self.half_life_s)

    def add_samples(
        self,
        series_idx: np.ndarray,   # [K] i32
        values: np.ndarray,       # [K]
        weights: np.ndarray,      # [K]
        timestamps: np.ndarray,   # [K] epoch seconds
    ) -> None:
        """One batched scatter-add for any number of samples across any
        number of containers (decaying_histogram.go:AddSample, vectorized)."""
        if len(series_idx) == 0:
            return
        buckets = self.spec.bucket_of(values)
        # Re-reference BEFORE weighting: with real wall-clock epochs the very
        # first sample sits ~1.7e9s past the initial ref_ts=0 and
        # 2^(dt/half_life) overflows float64, poisoning every weight. Decay
        # the existing mass to the new reference first (0.5^(shift/hl) — 0.0
        # for anything 10+ half-lives stale, which is exact enough), then
        # weight this batch against the fresh reference.
        max_ts = float(np.max(timestamps))
        if max_ts - self.ref_ts > 10 * self.half_life_s:
            factor = np.float32(0.5 ** ((max_ts - self.ref_ts) / self.half_life_s))
            self.weights = self.weights * factor
            self.total = self.total * factor
            self.ref_ts = max_ts
        w = np.asarray(weights, np.float64) * self._decay_factor(timestamps)
        flat = np.asarray(series_idx, np.int64) * self.spec.num_buckets + buckets
        self.weights = (
            self.weights.ravel()
            .at[jnp.asarray(flat)]
            .add(jnp.asarray(w, jnp.float32))
            .reshape(self.weights.shape)
        )
        self.total = self.total.at[jnp.asarray(series_idx)].add(
            jnp.asarray(w, jnp.float32)
        )

    def percentile(self, p: float) -> jax.Array:
        """[C] — weighted percentile per series in one cumsum
        (histogram.go:159 Percentile). Empty series → 0."""
        cum = jnp.cumsum(self.weights, axis=1)
        total = self.total[:, None]
        target = p * total
        idx = jnp.argmax(cum >= target - 1e-9, axis=1)
        # reference returns the bucket END value (start of next bucket) so the
        # recommendation covers the observed sample
        ends = jnp.asarray(
            self.spec.bucket_start(np.arange(1, self.spec.num_buckets + 1)),
            jnp.float32,
        )
        out = ends[idx]
        return jnp.where(self.total > 0, out, 0.0)

    # -- checkpoints (histogram.go:224,249 SaveToChekpoint/LoadFromCheckpoint)
    def checkpoint(self, series: int) -> Dict:
        w = np.asarray(self.weights[series], np.float64)
        total = float(w.sum())
        if total <= 0:
            return {"total_weight": 0.0, "bucket_weights": {}, "ref_ts": self.ref_ts}
        maxw = w.max()
        # reference normalizes to ints in 0..10000 relative to max bucket
        norm = {
            int(i): int(round(x / maxw * 10000))
            for i, x in enumerate(w)
            if round(x / maxw * 10000) > 0
        }
        return {"total_weight": total, "bucket_weights": norm, "ref_ts": self.ref_ts}

    def restore(self, series: int, ckpt: Dict) -> None:
        bw = ckpt.get("bucket_weights", {})
        w = np.zeros(self.spec.num_buckets, np.float32)
        norm_sum = sum(bw.values())
        total = float(ckpt.get("total_weight", 0.0))
        if norm_sum > 0:
            for i, x in bw.items():
                w[int(i)] = x
            w = w / w.sum() * total
        # Stored weights are relative to the checkpoint's decay reference.
        # Adopt it (a fresh bank has ref_ts=0; without this, the first live
        # sample at a real epoch would trip the re-reference branch and
        # multiply the restored mass by ~0). If the bank already carries a
        # newer reference, re-base the restored mass onto it instead.
        saved_ref = float(ckpt.get("ref_ts", 0.0))
        if saved_ref > self.ref_ts:
            factor = np.float32(0.5 ** ((saved_ref - self.ref_ts) / self.half_life_s))
            self.weights = self.weights * factor
            self.total = self.total * factor
            self.ref_ts = saved_ref
        elif saved_ref < self.ref_ts:
            rebase = float(0.5 ** ((self.ref_ts - saved_ref) / self.half_life_s))
            w = w * rebase
            total = total * rebase
        self.weights = self.weights.at[series].set(jnp.asarray(w))
        self.total = self.total.at[series].set(total)
