"""Prometheus history provider for the VPA recommender warm start.

Concrete ``HistorySource`` (vpa/feeder.py) speaking the Prometheus HTTP API,
matching the reference's provider behavior
(vertical-pod-autoscaler/pkg/recommender/input/history/history_provider.go):

- CPU: ``rate(container_cpu_usage_seconds_total{<selector>}[<resolution>])``
  range-queried over the history window (cores).
- Memory: ``container_memory_working_set_bytes{<selector>}`` range-queried
  over the same window (bytes).
- Pod labels: one instant query of the kube-state-metrics series
  (``up{job="kube-state-metrics"}``-style, configurable) whose label set
  carries ``<pod_label_prefix>*`` keys; the freshest sample per pod wins
  (readLastLabels, history_provider.go:225).

Transport is stdlib urllib (zero extra deps, same choice as kube/client.py);
results parse from the standard ``/api/v1/query_range`` / ``/api/v1/query``
JSON envelope. Queries are built exactly like the reference's (selector
structure incl. the cadvisor job matcher, the ``name!="POD"`` pause-container
exclusion, and the optional namespace pin) so a recorded reference-shaped
server answers them — tests/test_vpa_prometheus.py locks the query strings
against the reference's own test expectations (history_provider_test.go:34).

Durations accept the Prometheus forms the reference parses via
``prommodel.ParseDuration``: ``30s``, ``5m``, ``1h``, ``8d``, ``2w``, ``1y``.
"""
from __future__ import annotations

import json
import logging
import re
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Dict, List, Tuple

from autoscaler_tpu.vpa.feeder import HistorySource

log = logging.getLogger("vpa.prometheus")

_DURATION_RE = re.compile(r"(\d+)(ms|s|m|h|d|w|y)")
_DURATION_S = {
    "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
    "d": 86400.0, "w": 7 * 86400.0, "y": 365 * 86400.0,
}


def parse_duration_s(s: str) -> float:
    """Prometheus duration string → seconds, incl. compound forms like
    ``1h30m`` / ``1d12h`` (prommodel.ParseDuration grammar: units in
    strictly descending order, each at most once)."""
    text = s.strip()
    if text == "0":
        return 0.0    # prommodel special-cases the bare "0" (no unit)
    pos, total = 0, 0.0
    last_rank = -1
    ranks = {u: r for r, u in enumerate(("y", "w", "d", "h", "m", "s", "ms"))}
    while pos < len(text):
        m = _DURATION_RE.match(text, pos)
        if not m:
            raise ValueError(f"{s!r} is not a valid Prometheus duration")
        rank = ranks[m.group(2)]
        if rank <= last_rank:  # repeated or out-of-order unit
            raise ValueError(f"{s!r} is not a valid Prometheus duration")
        last_rank = rank
        total += int(m.group(1)) * _DURATION_S[m.group(2)]
        pos = m.end()
    if pos == 0:
        raise ValueError(f"{s!r} is not a valid Prometheus duration")
    return total


@dataclass
class PrometheusHistoryConfig:
    """Mirror of PrometheusHistoryProviderConfig (history_provider.go:37),
    defaults matching the reference recommender's flags."""

    address: str                       # e.g. http://prometheus.monitoring:9090
    history_length: str = "8d"
    history_resolution: str = "1h"
    query_timeout_s: float = 5 * 60.0
    pod_label_prefix: str = "pod_label_"
    pod_labels_metric_name: str = (
        'up{job="kube-state-metrics"}[8d]'
    )
    pod_namespace_label: str = "kubernetes_namespace"
    pod_name_label: str = "kubernetes_pod_name"
    ctr_namespace_label: str = "namespace"
    ctr_pod_name_label: str = "pod_name"
    ctr_name_label: str = "name"
    cadvisor_job_name: str = "kubernetes-cadvisor"
    namespace: str = ""                # "" = all namespaces


Series = Dict[Tuple[str, str, str], List[Tuple[float, float]]]


class PrometheusHistorySource(HistorySource):
    """Fetch-on-demand HistorySource: the three queries run once on the
    first accessor and cache (the feeder replays history exactly once at
    startup — cluster_feeder.go InitFromHistoryProvider)."""

    def __init__(self, config: PrometheusHistoryConfig, opener=None):
        self.config = config
        # injectable opener for tests; urllib's default otherwise
        self._open = opener or urllib.request.urlopen
        self._cpu: Series | None = None
        self._mem: Series | None = None
        self._labels: Dict[Tuple[str, str], Dict[str, str]] | None = None

    # -- query construction (GetClusterHistory, history_provider.go:263) ---
    def _pod_selector(self) -> str:
        c = self.config
        parts = []
        if c.cadvisor_job_name:
            parts.append(f'job="{c.cadvisor_job_name}"')
        parts.append(f'{c.ctr_pod_name_label}=~".+"')
        parts.append(f'{c.ctr_name_label}!="POD"')
        parts.append(f'{c.ctr_name_label}!=""')
        if c.namespace:
            parts.append(f'{c.ctr_namespace_label}="{c.namespace}"')
        return ", ".join(parts)

    def cpu_query(self) -> str:
        return (
            f"rate(container_cpu_usage_seconds_total{{{self._pod_selector()}}}"
            f"[{self.config.history_resolution}])"
        )

    def memory_query(self) -> str:
        return f"container_memory_working_set_bytes{{{self._pod_selector()}}}"

    # -- HTTP --------------------------------------------------------------
    def _api(self, path: str, params: Dict[str, str]) -> list:
        url = (
            self.config.address.rstrip("/")
            + path + "?" + urllib.parse.urlencode(params)
        )
        with self._open(url, timeout=self.config.query_timeout_s) as resp:
            body = json.loads(resp.read().decode())
        if body.get("status") != "success":
            raise RuntimeError(
                f"prometheus query failed: {body.get('error', body)}"
            )
        data = body.get("data", {})
        if data.get("resultType") != "matrix":
            raise RuntimeError(
                f"expected a matrix result, got {data.get('resultType')!r}"
            )
        return data.get("result", [])

    def _query_range(self, query: str) -> list:
        end = time.time()
        start = end - parse_duration_s(self.config.history_length)
        # step as plain float seconds: Prometheus accepts that form for any
        # resolution, while a composed duration string like "0.5s" is
        # rejected (decimal durations are invalid duration syntax)
        step = parse_duration_s(self.config.history_resolution)
        return self._api(
            "/api/v1/query_range",
            {"query": query, "start": f"{start:.3f}", "end": f"{end:.3f}",
             "step": f"{step:g}"},
        )

    def _query_instant(self, query: str) -> list:
        return self._api(
            "/api/v1/query", {"query": query, "time": f"{time.time():.3f}"}
        )

    # -- parsing -----------------------------------------------------------
    def _container_series(self, result: list) -> Series:
        c = self.config
        out: Series = {}
        for ts in result:
            metric = ts.get("metric", {})
            try:
                key = (
                    metric[c.ctr_namespace_label],
                    metric[c.ctr_pod_name_label],
                    metric[c.ctr_name_label],
                )
            except KeyError as e:
                # the reference hard-fails here (getContainerIDFromLabels);
                # a permissive skip would hide a mislabeled scrape config
                raise RuntimeError(
                    f"timeseries metric lacks the {e.args[0]!r} label: {metric}"
                ) from e
            points = [
                (float(t), float(v))
                for t, v in ts.get("values", [])
                if v not in ("NaN", "+Inf", "-Inf")
            ]
            out.setdefault(key, []).extend(points)
        for pts in out.values():
            pts.sort(key=lambda p: p[0])
        return out

    def _fetch(self) -> None:
        # guard on the LAST field assigned: a failure mid-way (memory or
        # labels query) must leave the cache unset so a retry re-fetches
        # instead of returning a half-initialized None
        if self._labels is not None:
            return
        t0 = time.monotonic()
        cpu = self._container_series(self._query_range(self.cpu_query()))
        mem = self._container_series(
            self._query_range(self.memory_query())
        )
        c = self.config
        labels: Dict[Tuple[str, str], Dict[str, str]] = {}
        freshest: Dict[Tuple[str, str], float] = {}
        for ts in self._query_instant(c.pod_labels_metric_name):
            metric = ts.get("metric", {})
            ns = metric.get(c.pod_namespace_label)
            pod = metric.get(c.pod_name_label)
            if ns is None or pod is None:
                raise RuntimeError(
                    f"labels series lacks {c.pod_namespace_label}/"
                    f"{c.pod_name_label}: {metric}"
                )
            values = ts.get("values", [])
            if not values:
                continue
            last_ts = float(values[-1][0])
            if last_ts <= freshest.get((ns, pod), -1.0):
                continue
            freshest[(ns, pod)] = last_ts
            labels[(ns, pod)] = {
                k[len(c.pod_label_prefix):]: v
                for k, v in metric.items()
                if k.startswith(c.pod_label_prefix)
            }
        # all three queries succeeded: publish atomically
        self._cpu, self._mem, self._labels = cpu, mem, labels
        log.info(
            "prometheus history: %d cpu series, %d memory series, %d "
            "labeled pods in %.1fs",
            len(cpu), len(mem), len(labels),
            time.monotonic() - t0,
        )

    # -- HistorySource -----------------------------------------------------
    def cpu_series(self) -> Series:
        self._fetch()
        return self._cpu

    def memory_series(self) -> Series:
        self._fetch()
        return self._mem

    def pod_labels(self) -> Dict[Tuple[str, str], Dict[str, str]]:
        self._fetch()
        return self._labels
