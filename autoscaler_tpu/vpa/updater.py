"""VPA updater: evict pods whose requests drift from the recommendation.

Reference: vertical-pod-autoscaler/pkg/updater/ — logic/updater.go:109
RunOnce, update_priority_calculator.go:47,81 (evict when any container's
request is off by >10% either way, quick path for recent OOMs, and
long-persisting (12h+) significant changes), eviction rate limiter :235,
PDB-aware eviction via pkg/updater/eviction (here: RemainingPdbTracker).
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from autoscaler_tpu.core.scaledown.tracking import RemainingPdbTracker
from autoscaler_tpu.kube.objects import Pod
from autoscaler_tpu.vpa.recommender import ContainerKey, Recommendation

log = logging.getLogger("vpa.updater")

DEFAULT_DRIFT_THRESHOLD = 0.10         # updatePriorityCalculator 10%
SIGNIFICANT_CHANGE_AFTER_S = 12 * 3600.0
OOM_QUICK_PATH_WINDOW_S = 10 * 60.0


@dataclass
class PodUpdatePriority:
    pod: Pod
    priority: float
    outside_recommended_range: bool
    oom_quick_path: bool


class UpdatePriorityCalculator:
    def __init__(self, drift_threshold: float = DEFAULT_DRIFT_THRESHOLD):
        self.drift_threshold = drift_threshold

    def priority_of(
        self,
        pod: Pod,
        recommendation: Recommendation,
        now_ts: float,
        last_oom_ts: Optional[float] = None,
        recommendation_age_s: float = 0.0,
    ) -> Optional[PodUpdatePriority]:
        """→ update priority, or None when no update is warranted
        (update_priority_calculator.go:47 AddPod / :81 getUpdatePriority)."""
        req_cpu = pod.requests.cpu_m / 1000.0
        req_mem = pod.requests.memory
        drift = 0.0
        outside = False
        if req_cpu > 0:
            drift += abs(recommendation.target_cpu - req_cpu) / req_cpu
            if not (recommendation.lower_cpu <= req_cpu <= recommendation.upper_cpu):
                outside = True
        if req_mem > 0:
            drift += abs(recommendation.target_memory - req_mem) / req_mem
            if not (
                recommendation.lower_memory <= req_mem <= recommendation.upper_memory
            ):
                outside = True

        oom_quick = (
            last_oom_ts is not None and now_ts - last_oom_ts < OOM_QUICK_PATH_WINDOW_S
        )
        significant = drift > self.drift_threshold and (
            outside or recommendation_age_s >= SIGNIFICANT_CHANGE_AFTER_S
        )
        if not (oom_quick or significant):
            return None
        return PodUpdatePriority(
            pod=pod,
            priority=drift + (10.0 if oom_quick else 0.0),
            outside_recommended_range=outside,
            oom_quick_path=oom_quick,
        )


class EvictionRateLimiter:
    """At most a fraction of a workload's replicas may be disrupted per pass
    (updater.go:235 + eviction tolerance)."""

    def __init__(self, eviction_tolerance: float = 0.5, min_replicas: int = 2):
        self.eviction_tolerance = eviction_tolerance
        self.min_replicas = min_replicas

    def budget_for(self, replica_count: int) -> int:
        if replica_count < self.min_replicas:
            return 0
        if self.eviction_tolerance <= 0:
            # tolerance 0 means "never disrupt", not "one per pass"
            return 0
        return max(1, int(replica_count * self.eviction_tolerance))


class Updater:
    def __init__(
        self,
        calculator: Optional[UpdatePriorityCalculator] = None,
        rate_limiter: Optional[EvictionRateLimiter] = None,
    ):
        self.calculator = calculator or UpdatePriorityCalculator()
        self.rate_limiter = rate_limiter or EvictionRateLimiter()

    def run_once(
        self,
        pods_by_workload: Dict[str, List[Pod]],
        recommendations: Dict[ContainerKey, Recommendation],
        vpa_of_workload: Dict[str, str],
        now_ts: float,
        pdb_tracker: Optional[RemainingPdbTracker] = None,
        evict_fn=None,
        oom_ts: Optional[Dict[str, float]] = None,
        recommendation_age_s: float = SIGNIFICANT_CHANGE_AFTER_S,
        vpas: Optional[Dict[str, "object"]] = None,
    ) -> List[Pod]:
        """→ pods evicted, highest priority first, PDB- and rate-limited.

        `vpas` maps VPA name → Vpa; when given, only Recreate/Auto VPAs
        evict (updater.go:109 skips Off/Initial — Initial applies at
        admission only)."""
        from autoscaler_tpu.vpa.api import UpdateMode

        evicted: List[Pod] = []
        oom_ts = oom_ts or {}
        for workload, pods in pods_by_workload.items():
            vpa = vpa_of_workload.get(workload)
            if vpa is None:
                continue
            if vpas is not None:
                # fail CLOSED: an unresolvable VPA (cache lag, rename) or one
                # without a readable mode must not evict — Off mode exists
                # precisely to prevent disruption (updater.go resolves the
                # VPA first and skips when it can't). Lookup tries the
                # workload key first (unique: callers key it by ns/name so
                # same-named VPAs in two namespaces can't collide), then the
                # bare VPA name for callers with a flat map.
                resolved = vpas.get(workload, vpas.get(vpa))
                mode = getattr(resolved, "update_mode", None)
                if mode not in (UpdateMode.RECREATE, UpdateMode.AUTO):
                    continue
            budget = self.rate_limiter.budget_for(len(pods))
            candidates: List[PodUpdatePriority] = []
            for pod in pods:
                key = ContainerKey(vpa, pod.name.rsplit("-", 1)[0], pod.namespace)
                rec = recommendations.get(key) or next(
                    (
                        r
                        for k, r in recommendations.items()
                        if k.vpa == vpa and k.namespace == pod.namespace
                    ),
                    None,
                )
                if rec is None:
                    continue
                p = self.calculator.priority_of(
                    pod,
                    rec,
                    now_ts,
                    last_oom_ts=oom_ts.get(pod.key()),
                    recommendation_age_s=recommendation_age_s,
                )
                if p is not None:
                    candidates.append(p)
            candidates.sort(key=lambda c: -c.priority)
            for cand in candidates[:budget]:
                if pdb_tracker is not None and not pdb_tracker.can_remove_pods([cand.pod]):
                    continue
                if evict_fn is not None:
                    try:
                        evict_fn(cand.pod)
                    except Exception as e:  # noqa: BLE001
                        # eviction races are normal control-plane weather
                        # (429 from a PDB admission check, pod already gone):
                        # skip THIS pod, keep the pass alive, retry next pass
                        # — the reference updater logs and continues
                        # (logic/updater.go:109 eviction loop). The PDB
                        # tracker is only charged after a successful evict.
                        # Logged so persistent non-weather failures (RBAC,
                        # bugs) stay visible.
                        log.warning("evicting %s failed: %s", cand.pod.key(), e)
                        continue
                if pdb_tracker is not None:
                    pdb_tracker.remove_pods([cand.pod])
                evicted.append(cand.pod)
        return evicted


def apply_recommendation(pod: Pod, rec: Recommendation) -> Pod:
    """Admission-controller analog: patch a (new) pod's requests to the
    recommended target (reference pkg/admission-controller/logic/server.go:37
    — the mutating webhook patches at create time; embed this at your pod
    creation path)."""
    import dataclasses

    new_requests = dataclasses.replace(
        pod.requests,
        cpu_m=rec.target_cpu * 1000.0,
        memory=rec.target_memory,
    )
    return dataclasses.replace(pod, requests=new_requests)
